"""Declarative alert engine (``exp_manager.telemetry.alerts``).

A validated list of rules evaluated boundary-side over the streamed metrics
— the per-host boundary fetch the loop already performs, plus the
``fleet/*`` metrics when the fleet plane is on.  No new host syncs, no
graph changes: the engine only ever sees host floats.

.. code-block:: yaml

    exp_manager:
      telemetry:
        alerts:
          - metric: data_wait        # bare span names resolve to time/<name>
            window: 3                # boundaries averaged (default 1)
            threshold: 30.0          # fires when the windowed mean >= this
            action: halt             # log | dump | halt   (default log)
          - metric: mfu
            window: 5
            rel_drop: 0.2            # fires when the windowed mean falls
                                     # >= 20% below its own running peak
            action: dump
          - metric: loss
            below: 0.0               # fires when the windowed mean <= this
            action: log
          - metric: tensorstats/pre/layers.attn/subnormal_frac
            window: 5
            rel_rise: 0.5            # fires when the windowed mean rises
                                     # >= 50% above its own running minimum
            action: dump

Rule grammar (validated at config load — a typo'd rule dies there, not at
step 10k): ``metric`` (required; matched against the logged metric keys,
with a ``time/<metric>`` fallback so span rules read naturally), ``window``
(>= 1 boundaries averaged), exactly ONE of ``threshold`` (fires high) /
``below`` (fires low) / ``rel_drop`` (fires on a relative drop vs the
windowed mean's running peak — the "throughput fell off a cliff" form) /
``rel_rise`` (the mirror: fires on a relative rise vs the windowed mean's
running MINIMUM — the "underflow fraction is creeping up" form),
``action`` (``log`` warns, ``dump`` writes a flight-recorder bundle
``alert_<step>/`` through the same machinery anomaly forensics use,
``halt`` requests a graceful stop whose reason lands in
``run_summary.json``), and an optional ``name``.

Firings are edge-triggered: a rule in continuous violation fires once and
re-arms only after a clean boundary — a stuck metric must not write a
bundle per boundary.  Every firing is appended to the ``alerts`` trail in
``run_summary.json`` as it happens (capped per rule), so a halt's reason
survives even if teardown never runs.

Stdlib-only at import time (like ``telemetry.fleet``) so the offline tools
can load it without jax.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
from typing import Any, Callable, Mapping, Optional, Sequence

logger = logging.getLogger(__name__)

ALERT_ACTIONS = ("log", "dump", "halt")

#: recorded firings per rule (the trail in run_summary.json stays bounded
#: even under a pathological flap)
MAX_FIRINGS_PER_RULE = 20

_RULE_KEYS = {"name", "metric", "window", "threshold", "below", "rel_drop",
              "rel_rise", "action"}


@dataclasses.dataclass(frozen=True)
class AlertRule:
    metric: str
    window: int = 1
    threshold: Optional[float] = None
    below: Optional[float] = None
    rel_drop: Optional[float] = None
    rel_rise: Optional[float] = None
    action: str = "log"
    name: str = ""

    @property
    def mode(self) -> str:
        if self.threshold is not None:
            return "threshold"
        if self.below is not None:
            return "below"
        if self.rel_drop is not None:
            return "rel_drop"
        return "rel_rise"

    @classmethod
    def from_config(cls, block: Any, index: int = 0) -> "AlertRule":
        where = f"exp_manager.telemetry.alerts[{index}]"
        if not isinstance(block, Mapping):
            raise ValueError(
                f"{where} must be a mapping of {sorted(_RULE_KEYS)}, got "
                f"{type(block).__name__}"
            )
        unknown = set(block) - _RULE_KEYS
        if unknown:
            from neuronx_distributed_training_tpu.config.loader import (
                did_you_mean,
            )

            raise ValueError(
                f"unknown {where} keys {sorted(unknown)}; supported: "
                f"{sorted(_RULE_KEYS)}" + did_you_mean(unknown, _RULE_KEYS)
            )
        metric = str(block.get("metric", "") or "")
        if not metric:
            raise ValueError(f"{where}.metric is required (a logged metric "
                             f"key, e.g. 'loss', 'mfu', 'data_wait', "
                             f"'fleet/goodput_fraction')")
        action = str(block.get("action", "log"))
        if action not in ALERT_ACTIONS:
            raise ValueError(
                f"{where}.action must be one of {'/'.join(ALERT_ACTIONS)}, "
                f"got {action!r}"
            )
        modes = [k for k in ("threshold", "below", "rel_drop", "rel_rise")
                 if block.get(k) is not None]
        if len(modes) != 1:
            raise ValueError(
                f"{where} must set exactly ONE of threshold (fires high) / "
                f"below (fires low) / rel_drop (fires on a relative drop vs "
                f"the running peak) / rel_rise (fires on a relative rise vs "
                f"the running minimum); got {modes or 'none'}"
            )
        try:
            window = int(block.get("window", 1))
        except (TypeError, ValueError):
            raise ValueError(f"{where}.window must be an integer >= 1, got "
                             f"{block.get('window')!r}")
        if window < 1:
            raise ValueError(f"{where}.window must be >= 1, got {window}")

        def _f(key: str) -> Optional[float]:
            v = block.get(key)
            if v is None:
                return None
            try:
                return float(v)
            except (TypeError, ValueError):
                raise ValueError(f"{where}.{key} must be a number, got {v!r}")

        rel_drop = _f("rel_drop")
        if rel_drop is not None and not (0.0 < rel_drop <= 1.0):
            raise ValueError(
                f"{where}.rel_drop must be a fraction in (0, 1], got "
                f"{rel_drop}"
            )
        # unlike rel_drop there is no upper bound: a metric can rise by more
        # than 100% of its minimum (rel_rise: 3.0 = "quadrupled")
        rel_rise = _f("rel_rise")
        if rel_rise is not None and rel_rise <= 0.0:
            raise ValueError(
                f"{where}.rel_rise must be a positive fraction (0.5 = fires "
                f"50% above the running minimum), got {rel_rise}"
            )
        rule = cls(
            metric=metric, window=window, threshold=_f("threshold"),
            below=_f("below"), rel_drop=rel_drop, rel_rise=rel_rise,
            action=action, name=str(block.get("name", "") or ""),
        )
        if not rule.name:
            rule = dataclasses.replace(rule, name=f"{metric}_{rule.mode}")
        return rule

    def to_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v not in (None, "")}


def parse_alerts(block: Any) -> tuple[AlertRule, ...]:
    """Parse (and validate) the ``exp_manager.telemetry.alerts`` list.
    ``None``/``[]`` -> no rules; anything but a sequence of rule mappings
    raises.  Duplicate rule names raise too — every firing must be
    attributable to exactly one rule."""
    if block is None:
        return ()
    if isinstance(block, Mapping) or isinstance(block, (str, bytes)) \
            or not isinstance(block, Sequence):
        raise ValueError(
            f"exp_manager.telemetry.alerts must be a LIST of rule mappings "
            f"(metric/window/threshold|below|rel_drop|rel_rise/action), got "
            f"{type(block).__name__}"
        )
    rules = tuple(AlertRule.from_config(b, i) for i, b in enumerate(block))
    names = [r.name for r in rules]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        raise ValueError(
            f"exp_manager.telemetry.alerts has duplicate rule names {dupes}; "
            f"set an explicit 'name' on one of them"
        )
    return rules


@dataclasses.dataclass
class AlertFiring:
    step: int
    rule: str
    metric: str
    action: str
    value: float
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class _RuleState:
    def __init__(self, rule: AlertRule) -> None:
        self.rule = rule
        self.values: collections.deque = collections.deque(
            maxlen=rule.window)
        self.peak: Optional[float] = None  # running peak of windowed means
        self.trough: Optional[float] = None  # running MINIMUM (rel_rise)
        self.active = False  # edge trigger: in-violation since last firing
        self.fired = 0


class AlertEngine:
    """Evaluates the rule list at each boundary; returns the firings for the
    loop to act on and mirrors the trail into ``run_summary.json``."""

    def __init__(
        self,
        rules: Sequence[AlertRule],
        *,
        write_run_summary: Optional[Callable[[dict], None]] = None,
    ) -> None:
        self._states = [_RuleState(r) for r in rules]
        self._write_run_summary = write_run_summary
        #: full firing trail (capped per rule), mirrored to run_summary.json
        self.firings: list[dict] = []

    @staticmethod
    def resolve(metric: str, metrics: Mapping[str, Any]) -> Optional[float]:
        """Exact key first, then the ``time/<metric>`` span fallback so a
        rule on ``data_wait`` reads the span without the prefix."""
        for key in (metric, f"time/{metric}"):
            v = metrics.get(key)
            if v is None:
                continue
            try:
                f = float(v)
            except (TypeError, ValueError):
                continue
            if f == f:  # NaN never matches a threshold; skip it
                return f
        return None

    def observe(self, step: int,
                metrics: Mapping[str, Any]) -> list[AlertFiring]:
        out: list[AlertFiring] = []
        for st in self._states:
            rule = st.rule
            v = self.resolve(rule.metric, metrics)
            if v is None:
                continue
            st.values.append(v)
            if len(st.values) < rule.window:
                continue
            mean = sum(st.values) / len(st.values)
            violated, msg = self._check(st, mean)
            if rule.mode == "rel_drop":
                # the peak only advances on CLEAN windows: a collapsed
                # metric must not ratchet its own baseline down
                if not violated and (st.peak is None or mean > st.peak):
                    st.peak = mean
            elif rule.mode == "rel_rise":
                # same discipline, mirrored: the trough only advances DOWN
                # on clean windows — a spiked metric must not ratchet its
                # own baseline up
                if not violated and (st.trough is None or mean < st.trough):
                    st.trough = mean
            if violated and not st.active:
                st.active = True
                st.fired += 1
                firing = AlertFiring(
                    step=int(step), rule=rule.name, metric=rule.metric,
                    action=rule.action, value=round(mean, 6), message=msg,
                )
                out.append(firing)
                logger.warning("alert %s fired at step %d: %s (action=%s)",
                               rule.name, step, msg, rule.action)
                if st.fired <= MAX_FIRINGS_PER_RULE:
                    self.firings.append(firing.to_dict())
                    if self._write_run_summary is not None:
                        try:
                            self._write_run_summary(
                                {"alerts": self.firings})
                        except Exception as e:  # noqa: BLE001
                            logger.warning(
                                "alert trail write failed: %s", e)
            elif not violated:
                st.active = False
        return out

    def _check(self, st: _RuleState, mean: float) -> tuple[bool, str]:
        rule = st.rule
        w = (f" (mean of last {rule.window} boundaries)"
             if rule.window > 1 else "")
        if rule.mode == "threshold":
            return (
                mean >= rule.threshold,
                f"{rule.metric} = {mean:.6g}{w} >= threshold "
                f"{rule.threshold:.6g}",
            )
        if rule.mode == "below":
            return (
                mean <= rule.below,
                f"{rule.metric} = {mean:.6g}{w} <= floor {rule.below:.6g}",
            )
        if rule.mode == "rel_drop":
            if st.peak is None or st.peak <= 0:
                return False, ""
            floor = st.peak * (1.0 - rule.rel_drop)
            return (
                mean < floor,
                f"{rule.metric} = {mean:.6g}{w} fell "
                f"{100 * rule.rel_drop:.0f}% below its running peak "
                f"{st.peak:.6g}",
            )
        if st.trough is None or st.trough <= 0:
            return False, ""
        ceiling = st.trough * (1.0 + rule.rel_rise)
        return (
            mean > ceiling,
            f"{rule.metric} = {mean:.6g}{w} rose {100 * rule.rel_rise:.0f}% "
            f"above its running minimum {st.trough:.6g}",
        )
