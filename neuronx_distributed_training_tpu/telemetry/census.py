"""First-compile census: memory analysis, HLO collective counts, FLOPs model.

The one moment the whole compiled program is in hand — right after the train
step's first (and, in a healthy run, only) compile — is the cheapest place to
record everything static about the run: XLA's own memory accounting, the
collective census (the communication pattern GSPMD actually inserted, the
quantity DeepCompile-style profiling reasons about), and the analytic FLOPs
estimate MFU is computed against.  ``compile_census`` harvests all of it from
an AOT-``compile()``d step with zero extra compiles; the trainer persists the
result to ``run_summary.json`` next to ``metrics.jsonl``.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

logger = logging.getLogger(__name__)

_MEMORY_FIELDS = (
    "temp_size_in_bytes",
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)


def hlo_texts_from_compiled(compiled: Any) -> list[str]:
    """Post-SPMD HLO module texts of a ``.compile()``d executable — the one
    artifact both the collective census (``utils.debug``) and the static
    graph auditor (``analysis.graph_audit``) parse.  Kept here so "what the
    compiler actually produced" has a single accessor."""
    return [m.to_string() for m in compiled.runtime_executable().hlo_modules()]


def memory_analysis_bytes(compiled: Any) -> Optional[dict[str, int]]:
    """``compiled.memory_analysis()`` -> plain dict (None when the backend
    doesn't implement it).  ``peak_bytes`` is the classic static estimate
    arguments + outputs + temporaries — what the program needs resident at
    once, ignoring donation overlap (aliased bytes are reported separately so
    readers can subtract them)."""
    try:
        ma = compiled.memory_analysis()
    except Exception as e:  # noqa: BLE001 — census must never fail the run
        logger.debug("memory_analysis unavailable: %s", e)
        return None
    if ma is None:
        return None
    out: dict[str, int] = {}
    for field in _MEMORY_FIELDS:
        val = getattr(ma, field, None)
        if val is not None:
            out[field] = int(val)
    if not out:
        return None
    out["peak_bytes"] = (
        out.get("argument_size_in_bytes", 0)
        + out.get("output_size_in_bytes", 0)
        + out.get("temp_size_in_bytes", 0)
    )
    return out


def compile_census(
    compiled: Any,
    *,
    compile_seconds: Optional[float] = None,
    flops_per_token: Optional[float] = None,
    extra: Optional[dict[str, Any]] = None,
) -> dict[str, Any]:
    """Everything static about a compiled train step, JSON-ready.

    ``flops_per_token`` is the analytic FORWARD estimate (``utils.perf``);
    the train-step figure (fwd + 2x bwd) is derived here so the file carries
    both under their explicit names.
    """
    from neuronx_distributed_training_tpu.utils.debug import (
        collective_counts_from_compiled,
    )
    from neuronx_distributed_training_tpu.utils.perf import (
        train_step_flops_per_token,
    )

    census: dict[str, Any] = {}
    if compile_seconds is not None:
        census["compile_seconds"] = round(float(compile_seconds), 3)
    try:
        census["collectives"] = collective_counts_from_compiled(compiled)
    except Exception as e:  # noqa: BLE001 — census must never fail the run
        logger.warning("collective census unavailable: %s", e)
    mem = memory_analysis_bytes(compiled)
    if mem is not None:
        census["memory_analysis"] = mem
    if flops_per_token is not None:
        census["fwd_flops_per_token"] = float(flops_per_token)
        census["train_step_flops_per_token"] = train_step_flops_per_token(
            float(flops_per_token)
        )
    if extra:
        census.update(extra)
    return census
