"""First-compile census: memory analysis, HLO collective counts, FLOPs model.

The one moment the whole compiled program is in hand — right after the train
step's first (and, in a healthy run, only) compile — is the cheapest place to
record everything static about the run: XLA's own memory accounting, the
collective census (the communication pattern GSPMD actually inserted, the
quantity DeepCompile-style profiling reasons about), and the analytic FLOPs
estimate MFU is computed against.  ``compile_census`` harvests all of it from
an AOT-``compile()``d step with zero extra compiles; the trainer persists the
result to ``run_summary.json`` next to ``metrics.jsonl``.
"""

from __future__ import annotations

import logging
import math
import re
from typing import Any, Optional

logger = logging.getLogger(__name__)

_MEMORY_FIELDS = (
    "temp_size_in_bytes",
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)


def hlo_texts_from_compiled(compiled: Any) -> list[str]:
    """Post-SPMD HLO module texts of a ``.compile()``d executable — the one
    artifact both the collective census (``utils.debug``) and the static
    graph auditor (``analysis.graph_audit``) parse.  Kept here so "what the
    compiler actually produced" has a single accessor."""
    return [m.to_string() for m in compiled.runtime_executable().hlo_modules()]


# -- structured collective parse (the graph-contract provenance input) -----

#: collective op line: `%all-gather.5 = bf16[...] all-gather(...)` (async
#: `-start` forms count once; `-done` halves are the completion wait)
_COLLECTIVE_LINE_RE = re.compile(
    r"(?P<op>%[\w.-]+)\s*=\s*[^=]*?"
    r"\s(?P<kind>all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?P<start>-start)?\("
)
_EXPLICIT_GROUPS_RE = re.compile(r"replica_groups=\{(\{[0-9,{} ]*\})?\}")
_IOTA_GROUPS_RE = re.compile(
    r"replica_groups=\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?"
)
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)*)\}")
_OPNAME_META_RE = re.compile(r'op_name="([^"]*)"')


def _parse_iota_groups(dims: str, reshape: str,
                       perm: Optional[str]) -> list[list[int]]:
    """``replica_groups=[G,S]<=[r0,r1]T(p0,p1)``: iota over the reshape
    dims, transposed by the permutation, re-flattened to G groups of S."""
    out_dims = [int(d) for d in dims.split(",") if d]
    r_dims = [int(d) for d in reshape.split(",") if d]
    n = math.prod(r_dims) if r_dims else 0
    ids = list(range(n))
    if perm:
        p = [int(x) for x in perm.split(",") if x]
        # index math without numpy: value at transposed flat position
        strides = [0] * len(r_dims)
        acc = 1
        for i in reversed(range(len(r_dims))):
            strides[i] = acc
            acc *= r_dims[i]
        t_dims = [r_dims[i] for i in p]
        t_strides = [strides[i] for i in p]
        ids = []
        idx = [0] * len(t_dims)
        for _ in range(n):
            ids.append(sum(i * s for i, s in zip(idx, t_strides)))
            for d in reversed(range(len(t_dims))):
                idx[d] += 1
                if idx[d] < t_dims[d]:
                    break
                idx[d] = 0
    size = out_dims[-1] if out_dims else n
    return [ids[i: i + size] for i in range(0, n, max(size, 1))]


def collective_ops_from_texts(texts: list[str]) -> list[dict[str, Any]]:
    """Structured census: one entry per collective op in the compiled HLO —
    ``{op, kind, groups, pairs, source_op}`` where ``groups`` is the parsed
    replica-group partition (``None`` for "all devices" / unparseable),
    ``pairs`` the source→target id pairs of a collective-permute, and
    ``source_op`` the ``metadata op_name`` attribution XLA recorded (the
    nearest named source op — what provenance findings cite).  The
    kind-counting convention matches ``utils.debug``: ``-start`` counts,
    ``-done`` does not."""
    out: list[dict[str, Any]] = []
    for text in texts:
        for line in text.splitlines():
            if "=" not in line:
                continue
            head, _, meta = line.partition("metadata=")
            m = _COLLECTIVE_LINE_RE.search(head)
            if not m:
                continue
            groups: Optional[list[list[int]]] = None
            gm = _EXPLICIT_GROUPS_RE.search(head)
            if gm and gm.group(1):
                groups = [
                    [int(x) for x in g.split(",") if x.strip()]
                    for g in re.findall(r"\{([0-9, ]*)\}", gm.group(1))
                ]
            else:
                im = _IOTA_GROUPS_RE.search(head)
                if im:
                    groups = _parse_iota_groups(im.group(1), im.group(2),
                                                im.group(3))
            pairs: Optional[list[tuple[int, int]]] = None
            pm = _PAIRS_RE.search(head)
            if pm:
                pairs = [tuple(int(x) for x in p.split(","))
                         for p in re.findall(r"\{(\d+,\d+)\}", pm.group(1))]
            nm = _OPNAME_META_RE.search(meta)
            out.append({
                "op": m.group("op").lstrip("%"),
                "kind": m.group("kind"),
                "groups": groups,
                "pairs": pairs,
                "source_op": nm.group(1) if nm else "",
            })
    return out


def collective_ops_from_compiled(compiled: Any) -> list[dict[str, Any]]:
    """Structured collective census of an already-compiled executable."""
    return collective_ops_from_texts(hlo_texts_from_compiled(compiled))


def memory_analysis_bytes(compiled: Any) -> Optional[dict[str, int]]:
    """``compiled.memory_analysis()`` -> plain dict (None when the backend
    doesn't implement it).  ``peak_bytes`` is the classic static estimate
    arguments + outputs + temporaries — what the program needs resident at
    once, ignoring donation overlap (aliased bytes are reported separately so
    readers can subtract them)."""
    try:
        ma = compiled.memory_analysis()
    except Exception as e:  # noqa: BLE001 — census must never fail the run
        logger.debug("memory_analysis unavailable: %s", e)
        return None
    if ma is None:
        return None
    out: dict[str, int] = {}
    for field in _MEMORY_FIELDS:
        val = getattr(ma, field, None)
        if val is not None:
            out[field] = int(val)
    if not out:
        return None
    out["peak_bytes"] = (
        out.get("argument_size_in_bytes", 0)
        + out.get("output_size_in_bytes", 0)
        + out.get("temp_size_in_bytes", 0)
    )
    return out


def compile_census(
    compiled: Any,
    *,
    compile_seconds: Optional[float] = None,
    flops_per_token: Optional[float] = None,
    extra: Optional[dict[str, Any]] = None,
) -> dict[str, Any]:
    """Everything static about a compiled train step, JSON-ready.

    ``flops_per_token`` is the analytic FORWARD estimate (``utils.perf``);
    the train-step figure (fwd + 2x bwd) is derived here so the file carries
    both under their explicit names.
    """
    from neuronx_distributed_training_tpu.utils.debug import (
        collective_counts_from_compiled,
    )
    from neuronx_distributed_training_tpu.utils.perf import (
        train_step_flops_per_token,
    )

    census: dict[str, Any] = {}
    if compile_seconds is not None:
        census["compile_seconds"] = round(float(compile_seconds), 3)
    try:
        census["collectives"] = collective_counts_from_compiled(compiled)
    except Exception as e:  # noqa: BLE001 — census must never fail the run
        logger.warning("collective census unavailable: %s", e)
    mem = memory_analysis_bytes(compiled)
    if mem is not None:
        census["memory_analysis"] = mem
    if flops_per_token is not None:
        census["fwd_flops_per_token"] = float(flops_per_token)
        census["train_step_flops_per_token"] = train_step_flops_per_token(
            float(flops_per_token)
        )
    if extra:
        census.update(extra)
    return census
