"""Interconnect observatory: measured collective bandwidth.

Every other roofline term is measured and gated — overlap (telemetry.trace),
HBM (telemetry.memory), bubbles (step timeline) — but the comms term itself
was priced purely from the static ``ici_bandwidth_bytes`` tables in
``autotune/topology.py``.  This module closes that gap in three layers
(docs/observability.md "Interconnect observatory"):

- **In-loop achieved bandwidth** — :func:`comms_section` joins the
  per-collective-class wire seconds the trace analytics already extract
  (``trace_summary.json``'s ``overlap_by_class``) with the per-class byte
  volumes the planner already computes
  (``autotune.cost_model.collective_byte_volumes``) into
  ``comms/<class>/achieved_gbps`` + ``comms/<class>/efficiency`` (vs the
  topology table's peak).  The join is pure host arithmetic over two
  artifacts the run produces anyway — no new syncs, no graph changes.

- **Standalone microbenchmark** — :func:`run_comms_sweep` drives
  {all-reduce, all-gather, reduce-scatter, collective-permute, all-to-all}
  x mesh axis x message size through the real mesh machinery
  (``parallel.mesh`` + ``parallel.sharding.shard_map``), with warmup +
  timed reps, and :func:`build_comms_summary` fits per-axis bandwidth +
  latency out of the sweep (the measured analog of the topology table)
  plus per-device timing skew that names a degraded link/host as a finding.
  ``tools/comms_bench.py`` is the CLI.

- **Close the loop** — ``comms_summary.json`` (:func:`write_comms_summary`,
  byte-stable) is content-sniffed by ``plan.py --calibrate-from``
  (:func:`is_comms_summary`) and turned into measured/prior per-axis
  bandwidth ratios by ``autotune.cost_model.comms_calibration_from_summary``
  so ``estimate_plan`` prices comms from what the wire actually delivered.

Bus-bandwidth conventions (the NCCL-tests vocabulary): for a logical
payload of ``B`` bytes over ``n`` ranks, a ring all-gather/reduce-scatter
moves ``B(n-1)/n`` per rank, an all-reduce twice that, a point-to-point
permute exactly ``B``, and an all-to-all ``B(n-1)/n`` — the same factors
``autotune.cost_model._ring_seconds`` prices, so measured and predicted
bandwidth are directly comparable.  ``achieved_gbps`` is always BUS
bandwidth (bus bytes / wire seconds), never algorithm bandwidth.

Stdlib-only at import time (like ``telemetry.fleet``) so the offline tools
can load it without jax; the sweep runner imports jax lazily.
"""

from __future__ import annotations

import json
import logging
import os
import statistics
import time
from pathlib import Path
from typing import Any, Mapping, Optional, Sequence

logger = logging.getLogger(__name__)

#: summary filename (next to run_summary.json / trace_summary.json)
COMMS_SUMMARY_NAME = "comms_summary.json"

COMMS_SUMMARY_SCHEMA = 1

#: collective-class vocabulary — must match utils.debug.COLLECTIVE_KINDS
#: (asserted by tests/test_comms.py; duplicated here so this module stays
#: importable without jax)
COMMS_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all")

#: cost-model axis name <-> mesh axis name (parallel.mesh.AXES)
AXIS_TO_MESH = {"tp": "model", "dp": "data", "pp": "pipe",
                "cp": "context", "ep": "expert"}
MESH_TO_AXIS = {v: k for k, v in AXIS_TO_MESH.items()}

#: a device whose timing probe runs this much slower than the fleet median
#: is named a degraded-link/host finding
SKEW_REL_THRESHOLD = 1.5


# --------------------------------------------------------------------------
# bus-bandwidth conventions
# --------------------------------------------------------------------------


def bus_bytes(kind: str, payload_bytes: float, n: int) -> float:
    """Bytes actually traversing the wire per rank for a logical payload of
    ``payload_bytes`` over ``n`` ranks (ring algorithm factors — the same
    ones ``cost_model._ring_seconds`` prices)."""
    if n <= 1 or payload_bytes <= 0:
        return 0.0
    b = float(payload_bytes)
    if kind == "all-reduce":
        return 2.0 * b * (n - 1) / n
    if kind in ("all-gather", "reduce-scatter", "all-to-all"):
        return b * (n - 1) / n
    if kind == "collective-permute":
        return b
    raise ValueError(f"unknown collective kind {kind!r}; expected one of "
                     f"{COMMS_KINDS}")


def ring_hops(kind: str, n: int) -> int:
    """Latency hops a ring algorithm pays for one collective over ``n``
    ranks — the per-point intercept weight the per-axis fit uses."""
    if n <= 1:
        return 0
    if kind == "all-reduce":
        return 2 * (n - 1)
    if kind in ("all-gather", "reduce-scatter", "all-to-all"):
        return n - 1
    if kind == "collective-permute":
        return 1
    raise ValueError(f"unknown collective kind {kind!r}; expected one of "
                     f"{COMMS_KINDS}")


# --------------------------------------------------------------------------
# layer 1: the in-loop achieved-bandwidth join
# --------------------------------------------------------------------------


def class_bus_bytes_per_step(byte_volumes: Mapping[str, Mapping[str, float]],
                             axis_sizes: Mapping[str, int]
                             ) -> dict[str, float]:
    """Per-collective-class BUS bytes per step: the planner's logical
    per-axis volumes (``collective_byte_volumes``) folded through the ring
    factors, summed over axes.  Axes with unknown/degenerate degree
    contribute nothing."""
    out: dict[str, float] = {}
    for axis, kinds in (byte_volumes or {}).items():
        try:
            n = int((axis_sizes or {}).get(axis, 0))
        except (TypeError, ValueError):
            continue
        if n <= 1 or not isinstance(kinds, Mapping):
            continue
        for kind, vol in kinds.items():
            try:
                bb = bus_bytes(str(kind), float(vol), n)
            except (TypeError, ValueError):
                continue
            if bb > 0:
                out[str(kind)] = out.get(str(kind), 0.0) + bb
    return out


def comms_section(facts: Mapping[str, Any],
                  overlap_by_class: Mapping[str, Any],
                  *, window_steps: int) -> Optional[dict]:
    """The ``comms`` section for ``trace_summary.json``/``run_summary.json``:
    measured wire seconds per class (trace analytics) joined with predicted
    bus bytes per class (cost model) into achieved Gb/s + efficiency vs the
    topology peak.

    ``facts`` is what the trainer arms via ``exp_manager.set_comms_facts``:
    ``byte_volumes`` (``collective_byte_volumes`` output), ``axis_sizes``
    (cost-model axis -> mesh degree), ``peak_bandwidth_bytes`` (the
    topology table's ICI prior), ``topology`` (its name).  Returns None
    when the join has nothing to say (no collectives traced, or no byte
    volumes) — observability never invents numbers.
    """
    if not facts or window_steps < 1:
        return None
    per_class = class_bus_bytes_per_step(
        facts.get("byte_volumes") or {}, facts.get("axis_sizes") or {})
    if not per_class:
        return None
    peak = float(facts.get("peak_bandwidth_bytes") or 0.0)
    classes: dict[str, dict] = {}
    for kind, bytes_step in sorted(per_class.items()):
        c = (overlap_by_class or {}).get(kind)
        if not isinstance(c, Mapping):
            continue
        try:
            wire = float(c.get("wire_seconds") or 0.0)
        except (TypeError, ValueError):
            continue
        if wire <= 0:
            continue
        wire_step = wire / float(window_steps)
        achieved_bps = bytes_step / wire_step
        entry = {
            "bus_bytes_per_step": round(bytes_step, 1),
            "wire_seconds_per_step": round(wire_step, 9),
            "achieved_gbps": round(achieved_bps / 1e9, 6),
            "count": int(c.get("count") or 0),
        }
        if peak > 0:
            entry["efficiency"] = round(achieved_bps / peak, 6)
        classes[kind] = entry
    if not classes:
        return None
    out: dict[str, Any] = {
        "classes": classes,
        "window_steps": int(window_steps),
    }
    if peak > 0:
        out["peak_bandwidth_gbps"] = round(peak / 1e9, 6)
    if facts.get("topology"):
        out["topology"] = str(facts["topology"])
    return out


def comms_metrics(section: Optional[Mapping[str, Any]]
                  ) -> dict[str, float]:
    """Flatten a ``comms`` section into the scalar metrics that ride the
    logging boundary (every sink + fleet beacons):
    ``comms/<class>/achieved_gbps`` and ``comms/<class>/efficiency``."""
    out: dict[str, float] = {}
    if not section:
        return out
    for kind, entry in (section.get("classes") or {}).items():
        if not isinstance(entry, Mapping):
            continue
        for field in ("achieved_gbps", "efficiency"):
            v = entry.get(field)
            if v is not None:
                try:
                    out[f"comms/{kind}/{field}"] = float(v)
                except (TypeError, ValueError):
                    continue
    return out


def degraded_link_alert_rule(kind: str = "all-gather", *, window: int = 3,
                             rel_drop: float = 0.5, action: str = "log"
                             ) -> dict:
    """The worked fleet-alert rule for interconnect degradation: achieved
    bandwidth for a collective class falling ``rel_drop`` below its own
    running peak (a flapping ICI link, a host on a degraded DCN path).
    Drop-in block for ``exp_manager.telemetry.alerts``; validated by
    ``telemetry.alerts.AlertRule.from_config`` like any other rule."""
    return {
        "metric": f"comms/{kind}/achieved_gbps",
        "window": int(window),
        "rel_drop": float(rel_drop),
        "action": str(action),
        "name": "comms_degraded_link",
    }


# --------------------------------------------------------------------------
# layer 2: the microbenchmark sweep + per-axis fit
# --------------------------------------------------------------------------


def fit_axis_bandwidth(points: Sequence[Mapping[str, float]]
                       ) -> Optional[dict]:
    """Least-squares fit of ``t = bus_bytes / bandwidth + hops * latency``
    over a sweep's (bus_bytes, hops, seconds) points — the measured analog
    of one topology-table row.

    Two-parameter linear fit via the normal equations (stdlib only).  When
    the system is degenerate (one message size, collinear points) or the
    fitted slope is non-positive (timing noise), falls back to the aggregate
    bus bandwidth ``sum(bytes)/sum(seconds)`` with zero latency — a fit
    never returns a negative or infinite bandwidth.  None when no usable
    points.
    """
    xs, hs, ys = [], [], []
    for p in points or ():
        try:
            x = float(p["bus_bytes"])
            h = float(p.get("hops", 0.0))
            y = float(p["seconds"])
        except (KeyError, TypeError, ValueError):
            continue
        if x > 0 and y > 0:
            xs.append(x)
            hs.append(h)
            ys.append(y)
    if not xs:
        return None
    sxx = sum(x * x for x in xs)
    shh = sum(h * h for h in hs)
    sxh = sum(x * h for x, h in zip(xs, hs))
    sxy = sum(x * y for x, y in zip(xs, ys))
    shy = sum(h * y for h, y in zip(hs, ys))
    det = sxx * shh - sxh * sxh
    slope = intercept = None
    if det > 0 and sxx > 0 and shh > 0:
        s = (sxy * shh - shy * sxh) / det
        l = (shy * sxx - sxy * sxh) / det
        if s > 0 and l >= 0:
            slope, intercept = s, l
    if slope is None and sxx > 0:
        s = sxy / sxx  # latency-free slope-only fit
        if s > 0:
            slope, intercept = s, 0.0
    if slope is None:
        slope = sum(ys) / sum(xs)  # aggregate bus bandwidth
        intercept = 0.0
    return {
        "bandwidth_bytes_per_s": round(1.0 / slope, 1),
        "latency_seconds": round(float(intercept), 9),
        "n_points": len(xs),
    }


def skew_findings(per_device: Mapping[str, float], *,
                  rel_threshold: float = SKEW_REL_THRESHOLD) -> list[dict]:
    """Degraded-link/host findings out of per-device probe timings: any
    device whose time exceeds ``rel_threshold`` x the fleet median is named
    (SPMD collectives run at the slowest participant's pace, so one slow
    device IS a degraded interconnect as far as the step time is
    concerned).  Pure function — the seeded-slow-device test feeds it
    directly."""
    vals = {}
    for dev, t in (per_device or {}).items():
        try:
            f = float(t)
        except (TypeError, ValueError):
            continue
        if f > 0:
            vals[str(dev)] = f
    if len(vals) < 2:
        return []
    med = statistics.median(vals.values())
    if med <= 0:
        return []
    out = []
    for dev in sorted(vals, key=lambda d: -vals[d]):
        ratio = vals[dev] / med
        if ratio > rel_threshold:
            out.append({
                "kind": "degraded_link",
                "device": dev,
                "seconds": round(vals[dev], 9),
                "median_seconds": round(med, 9),
                "ratio": round(ratio, 3),
                "message": (
                    f"device {dev} timing probe ran {ratio:.2f}x the fleet "
                    f"median ({vals[dev]:.6g}s vs {med:.6g}s; threshold "
                    f"{rel_threshold:g}x) — degraded link or host; SPMD "
                    f"collectives run at its pace"),
            })
    return out


def measure_device_skew(devices: Optional[Sequence[Any]] = None, *,
                        reps: int = 3, payload_bytes: int = 1 << 16
                        ) -> dict[str, float]:
    """Per-device timing probe (host->device transfer + a trivial op,
    blocked): median seconds per device, keyed by device id.  The relative
    spread — not the absolute number — is the signal: a degraded host/link
    shows up as one device far off the fleet median
    (:func:`skew_findings`)."""
    import jax
    import numpy as np

    devs = list(devices) if devices is not None else list(jax.devices())
    arr = np.zeros(max(int(payload_bytes) // 4, 1), dtype=np.float32)
    out: dict[str, float] = {}
    for d in devs:
        times = []
        for rep in range(max(int(reps), 1) + 1):
            t0 = time.perf_counter()
            x = jax.device_put(arr, d)
            (x + 1.0).block_until_ready()
            if rep > 0:  # rep 0 is warmup (compile + first transfer)
                times.append(time.perf_counter() - t0)
        out[str(d.id)] = statistics.median(times)
    return out


def _sweep_op(kind: str, mesh: Any, axis: str, payload_bytes: int):
    """Build (jitted_fn, placed_input, actual_payload_bytes) for one
    collective over one mesh axis.  Per-device logical payload is
    ``payload_bytes`` (shapes round down so tiny smoke sizes stay valid);
    the actual bytes are returned so the recorded rows never lie."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from neuronx_distributed_training_tpu.parallel.sharding import shard_map

    n = int(mesh.shape[axis])
    spec = P(axis, None)

    if kind in ("all-reduce", "collective-permute"):
        elems = max(int(payload_bytes) // 4, 1)
        shape = (n, elems)  # per-device (1, elems) = the logical payload
        payload = elems * 4
    else:
        # AG shard / RS row / A2A chunk: per-device dim must split n ways
        elems = max(int(payload_bytes) // (4 * n), 1)
        shape = (n * n, elems) if kind in ("reduce-scatter", "all-to-all") \
            else (n, elems)
        payload = elems * 4 * n

    if kind == "all-reduce":
        def f(x):
            return lax.psum(x, axis)
        out_spec = spec
    elif kind == "all-gather":
        def f(x):
            return lax.all_gather(x, axis, axis=0, tiled=True)
        # no replication claim: each device keeps its gathered copy and the
        # out spec concatenates them — only the wire traffic matters here
        out_spec = spec
    elif kind == "reduce-scatter":
        def f(x):
            return lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
        out_spec = spec
    elif kind == "collective-permute":
        perm = [(i, (i + 1) % n) for i in range(n)]

        def f(x):
            return lax.ppermute(x, axis, perm=perm)
        out_spec = spec
    elif kind == "all-to-all":
        def f(x):
            return lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
        out_spec = spec
    else:
        raise ValueError(f"unknown collective kind {kind!r}")

    fn = jax.jit(shard_map(f, mesh=mesh, in_specs=spec, out_specs=out_spec))
    x = jax.device_put(
        jnp.arange(shape[0] * shape[1], dtype=jnp.float32).reshape(shape),
        NamedSharding(mesh, spec))
    return fn, x, payload


def run_comms_sweep(mesh: Any, *,
                    sizes_bytes: Sequence[int] = (1 << 20, 4 << 20),
                    kinds: Optional[Sequence[str]] = None,
                    warmup: int = 1, reps: int = 3
                    ) -> dict[str, dict]:
    """Sweep collective kinds x mesh axes x message sizes on a live mesh.

    Per mesh axis with degree > 1 (named by its cost-model alias: model->tp,
    data->dp, pipe->pp, context->cp, expert->ep) runs each applicable
    collective class (``utils.debug.AXIS_COLLECTIVE_KINDS``) at each
    message size: ``warmup`` untimed reps (compile + first dispatch), then
    ``reps`` timed reps blocked individually.  Returns
    ``{axis: {mesh_axis, size, sweep: [rows...]}}`` ready for
    :func:`build_comms_summary`.  CPU-mesh testable: the virtual-device
    CPU backend executes the same collectives the TPU mesh would.
    """
    from neuronx_distributed_training_tpu.utils.debug import (
        AXIS_COLLECTIVE_KINDS,
    )

    results: dict[str, dict] = {}
    for mesh_axis, size in dict(mesh.shape).items():
        n = int(size)
        axis = MESH_TO_AXIS.get(str(mesh_axis))
        if n <= 1 or axis is None:
            continue
        axis_kinds = [k for k in AXIS_COLLECTIVE_KINDS.get(axis, ())
                      if kinds is None or k in kinds]
        rows = []
        for kind in axis_kinds:
            for size_bytes in sizes_bytes:
                try:
                    fn, x, payload = _sweep_op(kind, mesh, mesh_axis,
                                               int(size_bytes))
                    for _ in range(max(int(warmup), 1)):
                        fn(x).block_until_ready()
                    times = []
                    for _ in range(max(int(reps), 1)):
                        t0 = time.perf_counter()
                        fn(x).block_until_ready()
                        times.append(time.perf_counter() - t0)
                except Exception as e:  # noqa: BLE001 — one op failing must
                    # not void the rest of the sweep (e.g. a backend without
                    # a given collective); the gap is visible in the rows
                    logger.warning("comms sweep %s over %s @ %d bytes "
                                   "failed: %s", kind, mesh_axis,
                                   size_bytes, e)
                    continue
                bb = bus_bytes(kind, payload, n)
                t_med = statistics.median(times)
                rows.append({
                    "collective": kind,
                    "payload_bytes": int(payload),
                    "bus_bytes": round(bb, 1),
                    "hops": ring_hops(kind, n),
                    "seconds_median": round(t_med, 9),
                    "seconds_min": round(min(times), 9),
                    "reps": len(times),
                    "bus_gbps": round(bb / t_med / 1e9, 6),
                })
        if rows:
            results[axis] = {
                "mesh_axis": str(mesh_axis),
                "size": n,
                "sweep": rows,
            }
    return results


def build_comms_summary(axis_results: Mapping[str, Mapping[str, Any]], *,
                        topology_name: str,
                        prior_bandwidth_bytes: float,
                        prior_latency_seconds: float,
                        device_skew: Optional[Mapping[str, float]] = None,
                        skew_rel_threshold: float = SKEW_REL_THRESHOLD
                        ) -> dict:
    """Assemble the ``comms_summary.json`` document: per-axis sweep rows +
    fitted bandwidth/latency, measured/prior ratios against the topology
    table (recorded IN the summary so calibration is self-contained — the
    reader never has to guess which prior the bench saw), and per-device
    skew findings."""
    axes: dict[str, Any] = {}
    findings: list[dict] = []
    for axis in sorted(axis_results or {}):
        r = axis_results[axis]
        fit = fit_axis_bandwidth([
            {"bus_bytes": row["bus_bytes"], "hops": row.get("hops", 0),
             "seconds": row["seconds_median"]}
            for row in r.get("sweep") or ()
        ])
        entry: dict[str, Any] = {
            "mesh_axis": r.get("mesh_axis"),
            "size": int(r.get("size") or 0),
            "sweep": list(r.get("sweep") or ()),
        }
        if fit:
            entry["fit"] = fit
            if prior_bandwidth_bytes > 0:
                entry["bandwidth_ratio"] = round(
                    fit["bandwidth_bytes_per_s"] / prior_bandwidth_bytes, 6)
            if prior_latency_seconds > 0 and fit["latency_seconds"] > 0:
                entry["latency_ratio"] = round(
                    fit["latency_seconds"] / prior_latency_seconds, 6)
        axes[axis] = entry
    skew_block = None
    if device_skew:
        per_dev = {str(k): round(float(v), 9)
                   for k, v in device_skew.items()}
        findings = skew_findings(per_dev, rel_threshold=skew_rel_threshold)
        skew_block = {
            "per_device": per_dev,
            "median_seconds": round(
                statistics.median(per_dev.values()), 9) if per_dev else None,
            "rel_threshold": float(skew_rel_threshold),
            "findings": findings,
        }
    out: dict[str, Any] = {
        "schema": COMMS_SUMMARY_SCHEMA,
        "kind": "comms_summary",
        "topology": str(topology_name),
        "prior": {
            "ici_bandwidth_bytes": float(prior_bandwidth_bytes),
            "ici_latency_seconds": float(prior_latency_seconds),
        },
        "axes": axes,
        "findings": findings,
    }
    if skew_block is not None:
        out["device_skew"] = skew_block
    return out


# --------------------------------------------------------------------------
# layer 3: the artifact (sniff / load / write)
# --------------------------------------------------------------------------


def is_comms_summary(doc: Any) -> bool:
    """Content sniff for ``plan.py --calibrate-from`` (the comms analog of
    ``telemetry.memory.is_memory_summary``): the explicit ``kind`` marker,
    or the axes+prior pair no other summary carries."""
    if not isinstance(doc, Mapping):
        return False
    if doc.get("kind") == "comms_summary":
        return True
    return isinstance(doc.get("axes"), Mapping) \
        and isinstance(doc.get("prior"), Mapping)


def load_comms_summary(source: Any) -> dict:
    """Tolerant loader: a summary dict passes through; a file path is
    parsed; a run directory resolves ``comms_summary.json`` inside it.
    Raises ``ValueError`` (not FileNotFoundError tracebacks) on anything
    unusable — the planner turns that into a report error."""
    if isinstance(source, Mapping):
        return dict(source)
    path = Path(source)
    if path.is_dir():
        path = path / COMMS_SUMMARY_NAME
    if not path.is_file():
        raise ValueError(f"no comms summary at {path}")
    try:
        doc = json.loads(path.read_text())
    except (OSError, ValueError) as e:
        raise ValueError(f"unreadable comms summary {path}: {e}")
    if not isinstance(doc, Mapping):
        raise ValueError(f"comms summary {path} is not a JSON object")
    return dict(doc)


def write_comms_summary(summary: Mapping[str, Any],
                        path: str | Path) -> None:
    """Byte-stable atomic write (sorted keys, indent 1, trailing newline —
    the same serialize-first + temp/rename contract as
    ``fleet.write_fleet_summary``): identical content always produces
    identical bytes, so committed fixtures diff cleanly."""
    data = json.dumps(summary, indent=1, sort_keys=True) + "\n"
    spath = str(path)
    tmp = f"{spath}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(data)
        f.flush()
        try:
            os.fsync(f.fileno())
        except OSError:  # pragma: no cover — some filesystems refuse
            pass
    os.replace(tmp, spath)


def bench_comms_facts(summary: Mapping[str, Any]) -> dict:
    """The perf-contract facts block out of a comms summary: per-axis
    fitted bandwidth (+ measured/prior ratio) and per-class best achieved
    bus Gb/s across the sweep — what ``perf_facts_from_bench`` picks up and
    PC204 gates against the committed ``cpu_comms`` baseline."""
    prior = float((summary.get("prior") or {}).get(
        "ici_bandwidth_bytes") or 0.0)
    axes: dict[str, Any] = {}
    classes: dict[str, Any] = {}
    for axis, entry in sorted((summary.get("axes") or {}).items()):
        if not isinstance(entry, Mapping):
            continue
        fit = entry.get("fit")
        if isinstance(fit, Mapping) and fit.get("bandwidth_bytes_per_s"):
            rec = {
                "bandwidth_gbps": round(
                    float(fit["bandwidth_bytes_per_s"]) / 1e9, 6),
                "latency_us": round(
                    float(fit.get("latency_seconds") or 0.0) * 1e6, 3),
            }
            if entry.get("bandwidth_ratio") is not None:
                rec["bandwidth_ratio"] = float(entry["bandwidth_ratio"])
            axes[axis] = rec
        for row in entry.get("sweep") or ():
            if not isinstance(row, Mapping):
                continue
            kind = str(row.get("collective") or "")
            try:
                gbps = float(row.get("bus_gbps") or 0.0)
            except (TypeError, ValueError):
                continue
            if kind and gbps > 0:
                cur = classes.setdefault(kind, {"achieved_gbps": 0.0})
                cur["achieved_gbps"] = round(
                    max(cur["achieved_gbps"], gbps), 6)
    if prior > 0:
        for rec in classes.values():
            rec["efficiency"] = round(
                rec["achieved_gbps"] * 1e9 / prior, 6)
    out: dict[str, Any] = {}
    if classes:
        out["classes"] = classes
    if axes:
        out["axes"] = axes
    return out
