"""``exp_manager.telemetry`` — the unified step-telemetry knob block.

One frozen dataclass owns every on/off switch so the trainer, the exp
manager, and the config validator all agree on the schema:

.. code-block:: yaml

    exp_manager:
      telemetry:
        spans: true           # host-side step decomposition + profiler annot.
        mfu: true             # MFU + tokens/sec/chip from utils.perf
        compile_census: true  # first-compile memory/collective/FLOPs census
        device_memory: false  # per-boundary live HBM stats (memory_stats())
        goodput: true         # cumulative productive-seconds accounting

Everything defaults ON except ``device_memory`` (``memory_stats()`` is a
backend query some runtimes answer slowly) — the layer is designed to be
cheap enough to leave on: span timing is ``time.perf_counter`` bookkeeping,
MFU is arithmetic on the already-maintained throughput window, and the census
runs once at first compile.  None of the knobs adds a host sync between
logging boundaries.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

#: knob name -> default; the single source of truth for schema validation
TELEMETRY_KNOBS: dict[str, bool] = {
    "spans": True,
    "mfu": True,
    "compile_census": True,
    "device_memory": False,
    "goodput": True,
}


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    spans: bool = True
    mfu: bool = True
    compile_census: bool = True
    device_memory: bool = False
    goodput: bool = True

    @classmethod
    def from_config(cls, block: Any) -> "TelemetryConfig":
        """Parse (and validate) an ``exp_manager.telemetry`` block.

        Accepts ``None``/``{}`` (all defaults), a mapping of knob -> bool, or
        a single bool (``telemetry: false`` switches the whole layer off).
        Unknown keys and non-boolean values raise ``ValueError`` — a typo'd
        knob must not silently run with defaults.
        """
        if block is None:
            return cls()
        if isinstance(block, bool):
            return cls(**{k: block and v for k, v in TELEMETRY_KNOBS.items()}) \
                if block else cls(**{k: False for k in TELEMETRY_KNOBS})
        if not isinstance(block, Mapping):
            raise ValueError(
                f"exp_manager.telemetry must be a mapping of "
                f"{sorted(TELEMETRY_KNOBS)} to booleans (or a single bool), "
                f"got {type(block).__name__}"
            )
        unknown = set(block) - set(TELEMETRY_KNOBS)
        if unknown:
            raise ValueError(
                f"unknown exp_manager.telemetry keys {sorted(unknown)}; "
                f"supported: {sorted(TELEMETRY_KNOBS)}"
            )
        values: dict[str, bool] = {}
        for k, v in block.items():
            if not isinstance(v, bool):
                raise ValueError(
                    f"exp_manager.telemetry.{k} must be a boolean, got {v!r}"
                )
            values[k] = v
        return cls(**values)

    def to_dict(self) -> dict[str, bool]:
        return dataclasses.asdict(self)
