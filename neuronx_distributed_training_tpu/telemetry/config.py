"""``exp_manager.telemetry`` — the unified step-telemetry knob block.

One frozen dataclass owns every on/off switch so the trainer, the exp
manager, and the config validator all agree on the schema:

.. code-block:: yaml

    exp_manager:
      telemetry:
        spans: true           # host-side step decomposition + profiler annot.
        mfu: true             # MFU + tokens/sec/chip from utils.perf
        compile_census: true  # first-compile memory/collective/FLOPs census
        device_memory: false  # per-boundary live HBM stats (memory_stats())
        goodput: true         # cumulative productive-seconds accounting
        health:               # numerics flight recorder (telemetry.health)
          enabled: false
          policy: dump_and_continue
        trace:                # windowed device-time capture (telemetry.trace)
          enabled: false
          start_step: 1
          num_steps: 3
        fleet:                # per-host beacons + aggregation (telemetry.fleet)
          enabled: false
          stale_after_seconds: 600
        tensorstats:          # tensor numerics observatory (telemetry.tensorstats)
          enabled: false
          pre_clip: true
          post_clip: true
        alerts:               # declarative alert rules (telemetry.alerts)
          - metric: data_wait
            threshold: 30.0
            action: halt

Everything defaults ON except ``device_memory`` (``memory_stats()`` is a
backend query some runtimes answer slowly), ``health`` (its anomaly
counters live inside the optimizer state, so enabling it changes the
checkpoint tree — an explicit opt-in), ``batch_stats`` (per-boundary
data-pipeline stats cost an O(batch) numpy pass on the prefetch thread),
``fleet``/``alerts`` (multi-host surfaces an operator opts into), and
``trace`` (a profiler window
has real capture overhead inside it) — the layer is designed to be
cheap enough to leave on: span timing is ``time.perf_counter`` bookkeeping,
MFU is arithmetic on the already-maintained throughput window, and the census
runs once at first compile.  None of the knobs adds a host sync between
logging boundaries.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

from neuronx_distributed_training_tpu.telemetry.alerts import (
    AlertRule,
    parse_alerts,
)
from neuronx_distributed_training_tpu.telemetry.fleet import FleetConfig
from neuronx_distributed_training_tpu.telemetry.health import HealthConfig
from neuronx_distributed_training_tpu.telemetry.memory import MemoryConfig
from neuronx_distributed_training_tpu.telemetry.tensorstats import (
    TensorStatsConfig,
)
from neuronx_distributed_training_tpu.telemetry.trace import TraceConfig
from neuronx_distributed_training_tpu.trainer.control import ControlConfig

#: boolean knob name -> default; the single source of truth for schema
#: validation (the nested ``health``/``trace``/``fleet``/``alerts`` blocks
#: validate via their own dataclasses)
TELEMETRY_KNOBS: dict[str, bool] = {
    "spans": True,
    "mfu": True,
    "compile_census": True,
    "device_memory": False,
    "goodput": True,
    # static graph audit of the census executable (analysis.graph_audit):
    # donation/collective/replication/precision contract checks on the very
    # step about to run, logged + persisted to run_summary.json.  Host-side
    # HLO text parsing at first compile only; off by default because large
    # programs make the text walk a noticeable one-time cost.
    "graph_audit": False,
    # per-boundary data-pipeline stats (padding fraction, packing
    # efficiency, seq-len spread) computed host-side on the prefetch thread
    # from the already-materialized numpy batch (data.loader.BatchStats);
    # off by default: an O(batch) numpy pass per global batch.
    "batch_stats": False,
}

#: nested (non-boolean) telemetry blocks, each validated by its own parser
_NESTED_BLOCKS = ("health", "trace", "fleet", "alerts", "control", "memory",
                  "tensorstats")


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    spans: bool = True
    mfu: bool = True
    compile_census: bool = True
    device_memory: bool = False
    goodput: bool = True
    graph_audit: bool = False
    batch_stats: bool = False
    health: HealthConfig = dataclasses.field(default_factory=HealthConfig)
    trace: TraceConfig = dataclasses.field(default_factory=TraceConfig)
    fleet: FleetConfig = dataclasses.field(default_factory=FleetConfig)
    # live HBM attribution + OOM forensics (telemetry.memory):
    # boundary-cadence allocator sampling across the mesh, the windowed
    # device_memory_profile capture -> memory_summary.json, oom_<step>/
    # forensic bundles (docs/observability.md "Memory observability")
    memory: MemoryConfig = dataclasses.field(default_factory=MemoryConfig)
    # tensor numerics observatory (telemetry.tensorstats): in-graph per
    # layer-group dynamic-range stats for the optimizer-boundary grads —
    # like health, the cumulative record lives in opt_state, so enabling it
    # changes the checkpoint tree: an explicit opt-in
    # (docs/observability.md "Tensor numerics observatory")
    tensorstats: TensorStatsConfig = dataclasses.field(
        default_factory=TensorStatsConfig)
    alerts: tuple[AlertRule, ...] = ()
    # coordinated fleet control (trainer.control): consensus stop decisions
    # via the boundary control word + the operator command channel
    control: ControlConfig = dataclasses.field(default_factory=ControlConfig)

    @classmethod
    def from_config(cls, block: Any) -> "TelemetryConfig":
        """Parse (and validate) an ``exp_manager.telemetry`` block.

        Accepts ``None``/``{}`` (all defaults), a mapping of knob -> bool, or
        a single bool (``telemetry: false`` switches the whole layer off).
        Unknown keys and non-boolean values raise ``ValueError`` — a typo'd
        knob must not silently run with defaults.
        """
        if block is None:
            return cls()
        if isinstance(block, bool):
            # blanket bool switches the boolean knobs (True keeps each knob's
            # default, False forces all off); health (an opt-in that changes
            # the opt-state tree) and trace (an opt-in capture window) stay
            # at their defaults: disabled
            return cls(**{k: block and v for k, v in TELEMETRY_KNOBS.items()})
        if not isinstance(block, Mapping):
            raise ValueError(
                f"exp_manager.telemetry must be a mapping of "
                f"{sorted(TELEMETRY_KNOBS) + list(_NESTED_BLOCKS)} (or a "
                f"single bool), got {type(block).__name__}"
            )
        unknown = set(block) - set(TELEMETRY_KNOBS) - set(_NESTED_BLOCKS)
        if unknown:
            from neuronx_distributed_training_tpu.config.loader import (
                did_you_mean,
            )

            options = sorted(TELEMETRY_KNOBS) + list(_NESTED_BLOCKS)
            raise ValueError(
                f"unknown exp_manager.telemetry keys {sorted(unknown)}; "
                f"supported: {options}" + did_you_mean(unknown, options)
            )
        values: dict[str, Any] = {}
        for k, v in block.items():
            if k == "health":
                values[k] = HealthConfig.from_config(v)
                continue
            if k == "trace":
                values[k] = TraceConfig.from_config(v)
                continue
            if k == "memory":
                values[k] = MemoryConfig.from_config(v)
                continue
            if k == "tensorstats":
                values[k] = TensorStatsConfig.from_config(v)
                continue
            if k == "fleet":
                values[k] = FleetConfig.from_config(v)
                continue
            if k == "alerts":
                values[k] = parse_alerts(v)
                continue
            if k == "control":
                values[k] = ControlConfig.from_config(v)
                continue
            if not isinstance(v, bool):
                raise ValueError(
                    f"exp_manager.telemetry.{k} must be a boolean, got {v!r}"
                )
            values[k] = v
        out = cls(**values)
        # cross-block rule: the hang watchdog dumps through a bundle-capable
        # monitor, which any of health / fleet / a dump-action alert rule /
        # the fleet control plane arms — with NONE of them on, a positive
        # timeout would silently never arm
        if out.health.watchdog_timeout_seconds > 0 and not (
                out.health.enabled or out.fleet.enabled
                or out.control.enabled
                or any(r.action == "dump" for r in out.alerts)):
            raise ValueError(
                "exp_manager.telemetry.health.watchdog_timeout_seconds > 0 "
                "needs a bundle-capable monitor: enable telemetry.health, "
                "telemetry.fleet, telemetry.control, or a dump-action alert "
                "rule — it would otherwise silently never arm"
            )
        return out

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)
