"""Fleet observability plane: per-host beacons + the cross-host aggregator.

Every observability surface before this one (spans, metrics.jsonl,
run_summary.json, health counters, trace analytics) is strictly
per-process: on a 32-host run there is no answer to "which host is slow,
which host is stalling data, which host went quiet" without ssh'ing
around.  This module closes that gap in three layers (docs/observability.md
"Fleet observability"):

- **Beacons** — each host process appends one compact heartbeat record to
  its own ``fleet/host_<id>.jsonl`` at every *existing* logging boundary:
  host id, step, boundary-arrival timestamps (monotonic for per-host window
  durations, wall for cross-host skew — wall comparison assumes NTP-synced
  hosts, the normal fleet posture), the cumulative span snapshot
  (``data_wait``/``host_sync``/``checkpoint``/...), the boundary metrics the
  loop already fetched (mfu, goodput, health counters), the device-memory
  watermark when known, and the last exception on the final record.  Zero
  new host syncs: every value rides the boundary fetch the loop performs
  anyway.  Appends are single ``write()`` calls of one newline-terminated
  JSON line, so a SIGKILL'd host leaves a valid file (at worst one torn
  tail line, which readers skip).

- **Aggregator** — rank 0 (in-loop) and the offline CLI
  (``tools/fleet_monitor.py``) fold the beacon files into
  ``fleet_summary.json``: per-step-window boundary-arrival skew with the
  straggler host named per window and its dominant cause (``compute_slow``
  vs ``data_stall`` vs ``checkpoint_blocked`` — from the straggler's own
  span deltas), per-host MFU/data_wait/goodput spread (min/p50/max with the
  owning host), quiet-host detection (no beacon within
  ``stale_after_seconds`` -> a named ``fleet_stall`` finding that also
  feeds the flight recorder's hang-bundle machinery), and a fleet goodput
  decomposition attributing the lost fraction to the slowest host vs
  overhead every host shares.  Reads are incremental (per-file offsets), so
  a long run's boundary-cadence aggregation stays O(new lines), not O(run).

Straggler semantics: SPMD training is lockstep — the collectives rendezvous
every host at (nearly) the same wall instant, so the *slow* host is not the
one that arrives late but the one that never waits.  Per window the
aggregator computes each host's busy seconds (window duration minus its
``host_sync`` span delta — the time it spent absorbing everyone else's
work); the straggler is the busiest host, and its own span deltas name the
cause.  ``arrival_skew_seconds`` (max-min wall arrival) is reported too:
genuinely non-lockstep skew (pre-rendezvous phases, dying hosts) shows up
there.

This module is deliberately **stdlib-only at import time** (no jax, no
package-wide imports) so ``tools/fleet_monitor.py`` can load it on a login
node the same way ``tools/metrics_report.py`` stays stdlib-only.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import statistics
import time
from pathlib import Path
from typing import Any, Callable, Mapping, Optional

logger = logging.getLogger(__name__)

#: subdirectory of the run dir holding one ``host_<id>.jsonl`` per host
FLEET_DIR = "fleet"

#: metric keys a beacon carries verbatim from the boundary fetch (plus every
#: ``health/``, ``data/``, and ``memory/`` key — the latter is the live HBM
#: watermark/headroom stream, ``telemetry.memory``) — compact on purpose:
#: beacons are appended every boundary for the life of the run
BEACON_METRICS = (
    "loss", "step_time", "mfu", "tokens_per_sec_per_chip",
    "goodput_fraction", "throughput_seqs_per_sec",
    "device_peak_bytes_in_use", "device_bytes_in_use",
)

#: straggler cause classes the aggregator can name
CAUSES = ("compute_slow", "data_stall", "checkpoint_blocked")


def _fleet_knobs() -> set:
    return {f.name for f in dataclasses.fields(FleetConfig)}


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """``exp_manager.telemetry.fleet`` knob block (validated at config load).

    .. code-block:: yaml

        exp_manager:
          telemetry:
            fleet:
              enabled: false           # per-host beacons + rank-0 aggregation
              stale_after_seconds: 600 # quiet-host threshold (fleet_stall)
              aggregate: true          # rank-0 in-loop fleet_summary.json
              max_windows: 64          # skew windows retained in the summary
    """

    enabled: bool = False
    stale_after_seconds: float = 600.0
    aggregate: bool = True
    max_windows: int = 64

    @classmethod
    def from_config(cls, block: Any) -> "FleetConfig":
        """Accepts ``None`` (defaults: disabled), a bare bool, or a mapping.
        Unknown keys raise with a did-you-mean hint — a typo'd knob must not
        silently observe nothing."""
        if block is None:
            return cls()
        if isinstance(block, bool):
            return cls(enabled=block)
        knobs = _fleet_knobs()
        if not isinstance(block, Mapping):
            raise ValueError(
                f"exp_manager.telemetry.fleet must be a mapping of "
                f"{sorted(knobs)} (or a single bool), got "
                f"{type(block).__name__}"
            )
        unknown = set(block) - knobs
        if unknown:
            from neuronx_distributed_training_tpu.config.loader import (
                did_you_mean,
            )

            raise ValueError(
                f"unknown exp_manager.telemetry.fleet keys {sorted(unknown)}; "
                f"supported: {sorted(knobs)}" + did_you_mean(unknown, knobs)
            )
        values = dict(block)
        for key in ("enabled", "aggregate"):
            if key in values and not isinstance(values[key], bool):
                raise ValueError(
                    f"exp_manager.telemetry.fleet.{key} must be a boolean, "
                    f"got {values[key]!r}"
                )
        out = cls(
            enabled=bool(values.get("enabled", cls.enabled)),
            stale_after_seconds=float(
                values.get("stale_after_seconds", cls.stale_after_seconds)),
            aggregate=bool(values.get("aggregate", cls.aggregate)),
            max_windows=int(values.get("max_windows", cls.max_windows)),
        )
        if out.stale_after_seconds <= 0:
            raise ValueError(
                f"exp_manager.telemetry.fleet.stale_after_seconds must be "
                f"> 0, got {out.stale_after_seconds}"
            )
        if out.max_windows < 1:
            raise ValueError(
                f"exp_manager.telemetry.fleet.max_windows must be >= 1, got "
                f"{out.max_windows}"
            )
        return out

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# -- layer 1: beacons --------------------------------------------------------


def beacon_path(fleet_dir: str | Path, host: int) -> Path:
    return Path(fleet_dir) / f"host_{int(host)}.jsonl"


class FleetBeacon:
    """One host's heartbeat writer.

    ``emit`` appends a single JSON line per logging boundary; the handle
    stays open for the run (append mode, flushed per write) and ``close``
    writes a final record carrying the clean/dying distinction.  All values
    must already be host floats — the caller passes the boundary metrics it
    has ALREADY fetched, never device arrays.
    """

    def __init__(self, fleet_dir: str | Path, host: int) -> None:
        self.host = int(host)
        self.path = beacon_path(fleet_dir, host)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = open(self.path, "a")
        self._closed = False

    def emit(
        self,
        step: int,
        metrics: Optional[Mapping[str, Any]] = None,
        *,
        spans: Optional[Mapping[str, float]] = None,
        closing: bool = False,
        last_exception: Optional[str] = None,
    ) -> None:
        if self._closed:
            return
        picked: dict[str, float] = {}
        for k, v in (metrics or {}).items():
            if k in BEACON_METRICS or k.startswith("health/") \
                    or k.startswith("data/") or k.startswith("memory/") \
                    or k.startswith("tensorstats/") \
                    or k.startswith("comms/"):
                try:
                    f = float(v)
                except (TypeError, ValueError):
                    continue
                # strict-JSON beacons: a NaN loss must not make the whole
                # line unparseable for non-Python consumers
                picked[k] = f if f == f and abs(f) != float("inf") else None
        rec: dict[str, Any] = {
            "host": self.host,
            "step": int(step),
            "t_mono": round(time.monotonic(), 6),
            "t_wall": round(time.time(), 6),
            "metrics": picked,
        }
        if spans:
            rec["spans"] = {
                k: round(f, 6)
                for k, v in spans.items()
                for f in [float(v)]
                if f == f and abs(f) != float("inf")
            }
        if closing:
            rec["closing"] = True
        if last_exception:
            rec["last_exception"] = str(last_exception)[:500]
        try:
            # strict JSON (allow_nan=False is belt-and-braces after the
            # sanitizing above), then ONE write() call of one full line: the
            # append is atomic enough that a reader never sees an
            # interleaved or half-flushed record from a live handle, and a
            # dying host leaves a valid file
            line = json.dumps(rec, allow_nan=False) + "\n"
            self._f.write(line)
            self._f.flush()
        except (OSError, ValueError, TypeError) as e:  # pragma: no cover
            # observability must not kill training
            logger.warning("fleet beacon write failed: %s", e)

    def close(self, last_exception: Optional[str] = None,
              step: Optional[int] = None) -> None:
        """Final beacon: ``closing: true`` marks a clean exit (the aggregator
        must not report it as a quiet host); ``last_exception`` marks a dying
        one (a ``host_died`` finding instead of silence)."""
        if self._closed:
            return
        self.emit(int(step if step is not None else -1), {},
                  closing=last_exception is None,
                  last_exception=last_exception)
        self._closed = True
        try:
            self._f.close()
        except OSError:  # pragma: no cover
            pass


# -- layer 2: the aggregator -------------------------------------------------


def _read_new_lines(path: Path, offset: int) -> tuple[list[dict], int]:
    """New COMPLETE records in ``path`` past ``offset`` -> (records, new
    offset).  A torn tail line (host died mid-write, or a live writer mid
    flush) is left for the next refresh; a malformed complete line is
    skipped with a warning."""
    try:
        size = path.stat().st_size
    except OSError:
        return [], offset
    if size <= offset:
        return [], offset
    with open(path) as f:
        f.seek(offset)
        chunk = f.read(size - offset)
    end = chunk.rfind("\n")
    if end < 0:
        return [], offset  # no complete line yet
    out = []
    for line in chunk[: end + 1].splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            logger.warning("fleet: skipping malformed beacon line in %s",
                           path.name)
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out, offset + end + 1


class _HostState:
    """Per-host fold state: the latest record, recent per-step records (for
    window math), and identity facts."""

    def __init__(self, host: int, keep_steps: int) -> None:
        self.host = host
        self.keep_steps = keep_steps
        self.beacons = 0
        self.last: Optional[dict] = None
        self.closed = False
        self.last_exception: Optional[str] = None
        # ordered step -> record of recent NON-final beacons
        self.recent: dict[int, dict] = {}
        # sticky comms/* metrics: the achieved-bandwidth join fires once
        # per trace window, not per beacon — the next regular beacon would
        # otherwise erase it from `last` before anyone reads the spread
        self.comms: dict[str, float] = {}

    def fold(self, rec: dict) -> None:
        self.beacons += 1
        if rec.get("closing") or rec.get("last_exception"):
            self.closed = True
            if rec.get("last_exception"):
                self.last_exception = str(rec["last_exception"])
            # final records carry no window data; keep the previous `last`
            # for metrics but remember the terminal wall time
            if self.last is not None:
                self.last = dict(self.last, t_wall=rec.get(
                    "t_wall", self.last.get("t_wall")))
            else:
                self.last = rec
            return
        self.last = rec
        for k, v in dict(rec.get("metrics") or {}).items():
            if k.startswith("comms/") and v is not None:
                try:
                    self.comms[k] = float(v)
                except (TypeError, ValueError):
                    pass
        try:
            step = int(rec["step"])
        except (KeyError, TypeError, ValueError):
            return
        self.recent[step] = rec
        while len(self.recent) > self.keep_steps:
            self.recent.pop(next(iter(self.recent)))

    def metric(self, key: str) -> Optional[float]:
        m = (self.last or {}).get("metrics") or {}
        v = m.get(key)
        try:
            return float(v) if v is not None else None
        except (TypeError, ValueError):
            return None

    def span(self, rec: dict, key: str) -> float:
        try:
            return float((rec.get("spans") or {}).get(key, 0.0) or 0.0)
        except (TypeError, ValueError):
            return 0.0


def _spread(values: dict[int, float]) -> Optional[dict]:
    """min/p50/max over per-host values, naming the owning hosts."""
    if not values:
        return None
    items = sorted(values.items(), key=lambda kv: kv[1])
    hosts = [h for h, _ in items]
    vals = [v for _, v in items]
    return {
        "min": {"host": hosts[0], "value": round(vals[0], 6)},
        "p50": round(statistics.median(vals), 6),
        "max": {"host": hosts[-1], "value": round(vals[-1], 6)},
    }


class FleetAggregator:
    """Folds ``fleet/host_*.jsonl`` streams into the fleet summary.

    Incremental by construction: ``refresh`` re-scans the directory for new
    host files, reads only bytes past each file's stored offset, and folds
    them into per-host state.  Call it at whatever cadence suits the caller
    (the trainer's rank 0 calls it every boundary; the CLI calls it once, or
    on a ``--follow`` interval)."""

    def __init__(self, fleet_dir: str | Path, *,
                 stale_after_seconds: float = 600.0,
                 max_windows: int = 64) -> None:
        self.fleet_dir = Path(fleet_dir)
        self.stale_after_seconds = float(stale_after_seconds)
        self.max_windows = max(int(max_windows), 1)
        self._offsets: dict[Path, int] = {}
        self._hosts: dict[int, _HostState] = {}
        #: retained cross-host windows, newest last
        self.windows: list[dict] = []
        self._windowed_upto: Optional[int] = None  # last step windowed

    # -- folding ------------------------------------------------------------

    def refresh(self, now: Optional[float] = None) -> dict:
        """Fold any new beacon lines and return the current summary dict.

        ``now`` (wall seconds) is the quiet-host reference for LIVE
        monitoring; offline analysis of a finished run leaves it ``None``
        and the newest beacon across the fleet anchors staleness instead —
        a file set copied off a dead fleet must not report every host quiet.
        """
        for path in sorted(self.fleet_dir.glob("host_*.jsonl")):
            try:
                host = int(path.stem.split("_", 1)[1])
            except (IndexError, ValueError):
                continue
            recs, self._offsets[path] = _read_new_lines(
                path, self._offsets.get(path, 0))
            if recs and host not in self._hosts:
                # windows need the predecessor record too: keep one extra
                self._hosts[host] = _HostState(
                    host, keep_steps=self.max_windows + 1)
            for rec in recs:
                self._hosts[host].fold(rec)
        self._update_windows()
        return self.summary(now=now)

    def _update_windows(self) -> None:
        """Windows over steps EVERY live host has reached.  A window is the
        interval between two consecutive common steps; per host its duration
        comes from the host's own monotonic clock (cross-host monotonic
        origins are not comparable), busy = duration - host_sync delta."""
        live = [h for h in self._hosts.values() if h.recent]
        if len(live) < 2:
            return
        common = set.intersection(*(set(h.recent) for h in live))
        steps = sorted(common)
        for prev_step, step in zip(steps, steps[1:]):
            if self._windowed_upto is not None and step <= self._windowed_upto:
                continue
            win = self._window(live, prev_step, step)
            if win is not None:
                self.windows.append(win)
                self._windowed_upto = step
        del self.windows[: max(0, len(self.windows) - self.max_windows)]

    def _window(self, live: list[_HostState], prev_step: int,
                step: int) -> Optional[dict]:
        busy: dict[int, float] = {}
        causes: dict[int, str] = {}
        arrivals: dict[int, float] = {}
        for h in live:
            a, b = h.recent[prev_step], h.recent[step]
            try:
                duration = float(b["t_mono"]) - float(a["t_mono"])
                arrivals[h.host] = float(b["t_wall"])
            except (KeyError, TypeError, ValueError):
                return None
            if duration <= 0:
                return None
            d_sync = h.span(b, "host_sync") - h.span(a, "host_sync")
            d_data = h.span(b, "data_wait") - h.span(a, "data_wait")
            d_ckpt = h.span(b, "checkpoint") - h.span(a, "checkpoint")
            hb = max(duration - max(d_sync, 0.0), 0.0)
            busy[h.host] = hb
            if d_ckpt > 0.5 * hb:
                causes[h.host] = "checkpoint_blocked"
            elif d_data > 0.5 * hb:
                causes[h.host] = "data_stall"
            else:
                causes[h.host] = "compute_slow"
        ranked = sorted(busy.items(), key=lambda kv: kv[1])
        straggler, worst = ranked[-1]
        fastest = ranked[0][1]
        # a balanced window has no straggler to name: within 10% of each
        # other every host is "the" bottleneck in turn
        balanced = worst <= 1.1 * fastest
        return {
            "step": int(step),
            "window_steps": int(step - prev_step),
            "arrival_skew_seconds": round(
                max(arrivals.values()) - min(arrivals.values()), 6),
            "busy_seconds": {str(h): round(v, 6) for h, v in busy.items()},
            "straggler_host": None if balanced else straggler,
            "cause": None if balanced else causes[straggler],
            "straggler_excess_seconds": round(worst - fastest, 6),
        }

    # -- the summary --------------------------------------------------------

    def quiet_hosts(self, now: Optional[float] = None) -> list[dict]:
        """Hosts with no beacon within ``stale_after_seconds`` of the
        reference time (``now`` for live monitoring, else the fleet's newest
        beacon).  Cleanly-closed hosts are never quiet; a host whose final
        record carried an exception is reported by ``findings`` as
        ``host_died`` rather than here."""
        last_wall: dict[int, float] = {}
        for h in self._hosts.values():
            if h.last is not None and h.last.get("t_wall") is not None:
                last_wall[h.host] = float(h.last["t_wall"])
        if not last_wall:
            return []
        ref = float(now) if now is not None else max(last_wall.values())
        out = []
        for h in sorted(self._hosts.values(), key=lambda s: s.host):
            if h.closed or h.host not in last_wall:
                continue
            silent = ref - last_wall[h.host]
            if silent > self.stale_after_seconds:
                out.append({
                    "host": h.host,
                    "last_step": int((h.last or {}).get("step", -1)),
                    "silent_seconds": round(silent, 3),
                })
        return out

    def summary(self, now: Optional[float] = None) -> dict:
        hosts_block: dict[str, Any] = {}
        per_metric: dict[str, dict[int, float]] = {
            "mfu": {}, "goodput_fraction": {}, "data_wait_seconds": {},
            "step_time": {}, "peak_hbm_bytes": {},
            "hbm_headroom_fraction": {},
        }
        for h in sorted(self._hosts.values(), key=lambda s: s.host):
            last = h.last or {}
            data_wait = h.span(last, "data_wait") if last else 0.0
            # live HBM watermark (telemetry.memory beacons first, the legacy
            # device_memory key as fallback) — per-host memory spread is how
            # a skewed-stage OOM-bound host shows up fleet-wide
            peak_hbm = h.metric("memory/peak_hbm_bytes")
            if peak_hbm is None:
                peak_hbm = h.metric("device_peak_bytes_in_use")
            headroom = h.metric("memory/hbm_headroom_fraction")
            hosts_block[str(h.host)] = {
                "beacons": h.beacons,
                "last_step": int(last.get("step", -1)),
                "last_wall": last.get("t_wall"),
                "closed": h.closed,
                "last_exception": h.last_exception,
                "mfu": h.metric("mfu"),
                "goodput_fraction": h.metric("goodput_fraction"),
                "step_time": h.metric("step_time"),
                "data_wait_seconds": round(data_wait, 6),
                "device_peak_bytes_in_use": h.metric(
                    "device_peak_bytes_in_use"),
                "peak_hbm_bytes": peak_hbm,
                "hbm_headroom_fraction": headroom,
            }
            for key, getter in (
                ("mfu", h.metric("mfu")),
                ("goodput_fraction", h.metric("goodput_fraction")),
                ("step_time", h.metric("step_time")),
                ("data_wait_seconds", data_wait if last else None),
                ("peak_hbm_bytes", peak_hbm),
                ("hbm_headroom_fraction", headroom),
            ):
                if getter is not None:
                    per_metric[key][h.host] = float(getter)
            # achieved interconnect bandwidth (telemetry.comms beacons):
            # per-host spread on comms/*/achieved_gbps is how ONE host's
            # degraded link shows up fleet-wide — the spread table renders
            # whatever keys land here, no per-metric plumbing needed
            for k, v in h.comms.items():
                if k.endswith("/achieved_gbps"):
                    per_metric.setdefault(k, {})[h.host] = float(v)

        quiet = self.quiet_hosts(now=now)
        findings: list[dict] = []
        for q in quiet:
            findings.append({
                "kind": "fleet_stall",
                "host": q["host"],
                "last_step": q["last_step"],
                "silent_seconds": q["silent_seconds"],
                "message": (
                    f"host {q['host']} quiet for {q['silent_seconds']:.0f}s "
                    f"(last beacon at step {q['last_step']}; "
                    f"stale_after_seconds={self.stale_after_seconds:.0f}) — "
                    f"absence of progress, not slow progress"),
            })
        for h in sorted(self._hosts.values(), key=lambda s: s.host):
            if h.last_exception:
                findings.append({
                    "kind": "host_died",
                    "host": h.host,
                    "last_step": int((h.last or {}).get("step", -1)),
                    "message": (f"host {h.host} exited with: "
                                f"{h.last_exception}"),
                })

        # modal straggler across the retained windows
        straggler_block = None
        led: dict[int, int] = {}
        led_causes: dict[int, dict[str, int]] = {}
        attributed = [w for w in self.windows
                      if w.get("straggler_host") is not None]
        for w in attributed:
            s = int(w["straggler_host"])
            led[s] = led.get(s, 0) + 1
            c = led_causes.setdefault(s, {})
            c[w["cause"]] = c.get(w["cause"], 0) + 1
        if led:
            modal = max(led.items(), key=lambda kv: kv[1])[0]
            cause = max(led_causes[modal].items(), key=lambda kv: kv[1])[0]
            straggler_block = {
                "host": modal,
                "cause": cause,
                "windows_led": led[modal],
                "windows_attributed": len(attributed),
                "windows_total": len(self.windows),
            }

        return {
            "n_hosts": len(self._hosts),
            "hosts": hosts_block,
            "windows": list(self.windows),
            "straggler": straggler_block,
            "spread": {k: _spread(v) for k, v in per_metric.items()
                       if _spread(v) is not None},
            "quiet_hosts": quiet,
            "findings": findings,
            "goodput": self._goodput_decomposition(
                per_metric["goodput_fraction"]),
            "stale_after_seconds": self.stale_after_seconds,
        }

    @staticmethod
    def _goodput_decomposition(g: dict[int, float]) -> Optional[dict]:
        """Fleet goodput = the worst host's (the fleet trains at its pace).
        The lost fraction splits into overhead every host shares (what even
        the BEST host loses) and the extra the slowest host adds on top —
        the part a straggler fix would recover."""
        if not g:
            return None
        items = sorted(g.items(), key=lambda kv: kv[1])
        (worst_h, worst), (best_h, best) = items[0], items[-1]
        return {
            "fleet_goodput_fraction": round(worst, 6),
            "common_overhead_fraction": round(max(1.0 - best, 0.0), 6),
            "straggler_loss_fraction": round(max(best - worst, 0.0), 6),
            "best_host": best_h,
            "worst_host": worst_h,
        }


def aggregate_fleet(fleet_dir: str | Path, *,
                    stale_after_seconds: float = 600.0,
                    max_windows: int = 64,
                    now: Optional[float] = None) -> dict:
    """One-shot fold of a beacon directory (the offline CLI's entry)."""
    agg = FleetAggregator(fleet_dir, stale_after_seconds=stale_after_seconds,
                          max_windows=max_windows)
    return agg.refresh(now=now)


def write_fleet_summary(summary: dict, path: str | Path) -> None:
    """Atomic ``fleet_summary.json`` write (same serialize-first +
    temp/fsync/rename contract as ``utils.io.atomic_write_json``, inlined
    here so the stdlib-only CLI can call it without importing the package —
    a SIGKILL mid-write never leaves torn JSON)."""
    data = json.dumps(summary, indent=1, sort_keys=True) + "\n"
    spath = str(path)
    tmp = f"{spath}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(data)
        f.flush()
        try:
            os.fsync(f.fileno())
        except OSError:  # pragma: no cover — some filesystems refuse
            pass
    os.replace(tmp, spath)


# -- the trainer-facing facade ----------------------------------------------


class FleetPlane:
    """What the fit loop holds: this host's beacon plus (rank 0 with
    ``aggregate: true``) the in-loop aggregator, quiet-host findings routed
    into the flight recorder's bundle machinery, and the ``fleet/*`` metrics
    the alert engine sees."""

    def __init__(
        self,
        cfg: FleetConfig,
        run_dir: str | Path,
        *,
        host: int = 0,
        aggregate: bool = False,
        write_run_summary: Optional[Callable[[dict], None]] = None,
    ) -> None:
        self.cfg = cfg
        self.run_dir = Path(run_dir)
        self.fleet_dir = self.run_dir / FLEET_DIR
        self.summary_path = self.run_dir / "fleet_summary.json"
        self.beacon = FleetBeacon(self.fleet_dir, host)
        self._write_run_summary = write_run_summary
        self.aggregator = (
            FleetAggregator(self.fleet_dir,
                            stale_after_seconds=cfg.stale_after_seconds,
                            max_windows=cfg.max_windows)
            if aggregate and cfg.aggregate else None
        )
        self._stall_reported: set[int] = set()
        self._closed = False

    def boundary(
        self,
        step: int,
        metrics: Optional[Mapping[str, Any]] = None,
        spans: Optional[Mapping[str, float]] = None,
        monitor: Optional[Any] = None,
    ) -> dict[str, float]:
        """One logging boundary: emit this host's beacon, (rank 0) fold the
        fleet and persist ``fleet_summary.json``, dump a ``fleet_stall``
        bundle through the flight recorder for each NEWLY quiet host, and
        return the ``fleet/*`` metrics for the alert engine.  Everything is
        host-side file I/O — zero device work, zero new syncs."""
        self.beacon.emit(step, metrics, spans=spans)
        if self.aggregator is None:
            return {}
        try:
            summary = self.aggregator.refresh(now=time.time())
            write_fleet_summary(summary, self.summary_path)
        except Exception as e:  # noqa: BLE001 — observability must not kill
            logger.warning("fleet aggregation failed: %s", e)
            return {}
        fresh = []
        for q in summary.get("quiet_hosts") or []:
            h = int(q["host"])
            if h in self._stall_reported:
                continue
            self._stall_reported.add(h)
            fresh.append(q)
            logger.warning(
                "fleet_stall: host %d quiet for %.0fs (last step %d)",
                h, q["silent_seconds"], q["last_step"])
        if fresh and monitor is not None:
            # the same forensic machinery a hung device sync feeds: a quiet
            # host IS a fleet-level hang.  One bundle per boundary covers
            # every host that went quiet in it (the dedupe key is
            # (kind, step), so per-host dumps would collide anyway).
            try:
                monitor.dump(
                    step, kind="fleet_stall", fetch_ring=False,
                    extra={"quiet_hosts": fresh,
                           "stale_after_seconds":
                               self.cfg.stale_after_seconds},
                )
            except Exception as e:  # noqa: BLE001
                logger.warning("fleet_stall bundle failed: %s", e)
        out: dict[str, float] = {
            "fleet/n_hosts": float(summary.get("n_hosts", 0)),
            "fleet/n_quiet_hosts": float(len(summary.get("quiet_hosts") or [])),
        }
        if summary.get("windows"):
            out["fleet/arrival_skew_seconds"] = float(
                summary["windows"][-1]["arrival_skew_seconds"])
        gp = summary.get("goodput") or {}
        if gp.get("fleet_goodput_fraction") is not None:
            out["fleet/goodput_fraction"] = float(
                gp["fleet_goodput_fraction"])
            out["fleet/straggler_loss_fraction"] = float(
                gp.get("straggler_loss_fraction", 0.0))
        sp = (summary.get("spread") or {}).get("mfu")
        if sp:
            out["fleet/mfu_min"] = float(sp["min"]["value"])
        return out

    def close(self, exc: Optional[BaseException] = None,
              step: Optional[int] = None) -> None:
        """Teardown: the final beacon (clean vs dying), one last aggregation
        pass, and the run-summary pointer.  Never raises."""
        if self._closed:
            return
        self._closed = True
        try:
            self.beacon.close(
                last_exception=(f"{type(exc).__name__}: {exc}"
                                if exc is not None else None),
                step=step,
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("fleet beacon close failed: %s", e)
        if self.aggregator is not None:
            try:
                summary = self.aggregator.refresh()
                write_fleet_summary(summary, self.summary_path)
                if self._write_run_summary is not None:
                    self._write_run_summary({"fleet": {
                        "n_hosts": summary.get("n_hosts"),
                        "straggler": summary.get("straggler"),
                        "quiet_hosts": [q["host"] for q in
                                        summary.get("quiet_hosts") or []],
                        "goodput": summary.get("goodput"),
                        "summary_path": str(self.summary_path),
                    }})
            except Exception as e:  # noqa: BLE001
                logger.warning("fleet teardown aggregation failed: %s", e)
