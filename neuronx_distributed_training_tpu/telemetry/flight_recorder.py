"""Numerics flight recorder: anomaly ring buffer, forensic bundles, hang watchdog.

The host-side half of the health subsystem (in-graph probes live in
``telemetry.health`` + ``optim.adamw``).  Three pieces:

- ``HealthMonitor.record`` ring-buffers the last N steps' forensic context —
  batch fingerprint (the PR-2 retrace detector's abstract signature), the RNG
  *recipe* (seed + fold_in step, replayable without touching the device), the
  step's health metrics as UNFETCHED device arrays (no conversion, no sync),
  and a cumulative span snapshot.  Cost per step: one deque append of host
  references.

- ``HealthMonitor.check_boundary`` runs at the loop's existing sync
  boundaries: it compares the cumulative ``health/nonfinite_count`` carried
  in the boundary metrics (already fetched by the loop's one host sync)
  against the last seen value.  Healthy boundary: an int compare, nothing
  else.  On an increase it writes a forensic bundle ``anomaly_<step>/`` —
  ``anomaly.json`` (trigger, policy, boundary metrics, run facts, RNG recipe,
  pointer into ``run_summary.json``'s compile census) and ``ring.json`` (the
  buffered steps, health scalars fetched NOW — the anomaly path may sync) —
  and returns the configured policy for the loop to apply
  (``halt`` stops the run; ``skip_update``/``dump_and_continue`` continue,
  the former having already suppressed the poisoned update in-graph).

- ``HangWatchdog.guard`` arms a timer around a blocking device op (the
  boundary metric fetch, the first compile).  If the op doesn't return within
  the timeout, the watchdog thread dumps Python stacks of every thread plus a
  device-safe bundle (host-side ring metadata only — fetching device arrays
  from a hung backend would hang the watchdog too) and optionally aborts the
  process so the scheduler can restart it from the last good checkpoint.
  Under the fleet control plane (``trainer.control``,
  docs/observability.md "Fleet control") the watchdog instead **escapes**:
  after the bundle it runs the registered teardown hooks (the final dying
  fleet beacon, the control-trail exit note) and ``os._exit``\\ s with the
  tagged ``EXIT_HANG_ESCAPE`` code — a dead peer mid-collective must never
  leave the survivors hanging forever; the orchestrator restarts the
  incarnation and elastic resume + integrity walk-back do the recovery.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
from pathlib import Path
from typing import Any, Callable, Optional

from neuronx_distributed_training_tpu.telemetry.health import HealthConfig

logger = logging.getLogger(__name__)

#: metric keys ring-buffered per step (besides every ``health/*`` key)
_CORE_METRICS = ("loss", "grad_norm", "lr")


def _to_float(v: Any) -> Any:
    """Device scalar -> host float (anomaly path only); non-scalars -> repr.

    Non-finite floats become strings ("nan"/"inf"): json.dump would emit
    bare ``NaN`` tokens — invalid strict JSON — for exactly the values an
    anomaly bundle exists to record, breaking every non-Python consumer."""
    import math

    try:
        f = float(v)
    except (TypeError, ValueError):
        return repr(v)
    return f if math.isfinite(f) else repr(f)


class HealthMonitor:
    """Ring buffer + anomaly-bundle writer.  All healthy-path methods are
    host-only and never convert device arrays."""

    def __init__(
        self,
        cfg: HealthConfig,
        dump_dir: str | Path,
        *,
        run_facts: Optional[dict] = None,
        write_run_summary: Optional[Callable[[dict], None]] = None,
        rng_seed: int = 0,
    ) -> None:
        self.cfg = cfg
        self.dump_dir = Path(dump_dir)
        self.run_facts = dict(run_facts or {})
        self._write_run_summary = write_run_summary
        # the base seed of the loop's per-step key derivation
        # (fold_in(PRNGKey(rng_seed), step)) — passed in by the trainer so
        # the bundles' replay recipe has one source of truth
        self._rng_seed = int(rng_seed)
        self._ring: collections.deque = collections.deque(
            maxlen=max(int(cfg.ring_buffer_steps), 1))
        self._seen_count = 0
        # the watchdog can fire on its timer thread while the main thread is
        # still dumping (abort=False, slow-but-not-hung fetch): bundle state
        # mutations serialize through this lock
        self._dump_lock = threading.Lock()
        self._dumped: set[tuple[str, int]] = set()  # (kind, step)
        #: [{step, bundle, policy}] — mirrored into run_summary.json
        self.anomalies: list[dict[str, Any]] = []
        # a restarted run must EXTEND the prior trail, not overwrite it:
        # re-seed the anomaly list (and the per-step dedupe) from the
        # run_summary.json the previous incarnation left behind
        try:
            with open(self.dump_dir / "run_summary.json") as f:
                prior = json.load(f).get("anomalies") or []
        except (OSError, ValueError, AttributeError):
            prior = []
        for a in prior:
            # per-entry tolerance: one malformed entry (older schema, hand
            # edit) must not drop the rest of the prior trail
            try:
                kind = str(a.get("bundle", "anomaly_")).split("_")[0]
                self._dumped.add((kind, int(a["step"])))
                self.anomalies.append(a)
            except (KeyError, TypeError, ValueError, AttributeError):
                logger.warning("health: skipping malformed prior anomaly "
                               "entry %r", a)

    def seed_counters(self, nonfinite_count: int) -> None:
        """Align the boundary comparator with counters RESTORED from a
        checkpoint (the trainer calls this after resume) — otherwise the
        first post-resume boundary would re-trigger the policy for an
        anomaly the previous incarnation already handled (fatal under
        ``halt``: a permanent halt/restart loop)."""
        self._seen_count = max(self._seen_count, int(nonfinite_count))

    # -- healthy path (per step / per boundary) -----------------------------

    def record(
        self,
        step: int,
        metrics: dict[str, Any],
        *,
        fingerprint: Optional[dict] = None,
        spans: Optional[dict] = None,
    ) -> None:
        """Append one step's forensic context.  ``metrics`` values stay as
        device arrays — conversion happens only inside an anomaly dump."""
        self._ring.append({
            "step": int(step),
            "fingerprint": fingerprint,
            "rng": {"seed": self._rng_seed, "fold_in": int(step)},
            "spans_cumulative": dict(spans) if spans else None,
            "metrics": {
                k: v for k, v in metrics.items()
                if k in _CORE_METRICS or k.startswith("health/")
                or k.startswith("tensorstats/")
            },
        })

    def check_boundary(self, step: int, fetched: dict[str, float]) -> Optional[str]:
        """Inspect already-fetched boundary metrics; returns the policy to
        apply when a new anomaly appeared since the last boundary, else None.

        When more than one step went bad inside the window, the ring buffer
        is scanned (fetching its per-step finite flags — the anomaly path may
        sync) so EVERY still-buffered bad step gets its own bundle; bad steps
        that already rotated out of the ring are only represented by the
        cumulative counter."""
        count = fetched.get("health/nonfinite_count")
        if count is None:
            return None
        count = int(count)
        if count <= self._seen_count:
            self._seen_count = count
            return None
        delta = count - self._seen_count
        prev_seen, self._seen_count = self._seen_count, count
        last_bad = int(fetched.get("health/last_nonfinite_step", step))
        bad_steps = {last_bad}
        if delta > 1:
            for entry in self._ring:
                flag = (entry.get("metrics") or {}).get("health/updates_finite")
                try:
                    if flag is not None and float(flag) == 0.0:
                        bad_steps.add(int(entry["step"]))
                except (TypeError, ValueError):
                    continue
        any_write_failed = False
        for s in sorted(bad_steps):
            bundle = self.dump(s, trigger_step=step, boundary_metrics=fetched)
            if (bundle is None and ("anomaly", s) not in self._dumped
                    and not self._anomaly_cap_reached()):
                # dump() returned None for a WRITE failure (not dedupe, not
                # cap): roll the comparator back below so the next boundary
                # retries — already-dumped steps are dedupe no-ops then
                any_write_failed = True
        if any_write_failed:
            self._seen_count = prev_seen
        return self.cfg.policy

    # -- anomaly path -------------------------------------------------------

    def _anomaly_cap_reached(self) -> bool:
        return (sum(1 for k, _ in self._dumped if k == "anomaly")
                >= max(int(self.cfg.max_bundles), 1))

    def _ring_payload(self, *, fetch: bool) -> list[dict]:
        out = []
        for entry in self._ring:
            e = dict(entry)
            m = e.pop("metrics", {}) or {}
            e["metrics"] = ({k: _to_float(v) for k, v in m.items()} if fetch
                            else {"keys": sorted(m)})
            out.append(e)
        return out

    def dump(
        self,
        anomaly_step: int,
        *,
        trigger_step: Optional[int] = None,
        boundary_metrics: Optional[dict] = None,
        kind: str = "anomaly",
        extra: Optional[dict] = None,
        fetch_ring: bool = True,
    ) -> Optional[Path]:
        """Write a forensic bundle for ``anomaly_step``; returns its dir.

        Exactly one bundle per (kind, step) — re-triggers are no-ops — and
        anomaly bundles are capped at ``max_bundles`` total so a run stuck in
        a NaN loop doesn't fill the disk with identical forensics.  Hang
        bundles bypass the cap and the anomaly dedupe: the watchdog fires at
        most once per process, and its stacks must not be starved by an
        earlier NaN cascade having spent the budget."""
        with self._dump_lock:
            return self._dump_locked(
                anomaly_step, trigger_step=trigger_step,
                boundary_metrics=boundary_metrics, kind=kind, extra=extra,
                fetch_ring=fetch_ring,
            )

    def _dump_locked(
        self,
        anomaly_step: int,
        *,
        trigger_step: Optional[int],
        boundary_metrics: Optional[dict],
        kind: str,
        extra: Optional[dict],
        fetch_ring: bool,
    ) -> Optional[Path]:
        if (kind, anomaly_step) in self._dumped:
            return None
        if kind == "anomaly" and self._anomaly_cap_reached():
            logger.warning(
                "health: max_bundles=%d reached; not dumping step %d",
                self.cfg.max_bundles, anomaly_step,
            )
            return None
        bundle = self.dump_dir / f"{kind}_{int(anomaly_step):08d}"
        try:
            bundle.mkdir(parents=True, exist_ok=True)
            summary = {
                "kind": kind,
                "anomaly_step": int(anomaly_step),
                "trigger_step": int(trigger_step if trigger_step is not None
                                    else anomaly_step),
                "policy": self.cfg.policy,
                "rng": {"seed": self._rng_seed, "fold_in": int(anomaly_step)},
                "boundary_metrics": {
                    k: _to_float(v) for k, v in (boundary_metrics or {}).items()
                },
                "run_facts": self.run_facts,
                # the compile census (memory_analysis / collectives /
                # compile_seconds) for THIS executable lives one level up
                "compile_census": str(self.dump_dir / "run_summary.json"),
                "ring_buffer_steps": len(self._ring),
            }
            if extra:
                summary.update(extra)
            with open(bundle / "anomaly.json", "w") as f:
                json.dump(summary, f, indent=1, sort_keys=True)
                f.write("\n")
            with open(bundle / "ring.json", "w") as f:
                json.dump(self._ring_payload(fetch=fetch_ring), f, indent=1)
                f.write("\n")
        except Exception as e:  # noqa: BLE001 — forensics must not kill training
            logger.warning("health: bundle write failed for step %d: %s",
                           anomaly_step, e)
            try:
                # best-effort cleanup of the partial bundle so a retry (or a
                # report tool walking the run dir) never sees half a bundle
                import shutil

                shutil.rmtree(bundle, ignore_errors=True)
            except Exception:  # noqa: BLE001
                pass
            return None
        # dedupe/budget consumed only AFTER a successful write: a transient
        # write failure (ENOSPC) must neither burn the cap nor permanently
        # suppress this step's forensics
        self._dumped.add((kind, anomaly_step))
        self.anomalies.append({
            "step": int(anomaly_step),
            "bundle": bundle.name,
            "policy": self.cfg.policy if kind == "anomaly" else kind,
        })
        if self._write_run_summary is not None:
            try:
                self._write_run_summary({"anomalies": self.anomalies})
            except Exception as e:  # noqa: BLE001
                logger.warning("health: run_summary anomaly update failed: %s", e)
        logger.warning(
            "health: %s at step %d — forensic bundle written to %s (policy=%s)",
            kind, anomaly_step, bundle, self.cfg.policy,
        )
        return bundle

    def dump_hang(self, step: int, what: str, stacks: str) -> Optional[Path]:
        """Hang bundle: stacks + host-side ring metadata.  NEVER fetches
        device arrays — the device is presumed hung."""
        bundle = self.dump(
            step, kind="hang", fetch_ring=False,
            extra={"hung_operation": what,
                   "watchdog_timeout_seconds": self.cfg.watchdog_timeout_seconds},
        )
        if bundle is not None:
            try:
                (bundle / "stacks.txt").write_text(stacks)
            except OSError as e:
                logger.warning("health: stack dump write failed: %s", e)
        return bundle


def _all_thread_stacks() -> str:
    """Formatted Python stacks of every live thread (watchdog forensics)."""
    import sys
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for tid, frame in sys._current_frames().items():
        parts.append(f"--- thread {names.get(tid, '?')} (ident {tid}) ---")
        parts.append("".join(traceback.format_stack(frame)))
    return "\n".join(parts)


class HangWatchdog:
    """Detects a blocking device op that never returns.

    ``guard(what, step)`` arms a daemon timer; if the guarded block doesn't
    finish within ``timeout_seconds`` the watchdog dumps Python stacks + a
    device-safe bundle via the monitor, then (``abort=True``) SIGABRTs the
    process — a hung collective is unrecoverable in-process, and a clean
    abort lets the scheduler restart from the last checkpoint instead of
    burning a slot until the job walltime expires.  Default OFF
    (``watchdog_timeout_seconds: 0``): tier-1 CPU runs and debuggers stop
    the world legitimately.
    """

    def __init__(
        self,
        timeout_seconds: float,
        monitor: Optional[HealthMonitor] = None,
        *,
        abort: bool = True,
    ) -> None:
        self.timeout_seconds = float(timeout_seconds)
        self.monitor = monitor
        self.abort = abort
        self.fired = False
        # hang-escape (trainer.control): when armed, a fire EXITS the
        # process with this tagged code after running the teardown hooks —
        # survivors of a dead peer never hang forever.  `_exit_fn` is the
        # test seam (tests record the code instead of dying).
        self.escape_code: Optional[int] = None
        self._escape_hooks: list = []
        self._exit_fn = os._exit

    def arm_escape(self, exit_code: int, *hooks) -> None:
        """Arm the collective-hang escape: on fire, after the forensic
        bundle, run ``hooks`` (best-effort — e.g. the final dying fleet
        beacon and the control-trail exit note; a hook must never touch the
        hung device) and ``os._exit(exit_code)``.  ``os._exit`` on purpose:
        ``finally`` blocks and atexit handlers would block on the very
        backend that is hung."""
        self.escape_code = int(exit_code)
        self._escape_hooks = list(hooks)

    def guard(self, what: str, step: int):
        return _WatchdogGuard(self, what, int(step))

    def _fire(self, what: str, step: int) -> None:
        # only the FIRST fire dumps a bundle: under abort=False a chronically
        # slow boundary would otherwise write a hang bundle per boundary —
        # unbounded, since hang bundles bypass max_bundles on the strength of
        # this very once-per-process guarantee
        first = not self.fired
        self.fired = True
        logger.critical(
            "health watchdog: %r did not complete within %.0fs at step %d — "
            "%s%s", what, self.timeout_seconds, step,
            "dumping stacks" if first else "already dumped once; not re-dumping",
            " and exiting with the hang-escape code"
            if self.escape_code is not None
            else " and aborting" if self.abort else "",
        )
        if self.monitor is not None and first:
            self.monitor.dump_hang(step, what, _all_thread_stacks())
        if self.escape_code is not None:
            if first:
                for hook in self._escape_hooks:
                    try:
                        hook(what, step)
                    except Exception as e:  # noqa: BLE001 — escape must win
                        logger.warning("hang-escape hook failed: %s", e)
            self._exit_fn(self.escape_code)
            return  # only reached when _exit_fn is a test seam
        if self.abort:
            import signal

            os.kill(os.getpid(), signal.SIGABRT)


class _WatchdogGuard:
    def __init__(self, wd: HangWatchdog, what: str, step: int) -> None:
        self._wd, self._what, self._step = wd, what, step
        self._timer: Optional[threading.Timer] = None

    def __enter__(self) -> "_WatchdogGuard":
        self._timer = threading.Timer(
            self._wd.timeout_seconds, self._wd._fire,
            args=(self._what, self._step))
        self._timer.daemon = True
        self._timer.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        if self._timer is not None:
            self._timer.cancel()
