"""``exp_manager.telemetry.health`` — in-graph numerics health probes.

The trainer is fast but blind to numerics: a NaN loss, a divergent grad norm,
or a silently poisoned optimizer state surfaces hours later as a dead run with
no forensic trail.  This module is the *in-graph* half of the numerics flight
recorder (the host-side half — ring buffer, anomaly bundles, hang watchdog —
lives in ``telemetry.flight_recorder``):

- a compact health pytree computed INSIDE the jitted train step, so it rides
  the existing compile (zero extra executables) and costs no host sync on
  healthy steps: per-layer-group grad norms whose squared sums also *produce*
  the global clipping norm (one reduction pass, one source of truth —
  ``optim.adamw.adamw_update(grad_group_fn=...)``), loss finiteness, a
  param-norm probe, and an ``updates_finite`` flag;
- cumulative anomaly counters carried in ``opt_state["health"]`` (so they
  thread step-to-step through the same donated state, survive checkpoints,
  and reach the host for free inside the boundary metric fetch the loop
  already performs);
- the ``skip_update`` policy: the AdamW update is zeroed in-graph via the
  finite flag (a ``select`` on every leaf — no recompile, no host round-trip,
  params bitwise-unchanged on the poisoned step), the NeMo/apex
  grad-scaler-skip behavior without a dynamic loss scale.

Knob block (validated through ``TelemetryConfig.from_config`` at config load):

.. code-block:: yaml

    exp_manager:
      telemetry:
        health:
          enabled: true
          policy: dump_and_continue   # halt | skip_update | dump_and_continue
          ring_buffer_steps: 32       # flight-recorder depth (host-side)
          param_norm: true            # in-graph param-norm drift probe
          max_bundles: 8              # stop dumping after N anomaly bundles
          watchdog_timeout_seconds: 0 # hung-device-sync watchdog (0 = off)
          watchdog_abort: true        # SIGABRT after a hang dump

Anomaly *detection* happens at the loop's existing sync boundaries (every
``log_every_n_steps``), preserving the dispatch-ahead contract; the
``skip_update`` protection itself is in-graph and therefore zero-latency.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

#: supported anomaly policies, in escalation order
HEALTH_POLICIES = ("dump_and_continue", "skip_update", "halt")


def _health_knobs() -> set[str]:
    """Accepted knob names — derived from the dataclass fields so there is
    exactly one place defaults live."""
    return {f.name for f in dataclasses.fields(HealthConfig)}


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    enabled: bool = False
    policy: str = "dump_and_continue"
    ring_buffer_steps: int = 32
    param_norm: bool = True
    max_bundles: int = 8
    watchdog_timeout_seconds: float = 0.0
    watchdog_abort: bool = True
    # data-stall watchdog (data/loader.py PrefetchIterator): a hung upstream
    # iterator (dead mount, wedged arrow page-in) otherwise blocks the step
    # boundary FOREVER with no diagnosis.  > 0: the data_wait span raises a
    # curated DataStallError after this many seconds and (health enabled)
    # dumps a hang bundle first.  0 disables.  Independent of ``enabled`` —
    # the curated error is useful even without the flight recorder.
    data_wait_timeout_seconds: float = 0.0

    @classmethod
    def from_config(cls, block: Any) -> "HealthConfig":
        """Parse (and validate) an ``exp_manager.telemetry.health`` block.

        Accepts ``None`` (defaults: disabled), a bare bool (``health: true``
        enables with defaults), or a mapping of knobs.  Unknown keys and
        out-of-range values raise ``ValueError`` — a typo'd policy must not
        silently run ``dump_and_continue``.
        """
        if block is None:
            return cls()
        if isinstance(block, bool):
            return cls(enabled=block)
        knobs = _health_knobs()
        if not isinstance(block, Mapping):
            raise ValueError(
                f"exp_manager.telemetry.health must be a mapping of "
                f"{sorted(knobs)} (or a single bool), got "
                f"{type(block).__name__}"
            )
        unknown = set(block) - knobs
        if unknown:
            from neuronx_distributed_training_tpu.config.loader import (
                did_you_mean,
            )

            raise ValueError(
                f"unknown exp_manager.telemetry.health keys {sorted(unknown)}; "
                f"supported: {sorted(knobs)}" + did_you_mean(unknown, knobs)
            )
        values = dict(block)
        policy = str(values.get("policy", cls.policy))
        if policy not in HEALTH_POLICIES:
            raise ValueError(
                f"exp_manager.telemetry.health.policy must be one of "
                f"{'/'.join(HEALTH_POLICIES)}, got {policy!r}"
            )
        for key in ("enabled", "param_norm", "watchdog_abort"):
            if key in values and not isinstance(values[key], bool):
                raise ValueError(
                    f"exp_manager.telemetry.health.{key} must be a boolean, "
                    f"got {values[key]!r}"
                )
        out = cls(
            enabled=bool(values.get("enabled", cls.enabled)),
            policy=policy,
            ring_buffer_steps=int(values.get("ring_buffer_steps",
                                             cls.ring_buffer_steps)),
            param_norm=bool(values.get("param_norm", cls.param_norm)),
            max_bundles=int(values.get("max_bundles", cls.max_bundles)),
            watchdog_timeout_seconds=float(
                values.get("watchdog_timeout_seconds",
                           cls.watchdog_timeout_seconds)),
            watchdog_abort=bool(values.get("watchdog_abort",
                                           cls.watchdog_abort)),
            data_wait_timeout_seconds=float(
                values.get("data_wait_timeout_seconds",
                           cls.data_wait_timeout_seconds)),
        )
        if out.ring_buffer_steps < 1:
            raise ValueError(
                f"exp_manager.telemetry.health.ring_buffer_steps must be >= 1, "
                f"got {out.ring_buffer_steps}"
            )
        if out.max_bundles < 1:
            raise ValueError(
                f"exp_manager.telemetry.health.max_bundles must be >= 1, got "
                f"{out.max_bundles} (disable the recorder with enabled: "
                f"false instead)"
            )
        if out.watchdog_timeout_seconds < 0:
            raise ValueError(
                f"exp_manager.telemetry.health.watchdog_timeout_seconds must "
                f"be >= 0 (0 disables the watchdog), got "
                f"{out.watchdog_timeout_seconds}"
            )
        # NOTE the watchdog needs a bundle-capable monitor to dump through,
        # but health.enabled is no longer the only thing that arms one: the
        # fleet plane, dump-action alert rules, and the fleet control plane
        # all arm a bundle-only monitor.  The cross-block check therefore
        # lives in TelemetryConfig.from_config, which sees every block.
        if out.data_wait_timeout_seconds < 0:
            raise ValueError(
                f"exp_manager.telemetry.health.data_wait_timeout_seconds "
                f"must be >= 0 (0 disables the data-stall watchdog), got "
                f"{out.data_wait_timeout_seconds}"
            )
        return out

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def grad_group_of(path: Any) -> str:
    """Map a grad-tree key path to its layer-group name.

    Grouping rule: drop the leaf name, keep the first two remaining path
    components — ``("layers","attn","qkv","w")`` -> ``layers/attn``,
    ``("embed","embedding")`` -> ``embed``, ``("final_norm","scale")`` ->
    ``final_norm``.  Coarse enough to stay a handful of scalars per step,
    fine enough to localize a blow-up to attention vs MLP vs embedding in
    the forensic bundle.
    """
    parts = [str(getattr(p, "key", getattr(p, "idx", p))) for p in path]
    head = parts[:-1][:2] if len(parts) > 1 else parts
    return "/".join(head).lower() or "params"
