"""Memory observability: live HBM attribution, OOM forensics, measured peaks.

The planner's HBM model (``autotune.cost_model.hbm_breakdown``) *prices*
memory; until now nothing *measured* it live — the only runtime signal was
an optional first-device ``memory_stats()`` watermark.  This module makes
peak HBM, its per-subsystem attribution, and OOM proximity first-class
measured observables (``exp_manager.telemetry.memory``):

- **allocator sampling** — per-device ``memory_stats()`` across the whole
  local mesh at every logging boundary: ``memory/bytes_in_use_max/min/p50``,
  ``memory/peak_hbm_bytes`` (running max of the worst device's watermark),
  ``memory/hbm_headroom_fraction`` (the WORST device's remaining fraction —
  a skewed-stage pp run cannot hide an OOM-bound device behind a roomy
  rank 0).  The metrics flow through every sink and into fleet beacons.

- **live-buffer attribution** — ``jax.profiler.device_memory_profile()``
  captured once inside the configured window, parsed from its pprof-format
  protobuf STDLIB-ONLY (:func:`parse_memory_profile` carries its own
  protobuf wire-format walker — no protobuf dependency), and every live
  buffer attributed to a subsystem (:func:`attribute_profile`).  Donation
  erases allocation-site stacks for persistent state (a donated buffer's
  traceback collapses to the dispatch site), so the attribution JOINS the
  stack-classified pool against the known per-subtree byte totals of the
  live params/opt-state trees (:func:`tree_bytes_by_subsystem` — exact,
  host-side metadata only): params / opt_state(mu·nu) / master / EMA are
  carved out of the dispatch-site pool by their exact sizes, stacks name
  the pipeline chunk-store / MoE workspace / batch / executable classes,
  and what nothing explains is reported ``unattributed`` — never silently
  dropped.  The result is ``memory_summary.json`` beside
  ``trace_summary.json``; the attribution total reconciles with the
  profile's in-use bytes BY CONSTRUCTION.

- **OOM forensics** — a ``RESOURCE_EXHAUSTED`` escaping the step boundary
  dumps a flight-recorder-style ``oom_<step>/`` bundle: the last boundary
  memory samples (the ring), the attribution table, the compile census's
  ``memory_analysis`` bytes, and the planner's predicted HBM breakdown for
  the resolved plan — predicted-vs-actual in one artifact.

- **the loop closed** — ``analysis.perf_contract`` gates measured peaks
  (PC501 growth ratchet, PC502 measured > predicted x calibration band),
  and ``tools/plan.py --calibrate-from memory_summary.json`` feeds the
  measured per-subsystem peaks back into the HBM model's transient
  constants as per-topology calibration ratios
  (``autotune.cost_model.hbm_calibration_from_memory_summary``).

Everything is host-side: zero graph changes, zero extra host syncs between
boundaries (``memory_stats`` is a local allocator query; the one profile
capture happens at a boundary inside the window).  Import stays
stdlib-only (jax is imported lazily inside the samplers) so the report
CLIs can file-path load this module on a login node — the
``metrics_report``/``fleet_monitor`` posture.
"""

from __future__ import annotations

import dataclasses
import gzip
import json
import logging
import time
from pathlib import Path
from typing import Any, Callable, Mapping, Optional

logger = logging.getLogger(__name__)

#: the summary artifact, written next to run_summary.json / trace_summary.json
MEMORY_SUMMARY_NAME = "memory_summary.json"

#: schema version stamped into memory_summary.json
MEMORY_SUMMARY_SCHEMA = 1

#: attribution classes, in render order.  ``params``/``opt_state``/
#: ``master``/``ema`` come from the exact tree-byte join; ``chunk_store``/
#: ``moe_workspace``/``batch``/``executable`` from allocation stacks/labels;
#: ``activations`` is the dispatch-site pool left after the state carve-out
#: (step transients + in-flight outputs); ``unattributed`` is the honest
#: remainder.
SUBSYSTEMS = (
    "params", "opt_state", "master", "ema", "activations",
    "chunk_store", "moe_workspace", "batch", "executable", "unattributed",
)

#: boundary sample records retained for OOM forensics
_RING_STEPS = 32


# ---------------------------------------------------------------------------
# allocator sampling (memory_stats across the local mesh)
# ---------------------------------------------------------------------------


def device_memory_samples(devices) -> list[dict[str, Any]]:
    """Per-device allocator stats: ``[{device, kind, bytes_in_use,
    peak_bytes_in_use, bytes_limit}, ...]``.  Devices whose backend doesn't
    implement ``memory_stats()`` (CPU, older plugins) are skipped — an empty
    list means "no allocator signal", never a crash."""
    out: list[dict[str, Any]] = []
    for d in devices:
        try:
            stats = d.memory_stats() or {}
        except Exception:  # noqa: BLE001 — optional observability
            continue
        if not stats:
            continue
        rec: dict[str, Any] = {
            "device": str(getattr(d, "id", len(out))),
            "kind": str(getattr(d, "device_kind", "?")),
        }
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in stats:
                rec[key] = int(stats[key])
        out.append(rec)
    return out


def _p50(values: list[float]) -> float:
    s = sorted(values)
    return float(s[len(s) // 2])


def memory_metrics(samples: list[Mapping[str, Any]]) -> dict[str, float]:
    """Boundary ``memory/`` metrics from one mesh-wide sample sweep.

    Max/min/p50 across the local devices plus the PEAK device's index —
    the spread is the point: a skewed-stage pp run shows a tight min but an
    OOM-bound max.  Headroom is the WORST device's remaining fraction of
    its allocator limit (absent when no device reports a limit)."""
    in_use = [float(s["bytes_in_use"]) for s in samples
              if s.get("bytes_in_use") is not None]
    if not in_use:
        return {}
    out = {
        "memory/bytes_in_use_max": max(in_use),
        "memory/bytes_in_use_min": min(in_use),
        "memory/bytes_in_use_p50": _p50(in_use),
    }
    peaks = [float(s["peak_bytes_in_use"]) for s in samples
             if s.get("peak_bytes_in_use") is not None]
    if peaks:
        out["memory/peak_bytes_max"] = max(peaks)
    # name the peak device (by allocator watermark when present, else
    # current in-use) as a numeric index the scalar sinks can carry; the
    # summary/bundles keep the string name
    ranked = sorted(
        samples,
        key=lambda s: float(s.get("peak_bytes_in_use",
                                  s.get("bytes_in_use", 0)) or 0),
    )
    try:
        out["memory/peak_device"] = float(ranked[-1]["device"])
    except (TypeError, ValueError):
        pass
    headrooms = []
    for s in samples:
        limit = s.get("bytes_limit")
        if limit:
            headrooms.append(
                1.0 - float(s.get("bytes_in_use", 0)) / float(limit))
    if headrooms:
        out["memory/hbm_headroom_fraction"] = min(headrooms)
        limits = [float(s["bytes_limit"]) for s in samples
                  if s.get("bytes_limit")]
        out["memory/bytes_limit_min"] = min(limits)
    return out


# ---------------------------------------------------------------------------
# pprof protobuf parsing (stdlib-only)
# ---------------------------------------------------------------------------
#
# ``jax.profiler.device_memory_profile()`` returns a gzipped pprof Profile
# protobuf (github.com/google/pprof/proto/profile.proto).  The fields this
# parser walks:
#
#   Profile:  1 sample_type (ValueType) / 2 sample (Sample) / 4 location /
#             5 function / 6 string_table
#   ValueType: 1 type (string idx) / 2 unit (string idx)
#   Sample:    1 location_id (repeated uint64, usually packed) /
#              2 value (repeated int64, usually packed) / 3 label (Label)
#   Label:     1 key (string idx) / 2 str (string idx) / 3 num
#   Location:  1 id / 4 line (Line)
#   Line:      1 function_id / 2 line
#   Function:  1 id / 2 name (string idx) / 4 filename (string idx)


def _varint(buf: bytes, i: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _wire_fields(buf: bytes) -> list[tuple[int, Any]]:
    """Decode one protobuf message into ``[(field_number, value), ...]``;
    length-delimited values stay ``bytes`` for the caller to interpret."""
    i, out = 0, []
    n = len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:          # varint
            v, i = _varint(buf, i)
            out.append((field, v))
        elif wire == 2:        # length-delimited
            ln, i = _varint(buf, i)
            out.append((field, buf[i:i + ln]))
            i += ln
        elif wire == 5:        # fixed32
            out.append((field, int.from_bytes(buf[i:i + 4], "little")))
            i += 4
        elif wire == 1:        # fixed64
            out.append((field, int.from_bytes(buf[i:i + 8], "little")))
            i += 8
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")
    return out


def _packed_varints(v: Any) -> list[int]:
    if not isinstance(v, bytes):
        return [int(v)]
    i, out = 0, []
    while i < len(v):
        x, i = _varint(v, i)
        out.append(x)
    return out


def parse_memory_profile(data: bytes) -> dict[str, Any]:
    """Parse a ``device_memory_profile()`` payload (gzipped or raw pprof)
    into plain dicts::

        {"samples": [{"bytes": int, "count": int,
                      "stack": [fn, ...],          # leaf-first
                      "files": [filename, ...],    # aligned with stack
                      "labels": {"kind": "buffer", "device": "...", ...}},
                     ...],
         "total_bytes": int, "total_count": int,
         "by_device": {device: bytes}}

    The value columns are selected by sample_type name (``space``/bytes and
    ``allocations``/count), not position, so a column reorder in a future
    jax cannot silently swap bytes for counts.
    """
    if data[:2] == b"\x1f\x8b":
        data = gzip.decompress(data)
    top = _wire_fields(data)
    strings: list[str] = []
    for field, v in top:
        if field == 6:
            strings.append(v.decode("utf-8", "replace")
                           if isinstance(v, bytes) else str(v))

    def s(idx: Any) -> str:
        try:
            return strings[int(idx)]
        except (IndexError, TypeError, ValueError):
            return "?"

    # value-column roles from sample_type
    bytes_col = count_col = None
    col = 0
    for field, v in top:
        if field != 1:
            continue
        vt = dict(_wire_fields(v))
        name = s(vt.get(1, 0))
        if name == "space":
            bytes_col = col
        elif name in ("allocations", "objects", "count"):
            count_col = col
        col += 1
    if bytes_col is None:       # fall back to pprof's conventional order
        bytes_col = 1 if col > 1 else 0

    functions: dict[int, tuple[str, str]] = {}
    for field, v in top:
        if field != 5:
            continue
        fn = dict(_wire_fields(v))
        functions[int(fn.get(1, 0))] = (s(fn.get(2, 0)), s(fn.get(4, 0)))

    locations: dict[int, list[tuple[str, str]]] = {}
    for field, v in top:
        if field != 4:
            continue
        loc_id = None
        frames: list[tuple[str, str]] = []
        for f2, v2 in _wire_fields(v):
            if f2 == 1:
                loc_id = int(v2)
            elif f2 == 4:
                line = dict(_wire_fields(v2))
                frames.append(functions.get(int(line.get(1, 0)), ("?", "?")))
        if loc_id is not None:
            locations[loc_id] = frames

    samples: list[dict[str, Any]] = []
    total_bytes = total_count = 0
    by_device: dict[str, int] = {}
    for field, v in top:
        if field != 2:
            continue
        loc_ids: list[int] = []
        values: list[int] = []
        labels: dict[str, Any] = {}
        for f2, v2 in _wire_fields(v):
            if f2 == 1:
                loc_ids.extend(_packed_varints(v2))
            elif f2 == 2:
                values.extend(_packed_varints(v2))
            elif f2 == 3:
                lab = dict(_wire_fields(v2))
                key = s(lab.get(1, 0))
                labels[key] = s(lab[2]) if 2 in lab else lab.get(3)
        stack, files = [], []
        for lid in loc_ids:
            for name, fname in locations.get(lid, ()):
                stack.append(name)
                files.append(fname)
        nbytes = int(values[bytes_col]) if len(values) > bytes_col else 0
        count = (int(values[count_col])
                 if count_col is not None and len(values) > count_col else 0)
        samples.append({"bytes": nbytes, "count": count, "stack": stack,
                        "files": files, "labels": labels})
        total_bytes += nbytes
        total_count += count
        dev = labels.get("device")
        if dev is not None:
            by_device[str(dev)] = by_device.get(str(dev), 0) + nbytes
    return {"samples": samples, "total_bytes": total_bytes,
            "total_count": total_count, "by_device": by_device}


# ---------------------------------------------------------------------------
# attribution: stacks + the exact tree-byte join
# ---------------------------------------------------------------------------

#: ordered (class, frame-token) scope rules — first match wins, specific
#: before generic.  Tokens match against function names; ``file:`` tokens
#: against the frame's filename suffix.  ``dispatch`` is an internal class:
#: buffers whose stack only shows the jit dispatch site (post-donation
#: persistent state AND step transients collapse here — jax's traceback
#: filtering strips internal frames), split afterwards by the tree-byte
#: join.
SCOPE_RULES: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("opt_state", ("init_opt_state",)),
    ("params", ("init_params", "param_builder", "add_lora")),
    ("chunk_store", ("pipeline_loss_and_grad", "pipeline_loss",
                     "to_interleaved", "file:parallel/pipeline.py")),
    ("moe_workspace", ("moe_dropless", "file:ops/moe.py")),
    ("batch", ("sharded_batches", "shard_batch", "device_put",
               "_batched_device_put_impl", "global_batches", "fetch_rows",
               "batched_device_put")),
    ("dispatch", ("cache_miss", "_pjit_call_impl_python",
                  "_python_pjit_helper", "apply_primitive", "fit",
                  "<module>")),
)


def _classify_sample(sample: Mapping[str, Any]) -> str:
    if (sample.get("labels") or {}).get("kind") == "executable":
        return "executable"
    stack = list(sample.get("stack") or ())
    files = list(sample.get("files") or ())
    for cls, tokens in SCOPE_RULES:
        for token in tokens:
            if token.startswith("file:"):
                suffix = token[len("file:"):]
                if any(f.endswith(suffix) for f in files):
                    return cls
            elif any(token in fn for fn in stack):
                return cls
    return "unattributed"


def attribute_profile(
    profile: Mapping[str, Any],
    tree_hints: Optional[Mapping[str, int]] = None,
) -> dict[str, dict[str, int]]:
    """Attribute a parsed profile's live bytes to :data:`SUBSYSTEMS`.

    Stage 1 classifies every sample by its allocation stack
    (:data:`SCOPE_RULES`).  Stage 2 joins the dispatch-site pool against
    ``tree_hints`` — the EXACT addressable byte totals of the live state
    trees (``{"params": b, "opt_state": b, "master": b, "ema": b}``,
    :func:`tree_bytes_by_subsystem`): each state class takes
    ``min(remaining pool, its exact size - whatever stage 1 already found)``
    and the leftover pool is ``activations`` (step transients / in-flight
    outputs).  Without hints the pool itself reports as ``activations``.

    The returned classes PARTITION the profile: their byte (and count)
    totals sum exactly to ``profile["total_bytes"]`` /
    ``["total_count"]`` — the unattributed remainder is a first-class row,
    never a silent drop."""
    out: dict[str, dict[str, int]] = {
        cls: {"bytes": 0, "count": 0} for cls in SUBSYSTEMS}
    pool_bytes = pool_count = 0
    for sample in profile.get("samples") or ():
        cls = _classify_sample(sample)
        if cls == "dispatch":
            pool_bytes += int(sample.get("bytes", 0))
            pool_count += int(sample.get("count", 0))
            continue
        out[cls]["bytes"] += int(sample.get("bytes", 0))
        out[cls]["count"] += int(sample.get("count", 0))
    for cls in ("params", "opt_state", "master", "ema"):
        want = int((tree_hints or {}).get(cls, 0) or 0)
        carve = min(max(want - out[cls]["bytes"], 0), pool_bytes)
        if carve > 0:
            out[cls]["bytes"] += carve
            pool_bytes -= carve
    out["activations"]["bytes"] += pool_bytes
    out["activations"]["count"] += pool_count
    return {cls: rec for cls, rec in out.items()
            if rec["bytes"] or rec["count"]}


def tree_bytes_by_subsystem(params: Any, opt_state: Any) -> dict[str, int]:
    """Exact ADDRESSABLE byte totals of the live state trees, by subsystem
    — pure host-side sharding metadata, no device work.

    Per-leaf bytes are the leaf's per-device shard size
    (``sharding.shard_shape``) times its addressable device count, so the
    totals are directly comparable to the memory profile's all-local-device
    sums (and, divided by the local device count, to the planner's
    per-device ``hbm_breakdown`` categories)."""
    import math

    def leaf_bytes(x: Any) -> int:
        shape = getattr(x, "shape", None)
        if shape is None:
            return 0
        itemsize = getattr(getattr(x, "dtype", None), "itemsize", 4)
        sharding = getattr(x, "sharding", None)
        try:
            shard = sharding.shard_shape(tuple(shape))
            n_local = len(sharding.addressable_devices)
        except Exception:  # noqa: BLE001 — unsharded test doubles
            shard, n_local = tuple(shape), 1
        return int(math.prod(shard)) * int(itemsize) * int(n_local)

    def tree_total(tree: Any) -> int:
        import jax

        return sum(leaf_bytes(x) for x in jax.tree_util.tree_leaves(tree))

    opt = dict(opt_state) if isinstance(opt_state, Mapping) else {}
    out = {"params": tree_total(params)}
    mu_nu = {k: v for k, v in opt.items()
             if k not in ("master", "ema", "health")}
    out["opt_state"] = tree_total(mu_nu)
    for key, cls in (("master", "master"), ("ema", "ema")):
        if key in opt:
            out[cls] = tree_total(opt[key])
    return out


# ---------------------------------------------------------------------------
# the knob block
# ---------------------------------------------------------------------------


def _memory_knobs() -> set[str]:
    return {f.name for f in dataclasses.fields(MemoryConfig)}


@dataclasses.dataclass(frozen=True)
class MemoryConfig:
    """``exp_manager.telemetry.memory`` knob block (validated at config
    load).

    .. code-block:: yaml

        exp_manager:
          telemetry:
            memory:
              enabled: false       # boundary allocator sampling + the window
              start_step: 1        # profile window start (skip step 0: compile)
              num_steps: 3         # window length
              profile: true        # capture device_memory_profile() in-window
              oom_forensics: true  # RESOURCE_EXHAUSTED -> oom_<step>/ bundle
              headroom_alert_fraction: 0.05  # warn when the worst device's
                                             # headroom falls below this
                                             # (0 disables the warning)
    """

    enabled: bool = False
    start_step: int = 1
    num_steps: int = 3
    profile: bool = True
    oom_forensics: bool = True
    headroom_alert_fraction: float = 0.05

    @classmethod
    def from_config(cls, block: Any) -> "MemoryConfig":
        """Accepts ``None`` (defaults: disabled), a bare bool, or a mapping
        of knobs.  Unknown keys raise with a did-you-mean hint — a typo'd
        knob must not silently observe nothing."""
        if block is None:
            return cls()
        if isinstance(block, bool):
            return cls(enabled=block)
        knobs = _memory_knobs()
        if not isinstance(block, Mapping):
            raise ValueError(
                f"exp_manager.telemetry.memory must be a mapping of "
                f"{sorted(knobs)} (or a single bool), got "
                f"{type(block).__name__}"
            )
        unknown = set(block) - knobs
        if unknown:
            from neuronx_distributed_training_tpu.config.loader import (
                did_you_mean,
            )

            raise ValueError(
                f"unknown exp_manager.telemetry.memory keys "
                f"{sorted(unknown)}; supported: {sorted(knobs)}"
                + did_you_mean(unknown, knobs)
            )
        values = dict(block)
        for key in ("enabled", "profile", "oom_forensics"):
            if key in values and not isinstance(values[key], bool):
                raise ValueError(
                    f"exp_manager.telemetry.memory.{key} must be a boolean, "
                    f"got {values[key]!r}"
                )
        out = cls(
            enabled=bool(values.get("enabled", cls.enabled)),
            start_step=int(values.get("start_step", cls.start_step)),
            num_steps=int(values.get("num_steps", cls.num_steps)),
            profile=bool(values.get("profile", cls.profile)),
            oom_forensics=bool(
                values.get("oom_forensics", cls.oom_forensics)),
            headroom_alert_fraction=float(
                values.get("headroom_alert_fraction",
                           cls.headroom_alert_fraction)),
        )
        if out.start_step < 0:
            raise ValueError(
                f"exp_manager.telemetry.memory.start_step must be >= 0, "
                f"got {out.start_step}"
            )
        if out.num_steps < 1:
            raise ValueError(
                f"exp_manager.telemetry.memory.num_steps must be >= 1, "
                f"got {out.num_steps}"
            )
        if not 0.0 <= out.headroom_alert_fraction < 1.0:
            raise ValueError(
                f"exp_manager.telemetry.memory.headroom_alert_fraction must "
                f"be in [0, 1), got {out.headroom_alert_fraction}"
            )
        return out

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# OOM detection
# ---------------------------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED", "Out of memory",
                "out of memory", "OOM")


def is_oom_error(exc: BaseException) -> bool:
    """Does this exception look like a device allocator exhaustion?  The
    backend surfaces OOM as an ``XlaRuntimeError`` whose message carries
    ``RESOURCE_EXHAUSTED`` (TPU/GPU) or ``Out of memory``; the drill
    injector (``trainer.elastic.FaultInjector`` mode=``oom``) raises the
    same marker."""
    msg = f"{type(exc).__name__}: {exc}"
    return any(marker in msg for marker in _OOM_MARKERS)


# ---------------------------------------------------------------------------
# the plane the trainer wires in
# ---------------------------------------------------------------------------


class MemoryPlane:
    """Boundary-cadence allocator sampling + the one windowed profile
    capture + OOM forensics.  Every failure degrades to a warning —
    observability must never kill training."""

    def __init__(
        self,
        cfg: MemoryConfig,
        out_dir: str | Path,
        *,
        devices: Any = None,
        tree_bytes_fn: Optional[Callable[[], Mapping[str, int]]] = None,
        predicted: Optional[Mapping[str, Any]] = None,
        run_facts: Optional[Mapping[str, Any]] = None,
        write_run_summary: Optional[Callable[[dict], None]] = None,
    ) -> None:
        self.cfg = cfg
        self.out_dir = Path(out_dir)
        self.summary_path = self.out_dir / MEMORY_SUMMARY_NAME
        self._devices = devices
        self._tree_bytes_fn = tree_bytes_fn
        self.predicted = dict(predicted) if predicted else None
        self.run_facts = dict(run_facts or {})
        self._write_run_summary = write_run_summary
        self._ring: list[dict[str, Any]] = []
        self._peak_bytes = 0.0
        self._headroom_warned = False
        self.profiled = False
        #: best in-window capture so far: (step, parsed profile, samples)
        self._best: Optional[tuple[int, dict, list]] = None
        self.summary: Optional[dict[str, Any]] = None
        self._oom_dumped = False

    # -- boundary cadence ----------------------------------------------------

    def _local_devices(self) -> list:
        if self._devices is None:
            return []
        devices = self._devices
        if callable(devices):
            devices = devices()
        return list(devices)

    def boundary(self, step: int) -> dict[str, float]:
        """One boundary: sample the local mesh, update the forensic ring +
        running peak, drive the profile window, and return the ``memory/``
        metrics for the sink stream.  Host-side only."""
        if not self.cfg.enabled:
            return {}
        samples = device_memory_samples(self._local_devices())
        metrics = memory_metrics(samples)
        if samples:
            self._ring.append({"step": int(step), "t": round(time.time(), 3),
                               "devices": samples})
            del self._ring[:-_RING_STEPS]
            self._peak_bytes = max(
                self._peak_bytes,
                metrics.get("memory/peak_bytes_max",
                            metrics.get("memory/bytes_in_use_max", 0.0)))
            metrics["memory/peak_hbm_bytes"] = self._peak_bytes
        headroom = metrics.get("memory/hbm_headroom_fraction")
        if (headroom is not None and self.cfg.headroom_alert_fraction > 0
                and headroom < self.cfg.headroom_alert_fraction
                and not self._headroom_warned):
            self._headroom_warned = True
            # only limit-reporting devices can be "near OOM" — a device
            # without a bytes_limit must not be (mis)named in the warning
            worst = min(
                (s for s in samples if s.get("bytes_limit")),
                key=lambda s: 1.0 - float(s.get("bytes_in_use", 0))
                / float(s["bytes_limit"]))
            logger.warning(
                "memory: HBM headroom %.1f%% on device %s (%s) fell below "
                "the %.1f%% alert fraction — OOM proximity; see "
                "memory_summary.json attribution and docs/observability.md "
                "'Memory observability'",
                100 * headroom, worst.get("device"), worst.get("kind"),
                100 * self.cfg.headroom_alert_fraction,
            )
        # the profile window [start_step, start_step + num_steps): every
        # in-window boundary captures and the LARGEST in-use capture wins
        # (the in-window peak); the summary is written when the window
        # passes.  A boundary cadence coarser than the window must not
        # silently skip the capture — the first boundary past it captures
        # late and finalizes immediately.
        if self.cfg.profile and not self.profiled \
                and step >= self.cfg.start_step:
            end = self.cfg.start_step + self.cfg.num_steps
            if step < end:
                self._capture_profile(step, samples)
            else:
                if self._best is None:
                    self._capture_profile(step, samples)
                self._finalize()
        return metrics

    def _capture_profile(self, step: int, samples: list[dict]) -> None:
        try:
            import jax

            payload = jax.profiler.device_memory_profile()
            profile = parse_memory_profile(payload)
        except Exception as e:  # noqa: BLE001 — capture is best-effort
            logger.warning("memory: device_memory_profile capture/parse "
                           "failed: %s", e)
            return
        if self._best is None or profile["total_bytes"] \
                > self._best[1]["total_bytes"]:
            self._best = (int(step), profile, list(samples))

    def _finalize(self) -> None:
        if self.profiled or self._best is None:
            self.profiled = True
            return
        self.profiled = True
        step, profile, samples = self._best
        tree_hints: Optional[dict[str, int]] = None
        if self._tree_bytes_fn is not None:
            try:
                tree_hints = dict(self._tree_bytes_fn())
            except Exception as e:  # noqa: BLE001
                logger.warning("memory: tree-byte hints failed: %s", e)
        attribution = attribute_profile(profile, tree_hints)
        n_dev = max(len(profile.get("by_device") or {}), 1)
        self.summary = {
            "schema": MEMORY_SUMMARY_SCHEMA,
            "window": {"start_step": self.cfg.start_step,
                       "num_steps": self.cfg.num_steps},
            "profiled_step": int(step),
            "profile": {
                "total_bytes": profile["total_bytes"],
                "total_count": profile["total_count"],
                "num_samples": len(profile["samples"]),
                "by_device": profile["by_device"],
                "num_devices": n_dev,
            },
            "attribution": attribution,
            "tree_bytes": tree_hints,
            "sampled": {
                "per_device": samples,
                "peak_hbm_bytes": int(self._peak_bytes) or None,
            },
            "predicted": self.predicted,
            "run_facts": self.run_facts or None,
        }
        try:
            from neuronx_distributed_training_tpu.utils.io import (
                atomic_write_json,
            )

            atomic_write_json(self.summary_path, self.summary)
        except Exception:  # noqa: BLE001 — stdlib fallback (file-path load)
            try:
                tmp = self.summary_path.with_suffix(".tmp")
                tmp.write_text(json.dumps(self.summary, indent=1,
                                          sort_keys=True) + "\n")
                tmp.replace(self.summary_path)
            except OSError as e:
                logger.warning("memory: summary write failed: %s", e)
                return
        if self._write_run_summary is not None:
            try:
                self._write_run_summary({"memory": self.summary_block()})
            except Exception as e:  # noqa: BLE001
                logger.warning("memory: run_summary update failed: %s", e)
        logger.info(
            "memory: profile captured at step %d — %d live buffers, "
            "%.1f MB in use, attribution -> %s",
            step, profile["total_count"] or len(profile["samples"]),
            profile["total_bytes"] / 1e6, self.summary_path,
        )

    def summary_block(self) -> dict[str, Any]:
        """Compact block mirrored into ``run_summary.json``."""
        s = self.summary or {}
        prof = s.get("profile") or {}
        return {
            "profiled_step": s.get("profiled_step"),
            "in_use_bytes": prof.get("total_bytes"),
            "peak_hbm_bytes": int(self._peak_bytes) or None,
            "attribution": {cls: rec.get("bytes")
                            for cls, rec in (s.get("attribution")
                                             or {}).items()},
            "predicted_hbm_bytes": ((self.predicted or {}).get("total")),
            "summary_path": str(self.summary_path),
        }

    # -- teardown / forensics -----------------------------------------------

    def close(self) -> None:
        """Teardown: finalize a still-open window (fit() ended inside it)
        — short runs must still produce a summary."""
        if self.cfg.enabled and self.cfg.profile and not self.profiled:
            if self._best is None:
                samples = device_memory_samples(self._local_devices())
                self._capture_profile(-1, samples)
            self._finalize()

    def dump_oom(
        self,
        step: int,
        exc: BaseException,
        *,
        boundary_metrics: Optional[Mapping[str, Any]] = None,
        memory_analysis: Optional[Mapping[str, Any]] = None,
    ) -> Optional[Path]:
        """Write the ``oom_<step>/`` forensic bundle: the allocator-sample
        ring, the attribution table (last captured — plus a best-effort
        fresh capture: the allocator usually survives the failed
        allocation), the compile census's ``memory_analysis`` bytes, and
        the planner's predicted HBM breakdown.  At most one per process."""
        if not self.cfg.enabled or not self.cfg.oom_forensics \
                or self._oom_dumped:
            return None
        self._oom_dumped = True
        bundle = self.out_dir / f"oom_{int(step):08d}"
        # a fresh profile at death: the failed allocation raised, but live
        # buffers are still registered — this is the attribution that names
        # the culprit.  Never let it mask the bundle write.
        fresh: Optional[dict[str, Any]] = None
        try:
            import jax

            profile = parse_memory_profile(
                jax.profiler.device_memory_profile())
            hints = (dict(self._tree_bytes_fn())
                     if self._tree_bytes_fn is not None else None)
            fresh = {
                "total_bytes": profile["total_bytes"],
                "by_device": profile["by_device"],
                "attribution": attribute_profile(profile, hints),
            }
        except Exception as e:  # noqa: BLE001 — the device may be gone
            logger.warning("memory: post-OOM profile capture failed: %s", e)
        summary = {
            "kind": "oom",
            "step": int(step),
            "error": f"{type(exc).__name__}: {exc}"[:2000],
            "boundary_metrics": {
                k: v for k, v in (boundary_metrics or {}).items()
                if isinstance(v, (int, float)) and v == v
            },
            "attribution": ((self.summary or {}).get("attribution")),
            "attribution_at_death": (fresh or {}).get("attribution"),
            "in_use_bytes_at_death": (fresh or {}).get("total_bytes"),
            "by_device_at_death": (fresh or {}).get("by_device"),
            "tree_bytes": (self.summary or {}).get("tree_bytes"),
            "peak_hbm_bytes": int(self._peak_bytes) or None,
            # predicted-vs-actual in ONE artifact: the planner's breakdown
            # for the resolved plan and the census's compiled bytes
            "predicted_hbm_breakdown": self.predicted,
            "memory_analysis": dict(memory_analysis or {}) or None,
            "run_facts": self.run_facts or None,
        }
        try:
            bundle.mkdir(parents=True, exist_ok=True)
            with open(bundle / "oom.json", "w") as f:
                json.dump(summary, f, indent=1, sort_keys=True)
                f.write("\n")
            with open(bundle / "samples.json", "w") as f:
                json.dump(self._ring, f, indent=1)
                f.write("\n")
        except Exception as e:  # noqa: BLE001 — forensics must not mask the
            # propagating OOM
            logger.warning("memory: oom bundle write failed: %s", e)
            return None
        if self._write_run_summary is not None:
            try:
                self._write_run_summary({"oom": {
                    "step": int(step), "bundle": bundle.name,
                    "error": summary["error"][:300],
                }})
            except Exception as e:  # noqa: BLE001
                logger.warning("memory: oom run_summary update failed: %s", e)
        logger.error(
            "memory: RESOURCE_EXHAUSTED at step %d — OOM forensic bundle "
            "written to %s (attribution, allocator ring, predicted-vs-"
            "actual)", step, bundle,
        )
        return bundle


# ---------------------------------------------------------------------------
# summary loading (the calibration / report surface)
# ---------------------------------------------------------------------------


def load_memory_summary(source: Any) -> dict[str, Any]:
    """A memory summary from any accepted source: the loaded dict, a
    ``memory_summary.json`` path, or a run dir containing one."""
    if isinstance(source, Mapping):
        return dict(source)
    p = Path(source)
    if p.is_dir():
        p = p / MEMORY_SUMMARY_NAME
    doc = json.loads(p.read_text())
    if not isinstance(doc, dict):
        raise ValueError(f"{p}: not a memory summary (expected an object)")
    return doc


def is_memory_summary(doc: Mapping[str, Any]) -> bool:
    """Distinguish a ``memory_summary.json`` payload from a trace summary
    (``tools/plan.py --calibrate-from`` accepts either)."""
    return "attribution" in doc or (
        isinstance(doc.get("profile"), Mapping)
        and "total_bytes" in doc["profile"])


#: attribution class -> the ``hbm_breakdown`` category it measures.  THE
#: one map ``cost_model.hbm_calibration_from_memory_summary`` and
#: ``tools/memory_report.py`` share (two hand-maintained copies of this
#: join would let the report's predicted-vs-measured table silently
#: disagree with the ratios the planner actually applies).  ``opt_state``
#: folds the state classes the model prices together (moments + master +
#: EMA under ``opt_mult``); the pipeline chunk-store calibrates the
#: ``pipeline_rings`` term, the MoE routing workspace the
#: ``gathered_experts`` term.
MEMORY_CLASS_TO_CATEGORY: dict[str, str] = {
    "params": "params",
    "opt_state": "opt_state",
    "master": "opt_state",
    "ema": "opt_state",
    "activations": "activations",
    "chunk_store": "pipeline_rings",
    "moe_workspace": "gathered_experts",
}


def measured_hbm_categories(summary: Mapping[str, Any]
                            ) -> tuple[dict[str, float], Optional[float]]:
    """``(per-device measured bytes by hbm_breakdown category, per-device
    measured peak)`` out of a memory summary — the measured side of every
    predicted-vs-measured consumer (planner calibration, the report's
    table, PC502's facts).

    Tree bytes are exact and beat the stack-derived attribution for the
    state classes; attribution/tree sums span ALL local devices and divide
    by the profile's device count, while ``sampled.peak_hbm_bytes`` is
    ALREADY per-device (the worst single device's allocator watermark) and
    is taken verbatim — only the profile-total fallback divides."""
    n_dev = max(int((summary.get("profile") or {}).get("num_devices", 1)
                    or 1), 1)
    measured_cls: dict[str, float] = {}
    for cls, rec in (summary.get("attribution") or {}).items():
        b = rec.get("bytes") if isinstance(rec, Mapping) else rec
        if b:
            measured_cls[cls] = float(b)
    for cls, b in (summary.get("tree_bytes") or {}).items():
        if b:
            measured_cls[cls] = float(b)
    per_category: dict[str, float] = {}
    for cls, cat in MEMORY_CLASS_TO_CATEGORY.items():
        if measured_cls.get(cls):
            per_category[cat] = per_category.get(cat, 0.0) \
                + measured_cls[cls] / n_dev
    peak = (summary.get("sampled") or {}).get("peak_hbm_bytes")
    if peak:
        peak = float(peak)
    else:
        total = (summary.get("profile") or {}).get("total_bytes")
        peak = float(total) / n_dev if total else None
    return per_category, peak
