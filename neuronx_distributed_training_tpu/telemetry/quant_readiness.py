"""Quantization-readiness analysis over tensorstats dynamic-range telemetry.

Host-side and stdlib-only: takes the cumulative per-layer-group statistics
the in-graph plane (``telemetry.tensorstats``) streamed into a run's
artifacts and SIMULATES block-scaled int8 quantization of each collective
class's payload — the study ROADMAP item 2 (int8/block-scaled compressed
collectives per EQuARX) needs before committing graph changes:

* **what compression would buy** — wire bytes saved per class (a pure
  function of the block size and scale width), joined with the planner's
  per-class byte volumes (``autotune.cost_model.collective_byte_volumes``)
  and, when a ``trace_summary.json`` is present, the MEASURED exposed
  seconds per class (``overlap_by_class``) — so savings are priced in
  exposed step time, not raw bytes;
* **what it would cost in error** — predicted SQNR and RMS relative error
  per layer-group at configurable block sizes, from the log2-exponent
  histograms: for an i.i.d. block of ``B`` elements the block absmax
  exponent is distributed as ``F(e)^B`` (``F`` the per-element exponent
  CDF, zeros counted below the lowest bin), each exponent implies an int8
  scale ``2^(e+1)/127`` (the bin's upper edge bounds the absmax), and
  round-to-nearest contributes ``scale^2/12`` noise variance per element.

The model is deliberately simple enough to hand-check (the unit tests pin a
uniform ``2^-3`` distribution to ``10*log10(12*127^2/4) ~= 46.85 dB``) — it
ranks classes and flags underflow-dominated groups; it does not replace
measuring a real compressed collective.

Collective classes map onto captured phases: ``reduce-scatter`` and
``all-reduce`` carry gradients (the ``pre``-clip phase — what a compressed
grad collective would see); ``all-gather`` carries packed ZeRO-1 bucket
payloads (the ``bucket`` phase, when ``tensorstats.buckets`` was on).
Classes whose payloads are activations (``collective-permute``,
``all-to-all``) still get the bytes/seconds side of the report, with the
error side marked unavailable — the observatory watches optimizer-boundary
tensors only.

CLI: ``tools/quant_readiness.py``.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Mapping, Optional, Sequence

__all__ = [
    "CLASS_PHASE",
    "DEFAULT_BLOCK_SIZES",
    "build_report",
    "bytes_saved_fraction",
    "load_run_dir",
    "pool_groups",
    "predict_block_quant",
]

#: which captured tensorstats phase models each collective class's payload
CLASS_PHASE: dict[str, str] = {
    "reduce-scatter": "pre",
    "all-reduce": "pre",
    "all-gather": "bucket",
}

DEFAULT_BLOCK_SIZES: tuple[int, ...] = (32, 128, 512)

#: int8 payload byte per element
_INT8_BYTES = 1.0
#: fp32 per-block scale
_SCALE_BYTES = 4.0


def bytes_saved_fraction(block_size: int,
                         orig_bytes_per_elem: float = 4.0) -> float:
    """Wire fraction saved by int8 + one fp32 scale per ``block_size``
    elements, vs ``orig_bytes_per_elem`` uncompressed.  Distribution-free."""
    b = int(block_size)
    if b < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    comp = _INT8_BYTES + _SCALE_BYTES / b
    return 1.0 - comp / float(orig_bytes_per_elem)


def predict_block_quant(
    hist: Sequence[int],
    hist_lo_exp: int,
    *,
    count: float,
    sumsq: float,
    zero_count: float = 0.0,
    block_size: int = 128,
    orig_bytes_per_elem: float = 4.0,
) -> dict[str, Any]:
    """Predicted block-scaled int8 quantization quality for one pooled
    distribution.

    ``hist[i]`` counts elements whose ``floor(log2 |x|)`` is
    ``hist_lo_exp + i`` (edge bins absorb the out-of-range tails — exactly
    the in-graph capture's convention).  ``count`` includes zeros;
    ``zero_count`` of them are exact zeros (quantized losslessly; an
    all-zero block has scale 0 and contributes no noise).

    Model: i.i.d. elements; P(block absmax exponent bin <= i) = F(i)^B with
    F the cumulative bin mass (zeros below bin 0); bin i implies scale
    ``2^(hist_lo_exp+i+1)/127``; noise variance per element is the scale's
    ``s^2/12`` weighted by the block-max bin distribution; signal is the
    mean square ``sumsq/count``.
    """
    b = int(block_size)
    if b < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    n = float(count)
    out: dict[str, Any] = {
        "block_size": b,
        "bytes_per_elem": _INT8_BYTES + _SCALE_BYTES / b,
        "bytes_saved_frac": bytes_saved_fraction(b, orig_bytes_per_elem),
        "sqnr_db": None,
        "rel_error_rms": None,
    }
    if n <= 0:
        return out
    nz = float(sum(hist))
    total = max(n, nz + float(zero_count))
    signal = float(sumsq) / n
    # exponent CDF including the zero mass below the lowest bin
    cum = float(zero_count)
    prev_pow = (cum / total) ** b
    noise = 0.0
    for i, c in enumerate(hist):
        cum += float(c)
        cur_pow = (cum / total) ** b
        p_max_bin = cur_pow - prev_pow
        prev_pow = cur_pow
        if p_max_bin <= 0.0:
            continue
        scale = (2.0 ** (hist_lo_exp + i + 1)) / 127.0
        noise += p_max_bin * scale * scale / 12.0
    if signal > 0.0 and noise > 0.0:
        out["sqnr_db"] = round(10.0 * math.log10(signal / noise), 3)
        out["rel_error_rms"] = round(math.sqrt(noise / signal), 9)
    return out


def pool_groups(groups: Mapping[str, Mapping[str, Any]]
                ) -> Optional[dict[str, Any]]:
    """Merge decoded per-group records (``tensorstats.decode_cum`` shape)
    into one pooled distribution: counts/sumsq/zero/hist sum, absmax maxes.
    All groups must share the histogram range.  ``None`` for no groups."""
    pooled: Optional[dict[str, Any]] = None
    for g in groups.values():
        if pooled is None:
            pooled = {
                "count": 0.0, "sumsq": 0.0, "zero": 0.0, "absmax": 0.0,
                "hist_lo_exp": int(g["hist_lo_exp"]),
                "hist_hi_exp": int(g["hist_hi_exp"]),
                "hist": [0] * len(g["hist"]),
            }
        if (int(g["hist_lo_exp"]) != pooled["hist_lo_exp"]
                or len(g["hist"]) != len(pooled["hist"])):
            raise ValueError(
                "cannot pool tensorstats groups with different histogram "
                "ranges — re-run with one hist_lo_exp/hist_hi_exp"
            )
        pooled["count"] += float(g["count"])
        pooled["sumsq"] += float(g["sumsq"])
        pooled["zero"] += float(g.get("zero", 0.0))
        pooled["absmax"] = max(pooled["absmax"], float(g["absmax"]))
        pooled["hist"] = [a + int(c)
                          for a, c in zip(pooled["hist"], g["hist"])]
    return pooled


def _predictions(dist: Mapping[str, Any], block_sizes: Sequence[int],
                 orig_bytes_per_elem: float) -> dict[str, dict[str, Any]]:
    return {
        str(b): predict_block_quant(
            dist["hist"], int(dist["hist_lo_exp"]),
            count=float(dist["count"]), sumsq=float(dist["sumsq"]),
            zero_count=float(dist.get("zero", 0.0)), block_size=b,
            orig_bytes_per_elem=orig_bytes_per_elem,
        )
        for b in block_sizes
    }


def _flatten_volumes(volumes: Optional[Mapping[str, Any]]
                     ) -> dict[str, float]:
    """Accept either kind-keyed bytes or the axis-nested shape
    ``collective_byte_volumes`` returns; fold to kind -> total bytes."""
    out: dict[str, float] = {}
    for k, v in (volumes or {}).items():
        if isinstance(v, Mapping):
            for kind, b in v.items():
                out[kind] = out.get(kind, 0.0) + float(b)
        else:
            out[k] = out.get(k, 0.0) + float(v)
    return out


def build_report(
    tensorstats: Optional[Mapping[str, Any]],
    *,
    block_sizes: Sequence[int] = DEFAULT_BLOCK_SIZES,
    byte_volumes: Optional[Mapping[str, Any]] = None,
    overlap_by_class: Optional[Mapping[str, Any]] = None,
    comms: Optional[Mapping[str, Any]] = None,
    orig_bytes_per_elem: float = 4.0,
) -> dict[str, Any]:
    """The quantization-readiness report: one entry per collective class,
    ranked by what compression would buy in EXPOSED step seconds.

    ``tensorstats`` — a streamed record (``run_summary.json["tensorstats"]``
    or a ``tensorstats.jsonl`` line): ``{"step", "groups": {"<phase>/<group>":
    decoded-cum, ...}}``.  ``byte_volumes`` — planner per-class logical wire
    bytes (``autotune.cost_model.collective_byte_volumes`` shape, or already
    kind-keyed).  ``overlap_by_class`` — the ``trace_summary.json`` section;
    supplies measured exposed seconds per class.  ``comms`` — the run/trace
    summary's ``comms`` section (``telemetry.comms.comms_section``): when a
    class carries a MEASURED achieved bus rate + bus bytes per step, saved
    seconds are priced at that wire rate (saved bus bytes / achieved rate)
    instead of the static exposed-seconds fraction — each class names its
    ``savings_source``.  Savings use the LARGEST block size (most
    aggressive) — the per-block table shows what backing off buys in
    error."""
    block_sizes = tuple(sorted({int(b) for b in block_sizes}))
    if not block_sizes:
        raise ValueError("need at least one block size")
    by_phase: dict[str, dict[str, dict[str, Any]]] = {}
    step = None
    if tensorstats:
        step = tensorstats.get("step")
        for key, rec in (tensorstats.get("groups") or {}).items():
            phase, _, group = str(key).partition("/")
            by_phase.setdefault(phase, {})[group or phase] = rec
    volumes = _flatten_volumes(byte_volumes)
    overlap = dict(overlap_by_class or {})
    comms_classes = dict((comms or {}).get("classes") or {}) \
        if isinstance(comms, Mapping) else {}

    classes: dict[str, dict[str, Any]] = {}
    best_b = block_sizes[-1]
    saved_frac = bytes_saved_fraction(best_b, orig_bytes_per_elem)
    for kind in sorted(set(CLASS_PHASE) | set(volumes) | set(overlap)):
        phase = CLASS_PHASE.get(kind)
        groups = by_phase.get(phase, {}) if phase else {}
        entry: dict[str, Any] = {
            "phase": phase,
            "bytes_per_step": volumes.get(kind),
            "bytes_saved_frac": round(saved_frac, 9),
            "block_size": best_b,
        }
        oc = overlap.get(kind) or {}
        exposed = oc.get("exposed_seconds")
        if exposed is None and oc.get("wire_seconds") is not None:
            exposed = float(oc["wire_seconds"]) \
                - float(oc.get("hidden_seconds", 0.0))
        entry["exposed_seconds"] = exposed
        # saved seconds: prefer the MEASURED wire rate (telemetry.comms —
        # saved bus bytes repriced at the class's achieved bandwidth);
        # fall back to the static assumption that exposed seconds shrink
        # proportionally with bytes.  The source is named either way.
        cc = comms_classes.get(kind)
        rate = None
        bus_bytes = None
        if isinstance(cc, Mapping):
            try:
                rate = float(cc.get("achieved_gbps") or 0.0) * 1e9
                bus_bytes = float(cc.get("bus_bytes_per_step") or 0.0)
            except (TypeError, ValueError):
                rate = bus_bytes = None
        if rate and bus_bytes:
            entry["predicted_seconds_saved"] = round(
                bus_bytes * saved_frac / rate, 9)
            entry["savings_source"] = "measured_wire_rate"
        elif exposed is not None:
            entry["predicted_seconds_saved"] = round(
                max(float(exposed), 0.0) * saved_frac, 9)
            entry["savings_source"] = "static_exposed_fraction"
        else:
            entry["predicted_seconds_saved"] = None
        if volumes.get(kind) is not None:
            entry["bytes_saved_per_step"] = round(
                float(volumes[kind]) * saved_frac, 3)
        if groups:
            pooled = pool_groups(groups)
            entry["pooled"] = _predictions(pooled, block_sizes,
                                           orig_bytes_per_elem)
            entry["per_group"] = {
                g: _predictions(rec, block_sizes, orig_bytes_per_elem)
                for g, rec in sorted(groups.items())
            }
        else:
            entry["note"] = (
                "no captured tensor distribution for this class"
                + ("" if phase else " (activation traffic — the observatory"
                   " watches optimizer-boundary tensors only)"))
        classes[kind] = entry

    def _rank_key(kind: str) -> tuple:
        e = classes[kind]
        s = e.get("predicted_seconds_saved")
        b = e.get("bytes_saved_per_step")
        # measured seconds first, byte volume as the tie-break/fallback
        return (-(s if s is not None else 0.0),
                -(b if b is not None else 0.0), kind)

    return {
        "step": step,
        "block_sizes": list(block_sizes),
        "orig_bytes_per_elem": orig_bytes_per_elem,
        "classes": classes,
        "ranking": sorted(classes, key=_rank_key),
    }


def load_run_dir(run_dir: str | os.PathLike) -> dict[str, Any]:
    """Gather a run directory's quant-readiness inputs: the last streamed
    tensorstats record (``run_summary.json["tensorstats"]`` preferred, else
    the last ``tensorstats.jsonl`` line) and, when present, the trace
    summary's ``overlap_by_class``.  Raises ``FileNotFoundError`` when the
    run carries no tensorstats at all."""
    d = os.fspath(run_dir)
    tensorstats: Optional[dict] = None
    rs = os.path.join(d, "run_summary.json")
    if os.path.exists(rs):
        with open(rs) as f:
            tensorstats = (json.load(f) or {}).get("tensorstats")
    if tensorstats is None:
        tj = os.path.join(d, "tensorstats.jsonl")
        if os.path.exists(tj):
            last = None
            with open(tj) as f:
                for line in f:
                    if line.strip():
                        last = line
            if last is not None:
                tensorstats = json.loads(last)
    if tensorstats is None:
        raise FileNotFoundError(
            f"{d} has no tensorstats telemetry (run_summary.json section or "
            f"tensorstats.jsonl) — enable exp_manager.telemetry.tensorstats "
            f"and re-run"
        )
    overlap = None
    comms = None
    ts_path = os.path.join(d, "trace_summary.json")
    if os.path.exists(ts_path):
        with open(ts_path) as f:
            doc = json.load(f) or {}
        overlap = doc.get("overlap_by_class")
        comms = doc.get("comms")
    if comms is None and os.path.exists(rs):
        with open(rs) as f:
            comms = (json.load(f) or {}).get("comms")
    return {"tensorstats": tensorstats, "overlap_by_class": overlap,
            "comms": comms}
