"""Recompile / retrace detection.

A jitted function silently retraces whenever an argument's abstract shape,
dtype, or tree structure changes — on TPU that is a multi-minute compile that
looks like a hung step, and the classic trigger is a data loader yielding a
ragged final batch.  ``RecompileDetector`` fingerprints the abstract
signature of each named function's arguments on every call (pure host-side
metadata: shapes and dtypes, never values — no device sync) and, when the
signature changes mid-run, logs a warning naming exactly which leaves changed
and how.

This detects the CAUSE (a signature change) at dispatch time rather than the
symptom (a stalled step) minutes later; when the trainer has swapped in an
AOT-compiled step, the same check turns XLA's opaque "argument mismatch"
error into a readable shape diff.
"""

from __future__ import annotations

import logging
from typing import Any

import jax

logger = logging.getLogger(__name__)


def _leaf_sig(x: Any) -> str:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None:
        return f"<{type(x).__name__}>"
    return f"{dtype}[{','.join(str(d) for d in shape)}]"


def _signature(args: tuple) -> dict[str, str]:
    """{leaf path: "dtype[shape]"} over all positional args."""
    out: dict[str, str] = {}
    for i, arg in enumerate(args):
        leaves = jax.tree_util.tree_flatten_with_path(arg)[0]
        for path, leaf in leaves:
            key = f"arg{i}" + "".join(str(p) for p in path)
            out[key] = _leaf_sig(leaf)
    return out


class RecompileDetector:
    """Warns (once per change) when a jitted fn's abstract arg signature
    changes mid-run — the retrace-about-to-happen signal."""

    #: retained event cap — a loader alternating between two signatures fires
    #: every step; the tail is what run_summary.json reports anyway
    MAX_EVENTS = 100

    def __init__(self) -> None:
        self._seen: dict[str, dict[str, str]] = {}
        self._warned: set[str] = set()
        self.events: list[str] = []

    def check(self, name: str, *args: Any) -> bool:
        """Record ``args``' signature under ``name``; returns True (and
        warns with the offending diff — once per distinct diff, so an
        alternating loader can't flood the log) when it changed since the
        last call."""
        sig = _signature(args)
        prev = self._seen.get(name)
        self._seen[name] = sig
        if prev is None or prev == sig:
            return False
        diff = self.describe_diff(prev, sig)
        event = f"{name}: {diff}"
        self.events.append(event)
        del self.events[:-self.MAX_EVENTS]
        if event not in self._warned:
            self._warned.add(event)
            logger.warning(
                "argument signature for %r changed mid-run: a jitted step now "
                "retraces (a full recompile); an AOT-compiled step will "
                "instead reject the call with an argument mismatch — %s",
                name, diff,
            )
        return True

    def signature(self, name: str) -> dict[str, str] | None:
        """The last recorded abstract signature for ``name`` — the batch
        fingerprint the numerics flight recorder ring-buffers per step (pure
        host metadata, shared with the retrace check: one source of truth)."""
        return self._seen.get(name)

    @staticmethod
    def describe_diff(prev: dict[str, str], cur: dict[str, str]) -> str:
        parts: list[str] = []
        for key in sorted(set(prev) | set(cur)):
            a, b = prev.get(key), cur.get(key)
            if a == b:
                continue
            if a is None:
                parts.append(f"{key}: added {b}")
            elif b is None:
                parts.append(f"{key}: removed (was {a})")
            else:
                parts.append(f"{key}: {a} -> {b}")
        if len(parts) > 8:
            parts = parts[:8] + [f"... and {len(parts) - 8} more"]
        return "; ".join(parts) or "tree structure changed"
