"""Host-side monotonic span timing + goodput accounting.

The trainer's wall time used to be one undifferentiated ``step_time``; a slow
data loader, a checkpoint stall, and a genuine step regression all looked the
same.  ``SpanTimer`` decomposes it with named, nestable-free spans measured by
``time.perf_counter`` only — no device sync, no array access — so the loop's
dispatch-ahead contract is untouched:

- ``data_wait``  host blocked on the prefetch iterator
- ``dispatch``   enqueueing the jitted step (NOT device execution time; under
  dispatch-ahead the host returns immediately and the device runs behind)
- ``host_sync``  the boundary metric fetch (the only place device time that
  outran the host gets absorbed)
- ``compile``    first-step lower+compile (when the census runs it explicitly)
- ``validate`` / ``checkpoint`` / ``restart``  non-productive phases

Two accounting windows run in parallel: per-boundary totals (``drain`` — the
``time/<span>`` metrics) and cumulative totals since construction (goodput).
Goodput follows the usual definition: the fraction of wall time spent in
productive training (everything not in a non-productive span), the quantity
that actually predicts time-to-trained-model across restarts and evals.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Iterator

#: spans counted against goodput AND excluded from the throughput window
#: ("replan" is the restart-time autotune re-plan on a changed world size —
#: docs/elasticity.md)
NON_PRODUCTIVE_SPANS = ("compile", "validate", "checkpoint", "restart",
                        "replan")


class SpanTimer:
    """Accumulates named wall-time spans; all methods are host-only."""

    def __init__(self, enabled: bool = True,
                 non_productive: tuple[str, ...] = NON_PRODUCTIVE_SPANS):
        self.enabled = enabled
        self.non_productive = frozenset(non_productive)
        self._since_drain: dict[str, float] = {}
        self._cumulative: dict[str, float] = {}
        self._excluded_since_take = 0.0
        self._t_start = time.perf_counter()

    @contextmanager
    def span(self, name: str) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float) -> None:
        if not self.enabled:
            return
        self._since_drain[name] = self._since_drain.get(name, 0.0) + seconds
        self._cumulative[name] = self._cumulative.get(name, 0.0) + seconds
        if name in self.non_productive:
            self._excluded_since_take += seconds

    def add_preexisting(self, name: str, seconds: float) -> None:
        """Account wall time spent BEFORE this timer existed (the CLI's
        restart-time replan runs before ``fit()`` constructs the timer):
        the span is added AND the wall-clock origin moves back by the same
        amount, so ``goodput_fraction`` keeps ``nonproductive <= wall``."""
        if not self.enabled or seconds <= 0.0:
            return
        self._t_start -= seconds
        self.add(name, seconds)

    # -- per-boundary window -------------------------------------------------

    def drain(self) -> dict[str, float]:
        """Span totals since the last ``drain`` (the ``time/<span>`` metrics)."""
        out, self._since_drain = self._since_drain, {}
        return out

    def snapshot(self) -> dict[str, float]:
        """Non-destructive copy of the cumulative span totals (the
        flight-recorder ring buffer stores one per step; ``drain``'s
        per-boundary window is untouched)."""
        return dict(self._cumulative)

    def take_excluded(self) -> float:
        """Non-productive seconds accumulated since the last take — the wall
        time ``ExpManager.step_timed`` must subtract from its throughput
        window so checkpoint/validation/compile stalls don't contaminate
        steady-state seq/s (and ``throughput_peak`` never records a window
        that includes them)."""
        out, self._excluded_since_take = self._excluded_since_take, 0.0
        return out

    # -- cumulative (goodput) ------------------------------------------------

    @property
    def wall_seconds(self) -> float:
        return time.perf_counter() - self._t_start

    def nonproductive_seconds(self) -> float:
        return sum(v for k, v in self._cumulative.items()
                   if k in self.non_productive)

    def goodput_fraction(self) -> float:
        """productive wall / total wall since construction, in [0, 1]."""
        wall = self.wall_seconds
        if wall <= 0.0:
            return 1.0
        return max(0.0, min(1.0, 1.0 - self.nonproductive_seconds() / wall))

    def goodput_summary(self) -> dict:
        """The ``goodput`` section of ``run_summary.json``."""
        wall = self.wall_seconds
        nonprod = self.nonproductive_seconds()
        return {
            "wall_seconds": round(wall, 3),
            "productive_seconds": round(max(wall - nonprod, 0.0), 3),
            "nonproductive_seconds": round(nonprod, 3),
            "goodput_fraction": round(self.goodput_fraction(), 6),
            "breakdown_seconds": {
                k: round(v, 3)
                for k, v in sorted(self._cumulative.items())
                if k in self.non_productive and v > 0.0
            },
        }
