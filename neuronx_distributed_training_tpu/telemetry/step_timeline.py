"""Pipeline step timelines: a measured per-stage/per-tick Gantt from traces.

The planner *predicts* a bubble fraction per pipeline schedule
(``parallel.pipeline.predicted_bubble_fraction``) and the trace analytics
*measure* collective overlap — but until now nothing measured the bubble
itself, so ROADMAP item 1's success metric ("measured step time within the
calibration band of the per-schedule bubble prediction") was unenforceable.
This module reconstructs the pipeline execution timeline from the very
Chrome traces ``telemetry.trace`` already captures:

- **stage lanes** — each device process lane (``/device:TPU:N``) is one
  pipeline stage's timeline.  Single-process captures (the CPU backend,
  where XLA thunks share the host lane) collapse to one *aggregate* lane:
  the busy/idle split is still measured, per-stage attribution degrades to
  whole-step idle (``lane_resolution: "aggregate"``) — which is exactly
  what makes the path tier-1 testable off hardware.
- **ticks** — the scan tick loop emits one pp-hop collective
  (``utils.debug.AXIS_COLLECTIVE_KINDS['pp']``, collective-permutes) per
  tick; marker *end* times are the tick boundaries, so the per-lane tick
  Gantt falls out of the marker chain inside each ``StepTraceAnnotation``
  window.
- **measured bubble fraction** — idle lane-time over total lane-time inside
  the step windows: ``1 - busy / (lanes x window)``.  Beside the predicted
  fraction it turns the bubble into a *residual* the perf contracts
  (``analysis.perf_contract``, PC301/PC302) can gate.
- **straggler attribution** — the lane with the largest busy time bounds
  the step; its share names the stage to rebalance.

The section lands in ``trace_summary.json`` under ``"pipeline"`` (beside
``achieved_overlap``) whenever the run's schedule facts say pp > 1, and
``bubble_fraction_measured`` is mirrored into ``run_summary.json`` next to
the long-standing ``bubble_fraction_predicted`` run fact.
"""

from __future__ import annotations

from typing import Any, Iterable, Mapping, Optional

from neuronx_distributed_training_tpu.telemetry.trace_analysis import (
    OpEvent,
    _merge_intervals,
    _overlap_us,
    parse_op_events,
    step_windows,
)

#: gantt rows recorded per summary — bounds trace_summary.json growth on
#: long windows (ticks beyond the cap are still COUNTED, just not listed)
MAX_TICK_ROWS = 160


def pipeline_facts(schedule: Optional[str], pp: int, num_microbatches: int,
                   vp: int = 1,
                   bubble_fraction_predicted: Optional[float] = None,
                   ticks_per_step: Optional[Mapping[str, int]] = None
                   ) -> dict[str, Any]:
    """The schedule facts the timeline reconstruction needs — built once by
    the trainer (which already knows them) and threaded through the trace
    capture so the analysis never re-derives scheduling from config.

    ``ticks_per_step`` carries the work-compacted executor's tick counts
    (``parallel.pipeline.WorkTable.tick_counts``) for the manual-vjp
    schedules: on a compacted execution the number of detected ticks is NOT
    the old lockstep trip count — the summary echoes the expected counts so
    a reader can tell compaction from a broken marker chain."""
    out = {
        "schedule": schedule,
        "pp": int(pp),
        "num_microbatches": int(num_microbatches),
        "vp": int(vp or 1),
        "bubble_fraction_predicted": bubble_fraction_predicted,
    }
    if ticks_per_step:
        out["ticks_per_step"] = dict(ticks_per_step)
    return out


def _pp_marker_kinds() -> tuple[str, ...]:
    from neuronx_distributed_training_tpu.utils.debug import (
        AXIS_COLLECTIVE_KINDS,
    )

    return AXIS_COLLECTIVE_KINDS["pp"]


def _category_union(ops: list[OpEvent], pred) -> list[tuple[float, float]]:
    return _merge_intervals([(o.start_us, o.end_us) for o in ops if pred(o)])


def _lane_order(name: str) -> tuple:
    """Natural sort key for device lane names: ``/device:TPU:10`` must rank
    after ``/device:TPU:9``, not after ``/device:TPU:1`` — stage indices
    follow device order, and a lexicographic sort would scramble them on
    any pp >= 10 capture (exactly the deep-pipeline configs this exists
    for)."""
    import re

    parts = re.split(r"(\d+)", name)
    return tuple(int(p) if p.isdigit() else p for p in parts)


def _span_us(merged: list[tuple[float, float]]) -> float:
    return sum(e - s for s, e in merged)


def _lane_ticks(windows: list[tuple[float, float]],
                marker_ends: list[float]) -> list[tuple[float, float]]:
    """Tick intervals for one lane: within each step window, consecutive
    pp-hop marker END times are the boundaries (the hop completes the tick);
    the window edges close the first/last tick."""
    ticks: list[tuple[float, float]] = []
    for ws, we in windows:
        bounds = [ws] + [t for t in marker_ends if ws < t < we] + [we]
        for a, b in zip(bounds, bounds[1:]):
            if b - a > 0:
                ticks.append((a, b))
    return ticks


def analyze_pipeline(events: Iterable[dict], *,
                     facts: Optional[Mapping[str, Any]] = None,
                     max_tick_rows: int = MAX_TICK_ROWS
                     ) -> Optional[dict[str, Any]]:
    """The ``trace_summary.json`` ``"pipeline"`` section, or ``None`` when
    there is nothing to reconstruct (no schedule facts, pp <= 1, or no
    device ops in the window).

    Busy/idle definition: a lane is *busy* while ANY op (compute or
    collective) runs on it — a tick spent waiting on a hop is exactly the
    bubble the lockstep executor is supposed to mask away, so collective
    wire time counts as busy and only true gaps count as idle.  The
    measurement span is the union of the ``StepTraceAnnotation`` windows
    (whole-capture op extent when a caller traced without annotations).
    """
    facts = dict(facts or {})
    pp = int(facts.get("pp", 0) or 0)
    if pp <= 1:
        return None
    events = list(events)
    ops = parse_op_events(events)
    if not ops:
        return None

    by_lane: dict[str, list[OpEvent]] = {}
    for op in ops:
        by_lane.setdefault(op.device, []).append(op)
    lanes = sorted(by_lane, key=_lane_order)

    windows = _merge_intervals(
        [w for wins in step_windows(events).values() for w in wins])
    if not windows:
        windows = [(min(o.start_us for o in ops),
                    max(o.end_us for o in ops))]
    window_us = _span_us(windows)
    if window_us <= 0:
        return None

    marker_kinds = set(_pp_marker_kinds())
    stages: dict[str, dict[str, Any]] = {}
    tick_rows: list[dict[str, Any]] = []
    ticks_total = 0
    busy_total_us = 0.0
    for idx, lane in enumerate(lanes):
        lane_ops = by_lane[lane]
        busy = _category_union(lane_ops, lambda o: True)
        busy_us = sum(_overlap_us(s, e, windows) for s, e in busy)
        compute_us = sum(
            _overlap_us(s, e, windows)
            for s, e in _category_union(lane_ops, lambda o: o.kind is None))
        coll_us = sum(
            _overlap_us(s, e, windows)
            for s, e in _category_union(lane_ops, lambda o: o.kind is not None))
        marker_ends = sorted(
            o.end_us for o in lane_ops if o.kind in marker_kinds)
        ticks = _lane_ticks(windows, marker_ends)
        ticks_total += len(ticks)
        for t, (a, b) in enumerate(ticks):
            if len(tick_rows) >= max_tick_rows:
                break
            tick_busy = sum(_overlap_us(s, e, [(a, b)]) for s, e in busy)
            tick_rows.append({
                "stage": idx,
                "tick": t,
                "start_us": round(a, 3),
                "dur_us": round(b - a, 3),
                "busy_fraction": round(tick_busy / (b - a), 6),
            })
        busy_total_us += busy_us
        stages[lane] = {
            "stage": idx,
            "busy_seconds": round(busy_us / 1e6, 9),
            "idle_seconds": round((window_us - busy_us) / 1e6, 9),
            "busy_fraction": round(busy_us / window_us, 6),
            "compute_seconds": round(compute_us / 1e6, 9),
            "collective_seconds": round(coll_us / 1e6, 9),
            "ticks_detected": len(ticks),
        }

    measured = 1.0 - busy_total_us / (len(lanes) * window_us)
    straggler = max(lanes, key=lambda l: stages[l]["busy_seconds"])
    predicted = facts.get("bubble_fraction_predicted")
    out: dict[str, Any] = {
        "schedule": facts.get("schedule"),
        "pp": pp,
        "num_microbatches": facts.get("num_microbatches"),
        "vp": facts.get("vp", 1),
        "lane_resolution": "device" if len(lanes) > 1 else "aggregate",
        "num_lanes": len(lanes),
        "window_seconds": round(window_us / 1e6, 9),
        "stages": stages,
        "bubble_fraction_measured": round(measured, 6),
        "bubble_fraction_predicted": predicted,
        "straggler_stage": straggler,
        "straggler_busy_fraction": stages[straggler]["busy_fraction"],
        "ticks": tick_rows,
        "ticks_detected": ticks_total,
        "ticks_truncated": ticks_total > len(tick_rows),
    }
    if facts.get("ticks_per_step"):
        # the compacted executor's expected per-step tick counts (schedule
        # table, not a measurement): detected ticks on a compacted run are
        # bounded by the executed hop count, not the lockstep trip count
        out["ticks_per_step"] = dict(facts["ticks_per_step"])
    if predicted is not None:
        out["bubble_residual"] = round(measured - float(predicted), 6)
    return out
