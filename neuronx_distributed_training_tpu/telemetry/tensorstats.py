"""``exp_manager.telemetry.tensorstats`` — the tensor numerics observatory.

The trainer can see time (spans/traces), memory (``telemetry.memory``), and
the fleet, but is blind to the *contents* of the tensors it moves.  This
module is the in-graph half of that missing plane: per layer-group streaming
dynamic-range statistics for the gradients at the optimizer boundary —
absmax, rms, zero/subnormal fraction, and a compact log2-exponent histogram —
computed INSIDE the one jitted train step (``optim.adamw.adamw_update(
tensorstats_cfg=...)``), pre- and post-clip, and optionally for the packed
ZeRO-1 bucket payloads of ``optim.overlap``.

Discipline (shared with ``telemetry.health``):

- the cumulative record lives in ``opt_state["tensorstats"]`` (one packed
  f32 vector per phase x layer-group, see :data:`CUM_HEADER`), so it threads
  step-to-step through the donated state, survives checkpoints, and reaches
  the host for free inside the boundary metric fetch the loop already
  performs — ZERO extra host syncs, zero extra executables;
- the pre-clip rms reuses the per-group squared sums that already produce
  the global clipping norm (``optim.adamw.grouped_sq_norms`` — one reduction
  pass, one source of truth);
- per-step scalars stream under ``tensorstats/<phase>/<group>/<stat>``
  through every scalar sink (metrics.jsonl, the flight-recorder ring, fleet
  beacons, alert rules); the cumulative histogram vectors stream under
  ``tensorstats_hist/<phase>/<group>`` into the dedicated
  ``tensorstats.jsonl`` (``ExpManager.log_tensorstats``) and the
  ``tensorstats`` section of ``run_summary.json`` — NOT through the scalar
  sinks (they are arrays).

The harvested histograms are what ``telemetry.quant_readiness`` (and the
``tools/quant_readiness.py`` CLI) turn into the block-scaled int8
quantization-readiness report ROADMAP item 2 (EQuARX-style compressed
collectives) prices itself from.

Knob block (validated through ``TelemetryConfig.from_config`` at config
load):

.. code-block:: yaml

    exp_manager:
      telemetry:
        tensorstats:
          enabled: false
          pre_clip: true       # grads at the optimizer boundary, pre-clip
          post_clip: true      # same grads after global-norm clipping
          buckets: false       # packed ZeRO-1 bucket payloads (needs
                               # distributed_strategy.overlap bucketing)
          hist_lo_exp: -24     # lowest log2-exponent histogram bin
          hist_hi_exp: 8       # highest bin; edge bins absorb out-of-range

Module import stays stdlib-only (the config parses on login nodes and in
offline tools); jax is imported lazily inside the traced helpers.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Mapping, Optional, Sequence

#: per-step scalar metric prefix — every key under it is float-coercible and
#: rides the ordinary scalar sinks (metrics.jsonl, ring, beacons, alerts)
SCALAR_PREFIX = "tensorstats/"

#: packed cumulative-vector metric prefix — array-valued, routed AROUND the
#: scalar sinks into tensorstats.jsonl.  Deliberately NOT under
#: ``tensorstats/`` so prefix filters on the scalar stream never admit it.
HIST_PREFIX = "tensorstats_hist/"

#: slot names of the packed cumulative vector, before the histogram bins:
#: ``vec = [count, sumsq, absmax, zero, subnormal, hist_0 .. hist_{n-1}]``.
#: count/sumsq/zero/subnormal accumulate across steps; absmax is a running
#: max; hist accumulates per-bin counts of nonzero values by floor(log2|x|).
CUM_HEADER = ("count", "sumsq", "absmax", "zero", "subnormal")

#: phases a stat record can belong to
PHASES = ("pre", "post", "bucket")

_COUNT, _SUMSQ, _ABSMAX, _ZERO, _SUBNORMAL = range(len(CUM_HEADER))


def _tensorstats_knobs() -> set[str]:
    return {f.name for f in dataclasses.fields(TensorStatsConfig)}


@dataclasses.dataclass(frozen=True)
class TensorStatsConfig:
    enabled: bool = False
    pre_clip: bool = True
    post_clip: bool = True
    buckets: bool = False
    hist_lo_exp: int = -24
    hist_hi_exp: int = 8

    @property
    def nbins(self) -> int:
        return self.hist_hi_exp - self.hist_lo_exp + 1

    @property
    def vec_len(self) -> int:
        return len(CUM_HEADER) + self.nbins

    @classmethod
    def from_config(cls, block: Any) -> "TensorStatsConfig":
        """Parse (and validate) an ``exp_manager.telemetry.tensorstats``
        block: ``None`` (defaults: disabled), a bare bool, or a mapping.
        Unknown keys and out-of-range values raise ``ValueError``."""
        if block is None:
            return cls()
        if isinstance(block, bool):
            return cls(enabled=block)
        knobs = _tensorstats_knobs()
        if not isinstance(block, Mapping):
            raise ValueError(
                f"exp_manager.telemetry.tensorstats must be a mapping of "
                f"{sorted(knobs)} (or a single bool), got "
                f"{type(block).__name__}"
            )
        unknown = set(block) - knobs
        if unknown:
            from neuronx_distributed_training_tpu.config.loader import (
                did_you_mean,
            )

            raise ValueError(
                f"unknown exp_manager.telemetry.tensorstats keys "
                f"{sorted(unknown)}; supported: {sorted(knobs)}"
                + did_you_mean(unknown, knobs)
            )
        values = dict(block)
        for key in ("enabled", "pre_clip", "post_clip", "buckets"):
            if key in values and not isinstance(values[key], bool):
                raise ValueError(
                    f"exp_manager.telemetry.tensorstats.{key} must be a "
                    f"boolean, got {values[key]!r}"
                )
        for key in ("hist_lo_exp", "hist_hi_exp"):
            v = values.get(key)
            if v is not None and (isinstance(v, bool)
                                  or not isinstance(v, int)):
                raise ValueError(
                    f"exp_manager.telemetry.tensorstats.{key} must be an "
                    f"integer log2 exponent, got {values[key]!r}"
                )
        out = cls(
            enabled=bool(values.get("enabled", cls.enabled)),
            pre_clip=bool(values.get("pre_clip", cls.pre_clip)),
            post_clip=bool(values.get("post_clip", cls.post_clip)),
            buckets=bool(values.get("buckets", cls.buckets)),
            hist_lo_exp=int(values.get("hist_lo_exp", cls.hist_lo_exp)),
            hist_hi_exp=int(values.get("hist_hi_exp", cls.hist_hi_exp)),
        )
        if out.hist_hi_exp <= out.hist_lo_exp:
            raise ValueError(
                f"exp_manager.telemetry.tensorstats.hist_hi_exp "
                f"({out.hist_hi_exp}) must be > hist_lo_exp "
                f"({out.hist_lo_exp})"
            )
        if out.nbins > 256:
            raise ValueError(
                f"exp_manager.telemetry.tensorstats histogram spans "
                f"{out.nbins} bins ({out.hist_lo_exp}..{out.hist_hi_exp}); "
                f"cap is 256 — the point is a COMPACT record"
            )
        if out.enabled and not (out.pre_clip or out.post_clip or out.buckets):
            raise ValueError(
                "exp_manager.telemetry.tensorstats is enabled but every "
                "phase (pre_clip/post_clip/buckets) is off — nothing to "
                "record; disable it instead"
            )
        return out

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# state layout (opt_state["tensorstats"])
# ---------------------------------------------------------------------------


def state_key(phase: str, group: str) -> str:
    """State-dict key for one phase x layer-group cumulative vector.  Group
    names carry ``/`` (``layers/attn``) which checkpoint path-naming must not
    see — state keys use ``.`` (``pre.layers.attn``); metric keys keep the
    ``/`` spelling."""
    return f"{phase}.{group.replace('/', '.')}"


def split_state_key(key: str) -> tuple[str, str]:
    """Inverse of :func:`state_key` -> ``(phase, group)`` with ``/`` groups."""
    phase, _, rest = key.partition(".")
    return phase, rest.replace(".", "/")


def param_groups(params: Any, group_fn: Optional[Callable] = None
                 ) -> tuple[str, ...]:
    """Sorted layer-group names of a (possibly abstract) params tree under
    ``group_fn`` (default: ``telemetry.health.grad_group_of``)."""
    import jax

    if group_fn is None:
        from neuronx_distributed_training_tpu.telemetry.health import (
            grad_group_of,
        )

        group_fn = grad_group_of
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    return tuple(sorted({group_fn(path) for path, _ in leaves}))


def state_keys(cfg: TensorStatsConfig, groups: Sequence[str],
               bucket_groups: Sequence[str] = ()) -> tuple[str, ...]:
    keys: list[str] = []
    if cfg.pre_clip:
        keys += [state_key("pre", g) for g in groups]
    if cfg.post_clip:
        keys += [state_key("post", g) for g in groups]
    if cfg.buckets:
        keys += [state_key("bucket", g) for g in bucket_groups]
    return tuple(keys)


def init_tensorstats_state(cfg: TensorStatsConfig, params: Any = None, *,
                           groups: Optional[Sequence[str]] = None,
                           bucket_groups: Sequence[str] = ()) -> dict:
    """Fresh cumulative state: a zero packed vector per phase x group plus a
    ``steps`` counter.  ``params`` may be abstract (shapes only)."""
    import jax.numpy as jnp

    if groups is None:
        groups = param_groups(params)
    state: dict[str, Any] = {"steps": jnp.zeros((), jnp.int32)}
    for k in state_keys(cfg, groups, bucket_groups):
        state[k] = jnp.zeros((cfg.vec_len,), jnp.float32)
    return state


def tensorstats_state_specs(cfg: TensorStatsConfig, params: Any = None, *,
                            groups: Optional[Sequence[str]] = None,
                            bucket_groups: Sequence[str] = ()) -> dict:
    """Sharding specs mirroring :func:`init_tensorstats_state` — everything
    replicated (the vectors are tiny)."""
    from jax.sharding import PartitionSpec as P

    if groups is None:
        groups = param_groups(params)
    specs: dict[str, Any] = {"steps": P()}
    for k in state_keys(cfg, groups, bucket_groups):
        specs[k] = P()
    return specs


# ---------------------------------------------------------------------------
# in-graph statistics (traced — called from optim.adamw.adamw_update)
# ---------------------------------------------------------------------------


def leaf_stats_vec(x: Any, cfg: TensorStatsConfig) -> Any:
    """This-step packed stats vector of ONE array (see :data:`CUM_HEADER`).

    Non-finite values: NaN joins neither the zero count nor the histogram
    (``|x| > 0`` is False for NaN) but poisons absmax/sumsq — honest for the
    per-step trajectory; the cumulative merge sanitizes (:func:`merge_cum`).
    +/-inf lands in the top histogram bin.  Subnormal means
    ``0 < |x| < finfo(float32).tiny`` — the stats run on the f32 grads at
    the optimizer boundary."""
    import jax.numpy as jnp

    # stats are computed on the array's NATIVE shape: a reshape(-1) of a
    # sharded input (e.g. the [dp, cols] packed ZeRO-1 bucket payload) would
    # make GSPMD insert an all-to-all reshard just to observe it — the whole
    # reduction below is shape-agnostic
    x = jnp.asarray(x, jnp.float32)
    ax = jnp.abs(x)
    nz = ax > 0
    nzf = nz.astype(jnp.float32)
    absmax = jnp.max(ax)
    sumsq = jnp.sum(x * x)
    zero = jnp.sum((ax == 0).astype(jnp.float32))
    tiny = jnp.float32(jnp.finfo(jnp.float32).tiny)
    subnormal = jnp.sum((nz & (ax < tiny)).astype(jnp.float32))
    # log2-exponent histogram of the nonzero values: bin i counts values with
    # floor(log2|x|) == hist_lo_exp + i; the edge bins absorb out-of-range.
    # NOTE the scatter-add's partitioner prefers replicated updates, a
    # preference that propagates BACKWARD into the grad producers — the
    # grad-accumulation carry is sharding-pinned in trainer/step.py so it
    # cannot tip the loop-carry layout (a broadcast-compare-reduce binning
    # has no such preference but materializes an nbins-times-larger temp)
    e = jnp.floor(jnp.log2(jnp.where(nz, ax, jnp.float32(1.0))))
    idx = jnp.clip(e - cfg.hist_lo_exp, 0, cfg.nbins - 1).astype(jnp.int32)
    hist = jnp.zeros((cfg.nbins,), jnp.float32).at[idx].add(nzf)
    head = jnp.stack([jnp.float32(x.size), sumsq, absmax, zero,
                      subnormal])
    return jnp.concatenate([head, hist])


def merge_step_vecs(a: Any, b: Any) -> Any:
    """Combine two this-step vectors (sum slots add, absmax slot maxes)."""
    import jax.numpy as jnp

    s = a + b
    return s.at[_ABSMAX].set(jnp.maximum(a[_ABSMAX], b[_ABSMAX]))


def group_step_vectors(tree: Any, group_fn: Callable,
                       cfg: TensorStatsConfig, *,
                       group_sq: Optional[Mapping[str, Any]] = None) -> dict:
    """Per layer-group this-step vectors over a grads tree.  ``group_sq`` —
    the per-group squared sums ``optim.adamw.grouped_sq_norms`` already
    computed for the clipping norm — replaces the sumsq slot so the rms
    shares that one reduction pass instead of adding its own."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out: dict[str, Any] = {}
    for path, leaf in leaves:
        g = group_fn(path)
        v = leaf_stats_vec(leaf, cfg)
        out[g] = v if g not in out else merge_step_vecs(out[g], v)
    if group_sq is not None:
        for g, sq in group_sq.items():
            if g in out:
                out[g] = out[g].at[_SUMSQ].set(sq)
    return out


def merge_cum(cum: Any, step_vec: Any) -> Any:
    """Fold one this-step vector into the cumulative record.  Non-finite
    step contributions are dropped (a single NaN step must not poison the
    whole run's distribution — the per-step scalars still show it)."""
    import jax.numpy as jnp

    safe = jnp.where(jnp.isfinite(step_vec), step_vec, jnp.float32(0.0))
    new = cum + safe
    return new.at[_ABSMAX].set(jnp.maximum(cum[_ABSMAX], safe[_ABSMAX]))


def step_scalar_metrics(phase: str, group: str, vec: Any) -> dict:
    """Per-step float-coercible metrics of one this-step vector."""
    import jax.numpy as jnp

    n = jnp.maximum(vec[_COUNT], jnp.float32(1.0))
    base = f"{SCALAR_PREFIX}{phase}/{group}"
    return {
        f"{base}/absmax": vec[_ABSMAX],
        f"{base}/rms": jnp.sqrt(vec[_SUMSQ] / n),
        f"{base}/zero_frac": vec[_ZERO] / n,
        f"{base}/subnormal_frac": vec[_SUBNORMAL] / n,
    }


def tensorstats_update(
    prev_state: Mapping[str, Any],
    cfg: TensorStatsConfig,
    *,
    group_fn: Optional[Callable] = None,
    grads_pre: Any = None,
    grads_post: Any = None,
    group_sq: Optional[Mapping[str, Any]] = None,
    packed: Optional[Mapping[str, Any]] = None,
) -> tuple[dict, dict]:
    """One traced step of the observatory.

    Returns ``(new_state, metrics)``: the updated cumulative state (same
    tree structure as ``prev_state``) and the boundary metrics — per-step
    scalars under :data:`SCALAR_PREFIX` plus the cumulative packed vectors
    under :data:`HIST_PREFIX`.  ``packed`` maps bucket name -> the packed
    ``[dp, cols]`` ZeRO-1 payload buffer."""
    new_state = dict(prev_state)
    new_state["steps"] = prev_state["steps"] + 1
    metrics: dict[str, Any] = {}

    def fold(phase: str, vectors: Mapping[str, Any]) -> None:
        for g, sv in vectors.items():
            key = state_key(phase, g)
            if key not in prev_state:
                raise KeyError(
                    f"tensorstats state has no slot {key!r} — init_opt_state "
                    f"and adamw_update disagree on the layer groups"
                )
            cum = merge_cum(prev_state[key], sv)
            new_state[key] = cum
            metrics.update(step_scalar_metrics(phase, g, sv))
            metrics[f"{HIST_PREFIX}{phase}/{g}"] = cum

    if cfg.pre_clip and grads_pre is not None:
        fold("pre", group_step_vectors(grads_pre, group_fn, cfg,
                                       group_sq=group_sq))
    if cfg.post_clip and grads_post is not None:
        fold("post", group_step_vectors(grads_post, group_fn, cfg))
    if cfg.buckets and packed:
        fold("bucket", {name: leaf_stats_vec(buf, cfg)
                        for name, buf in packed.items()})
    return new_state, metrics


# ---------------------------------------------------------------------------
# host-side decode (boundary fetch -> tensorstats.jsonl / run_summary)
# ---------------------------------------------------------------------------


def decode_cum(vec: Any, cfg_or_lo: Any) -> dict:
    """Decode one fetched packed cumulative vector into the JSON record the
    ``tensorstats.jsonl`` stream and ``run_summary.json`` carry.  Accepts a
    :class:`TensorStatsConfig` or a bare ``hist_lo_exp`` int (the histogram
    length is self-describing).  Stdlib-only — numpy arrays arrive as any
    float-indexable sequence."""
    vals = [float(v) for v in vec]
    head = vals[:len(CUM_HEADER)]
    hist = vals[len(CUM_HEADER):]
    lo = (cfg_or_lo.hist_lo_exp if hasattr(cfg_or_lo, "hist_lo_exp")
          else int(cfg_or_lo))
    count = head[_COUNT]
    rec = {
        "count": count,
        "sumsq": head[_SUMSQ],
        "absmax": head[_ABSMAX],
        "zero": head[_ZERO],
        "subnormal": head[_SUBNORMAL],
        "rms": math.sqrt(head[_SUMSQ] / count) if count > 0 else 0.0,
        "zero_frac": head[_ZERO] / count if count > 0 else 0.0,
        "subnormal_frac": head[_SUBNORMAL] / count if count > 0 else 0.0,
        "hist_lo_exp": lo,
        "hist_hi_exp": lo + len(hist) - 1,
        "hist": [int(h) for h in hist],
    }
    return rec
