"""Windowed device-time trace capture (``exp_manager.telemetry.trace``).

A programmatic ``jax.profiler`` window around a few steady-state steps: the
trainer starts the trace when the loop reaches ``start_step``, stops it
``num_steps`` later, parses the emitted artifacts into the device-time
summary (``telemetry.trace_analysis``), and writes ``trace_summary.json``
next to ``run_summary.json``.  Steps outside the window are untouched — the
capture adds no host syncs and no graph changes, so the AOT-once /
dispatch-ahead contract tests hold with the knob on or off.

.. code-block:: yaml

    exp_manager:
      telemetry:
        trace:
          enabled: false    # the windowed capture (off by default)
          start_step: 1     # first traced step (skip step 0: compile lives there)
          num_steps: 3      # window length
          keep_raw: false   # keep the raw profiler artifacts (TensorBoard's
                            # profile plugin reads them); default: delete
                            # after analysis — the summary is the product

The profiler session is process-global in jax — only one trace can be live.
``start_session``/``stop_session`` guard it with an owner token so the
legacy ``profile_start_step`` window, this capture, and teardown can never
double-start or double-stop it (a ``stop_trace`` on an already-closed
session raises deep in teardown otherwise — the exact hazard the old
``exp_manager`` stop-at-window-end vs stop-at-close pair carried).
"""

from __future__ import annotations

import dataclasses
import logging
import shutil
import threading
from pathlib import Path
from typing import Any, Mapping, Optional

logger = logging.getLogger(__name__)

# -- the process-global profiler session guard ------------------------------

_SESSION_LOCK = threading.Lock()
_SESSION_OWNER: Optional[str] = None


def start_session(log_dir: str, owner: str) -> bool:
    """Start the global ``jax.profiler`` trace for ``owner``.  Returns False
    (and logs) instead of raising when another owner already holds the
    session or the profiler refuses — observability must not kill training."""
    global _SESSION_OWNER
    with _SESSION_LOCK:
        if _SESSION_OWNER is not None:
            logger.warning(
                "profiler trace requested by %r but %r already holds the "
                "session (jax allows one); skipping this window",
                owner, _SESSION_OWNER,
            )
            return False
        import jax

        try:
            jax.profiler.start_trace(str(log_dir))
        except Exception as e:  # noqa: BLE001 — capture is best-effort
            logger.warning("profiler start_trace failed for %r: %s", owner, e)
            return False
        _SESSION_OWNER = owner
        return True


def stop_session(owner: str) -> bool:
    """Stop the global trace IF ``owner`` holds it.  Never raises: a stop
    after the window already closed (or after an out-of-band stop) is a
    logged no-op, not a teardown crash."""
    global _SESSION_OWNER
    with _SESSION_LOCK:
        if _SESSION_OWNER != owner:
            return False
        import jax

        _SESSION_OWNER = None
        try:
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 — e.g. stopped out-of-band
            logger.warning("profiler stop_trace for %r: %s", owner, e)
            return False
        return True


def session_owner() -> Optional[str]:
    with _SESSION_LOCK:
        return _SESSION_OWNER


# -- the knob block ---------------------------------------------------------


def _trace_knobs() -> set[str]:
    return {f.name for f in dataclasses.fields(TraceConfig)}


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    enabled: bool = False
    start_step: int = 1
    num_steps: int = 3
    keep_raw: bool = False

    @classmethod
    def from_config(cls, block: Any) -> "TraceConfig":
        """Parse (and validate) an ``exp_manager.telemetry.trace`` block.

        Accepts ``None`` (defaults: disabled), a bare bool (``trace: true``
        enables the default window), or a mapping of knobs.  Unknown keys
        raise with a did-you-mean hint — a typo'd window must not silently
        trace nothing.
        """
        if block is None:
            return cls()
        if isinstance(block, bool):
            return cls(enabled=block)
        knobs = _trace_knobs()
        if not isinstance(block, Mapping):
            raise ValueError(
                f"exp_manager.telemetry.trace must be a mapping of "
                f"{sorted(knobs)} (or a single bool), got "
                f"{type(block).__name__}"
            )
        unknown = set(block) - knobs
        if unknown:
            from neuronx_distributed_training_tpu.config.loader import (
                did_you_mean,
            )

            raise ValueError(
                f"unknown exp_manager.telemetry.trace keys {sorted(unknown)}; "
                f"supported: {sorted(knobs)}" + did_you_mean(unknown, knobs)
            )
        values = dict(block)
        for key in ("enabled", "keep_raw"):
            if key in values and not isinstance(values[key], bool):
                raise ValueError(
                    f"exp_manager.telemetry.trace.{key} must be a boolean, "
                    f"got {values[key]!r}"
                )
        out = cls(
            enabled=bool(values.get("enabled", cls.enabled)),
            start_step=int(values.get("start_step", cls.start_step)),
            num_steps=int(values.get("num_steps", cls.num_steps)),
            keep_raw=bool(values.get("keep_raw", cls.keep_raw)),
        )
        if out.start_step < 0:
            raise ValueError(
                f"exp_manager.telemetry.trace.start_step must be >= 0, "
                f"got {out.start_step}"
            )
        if out.num_steps < 1:
            raise ValueError(
                f"exp_manager.telemetry.trace.num_steps must be >= 1, "
                f"got {out.num_steps}"
            )
        return out


# -- the windowed capture ---------------------------------------------------


class TraceCapture:
    """Drives one capture window over the training loop's step counter.

    The trainer calls :meth:`maybe_update` once per step (before dispatch,
    same cadence as ``maybe_profile``) and :meth:`close` at teardown; the
    window [start_step, start_step + num_steps) is traced, analyzed, and
    summarized exactly once.  Every failure degrades to a warning.
    """

    _OWNER = "telemetry.trace"

    def __init__(self, cfg: TraceConfig, out_dir: str | Path, *,
                 top_k: int = 15,
                 pipeline: Optional[Mapping[str, Any]] = None):
        self.cfg = cfg
        self.out_dir = Path(out_dir)
        self.raw_dir = self.out_dir / "trace"
        self.summary_path = self.out_dir / "trace_summary.json"
        self.top_k = top_k
        # schedule facts (telemetry.step_timeline.pipeline_facts) — the
        # trainer sets them once the schedule resolves; pp > 1 turns the
        # analyzed summary's "pipeline" section on
        self.pipeline = dict(pipeline) if pipeline else None
        # interconnect facts (telemetry.comms.comms_section inputs) — the
        # trainer sets them once the plan resolves; joining the analyzed
        # per-class wire seconds with the cost model's byte volumes turns
        # the summary's "comms" section (achieved_gbps / efficiency) on
        self.comms: Optional[dict[str, Any]] = None
        self.active = False
        self.done = False
        self.summary: Optional[dict[str, Any]] = None

    def maybe_update(self, step: int) -> Optional[dict[str, Any]]:
        """Advance the window against ``step``; returns the summary dict on
        the call that closes the window, else None."""
        if not self.cfg.enabled or self.done:
            return None
        end = self.cfg.start_step + self.cfg.num_steps
        if not self.active and self.cfg.start_step <= step < end:
            # a refused session (another owner holds the global profiler)
            # is retried at the NEXT in-window step — the window gate
            # bounds retries, and e.g. a legacy profile window may free
            # the session mid-way through ours
            self.active = start_session(str(self.raw_dir), self._OWNER)
            return None
        if self.active and step >= end:
            return self._finish()
        if step >= end:
            self.done = True  # window passed with no session: give up
        return None

    def close(self) -> Optional[dict[str, Any]]:
        """Teardown: close a still-open window (fit() ended inside it) and
        analyze what was captured.  Safe to call repeatedly."""
        if self.active:
            return self._finish()
        return None

    def _finish(self) -> Optional[dict[str, Any]]:
        self.active = False
        self.done = True
        stop_session(self._OWNER)
        try:
            from neuronx_distributed_training_tpu.telemetry.trace_analysis import (
                analyze_trace_dir,
            )

            self.summary = analyze_trace_dir(self.raw_dir, top_k=self.top_k,
                                             pipeline=self.pipeline)
            self.summary["window"] = {
                "start_step": self.cfg.start_step,
                "num_steps": self.cfg.num_steps,
            }
            if self.comms:
                try:
                    from neuronx_distributed_training_tpu.telemetry.comms \
                        import comms_section

                    section = comms_section(
                        self.comms,
                        self.summary.get("overlap_by_class") or {},
                        window_steps=self.cfg.num_steps,
                    )
                    if section:
                        self.summary["comms"] = section
                except Exception as e:  # noqa: BLE001 — telemetry only
                    logger.warning("comms bandwidth join failed: %s", e)
            # atomic (temp + rename): a kill mid-write must not leave torn
            # JSON for the report tools / perf-contract extraction to choke on
            from neuronx_distributed_training_tpu.utils.io import (
                atomic_write_json,
            )

            atomic_write_json(self.summary_path, self.summary)
            logger.info(
                "device-time trace window closed: achieved_overlap=%s "
                "exposed_collective_seconds=%s -> %s",
                self.summary.get("achieved_overlap"),
                self.summary.get("exposed_collective_seconds"),
                self.summary_path,
            )
        except Exception as e:  # noqa: BLE001 — analysis must not kill training
            logger.warning("trace analysis failed: %s", e)
            return None
        finally:
            if not self.cfg.keep_raw:
                shutil.rmtree(self.raw_dir, ignore_errors=True)
        return self.summary


def trace_steps(step_fn, num_steps: int, out_dir: str | Path, *,
                top_k: int = 15, keep_raw: bool = False,
                owner: str = "telemetry.trace_steps",
                pipeline: Optional[Mapping[str, Any]] = None
                ) -> Optional[dict[str, Any]]:
    """Capture ``num_steps`` calls of ``step_fn(step)`` under one trace
    window and return the analyzed summary (None when the profiler session
    is unavailable).  The bench's ``--trace`` path: each call is wrapped in
    a ``StepTraceAnnotation`` so per-step attribution works the same way it
    does inside the trainer."""
    import jax

    out_dir = Path(out_dir)
    if not start_session(str(out_dir), owner):
        if not keep_raw:  # the caller's capture dir must not leak
            shutil.rmtree(out_dir, ignore_errors=True)
        return None
    try:
        for i in range(num_steps):
            with jax.profiler.StepTraceAnnotation("train", step_num=i):
                step_fn(i)
    finally:
        stop_session(owner)
    try:
        from neuronx_distributed_training_tpu.telemetry.trace_analysis import (
            analyze_trace_dir,
        )

        return analyze_trace_dir(out_dir, top_k=top_k, pipeline=pipeline)
    except Exception as e:  # noqa: BLE001 — a failed parse is a None, not a crash
        logger.warning("trace analysis failed: %s", e)
        return None
    finally:
        if not keep_raw:
            shutil.rmtree(out_dir, ignore_errors=True)
