"""Device-time trace analytics: measured compute/comms overlap + attribution.

The host-side telemetry (spans, MFU, goodput) says what was *launched*; the
compile census says what the program *contains*.  Neither says where device
time actually went.  This module closes that gap: it parses the Chrome-trace
artifacts a windowed ``jax.profiler`` capture emits (``telemetry.trace``)
into per-device op timelines and computes the quantities every comms
optimization is judged by:

- **achieved overlap per collective class** — for each collective op
  interval, the fraction *hidden* under concurrent compute on the same
  device (interval intersection against the merged union of that device's
  compute intervals) vs *exposed* (device time the step actually pays).
  Classes are the census's collective kinds (``utils.debug
  .collective_kind_of``), so GA101/GA102 and the autotune cost model's
  per-collective byte volumes line up with what's measured here;
- **a top-K device-time op table** (ops aggregated by base name);
- **per-step device-time attribution** (the ``StepTraceAnnotation`` windows
  the trainer already emits bound each step's share of device time).

Everything is plain-JSON in, plain-JSON out: the parser reads
``*.trace.json(.gz)`` files (the format is shared by CPU, TPU, and committed
test fixtures — the whole path is tier-1 testable off hardware) and the
summary lands in ``trace_summary.json`` next to ``run_summary.json``.
``autotune.cost_model.overlap_from_trace_summary`` turns that file into the
planner's measured-overlap calibration.
"""

from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import os
import re
from typing import Any, Iterable, Mapping, Optional

from neuronx_distributed_training_tpu.utils.debug import (
    COLLECTIVE_KINDS,
    collective_kind_of,
)

#: op names must look like HLO instructions: lowercase mnemonic, optional
#: dash-words, optional ``.N`` uniquifier (``dot.3``, ``reduce-window``,
#: ``all-reduce-start.7``, ``wrapped_convert``).  Runtime/framework events
#: (``TfrtCpuExecutable::Execute``, ``$profiler.py:91 start_trace``,
#: ``PjitFunction(f)``, ``ThreadpoolListener::Record``) never match.
_HLO_NAME_RE = re.compile(r"^%?[a-z][a-z0-9_]*(?:-[a-z0-9_]+)*(?:\.\d+)?$")

#: framework events that pass the name shape test but are not device ops
#: (the StepTraceAnnotation name is caught by its ``step_num`` arg instead,
#: but users may nest other host annotations with op-like names)
_NOT_OPS = frozenset({"train", "transfer", "execute"})

#: async-collective completion halves (``all-reduce-done.3``): neither
#: compute NOR collective wire time — the ``-start`` op carries the wire
#: duration, and counting the ``-done`` wait as compute would fake overlap.
#: Same single-count convention as the census (utils.debug).
_COLLECTIVE_DONE_RE = re.compile(
    r"^(" + "|".join(re.escape(k) for k in COLLECTIVE_KINDS)
    + r")-done(\.\d+)?$"
)


@dataclasses.dataclass(frozen=True)
class OpEvent:
    """One device-timeline op occurrence (microsecond timestamps)."""

    name: str
    start_us: float
    dur_us: float
    device: str          # owning process lane, e.g. "/device:TPU:0"
    kind: Optional[str]  # collective kind, or None for compute

    @property
    def end_us(self) -> float:
        return self.start_us + self.dur_us

    @property
    def base_name(self) -> str:
        return re.sub(r"\.\d+$", "", self.name.lstrip("%"))


def load_trace_events(path: str | os.PathLike) -> list[dict]:
    """All ``traceEvents`` from ``path`` — a single ``.trace.json``/
    ``.trace.json.gz`` file, or a capture directory (searched recursively
    for the ``plugins/profile/<ts>/*.trace.json.gz`` artifacts
    ``jax.profiler.start_trace`` writes).  Raises ``FileNotFoundError``
    when nothing parseable is found — a silent empty summary would read as
    "perfect overlap"."""
    path = os.fspath(path)
    if os.path.isdir(path):
        files = sorted(
            glob.glob(os.path.join(path, "**", "*.trace.json.gz"),
                      recursive=True)
            + glob.glob(os.path.join(path, "**", "*.trace.json"),
                        recursive=True)
        )
    else:
        files = [path]
    events: list[dict] = []
    found = False
    for f in files:
        try:
            opener = gzip.open if f.endswith(".gz") else open
            with opener(f, "rt") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        found = True
        events.extend(doc.get("traceEvents") or [])
    if not found:
        raise FileNotFoundError(
            f"no *.trace.json(.gz) artifacts under {path!r} — did the "
            f"profiler window actually close?"
        )
    return events


def _lane_names(events: Iterable[dict]) -> tuple[dict, dict]:
    """Process/thread display names from the ``ph: 'M'`` metadata events:
    ``(pid -> process name, (pid, tid) -> thread name)``."""
    procs: dict[Any, str] = {}
    threads: dict[tuple, str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        name = (e.get("args") or {}).get("name")
        if not name:
            continue
        if e.get("name") == "process_name":
            procs[e.get("pid")] = str(name)
        elif e.get("name") == "thread_name":
            threads[(e.get("pid"), e.get("tid"))] = str(name)
    return procs, threads


def _is_device_lane(proc_name: str, thread_name: str) -> bool:
    """Does this lane carry device op execution?  TPU traces put ops on
    ``/device:TPU:N`` processes; CPU-backend traces run XLA thunks on the
    host process's ``tf_XLAEigen/...`` worker threads plus the
    ``tf_XLATfrtCpuClient`` dispatch thread (small ops execute inline
    there) — which is what makes the whole analytics path exercisable in
    tier-1 tests."""
    if "/device:" in proc_name:
        return True
    return thread_name.startswith("tf_XLA")


def parse_op_events(events: Iterable[dict]) -> list[OpEvent]:
    """Device-op occurrences out of raw Chrome-trace events.  Keeps complete
    (``ph: 'X'``) events on device lanes whose names look like HLO
    instructions; framework/runtime/host-python events and step annotations
    are dropped (unknown op-name shapes are deliberately IGNORED, not
    errors — profiler vocabularies grow)."""
    events = list(events)
    procs, threads = _lane_names(events)
    out: list[OpEvent] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        name = str(e.get("name") or "")
        if "step_num" in (e.get("args") or {}):
            continue  # StepTraceAnnotation window, handled separately
        bare = name.lstrip("%")
        if not _HLO_NAME_RE.match(name) or bare in _NOT_OPS \
                or _COLLECTIVE_DONE_RE.match(bare):
            continue
        pid, tid = e.get("pid"), e.get("tid")
        proc = procs.get(pid, "")
        if not _is_device_lane(proc, threads.get((pid, tid), "")):
            continue
        try:
            ts, dur = float(e.get("ts", 0.0)), float(e.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        if dur <= 0.0:
            continue
        out.append(OpEvent(
            name=name, start_us=ts, dur_us=dur,
            device=proc or f"pid:{pid}",
            kind=collective_kind_of(name.lstrip("%")),
        ))
    return out


def step_windows(events: Iterable[dict]) -> dict[int, list[tuple[float, float]]]:
    """``step_num -> [(start_us, end_us), ...]`` from the trainer's
    ``StepTraceAnnotation`` events (one window per annotated host call;
    multi-process traces can carry several per step)."""
    out: dict[int, list[tuple[float, float]]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        if "step_num" not in args:
            continue
        try:
            step = int(args["step_num"])
            ts, dur = float(e.get("ts", 0.0)), float(e.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        out.setdefault(step, []).append((ts, ts + dur))
    return out


def _merge_intervals(ivals: list[tuple[float, float]]) -> list[tuple[float, float]]:
    """Sorted disjoint union of ``[(start, end), ...]``."""
    if not ivals:
        return []
    ivals = sorted(ivals)
    merged = [ivals[0]]
    for s, e in ivals[1:]:
        ls, le = merged[-1]
        if s <= le:
            merged[-1] = (ls, max(le, e))
        else:
            merged.append((s, e))
    return merged


def _overlap_us(start: float, end: float,
                merged: list[tuple[float, float]]) -> float:
    """Length of ``[start, end)`` ∩ the merged interval union."""
    import bisect

    if end <= start or not merged:
        return 0.0
    total = 0.0
    i = bisect.bisect_left(merged, (start, float("-inf")))
    if i > 0 and merged[i - 1][1] > start:
        i -= 1
    while i < len(merged) and merged[i][0] < end:
        s, e = merged[i]
        total += max(0.0, min(e, end) - max(s, start))
        i += 1
    return total


def analyze_events(events: Iterable[dict], *, top_k: int = 15,
                   source: Optional[str] = None,
                   pipeline: Optional[Mapping[str, Any]] = None
                   ) -> dict[str, Any]:
    """The full device-time summary (the ``trace_summary.json`` payload).

    Overlap definition: a collective interval's *hidden* device time is its
    intersection with the union of **compute** op intervals on the same
    device lane (concurrent collectives do not hide each other);
    ``exposed = wire - hidden`` and ``achieved_overlap = hidden / wire``
    per collective class and overall.

    ``pipeline`` — the run's schedule facts
    (``telemetry.step_timeline.pipeline_facts``); when they say pp > 1 the
    summary additionally carries the reconstructed ``"pipeline"`` section
    (per-stage busy/idle, tick Gantt, measured bubble fraction).
    """
    events = list(events)
    ops = parse_op_events(events)
    by_device: dict[str, list[OpEvent]] = {}
    for op in ops:
        by_device.setdefault(op.device, []).append(op)

    compute_union: dict[str, list[tuple[float, float]]] = {
        dev: _merge_intervals([(o.start_us, o.end_us)
                               for o in devops if o.kind is None])
        for dev, devops in by_device.items()
    }

    classes: dict[str, dict[str, float]] = {}
    hidden_total = wire_total = 0.0
    for op in ops:
        if op.kind is None:
            continue
        hidden = _overlap_us(op.start_us, op.end_us,
                             compute_union[op.device])
        c = classes.setdefault(op.kind, {
            "count": 0, "wire_us": 0.0, "hidden_us": 0.0})
        c["count"] += 1
        c["wire_us"] += op.dur_us
        c["hidden_us"] += hidden
        wire_total += op.dur_us
        hidden_total += hidden

    overlap_by_class = {
        kind: {
            "count": int(c["count"]),
            "wire_seconds": round(c["wire_us"] / 1e6, 9),
            "hidden_seconds": round(c["hidden_us"] / 1e6, 9),
            "exposed_seconds": round((c["wire_us"] - c["hidden_us"]) / 1e6, 9),
            "achieved_overlap": round(c["hidden_us"] / c["wire_us"], 6)
            if c["wire_us"] > 0 else 0.0,
        }
        for kind, c in sorted(classes.items())
    }

    # top-K device-time table, ops aggregated by base name
    agg: dict[str, dict[str, Any]] = {}
    for op in ops:
        a = agg.setdefault(op.base_name, {
            "op": op.base_name,
            "class": op.kind or "compute",
            "count": 0, "total_us": 0.0})
        a["count"] += 1
        a["total_us"] += op.dur_us
    device_total_us = sum(o.dur_us for o in ops)
    top_ops = sorted(agg.values(), key=lambda a: -a["total_us"])[:top_k]
    top_ops = [
        {
            "op": a["op"], "class": a["class"], "count": a["count"],
            "total_seconds": round(a["total_us"] / 1e6, 9),
            "mean_us": round(a["total_us"] / a["count"], 3),
            "share": round(a["total_us"] / device_total_us, 6)
            if device_total_us > 0 else 0.0,
        }
        for a in top_ops
    ]

    # per-step attribution against the StepTraceAnnotation windows
    steps: dict[str, dict[str, float]] = {}
    for step, windows in sorted(step_windows(events).items()):
        merged = _merge_intervals(windows)
        dev_us = comp_us = coll_us = 0.0
        for op in ops:
            got = _overlap_us(op.start_us, op.end_us, merged)
            dev_us += got
            if op.kind is None:
                comp_us += got
            else:
                coll_us += got
        steps[str(step)] = {
            "device_seconds": round(dev_us / 1e6, 9),
            "compute_seconds": round(comp_us / 1e6, 9),
            "collective_seconds": round(coll_us / 1e6, 9),
        }

    compute_total_us = sum(o.dur_us for o in ops if o.kind is None)
    summary: dict[str, Any] = {
        "source": source,
        "num_events": len(events),
        "num_op_events": len(ops),
        "devices": sorted(by_device),
        "total_device_seconds": round(device_total_us / 1e6, 9),
        "compute_seconds": round(compute_total_us / 1e6, 9),
        "collective_seconds": round(wire_total / 1e6, 9),
        "hidden_collective_seconds": round(hidden_total / 1e6, 9),
        "exposed_collective_seconds": round(
            (wire_total - hidden_total) / 1e6, 9),
        "achieved_overlap": round(hidden_total / wire_total, 6)
        if wire_total > 0 else None,
        "overlap_by_class": overlap_by_class,
        "top_ops": top_ops,
        "steps": steps,
    }
    if pipeline is not None:
        from neuronx_distributed_training_tpu.telemetry.step_timeline import (
            analyze_pipeline,
        )

        section = analyze_pipeline(events, facts=pipeline)
        if section is not None:
            summary["pipeline"] = section
    return summary


def analyze_trace_dir(path: str | os.PathLike, *, top_k: int = 15,
                      pipeline: Optional[Mapping[str, Any]] = None
                      ) -> dict[str, Any]:
    """Parse + analyze a capture directory (or one trace file) in one call."""
    return analyze_events(load_trace_events(path), top_k=top_k,
                          source=os.fspath(path), pipeline=pipeline)


def load_trace_summary(path: str | os.PathLike) -> dict[str, Any]:
    """Read a ``trace_summary.json`` — accepts the file itself, a run dir
    containing one, or a Mapping passed through (the calibration loaders'
    one tolerant entry point)."""
    if isinstance(path, Mapping):
        return dict(path)
    p = os.fspath(path)
    if os.path.isdir(p):
        p = os.path.join(p, "trace_summary.json")
    with open(p) as f:
        return json.load(f)
