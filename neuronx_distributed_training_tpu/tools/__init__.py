"""Offline tooling: checkpoint converters, eval harness."""
