#!/usr/bin/env python
"""KV-cache decode throughput micro-benchmark (single chip).

The training-side counterpart is ``bench.py`` (the driver metric); this
measures the inference path the SFT-evaluation harness uses
(``models/decode.py``: prefill + single-token decode steps), reported as
steady-state decode tokens/sec and prefill tokens/sec.

Llama-3-8B per-layer shapes with the layer count scaled to fit the chip in
bf16 (same proxy convention as bench.py).  Prints ONE JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=512)
    ap.add_argument("--new-tokens", type=int, default=128)
    ap.add_argument("--layers", type=int, default=None)
    ap.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    from neuronx_distributed_training_tpu.models import decode, llama
    from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

    if on_tpu:
        try:
            hbm = dev.memory_stats()["bytes_limit"]
        except Exception:  # noqa: BLE001
            hbm = 16 << 30
        h, ffn, nh, nkv, vocab = 4096, 14336, 32, 8, 128256
        per_layer = h * (nh + 2 * nkv) * (h // nh) + nh * (h // nh) * h + 3 * h * ffn
        # conservative budget (35% of HBM for params): the tunnelled backend
        # surfaces over-allocation only at value materialization, so an
        # optimistic layer count produces fantasy timings instead of an error
        layers = args.layers or max(
            1, min(32, int((hbm * 0.35 / 2 - vocab * h) // per_layer))
        )
        cfg = llama.LlamaConfig(
            vocab_size=vocab, hidden_size=h, intermediate_size=ffn,
            num_layers=layers, num_attention_heads=nh, num_kv_heads=nkv,
            max_position_embeddings=args.prompt_len + args.new_tokens,
            rope_theta=500000.0, tie_word_embeddings=True,
            attention_impl="flash",
        )
    else:
        cfg = llama.LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=352,
            num_layers=args.layers or 2, num_attention_heads=8, num_kv_heads=4,
            max_position_embeddings=args.prompt_len + args.new_tokens,
            tie_word_embeddings=True,
        )
    policy = DtypePolicy.from_precision_config(
        {"type": "bf16SR"} if on_tpu else {"type": "fp32"}
    )
    key = jax.random.PRNGKey(0)
    params = llama.init_params(key, cfg, policy)
    b, plen, n = args.batch, args.prompt_len, args.new_tokens
    total = plen + n
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, plen), 3, cfg.vocab_size)

    prefill = jax.jit(lambda p, i: decode.prefill(p, i, cfg, policy, max_len=total))
    step = jax.jit(lambda p, c, t, pos: decode.decode_step(p, c, t, pos, cfg, policy))

    # warmup/compile
    h_out, cache = prefill(params, ids)
    tok = jnp.full((b,), 5, jnp.int32)
    pos = jnp.full((b,), plen, jnp.int32)
    _, cache_w = step(params, cache, tok, pos)
    jax.block_until_ready((h_out, cache_w["k"]))

    # fresh inputs per run; the timing barrier is a SCALAR FETCH (checksum),
    # not block_until_ready — on the tunnelled backend a failed/deferred
    # execution can pass block_until_ready and report fantasy rates, while a
    # value fetch forces real completion (and surfaces OOM as an error)
    reps = 3
    t0 = time.perf_counter()
    for r in range(reps):
        ids_r = jax.random.randint(
            jax.random.PRNGKey(100 + r), (b, plen), 3, cfg.vocab_size
        )
        h_out, cache = prefill(params, ids_r)
        float(jnp.sum(h_out[:, -1].astype(jnp.float32)))
    prefill_s = (time.perf_counter() - t0) / reps

    t0 = time.perf_counter()
    for i in range(n):
        logits, cache = step(params, cache, tok, pos + i)
    float(jnp.sum(logits.astype(jnp.float32)))  # completion barrier
    decode_s = time.perf_counter() - t0

    out = {
        "metric": "llama3_8B_cached_decode",
        "value": round(b * n / decode_s, 1),
        "unit": "decode_tokens_per_sec",
        "prefill_tokens_per_sec": round(b * plen / prefill_s, 1),
        "ms_per_decode_step": round(decode_s / n * 1000, 3),
        "batch": b, "prompt_len": plen, "new_tokens": n,
        "num_layers": cfg.num_layers,
        "device": dev.device_kind,
        "note": "layer count scaled to single-chip HBM (bench.py convention)",
    }
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
