"""Checkpoint converters: HF Llama/Mixtral <-> native param pytrees.

The reference ships CLI converters over NxD's ``CheckpointConverterBase``
(``checkpoint_converter.py:1-53``: HF full-state <-> TP/PP-sharded xser, GQA
``kv_size_multiplier`` interleaving; ``hf_nxdt_mixtral_ckpt_converter.py:26-91``:
per-expert w1/w2/w3 stacked into fused expert tensors).  TPU-native there is no
rank-sharded file layout to reproduce — the native format is ONE logical pytree
(Orbax shards storage transparently) — so conversion is pure tensor-name/layout
mapping:

HF Llama -> native:
  model.embed_tokens.weight [V,H]            -> embed.embedding [V,H]
  layers.i.self_attn.{q,k,v}_proj.weight     -> layers.attn.qkv.w [i,H,(nh+2kv)d]
  layers.i.self_attn.o_proj.weight [H,H]     -> layers.attn.o.w [i,H,H] (T)
  layers.i.mlp.{gate,up}_proj.weight         -> layers.mlp.gate_up.w [i,H,2F] (T, fused)
  layers.i.mlp.down_proj.weight [H,F]        -> layers.mlp.down.w [i,F,H] (T)
  layers.i.{input,post_attention}_layernorm  -> layers.{input,post_attn}_norm.scale
  model.norm.weight                          -> final_norm.scale
  lm_head.weight [V,H]                       -> lm_head.w [H,V] (T)

Mixtral adds: block_sparse_moe.gate.weight -> mlp.router.w; experts.j.{w1,w3}
stacked+fused -> mlp.experts.gate_up [i,E,H,2F]; w2 -> mlp.experts.down [i,E,F,H].

All weights transpose from torch's [out,in] to the MXU-friendly [in,out].
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

import numpy as np


def _t(x) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(x).T)


def _stack(layers: list[dict[str, Any]]) -> dict[str, Any]:
    """list of per-layer dicts -> dict of stacked arrays (leading layer dim)."""
    out: dict[str, Any] = {}
    for k in layers[0]:
        vals = [l[k] for l in layers]
        if isinstance(vals[0], dict):
            out[k] = _stack(vals)
        else:
            out[k] = np.stack(vals)
    return out


def _unstack(tree: dict[str, Any], i: int) -> dict[str, Any]:
    return {
        k: (_unstack(v, i) if isinstance(v, dict) else np.asarray(v[i]))
        for k, v in tree.items()
    }


def deinterleave_layers(params: Mapping[str, Any], num_layers: int,
                        moe_frequency: int = 1,
                        layout: str | None = None) -> dict[str, Any]:
    """Flatten a pipeline-interleaved ``layers`` stack back to ``[L, ...]``.

    ``layout`` is the authoritative branch when known: checkpoints record
    ``layer_layout`` ("flat" | "interleaved") in their meta JSON
    (``trainer/loop.py`` ``save_checkpoint``) — pass it through and the shape
    heuristic below is only the fallback for metadata-less pytrees
    (ADVICE r2: shape sniffing alone can misfire on exotic leaf shapes).

    Checkpoints trained under virtual pipeline parallelism store layers in the
    ``to_interleaved`` layout ``[vp, pp, Lc, ...]`` (``trainer/loop.py`` keeps
    the training layout in the checkpoint).  Detected per leaf against the
    EXPECTED leading count (``L``, or the group count ``G = L/f`` for grouped
    MoE leaves): interleaved leaves have their first three dims multiply to
    the expected count (``vp*pp*Lc == L``) where flat leaves lead with it —
    unambiguous, since a flat leaf's first three dims multiply to
    ``L * <param dims> > L``.  The reshape is exactly ``from_interleaved``
    (stage-major order).  No-op for already-flat params.
    """

    if layout == "flat":
        return dict(params)
    if layout not in (None, "interleaved"):
        raise ValueError(f"unknown layer layout {layout!r} (flat|interleaved)")

    def flat(x, expect: int):
        x = np.asarray(x)
        if (x.ndim >= 3 and x.shape[0] != expect
                and x.shape[0] * x.shape[1] * x.shape[2] == expect):
            return x.reshape((expect,) + x.shape[3:])
        if layout == "interleaved" and x.shape[0] != expect:
            raise ValueError(
                f"checkpoint meta says layer_layout=interleaved but a leaf "
                f"of shape {x.shape} cannot flatten to {expect} layers"
            )
        return x

    def visit(tree, expect: int):
        result = {}
        for k, v in tree.items():
            if isinstance(v, dict):
                if k == "mlp" and ("moe" in v or "dense" in v):
                    g = num_layers // moe_frequency
                    result[k] = {kk: visit(vv, g) for kk, vv in v.items()}
                else:
                    result[k] = visit(v, expect)
            else:
                result[k] = flat(v, expect)
        return result

    out = dict(params)
    out["layers"] = visit(dict(params["layers"]), num_layers)
    return out


def hf_llama_to_native(state: Mapping[str, Any], cfg) -> dict[str, Any]:
    """HF Llama state_dict (name -> array-like) -> native param pytree.

    ``cfg`` is a ``models.llama.LlamaConfig`` (``fuse_qkv`` must be True — the
    native layout fuses QKV and gate/up, reference ``modeling_llama.py:296-308``).
    """
    g = lambda name: np.asarray(state[name])
    layers = []
    for i in range(cfg.num_layers):
        pre = f"model.layers.{i}."
        qkv = np.concatenate(
            [_t(g(pre + "self_attn.q_proj.weight")),
             _t(g(pre + "self_attn.k_proj.weight")),
             _t(g(pre + "self_attn.v_proj.weight"))], axis=1,
        )
        gate_up = np.concatenate(
            [_t(g(pre + "mlp.gate_proj.weight")), _t(g(pre + "mlp.up_proj.weight"))],
            axis=1,
        )
        layers.append({
            "input_norm": {"scale": g(pre + "input_layernorm.weight")},
            "post_attn_norm": {"scale": g(pre + "post_attention_layernorm.weight")},
            "attn": {"qkv": {"w": qkv}, "o": {"w": _t(g(pre + "self_attn.o_proj.weight"))}},
            "mlp": {"gate_up": {"w": gate_up}, "down": {"w": _t(g(pre + "mlp.down_proj.weight"))}},
        })
    params: dict[str, Any] = {
        "embed": {"embedding": g("model.embed_tokens.weight")},
        "layers": _stack(layers),
        "final_norm": {"scale": g("model.norm.weight")},
    }
    if not cfg.tie_word_embeddings:
        head = state.get("lm_head.weight", state["model.embed_tokens.weight"])
        params["lm_head"] = {"w": _t(np.asarray(head))}
    return params


def native_to_hf_llama(params: Mapping[str, Any], cfg,
                       layer_layout: str | None = None) -> dict[str, np.ndarray]:
    """Native param pytree -> HF Llama state_dict (numpy).

    VPP-trained checkpoints (interleaved ``[vp, pp, Lc, ...]`` layer layout)
    are flattened transparently; pass the checkpoint's recorded
    ``layer_layout`` meta when available."""
    params = deinterleave_layers(params, cfg.num_layers, layout=layer_layout)
    nh, nkv, d = cfg.num_attention_heads, cfg.kv_heads, cfg.head_size
    out: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embed"]["embedding"]),
        "model.norm.weight": np.asarray(params["final_norm"]["scale"]),
    }
    if "lm_head" in params:
        out["lm_head.weight"] = _t(params["lm_head"]["w"])
    for i in range(cfg.num_layers):
        l = _unstack(params["layers"], i)
        pre = f"model.layers.{i}."
        qkv = l["attn"]["qkv"]["w"]  # [H, (nh+2kv)d]
        q, k, v = np.split(qkv, [nh * d, (nh + nkv) * d], axis=1)
        out[pre + "self_attn.q_proj.weight"] = _t(q)
        out[pre + "self_attn.k_proj.weight"] = _t(k)
        out[pre + "self_attn.v_proj.weight"] = _t(v)
        out[pre + "self_attn.o_proj.weight"] = _t(l["attn"]["o"]["w"])
        gate, up = np.split(l["mlp"]["gate_up"]["w"], 2, axis=1)
        out[pre + "mlp.gate_proj.weight"] = _t(gate)
        out[pre + "mlp.up_proj.weight"] = _t(up)
        out[pre + "mlp.down_proj.weight"] = _t(l["mlp"]["down"]["w"])
        out[pre + "input_layernorm.weight"] = l["input_norm"]["scale"]
        out[pre + "post_attention_layernorm.weight"] = l["post_attn_norm"]["scale"]
    return out


def hf_mixtral_to_native(state: Mapping[str, Any], cfg) -> dict[str, Any]:
    """HF Mixtral state_dict -> native pytree (fused expert stacking,
    the reference's ``hf_nxdt_mixtral_ckpt_converter.py:40-60`` role).

    ``moe_frequency > 1`` (interleaved dense/MoE): HF layer ``i`` is MoE iff
    ``i % f == 0`` (``block_sparse_moe.*`` keys); dense layers use the Llama
    ``mlp.{gate,up,down}_proj`` names.  Native ``layers.mlp`` becomes the
    grouped ``{"moe": [G, ...], "dense": [G, f-1, ...]}`` layout that
    ``mixtral.init_params`` produces.
    """
    lc, e = cfg.llama, cfg.moe.num_experts
    f = getattr(cfg, "moe_frequency", 1)
    g = lambda name: np.asarray(state[name])
    layers = []
    moe_mlps, dense_mlps = [], []
    for i in range(lc.num_layers):
        pre = f"model.layers.{i}."
        qkv = np.concatenate(
            [_t(g(pre + "self_attn.q_proj.weight")),
             _t(g(pre + "self_attn.k_proj.weight")),
             _t(g(pre + "self_attn.v_proj.weight"))], axis=1,
        )
        if i % f == 0:
            gate_up = np.stack([
                np.concatenate(
                    [_t(g(pre + f"block_sparse_moe.experts.{j}.w1.weight")),
                     _t(g(pre + f"block_sparse_moe.experts.{j}.w3.weight"))], axis=1)
                for j in range(e)
            ])  # [E, H, 2F]
            down = np.stack([
                _t(g(pre + f"block_sparse_moe.experts.{j}.w2.weight"))
                for j in range(e)
            ])  # [E, F, H]
            mlp = {
                "router": {"w": _t(g(pre + "block_sparse_moe.gate.weight"))},
                "experts": {"gate_up": gate_up, "down": down},
            }
            moe_mlps.append(mlp)
        else:
            mlp = {
                "gate_up": {"w": np.concatenate(
                    [_t(g(pre + "mlp.gate_proj.weight")),
                     _t(g(pre + "mlp.up_proj.weight"))], axis=1)},
                "down": {"w": _t(g(pre + "mlp.down_proj.weight"))},
            }
            dense_mlps.append(mlp)
        layers.append({
            "input_norm": {"scale": g(pre + "input_layernorm.weight")},
            "post_attn_norm": {"scale": g(pre + "post_attention_layernorm.weight")},
            "attn": {"qkv": {"w": qkv}, "o": {"w": _t(g(pre + "self_attn.o_proj.weight"))}},
        })
    stacked = _stack(layers)
    if f == 1:
        stacked["mlp"] = _stack(moe_mlps)
    else:
        gcount = lc.num_layers // f

        def regroup(tree):  # [L - G, ...] leaves -> [G, f-1, ...]
            return {
                k: (regroup(v) if isinstance(v, dict)
                    else v.reshape((gcount, f - 1) + v.shape[1:]))
                for k, v in tree.items()
            }

        stacked["mlp"] = {
            "moe": _stack(moe_mlps),
            "dense": regroup(_stack(dense_mlps)),
        }
    params: dict[str, Any] = {
        "embed": {"embedding": g("model.embed_tokens.weight")},
        "layers": stacked,
        "final_norm": {"scale": g("model.norm.weight")},
    }
    if not lc.tie_word_embeddings:
        params["lm_head"] = {"w": _t(g("lm_head.weight"))}
    return params


def native_to_hf_mixtral(params: Mapping[str, Any], cfg,
                         layer_layout: str | None = None) -> dict[str, np.ndarray]:
    """Native Mixtral pytree -> HF state_dict (inverse of
    ``hf_mixtral_to_native``; the reference's nxdt->HF direction,
    ``hf_nxdt_mixtral_ckpt_converter.py:62-91``).  Handles the grouped
    ``moe_frequency > 1`` layout (dense layers emit Llama ``mlp.*`` names)
    and flattens VPP-interleaved checkpoints transparently; pass the
    checkpoint's recorded ``layer_layout`` meta when available."""
    lc, e = cfg.llama, cfg.moe.num_experts
    freq = getattr(cfg, "moe_frequency", 1)
    params = deinterleave_layers(params, lc.num_layers, freq,
                                 layout=layer_layout)
    nh, nkv, d = lc.num_attention_heads, lc.kv_heads, lc.head_size
    f = lc.intermediate_size
    out: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embed"]["embedding"]),
        "model.norm.weight": np.asarray(params["final_norm"]["scale"]),
    }
    if "lm_head" in params:  # tied checkpoints simply have no head tensor
        out["lm_head.weight"] = _t(params["lm_head"]["w"])
    shared = {k: v for k, v in params["layers"].items() if k != "mlp"}
    mlp_tree = params["layers"]["mlp"]

    def emit_moe(pre: str, mlp) -> None:
        out[pre + "block_sparse_moe.gate.weight"] = _t(mlp["router"]["w"])
        gate_up = mlp["experts"]["gate_up"]  # [E, H, 2F]
        down = mlp["experts"]["down"]  # [E, F, H]
        for j in range(e):
            w1, w3 = np.split(np.asarray(gate_up[j]), [f], axis=1)
            out[pre + f"block_sparse_moe.experts.{j}.w1.weight"] = _t(w1)
            out[pre + f"block_sparse_moe.experts.{j}.w3.weight"] = _t(w3)
            out[pre + f"block_sparse_moe.experts.{j}.w2.weight"] = _t(down[j])

    def emit_dense(pre: str, mlp) -> None:
        gate, up = np.split(np.asarray(mlp["gate_up"]["w"]), 2, axis=1)
        out[pre + "mlp.gate_proj.weight"] = _t(gate)
        out[pre + "mlp.up_proj.weight"] = _t(up)
        out[pre + "mlp.down_proj.weight"] = _t(mlp["down"]["w"])

    for i in range(lc.num_layers):
        pre = f"model.layers.{i}."
        lp = _unstack(shared, i)
        out[pre + "input_layernorm.weight"] = lp["input_norm"]["scale"]
        out[pre + "post_attention_layernorm.weight"] = lp["post_attn_norm"]["scale"]
        qkv_t = _t(lp["attn"]["qkv"]["w"])  # [(nh+2kv)d, H]
        q, k, v = np.split(qkv_t, [nh * d, (nh + nkv) * d], axis=0)
        out[pre + "self_attn.q_proj.weight"] = q
        out[pre + "self_attn.k_proj.weight"] = k
        out[pre + "self_attn.v_proj.weight"] = v
        out[pre + "self_attn.o_proj.weight"] = _t(lp["attn"]["o"]["w"])
        if freq == 1:
            emit_moe(pre, _unstack(mlp_tree, i))
        elif i % freq == 0:
            emit_moe(pre, _unstack(mlp_tree["moe"], i // freq))
        else:
            grp = _unstack(mlp_tree["dense"], i // freq)
            emit_dense(pre, _unstack(grp, i % freq - 1))
    return out


def load_torch_state_dict(path: str) -> dict[str, np.ndarray]:
    """Load an HF checkpoint dir/file (safetensors or torch .bin) as numpy."""
    from pathlib import Path

    p = Path(path)
    files: list[Path]
    if p.is_dir():
        files = sorted(p.glob("*.safetensors")) or sorted(p.glob("pytorch_model*.bin"))
        if not files:
            raise FileNotFoundError(f"no safetensors/bin files under {p}")
    else:
        files = [p]
    state: dict[str, np.ndarray] = {}
    for f in files:
        if f.suffix == ".safetensors":
            from safetensors.numpy import load_file

            state.update(load_file(str(f)))
        else:
            import torch

            sd = torch.load(str(f), map_location="cpu", weights_only=True)
            state.update({k: v.numpy() for k, v in sd.items()})
    return state
