"""NeMo-Megatron (NNM) checkpoint converter: Megatron-named state dicts <->
native GPT pytrees.

The reference ships ``nnm_model_ckpt_to_nxdt_model_ckpt_converter.py`` (205
LoC): it walks ``tp_rank_XX_pp_rank_XXX/model_optim_rng.ckpt`` shard files,
offsets layer indices by ``pp_rank * layers_per_stage``, and re-serializes
per-rank xser files.  TPU-native there is no rank-sharded file layout — the
native format is ONE logical pytree (Orbax shards storage transparently) — so
the converter has two independent stages:

1. ``merge_nnm_shards``: dict[(tp_rank, pp_rank)] of Megatron-sharded state
   dicts -> one full Megatron-named state dict (concat TP shards on the
   parallel dim, offset PP-local layer indices) — replacing the reference's
   rank-file loop;
2. ``megatron_gpt_to_native`` / ``native_to_megatron_gpt``: pure name/layout
   mapping between Megatron naming (``language_model.encoder.layers.N...``)
   and the native stacked-layer pytree (``models.gpt``), including the
   QKV head-group de-interleave (Megatron stores per-group [q..q, k, v]; the
   native fused qkv is [all Q | all K | all V]).

All weights transpose from torch's [out, in] to the MXU-friendly [in, out].
"""

from __future__ import annotations

import re
from typing import Any, Mapping

import numpy as np

from neuronx_distributed_training_tpu.tools.convert import _stack, _t, _unstack

_LAYER_RE = re.compile(r"(\.layers\.)(\d+)(\.)")


def _norm_key(k: str) -> str:
    """Normalize prefixes: ``model.language_model...`` -> ``language_model...``
    (the reference strips the same prefix, converter ``:145``)."""
    if k.startswith("model."):
        k = k[len("model."):]
    return k


def _offset_layer(k: str, offset: int) -> str:
    m = _LAYER_RE.search(k)
    if not m:
        return k
    return k[: m.start(2)] + str(int(m.group(2)) + offset) + k[m.end(2):]


def _deinterleave_qkv(w: np.ndarray, nh: int, nkv: int, d: int):
    """Megatron fused qkv [(nkv*(q_per+2))*d, ...] -> (q [nh*d,...], k, v).

    Megatron groups by kv head: for each of the ``nkv`` groups the rows are
    ``q_per`` query heads then one K then one V head (reference
    ``transformer.py:470-777`` ParallelAttention layout).
    """
    q_per = nh // nkv
    g = w.reshape((nkv, q_per + 2, d) + w.shape[1:])
    q = g[:, :q_per].reshape((nh * d,) + w.shape[1:])
    k = g[:, q_per].reshape((nkv * d,) + w.shape[1:])
    v = g[:, q_per + 1].reshape((nkv * d,) + w.shape[1:])
    return q, k, v


def _interleave_qkv(q: np.ndarray, k: np.ndarray, v: np.ndarray,
                    nh: int, nkv: int, d: int) -> np.ndarray:
    q_per = nh // nkv
    tail = q.shape[1:]
    qg = q.reshape((nkv, q_per, d) + tail)
    kg = k.reshape((nkv, 1, d) + tail)
    vg = v.reshape((nkv, 1, d) + tail)
    return np.concatenate([qg, kg, vg], axis=1).reshape(
        (nkv * (q_per + 2) * d,) + tail
    )


def megatron_gpt_to_native(state: Mapping[str, Any], cfg) -> dict[str, Any]:
    """Full (unsharded) Megatron-named state dict -> native GPT param pytree.

    ``cfg`` is a ``models.gpt.GPTConfig``.  Accepts both ``model.language_model``
    and ``language_model`` prefixes.
    """
    st = {_norm_key(k): np.asarray(v) for k, v in state.items()}
    g = lambda name: st["language_model." + name]
    nh, nkv, d = cfg.num_attention_heads, cfg.kv_heads, cfg.head_size

    def norm(prefix):
        out = {"scale": g(prefix + ".weight")}
        if cfg.normalization == "layernorm":
            out["bias"] = g(prefix + ".bias")
        return out

    layers = []
    for i in range(cfg.num_layers):
        pre = f"encoder.layers.{i}."
        qw, kw, vw = _deinterleave_qkv(
            g(pre + "self_attention.query_key_value.weight"), nh, nkv, d
        )
        attn = {
            "qkv": {"w": _t(np.concatenate([qw, kw, vw], axis=0))},
            "o": {"w": _t(g(pre + "self_attention.dense.weight"))},
        }
        if cfg.bias:
            qb, kb, vb = _deinterleave_qkv(
                g(pre + "self_attention.query_key_value.bias"), nh, nkv, d
            )
            attn["qkv"]["bias"] = np.concatenate([qb, kb, vb], axis=0)
            attn["o"]["bias"] = g(pre + "self_attention.dense.bias")
        mlp = {
            "up": {"w": _t(g(pre + "mlp.dense_h_to_4h.weight"))},
            "down": {"w": _t(g(pre + "mlp.dense_4h_to_h.weight"))},
        }
        if cfg.bias:
            mlp["up"]["bias"] = g(pre + "mlp.dense_h_to_4h.bias")
            mlp["down"]["bias"] = g(pre + "mlp.dense_4h_to_h.bias")
        layer = {
            "input_norm": norm(pre + "input_layernorm"),
            "post_attn_norm": norm(pre + "post_attention_layernorm"),
            "attn": attn,
            "mlp": mlp,
        }
        if getattr(cfg, "transformer_block_type", "pre_ln") == "normformer":
            # reference normformer extras (transformer.py:1638-1644, 181-198)
            layer["nf_attn_norm"] = norm(pre + "post_attention_normformer_norm")
            layer["nf_mlp_norm"] = norm(pre + "mlp.normalization")
        layers.append(layer)

    params: dict[str, Any] = {
        "embed": {"embedding": g("embedding.word_embeddings.weight")},
        "layers": _stack(layers),
    }
    if getattr(cfg, "transformer_block_type", "pre_ln") != "post_ln":
        # post_ln has no final layernorm (reference transformer.py:2478)
        params["final_norm"] = norm("encoder.final_layernorm")
    if cfg.position_embedding_type == "learned_absolute":
        params["pos_embed"] = {
            "embedding": g("embedding.position_embeddings.weight")
        }
    if getattr(cfg, "num_tokentypes", 0) > 0:
        params["tokentype_embed"] = {
            "embedding": g("embedding.tokentype_embeddings.weight")
        }
    if not cfg.share_embeddings_and_output_weights:
        params["lm_head"] = {"w": _t(g("output_layer.weight"))}
    return params


def native_to_megatron_gpt(params: Mapping[str, Any], cfg,
                           layer_layout: str | None = None) -> dict[str, np.ndarray]:
    """Inverse of ``megatron_gpt_to_native`` (export / parity testing).

    VPP-interleaved checkpoints flatten transparently; pass the checkpoint's
    recorded ``layer_layout`` meta when available (same contract as
    ``convert.native_to_hf_llama``)."""
    from neuronx_distributed_training_tpu.tools.convert import deinterleave_layers

    params = deinterleave_layers(params, cfg.num_layers,
                                 getattr(cfg, "moe_frequency", 1),
                                 layout=layer_layout)
    nh, nkv, d = cfg.num_attention_heads, cfg.kv_heads, cfg.head_size
    out: dict[str, np.ndarray] = {}
    p = lambda name, v: out.update({"language_model." + name: np.asarray(v)})

    p("embedding.word_embeddings.weight", params["embed"]["embedding"])
    if cfg.position_embedding_type == "learned_absolute":
        p("embedding.position_embeddings.weight", params["pos_embed"]["embedding"])
    if getattr(cfg, "num_tokentypes", 0) > 0:
        p("embedding.tokentype_embeddings.weight",
          params["tokentype_embed"]["embedding"])

    def put_norm(prefix, tree):
        p(prefix + ".weight", tree["scale"])
        if cfg.normalization == "layernorm":
            p(prefix + ".bias", tree["bias"])

    for i in range(cfg.num_layers):
        pre = f"encoder.layers.{i}."
        lp = _unstack(params["layers"], i)
        put_norm(pre + "input_layernorm", lp["input_norm"])
        put_norm(pre + "post_attention_layernorm", lp["post_attn_norm"])
        if getattr(cfg, "transformer_block_type", "pre_ln") == "normformer":
            put_norm(pre + "post_attention_normformer_norm", lp["nf_attn_norm"])
            put_norm(pre + "mlp.normalization", lp["nf_mlp_norm"])
        qkv_t = _t(lp["attn"]["qkv"]["w"])  # [(nh+2kv)d, H]
        q, k, v = np.split(qkv_t, [nh * d, (nh + nkv) * d], axis=0)
        p(pre + "self_attention.query_key_value.weight",
          _interleave_qkv(q, k, v, nh, nkv, d))
        p(pre + "self_attention.dense.weight", _t(lp["attn"]["o"]["w"]))
        p(pre + "mlp.dense_h_to_4h.weight", _t(lp["mlp"]["up"]["w"]))
        p(pre + "mlp.dense_4h_to_h.weight", _t(lp["mlp"]["down"]["w"]))
        if cfg.bias:
            qb, kb, vb = np.split(
                lp["attn"]["qkv"]["bias"], [nh * d, (nh + nkv) * d], axis=0
            )
            p(pre + "self_attention.query_key_value.bias",
              _interleave_qkv(qb, kb, vb, nh, nkv, d))
            p(pre + "self_attention.dense.bias", lp["attn"]["o"]["bias"])
            p(pre + "mlp.dense_h_to_4h.bias", lp["mlp"]["up"]["bias"])
            p(pre + "mlp.dense_4h_to_h.bias", lp["mlp"]["down"]["bias"])
    if getattr(cfg, "transformer_block_type", "pre_ln") != "post_ln":
        put_norm("encoder.final_layernorm", params["final_norm"])
    if not cfg.share_embeddings_and_output_weights:
        p("output_layer.weight", _t(params["lm_head"]["w"]))
    return out


# TP-merge rules by key suffix: (concat_axis | None = replicated-take-rank0),
# matching Megatron's Column/RowParallelLinear shard dims in torch [out, in]
# layout (reference layers: qkv/h_to_4h column -> dim 0; dense/4h_to_h row ->
# dim 1; embeddings vocab -> dim 0; norms/biases-of-row replicated).
_TP_AXIS: list[tuple[str, int | None]] = [
    ("embedding.word_embeddings.weight", 0),
    ("embedding.position_embeddings.weight", None),
    ("self_attention.query_key_value.weight", 0),
    ("self_attention.query_key_value.bias", 0),
    ("self_attention.dense.weight", 1),
    ("self_attention.dense.bias", None),
    ("mlp.dense_h_to_4h.weight", 0),
    ("mlp.dense_h_to_4h.bias", 0),
    ("mlp.dense_4h_to_h.weight", 1),
    ("mlp.dense_4h_to_h.bias", None),
    ("output_layer.weight", 0),
    # reference normformer mid-MLP norm is PER-PARTITION (width ffn/tp,
    # transformer.py:181-198) — TP shards concatenate along the width
    ("mlp.normalization.weight", 0),
    ("mlp.normalization.bias", 0),
    ("embedding.tokentype_embeddings.weight", None),
    ("layernorm.weight", None),
    ("layernorm.bias", None),
    ("normformer_norm.weight", None),
    ("normformer_norm.bias", None),
]


def _tp_axis_for(key: str) -> int | None:
    for suffix, ax in _TP_AXIS:
        if key.endswith(suffix) or suffix in key:
            return ax
    return None  # unknown keys treated as replicated


def merge_nnm_shards(
    shards: Mapping[tuple[int, int], Mapping[str, Any]],
    *,
    tp: int,
    pp: int,
    num_layers: int,
    glu: bool = False,
) -> dict[str, np.ndarray]:
    """dict[(tp_rank, pp_rank)] of Megatron shard state dicts -> full dict.

    Layer indices in each pp shard are local; they are offset by
    ``pp_rank * num_layers // pp`` (the reference's ``modify_layer_string``).
    ``glu``: ``dense_h_to_4h`` holds [gate; up] per rank — merged per-half so
    the full tensor stays [gate_full; up_full].
    """
    per_stage = num_layers // pp
    full: dict[str, np.ndarray] = {}
    for pp_rank in range(pp):
        # gather each key's tp shards in rank order
        keys = [_norm_key(k) for k in shards[(0, pp_rank)].keys()]
        for key in keys:
            parts = [
                np.asarray(_lookup(shards[(r, pp_rank)], key)) for r in range(tp)
            ]
            ax = _tp_axis_for(key)
            if ax is None or tp == 1:
                merged = parts[0]
            elif glu and "dense_h_to_4h" in key:
                halves = [p.reshape((2, p.shape[0] // 2) + p.shape[1:]) for p in parts]
                merged = np.concatenate(halves, axis=1)
                merged = merged.reshape((-1,) + merged.shape[2:])
            else:
                merged = np.concatenate(parts, axis=ax)
            full[_offset_layer(key, pp_rank * per_stage)] = merged
    return full


def _lookup(shard: Mapping[str, Any], norm_key: str):
    if norm_key in shard:
        return shard[norm_key]
    return shard["model." + norm_key]
