"""Post-SFT generation evaluation: prompts -> generations -> metrics.

Re-design of the reference's ``examples/sft_evaluation/`` harness
(``evaluate.py:1-300``: jinja prompt templates, metric factory with ROUGE,
pluggable inference backends): dependency-free ROUGE-L / exact-match / F1
implementations and a small driver that runs ``models.generate`` over a
records file and scores against targets.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Any, Callable, Sequence


def _tokens(s: str) -> list[str]:
    return re.findall(r"\w+", s.lower())


def rouge_l(prediction: str, reference: str) -> float:
    """ROUGE-L F-measure on word tokens (LCS-based)."""
    p, r = _tokens(prediction), _tokens(reference)
    if not p or not r:
        return float(p == r)
    # LCS via DP over the shorter dimension
    prev = [0] * (len(r) + 1)
    for i in range(1, len(p) + 1):
        cur = [0] * (len(r) + 1)
        for j in range(1, len(r) + 1):
            cur[j] = prev[j - 1] + 1 if p[i - 1] == r[j - 1] else max(prev[j], cur[j - 1])
        prev = cur
    lcs = prev[-1]
    if lcs == 0:
        return 0.0
    prec, rec = lcs / len(p), lcs / len(r)
    return 2 * prec * rec / (prec + rec)


def exact_match(prediction: str, reference: str) -> float:
    return float(" ".join(_tokens(prediction)) == " ".join(_tokens(reference)))


def token_f1(prediction: str, reference: str) -> float:
    p, r = Counter(_tokens(prediction)), Counter(_tokens(reference))
    overlap = sum((p & r).values())
    if overlap == 0:
        return 0.0
    prec = overlap / sum(p.values())
    rec = overlap / sum(r.values())
    return 2 * prec * rec / (prec + rec)


METRICS: dict[str, Callable[[str, str], float]] = {
    "rouge_l": rouge_l,
    "exact_match": exact_match,
    "f1": token_f1,
}


def render_prompt(template: str, record: dict[str, Any]) -> str:
    """``{field}``-style prompt templating (the jinja-template role,
    reference ``evaluate.py`` prompt handling)."""
    return template.format(**record)


def score(
    predictions: Sequence[str],
    references: Sequence[str],
    metrics: Sequence[str] = ("rouge_l", "f1", "exact_match"),
) -> dict[str, float]:
    if len(predictions) != len(references):
        raise ValueError("predictions/references length mismatch")
    out = {}
    for m in metrics:
        fn = METRICS[m]
        vals = [fn(p, r) for p, r in zip(predictions, references)]
        out[m] = sum(vals) / max(len(vals), 1)
    return out


def evaluate_sft(
    records: Sequence[dict[str, Any]],
    generate_fn: Callable[[str], str],
    *,
    prompt_template: str = "{input}",
    target_field: str = "output",
    metrics: Sequence[str] = ("rouge_l", "f1", "exact_match"),
) -> dict[str, float]:
    """Run generation over records and score against targets."""
    preds, refs = [], []
    for r in records:
        preds.append(generate_fn(render_prompt(prompt_template, r)))
        refs.append(str(r[target_field]))
    return score(preds, refs, metrics)
