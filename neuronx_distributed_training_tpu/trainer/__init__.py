"""Training loop and step functions (the replacement for the reference's
PyTorch-Lightning integration layer, ``nlp_overrides.py`` + ``base.py``)."""
