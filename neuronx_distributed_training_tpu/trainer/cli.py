#!/usr/bin/env python
"""Training CLI — the L0 launch layer.

Replaces the reference's ``train.sh`` + ``training_orchestrator.py`` (torchrun +
Hydra + env-var projection, reference ``examples/train.sh:1-29``,
``training_orchestrator.py:25-149``) with one entry point:

    python examples/train.py --config examples/conf/hf_llama3_8B_config.yaml \
        [--set trainer.max_steps=100] [--compile-only]

- ``--set a.b.c=v`` dotted overrides (the Hydra override surface);
- ``--compile-only`` lowers + compiles the train step and exits — the
  ``COMPILE=1`` / ``neuron_parallel_compile`` AOT-warmup analogue
  (``train.sh:19-22``), populating the persistent XLA compilation cache;
- ``TRAIN_ITERS`` env var overrides ``trainer.max_steps`` (the reference's
  test hook, ``training_orchestrator.py:48-58``);
- multi-host: call ``jax.distributed.initialize()`` automatically when the
  cluster env provides coordination (TPU pods auto-detect).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

logger = logging.getLogger("nxdt.train")


def parse_overrides(pairs: list[str]) -> dict:
    out = {}
    for p in pairs:
        if "=" not in p:
            raise SystemExit(f"override must be key.path=value, got {p!r}")
        k, _, v = p.partition("=")
        try:
            import yaml

            out[k] = yaml.safe_load(v)
        except Exception:
            out[k] = v
    return out


def maybe_init_distributed(jax) -> bool:
    """Multi-host rendezvous from the cluster environment.

    The reference's ``train_setup.sh`` cases (SLURM nodelist -> MASTER_ADDR,
    MPI-on-EKS ``OMPI_COMM_WORLD_RANK``, reference ``train_setup.sh:8-67``)
    are handled by ``utils.launch.detect_cluster`` — an explicit
    ``(coordinator, num_processes, process_id)`` triple.  TPU-pod metadata
    (``COORDINATOR_ADDRESS``/``MEGASCALE_*``) keeps jax's own no-arg
    auto-detection, which owns that handshake.
    """
    env = os.environ
    from neuronx_distributed_training_tpu.utils.launch import (
        detect_cluster,
        initialize_distributed,
    )

    spec = detect_cluster(env)
    if spec.is_multiprocess:
        initialize_distributed(spec)
        return True
    explicit_env = bool(env.get("COORDINATOR_ADDRESS")
                        or env.get("MEGASCALE_COORDINATOR_ADDRESS"))
    if explicit_env:
        jax.distributed.initialize()  # jax's built-in cluster auto-detection
        logger.info(
            "distributed: process %d/%d", jax.process_index(), jax.process_count()
        )
        return True
    return False


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", required=True, help="YAML config (reference schema)")
    ap.add_argument("--set", dest="overrides", action="append", default=[],
                    metavar="KEY=VAL", help="dotted config override")
    ap.add_argument("--compile-only", action="store_true",
                    help="AOT-compile the train step and exit (COMPILE=1 analogue)")
    ap.add_argument("--audit-only", action="store_true",
                    help="pre-flight static audit (analysis.graph_audit) of "
                         "THIS config at true size on this machine's "
                         "devices, then exit non-zero on error findings — "
                         "no params materialized, no data opened")
    ap.add_argument("--autotune", nargs="?", const=0, type=int, default=None,
                    metavar="TOP_K",
                    help="plan the launch config before materializing "
                         "(autotune planner, docs/autotuning.md): rank the "
                         "legal tp/pp/cp/ep/mbs/remat/schedule lattice for "
                         "THIS machine's chip count, audit the top "
                         "candidates, impose the winner on the config, and "
                         "record the plan in run_summary.json.  Optional "
                         "value overrides autotune.top_k")
    ap.add_argument("--trace", nargs="?", const="", default=None,
                    metavar="START[:NUM]",
                    help="windowed device-time capture "
                         "(exp_manager.telemetry.trace): trace NUM steps "
                         "from START (default 1:3), analyze achieved "
                         "compute/comms overlap, and write "
                         "trace_summary.json next to run_summary.json — "
                         "shorthand for the --set knobs "
                         "(docs/observability.md 'Device-time profiling')")
    ap.add_argument("--compilation-cache", default=os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", "/tmp/nxdt_xla_cache"),
        help="persistent XLA compilation cache dir")
    ap.add_argument("--platform", default=None, choices=["cpu", "tpu"],
                    help="force a JAX platform (use cpu for off-hardware smoke "
                         "runs; set BEFORE backend init, overriding any "
                         "site-level TPU plugin registration)")
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    if args.compilation_cache:
        jax.config.update("jax_compilation_cache_dir", args.compilation_cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    maybe_init_distributed(jax)

    from neuronx_distributed_training_tpu.config.loader import load_config
    from neuronx_distributed_training_tpu.trainer.loop import Trainer

    overrides = parse_overrides(args.overrides)
    if os.environ.get("TRAIN_ITERS"):  # reference test hook
        overrides["trainer.max_steps"] = int(os.environ["TRAIN_ITERS"])
    if args.trace is not None:
        overrides["exp_manager.telemetry.trace.enabled"] = True
        if args.trace:
            start, _, num = args.trace.partition(":")
            try:
                overrides["exp_manager.telemetry.trace.start_step"] = int(start)
                if num:
                    overrides["exp_manager.telemetry.trace.num_steps"] = int(num)
            except ValueError:
                raise SystemExit(
                    f"--trace wants START[:NUM] step numbers, got {args.trace!r}")

    if args.audit_only:
        from neuronx_distributed_training_tpu.analysis.graph_audit import (
            audit_config,
        )

        report = audit_config(args.config, shrink=False, overrides=overrides)
        print(report.format())
        raise SystemExit(1 if report.failed("error") else 0)

    cfg = load_config(args.config, overrides)

    # -- engineered overlap env (distributed_strategy.overlap.xla_lhs): the
    # latency-hiding-scheduler flag set merges into XLA_FLAGS BEFORE the
    # backend initializes (first jax.devices() call below).  User-provided
    # flags win; each dropped knob flag is warned, not silently last-wins.
    from neuronx_distributed_training_tpu.optim.overlap import (
        OverlapConfig,
        merge_xla_flags,
        xla_lhs_flags,
    )

    overlap_cfg = OverlapConfig.from_config(
        (cfg.get("distributed_strategy", {}) or {}).get("overlap"))
    if overlap_cfg.xla_lhs:
        platform = args.platform or os.environ.get("JAX_PLATFORMS") or "tpu"
        lhs = xla_lhs_flags(platform)
        if not lhs:
            logging.getLogger(__name__).warning(
                "overlap.xla_lhs: no latency-hiding flag set for platform "
                "%r — knob is a no-op (TPU only)", platform)
        else:
            merged, conflicts = merge_xla_flags(
                os.environ.get("XLA_FLAGS", ""), lhs)
            for name, keep, drop in conflicts:
                logging.getLogger(__name__).warning(
                    "overlap.xla_lhs: XLA_FLAGS already sets %s (%s); "
                    "keeping yours, dropping knob flag %s", name, keep, drop)
            os.environ["XLA_FLAGS"] = merged

    # -- elastic replan-on-resume (docs/elasticity.md): if a resumable
    # checkpoint's manifest names a different world size than the live fleet,
    # re-run the autotune planner on the NEW world size (filtered to
    # checkpoint-layout-compatible plans) and impose the winner BEFORE
    # anything materializes.  Runs before --autotune: a replan IS the plan
    # for this incarnation.
    replan = None
    from neuronx_distributed_training_tpu.trainer.control import (
        EXIT_ALL_CORRUPT,
        EXIT_DATA_STALL,
        EXIT_ELASTIC_REFUSED,
        exit_code_for_stop,
    )
    from neuronx_distributed_training_tpu.trainer.elastic import (
        ElasticConfig,
        ElasticResumeError,
        maybe_replan,
    )

    elastic_cfg = ElasticConfig.from_config(
        dict(cfg.get("exp_manager", {}) or {}).get("elastic"))
    if elastic_cfg.enabled:
        from neuronx_distributed_training_tpu.checkpoint import (
            CheckpointIntegrityError,
        )

        try:
            replan = maybe_replan(cfg, len(jax.devices()), elastic=elastic_cfg)
        except ElasticResumeError as e:
            # curated operator-facing refusal (the message carries the --set
            # remediation) — a clean one-line exit with the tagged code
            # (trainer.control exit-code table), not a traceback
            print(f"elastic resume refused: {e}", file=sys.stderr)
            raise SystemExit(EXIT_ELASTIC_REFUSED) from e
        except CheckpointIntegrityError as e:
            # every retained checkpoint failed verification at discovery —
            # the message names each step's verdict (docs/elasticity.md
            # "Integrity & walk-back"); the tagged code tells the
            # orchestrator to PAGE, not blind-restart
            print(f"elastic resume refused: {e}", file=sys.stderr)
            raise SystemExit(EXIT_ALL_CORRUPT) from e
        if replan.replanned:
            cfg = replan.cfg
            logger.warning(
                "elastic replan imposed for %d chips (was %d): see "
                "run_summary.json elastic section",
                replan.record["new_world"], replan.record["old_world"],
            )

    # -- autotune: plan BEFORE materializing (no params, no data yet) ------
    plan_report = None
    at_block = dict(cfg.get("autotune", {}) or {})
    if replan is not None and replan.replanned:
        # the replanner already planned this world size against the
        # checkpoint's layout constraints; a second, layout-blind autotune
        # pass could impose an un-resumable mesh on top of it
        if args.autotune is not None or at_block.get("enabled"):
            logger.info("autotune skipped: elastic replan already planned "
                        "this restart")
    elif args.autotune is not None or at_block.get("enabled"):
        from neuronx_distributed_training_tpu.autotune import plan_config

        top_k = (args.autotune if (args.autotune or 0) > 0
                 else int(at_block.get("top_k", 5)))
        chips = len(jax.devices())
        plan_report = plan_config(
            cfg, chips=chips,
            topology=at_block.get("topology"),
            top_k=top_k,
            hbm_headroom=float(at_block.get("hbm_headroom", 0.9)),
            max_mbs=int(at_block.get("max_micro_batch_size", 8)),
            max_devices=min(8, chips),
        )
        print(plan_report.format())
        winner = plan_report.winner
        if winner is None:
            raise SystemExit(
                f"autotune: no surviving plan for {chips} chips"
                + (f" ({plan_report.error})" if plan_report.error else "")
            )
        if replan is not None and replan.manifest is not None:
            # a resumable checkpoint binds this launch even at the SAME
            # world size: a layout-blind winner could impose an
            # un-resumable mesh — take the best layout-compatible candidate
            from neuronx_distributed_training_tpu.trainer.elastic import (
                plan_layout_reason,
            )

            compatible = next(
                (c for c in plan_report.candidates
                 if not c.discarded
                 and plan_layout_reason(replan.manifest, c.plan) is None),
                None)
            if compatible is None:
                raise SystemExit(
                    "autotune: no candidate keeps the resumable "
                    "checkpoint's layer layout — drop --autotune to resume "
                    "with the declared mesh, or start fresh with "
                    "exp_manager.resume_if_exists=false")
            if compatible is not winner:
                logger.warning(
                    "autotune: top plan is incompatible with the resumable "
                    "checkpoint's layer layout; imposing %s instead",
                    compatible.plan.describe())
            winner = compatible
        logger.info("autotune: imposing %s", winner.plan.describe())
        cfg = load_config(
            args.config,
            {**overrides, **winner.plan.overrides(plan_report.facts)},
        )

    trainer = Trainer.from_config(cfg, enable_checkpointing=not args.compile_only)
    if replan is not None and replan.replanned:
        # fit() accounts the replan wall time as a goodput span and persists
        # the old-plan -> new-plan record in run_summary.json's elastic
        # section at teardown
        trainer.replan_record = replan.record
    if replan is not None and replan.integrity_trail:
        # discovery already verified (and possibly quarantined/walked back):
        # carry that trail so run_summary.json's integrity section reflects
        # the WHOLE restore story, not just the trainer's own (already
        # cleaned) restore
        trainer.discovery_integrity_trail = replan.integrity_trail
    if plan_report is not None:
        # the chosen plan becomes a static run fact: the compile census
        # carries it, and run_summary.json gets the full ranked report
        trainer.run_facts["autotune_plan"] = winner.plan.describe()
        trainer.exp.write_run_summary({"autotune": plan_report.to_dict()})

    if args.compile_only:
        from neuronx_distributed_training_tpu.parallel import sharding as shd

        batch = next(trainer.data_module.sharded_batches(trainer.mesh))
        # compile inside the same mesh context fit() uses, so the cached
        # executable is byte-identical to the real training step
        with trainer.mesh, shd.use_mesh(trainer.mesh):
            lowered = trainer.train_step.lower(
                trainer.params, trainer.opt_state, batch, jax.random.PRNGKey(0)
            )
            compiled = lowered.compile()
        cost = compiled.cost_analysis() or {}
        if isinstance(cost, (list, tuple)):  # older jax: list of per-program dicts
            cost = cost[0] if cost else {}
        logger.info("compile-only: train step compiled; flops=%s bytes=%s",
                    cost.get("flops"), cost.get("bytes accessed"))
        return

    from neuronx_distributed_training_tpu.data import DataStallError

    try:
        metrics = trainer.fit()
    except DataStallError as e:
        # the data-stall watchdog already dumped its bundle; exit with the
        # tagged code so the orchestrator pages instead of blind-restarting
        # into the same dead mount
        print(f"data stall: {e}", file=sys.stderr)
        raise SystemExit(EXIT_DATA_STALL) from e
    logger.info("done: %s", {k: round(v, 4) for k, v in metrics.items()})
    # failure-class exit codes (trainer.control, docs/observability.md
    # "Fleet control"): a health/alert halt exits tagged so restart-vs-page
    # policy needs nothing but the code; graceful stops (preemption,
    # operator stop, max_time) exit 0 — resume_if_exists continues the run
    code = exit_code_for_stop(getattr(trainer, "stop_class", None))
    if code:
        logger.warning("exiting with tagged code %d (%s)", code,
                       trainer.stop_class)
        raise SystemExit(code)


if __name__ == "__main__":
    main()
