"""Coordinated fleet control: consensus stop decisions + the operator channel.

Every *recovery* ingredient this repo ships — elastic resume, integrity
walk-back, per-host beacons and alerts — is host-local at the DECISION
layer: an ``action: halt`` alert, a health-policy halt, or a SIGTERM notice
on one host used to stop that host alone, deadlocking every other host at
the next collective rendezvous.  This module makes every stop/checkpoint
decision fleet-consistent (docs/observability.md "Fleet control"):

- **Control word** — each host folds its local conditions (alert halt,
  health halt, SIGTERM/preemption notice, max_time, operator command) into
  a small bitmask.  At every *deterministic* logging boundary (cadence
  steps every host computes identically — never a host-local trigger) the
  word rides ONE tiny replicated collective (:func:`fold_word_fleet`, a
  per-bit max ≡ bitwise OR across processes), so all hosts derive the SAME
  decision — ``stop`` (graceful, with the grace-window emergency save),
  ``halt`` (numerics: stop WITHOUT a checkpoint), ``checkpoint_now`` or
  ``dump`` — at the same step.  A SIGTERM that only one host received
  becomes a fleet-wide drained emergency save at the next boundary.

- **Operator command channel** — ``control/commands.jsonl`` in the run
  dir: ``tools/run_ctl.py`` appends one JSON line per command
  (``stop`` / ``checkpoint_now`` / ``dump``); rank 0 polls the file at the
  boundary, dedupes by command id, folds the bits into the same control
  word, and records parse/dedupe/ack as the ``control`` trail in
  ``run_summary.json``.

- **Exit-code table** — one table for the failure classes an orchestrator
  keys restart-vs-page policy off: hang escape, all-corrupt store, elastic
  refusal, alert/health halt, data stall, clean stop.  ``nxdt-train``
  exits with these codes and the drills assert them.

Deliberately **stdlib-only at import time** (the ``telemetry.fleet``
posture) so ``tools/run_ctl.py`` can load this file by path on a login
node; the one jax touch (:func:`fold_word_fleet`) imports lazily and is a
no-op in a single-process run.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
import uuid
from pathlib import Path
from typing import Any, Callable, Mapping, Optional

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# the control word
# ---------------------------------------------------------------------------

#: condition name -> bit.  The word is the bitwise OR of every host's local
#: conditions; per-bit max across processes == bitwise OR, so the fold is a
#: plain integer max/or collective.
CONDITION_BITS: dict[str, int] = {
    "preemption": 1 << 0,      # SIGTERM / preemption notice (graceful stop)
    "alert_halt": 1 << 1,      # alert rule with action: halt (graceful stop)
    "operator_stop": 1 << 2,   # run_ctl stop command (graceful stop)
    "max_time": 1 << 3,        # trainer.max_time budget spent (graceful stop)
    "health_halt": 1 << 4,     # numerics halt: stop WITHOUT a checkpoint
    "checkpoint_now": 1 << 5,  # operator checkpoint_now (one-shot)
    "dump": 1 << 6,            # operator dump: forensic bundle (one-shot)
    "data_stall": 1 << 7,      # data source stalled (exit-path annotation)
}

#: graceful-stop bits: the run checkpoints (grace-window emergency save)
#: and exits clean — an orchestrator just restarts it
STOP_MASK = (CONDITION_BITS["preemption"] | CONDITION_BITS["alert_halt"]
             | CONDITION_BITS["operator_stop"] | CONDITION_BITS["max_time"])

#: halt bits: the model state is poisoned — stop WITHOUT a checkpoint so
#: auto-resume finds the last good save
HALT_MASK = CONDITION_BITS["health_halt"]

#: one-shot bits: acted on at the deciding boundary, then cleared (a second
#: checkpoint_now command sets them again)
ONESHOT_MASK = CONDITION_BITS["checkpoint_now"] | CONDITION_BITS["dump"]

#: reason-priority order when several conditions land in one word
_PRIORITY = ("health_halt", "preemption", "alert_halt", "operator_stop",
             "max_time", "checkpoint_now", "dump", "data_stall")


def condition_names(word: int) -> list[str]:
    """The condition names set in ``word``, priority-ordered."""
    return [n for n in _PRIORITY if word & CONDITION_BITS[n]]


def fold_word_fleet(word: int) -> int:
    """The boundary's ONE tiny replicated collective: every process
    contributes its local word; the fold is a per-bit max (== bitwise OR).
    Single-process runs skip the collective entirely — zero cost, and the
    return value is exact either way.  Must ONLY be called at a step every
    host reaches (the deterministic boundary cadence): a host-local call
    site would be exactly the rendezvous mismatch this module exists to
    kill."""
    import jax

    if jax.process_count() == 1:
        return int(word)
    import numpy as np
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(np.int32(int(word)))
    return int(np.bitwise_or.reduce(np.asarray(gathered, np.int64).ravel()))


# ---------------------------------------------------------------------------
# exit-code table
# ---------------------------------------------------------------------------

#: THE exit-code table (docs/observability.md "Fleet control").  One module
#: owns it; ``nxdt-train`` exits with these and the drills assert them, so
#: an orchestrator can pick restart-vs-page policy from the code alone.
#: 0 = clean (completion OR a graceful consensus stop — resumable, just
#: restart); 1 = unclassified failure; the 83+ block is deliberately above
#: the shell/signal range (a SIGKILL'd process reports 137 = 128+9).
EXIT_OK = 0                  # clean completion / graceful stop: restart
EXIT_ERROR = 1               # unclassified failure: inspect
EXIT_HANG_ESCAPE = 83        # watchdog killed a hung collective: restart
EXIT_ALL_CORRUPT = 84        # every retained checkpoint corrupt: page
EXIT_ELASTIC_REFUSED = 85    # no legal plan resumes this save here: page
EXIT_HEALTH_HALT = 86        # numerics halt (state poisoned): restart+page
EXIT_ALERT_HALT = 87         # alert rule stopped the run: page
EXIT_DATA_STALL = 88         # data source hung past the watchdog: page

EXIT_CODES: dict[str, int] = {
    "ok": EXIT_OK,
    "error": EXIT_ERROR,
    "hang_escape": EXIT_HANG_ESCAPE,
    "all_corrupt": EXIT_ALL_CORRUPT,
    "elastic_refused": EXIT_ELASTIC_REFUSED,
    "health_halt": EXIT_HEALTH_HALT,
    "alert_halt": EXIT_ALERT_HALT,
    "data_stall": EXIT_DATA_STALL,
}

_EXIT_NAMES = {v: k for k, v in EXIT_CODES.items()}


def exit_code_name(code: int) -> str:
    """Reverse lookup for reports/drills; unknown codes render as the
    number."""
    return _EXIT_NAMES.get(int(code), str(int(code)))


def exit_code_for_stop(stop_class: Optional[str]) -> int:
    """Map a run's recorded stop class (``Trainer.stop_class``) to its exit
    code.  Graceful stops (preemption, operator, max_time, clean
    completion) are EXIT_OK — an orchestrator just restarts; only the
    classes that want a human land nonzero."""
    if stop_class in ("health_halt", "alert_halt", "data_stall"):
        return EXIT_CODES[stop_class]
    return EXIT_OK


# ---------------------------------------------------------------------------
# knob block: exp_manager.telemetry.control
# ---------------------------------------------------------------------------


def _control_knobs() -> set:
    return {f.name for f in dataclasses.fields(ControlConfig)}


@dataclasses.dataclass(frozen=True)
class ControlConfig:
    """``exp_manager.telemetry.control`` (validated at config load).

    .. code-block:: yaml

        exp_manager:
          telemetry:
            control:
              enabled: false      # consensus control word at each boundary
              poll_commands: true # rank 0 polls control/commands.jsonl
              hang_escape: true   # armed watchdog exits EXIT_HANG_ESCAPE
              max_trail: 64       # decisions/commands kept in run_summary
    """

    enabled: bool = False
    poll_commands: bool = True
    hang_escape: bool = True
    max_trail: int = 64

    @classmethod
    def from_config(cls, block: Any) -> "ControlConfig":
        """Accepts ``None`` (defaults: disabled), a bare bool, or a mapping.
        Unknown keys raise with a did-you-mean hint — a typo'd knob must not
        silently leave the fleet uncoordinated."""
        if block is None:
            return cls()
        if isinstance(block, bool):
            return cls(enabled=block)
        knobs = _control_knobs()
        if not isinstance(block, Mapping):
            raise ValueError(
                f"exp_manager.telemetry.control must be a mapping of "
                f"{sorted(knobs)} (or a single bool), got "
                f"{type(block).__name__}"
            )
        unknown = set(block) - knobs
        if unknown:
            from neuronx_distributed_training_tpu.config.loader import (
                did_you_mean,
            )

            raise ValueError(
                f"unknown exp_manager.telemetry.control keys "
                f"{sorted(unknown)}; supported: {sorted(knobs)}"
                + did_you_mean(unknown, knobs)
            )
        values = dict(block)
        for key in ("enabled", "poll_commands", "hang_escape"):
            if key in values and not isinstance(values[key], bool):
                raise ValueError(
                    f"exp_manager.telemetry.control.{key} must be a "
                    f"boolean, got {values[key]!r}"
                )
        if "max_trail" in values and (isinstance(values["max_trail"], bool)
                                      or not isinstance(values["max_trail"],
                                                        int)):
            raise ValueError(
                f"exp_manager.telemetry.control.max_trail must be an "
                f"integer, got {values['max_trail']!r}"
            )
        out = cls(
            enabled=bool(values.get("enabled", cls.enabled)),
            poll_commands=bool(values.get("poll_commands",
                                          cls.poll_commands)),
            hang_escape=bool(values.get("hang_escape", cls.hang_escape)),
            max_trail=int(values.get("max_trail", cls.max_trail)),
        )
        if out.max_trail < 1:
            raise ValueError(
                f"exp_manager.telemetry.control.max_trail must be >= 1, "
                f"got {out.max_trail}"
            )
        return out

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# the operator command channel
# ---------------------------------------------------------------------------

#: subdirectory of the run dir holding the command queue (+ room for future
#: control artifacts)
CONTROL_DIR = "control"
COMMANDS_FILE = "commands.jsonl"

#: operator command -> control-word condition
COMMAND_CONDITIONS: dict[str, str] = {
    "stop": "operator_stop",
    "checkpoint_now": "checkpoint_now",
    "dump": "dump",
}


def commands_path(run_dir: str | Path) -> Path:
    return Path(run_dir) / CONTROL_DIR / COMMANDS_FILE


def append_command(run_dir: str | Path, command: str,
                   note: Optional[str] = None) -> dict[str, Any]:
    """Enqueue one operator command (the ``tools/run_ctl.py`` entry): a
    single ``write()`` of one newline-terminated JSON line in append mode —
    the same torn-tail-tolerant contract the fleet beacons use, so a
    concurrent poll never sees half a record.  Returns the enqueued record
    (with its generated id)."""
    if command not in COMMAND_CONDITIONS:
        raise ValueError(
            f"unknown control command {command!r}; supported: "
            f"{sorted(COMMAND_CONDITIONS)}"
        )
    rec: dict[str, Any] = {
        "id": uuid.uuid4().hex[:12],
        "command": command,
        "t_wall": round(time.time(), 6),
    }
    if note:
        rec["note"] = str(note)[:200]
    path = commands_path(run_dir)
    path.parent.mkdir(parents=True, exist_ok=True)
    line = json.dumps(rec) + "\n"
    with open(path, "a") as f:
        f.write(line)
        f.flush()
        try:
            os.fsync(f.fileno())
        except OSError:  # pragma: no cover — some filesystems refuse
            pass
    return rec


def _read_new_lines(path: Path, offset: int) -> tuple[list[dict], int]:
    """New COMPLETE records past ``offset`` -> (records, new offset).  A
    torn tail line waits for the next poll; a malformed complete line is
    returned as ``{"_malformed": line}`` so the ack trail can name it
    instead of silently dropping an operator's command."""
    try:
        size = path.stat().st_size
    except OSError:
        return [], offset
    if size <= offset:
        return [], offset
    with open(path) as f:
        f.seek(offset)
        chunk = f.read(size - offset)
    end = chunk.rfind("\n")
    if end < 0:
        return [], offset
    out: list[dict] = []
    for line in chunk[: end + 1].splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            out.append({"_malformed": line[:200]})
            continue
        out.append(rec if isinstance(rec, dict)
                   else {"_malformed": repr(rec)[:200]})
    return out, offset + end + 1


# ---------------------------------------------------------------------------
# the boundary decision
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ControlDecision:
    """What ONE deterministic boundary decided, identically on every host."""

    step: int
    word: int
    conditions: list[str]
    stop: bool = False          # graceful stop (emergency save, exit clean)
    halt: bool = False          # numerics halt (NO checkpoint)
    checkpoint_now: bool = False
    dump: bool = False
    reason: str = ""
    source: str = "local"       # local | operator | fleet

    @property
    def any(self) -> bool:
        return bool(self.word)

    def to_dict(self) -> dict:
        out = {
            "step": int(self.step),
            "word": int(self.word),
            "conditions": list(self.conditions),
            "reason": self.reason,
            "source": self.source,
        }
        for k in ("stop", "halt", "checkpoint_now", "dump"):
            if getattr(self, k):
                out[k] = True
        return out


class ControlPlane:
    """What the fit loop holds: this host's local-condition register, the
    rank-0 command poll, the boundary fold, and the ``control`` trail in
    ``run_summary.json``.

    All methods are host-side bookkeeping except :meth:`boundary`'s fold,
    which is the documented one-per-boundary collective.  ``peer_words``
    is the drill/test seam: a callable returning extra word bits that
    stand in for other hosts' contributions on a single-process mesh (the
    production path folds real processes via :func:`fold_word_fleet`).
    """

    def __init__(
        self,
        cfg: ControlConfig,
        run_dir: str | Path,
        *,
        host: int = 0,
        poll_commands: Optional[bool] = None,
        write_run_summary: Optional[Callable[[dict], None]] = None,
        peer_words: Optional[Callable[[], int]] = None,
        fold: Optional[Callable[[int], int]] = None,
    ) -> None:
        self.cfg = cfg
        self.run_dir = Path(run_dir)
        self.host = int(host)
        # rank 0 polls by default; every host COULD poll (the file is
        # host-local on non-shared filesystems anyway) but one poller keeps
        # the ack trail single-writer
        self.poll = (cfg.poll_commands if poll_commands is None
                     else bool(poll_commands))
        self._write_run_summary = write_run_summary
        self._peer_words = peer_words
        self._fold = fold if fold is not None else fold_word_fleet
        self._offset = 0
        self._seen_ids: set[str] = set()
        # local conditions: persistent stop/halt bits + one-shot bits,
        # each with the host-local reason that requested it
        self._word = 0
        self._reasons: dict[str, str] = {}
        #: mirrored to run_summary.json "control" as things happen
        self.commands: list[dict] = []
        self.decisions: list[dict] = []
        # a restarted incarnation re-reads commands.jsonl from offset 0: a
        # command the PREVIOUS incarnation already acted on (an operator
        # `stop` that was obeyed, saved, and restarted) must come back as a
        # `duplicate`, not re-stop the run into a permanent stop/restart
        # loop — re-seed the dedupe set from the ack trail the previous
        # incarnation left in run_summary.json (the flight recorder's
        # anomaly-trail pattern).  The trail is capped at max_trail, so an
        # id older than the cap could in principle replay; operators should
        # not let hundreds of commands accumulate in one run dir.
        if self.poll:
            try:
                with open(self.run_dir / "run_summary.json") as f:
                    prior = (json.load(f).get("control") or {}).get(
                        "commands") or []
            except (OSError, ValueError, AttributeError):
                prior = []
            for ack in prior:
                try:
                    if ack.get("id"):
                        self._seen_ids.add(str(ack["id"]))
                except AttributeError:
                    continue

    # -- local conditions ---------------------------------------------------

    def request(self, condition: str, reason: str = "") -> None:
        """Register a host-local condition (alert halt, SIGTERM notice,
        health halt, max_time).  The bit rides the next boundary fold; the
        reason string stays host-local and becomes the decision's reason
        when this host's bit wins."""
        bit = CONDITION_BITS[condition]
        self._word |= bit
        if reason and condition not in self._reasons:
            self._reasons[condition] = reason

    @property
    def pending(self) -> bool:
        return bool(self._word)

    # -- the command poll (rank 0) ------------------------------------------

    def _poll_commands(self, step: int) -> None:
        recs, self._offset = _read_new_lines(
            commands_path(self.run_dir), self._offset)
        for rec in recs:
            if "_malformed" in rec:
                self._ack(step, {"id": None, "command": None},
                          "malformed", note=rec["_malformed"])
                continue
            cid = str(rec.get("id") or "")
            command = str(rec.get("command") or "")
            if cid and cid in self._seen_ids:
                self._ack(step, rec, "duplicate")
                continue
            if command not in COMMAND_CONDITIONS:
                self._ack(step, rec, "unknown")
                if cid:
                    self._seen_ids.add(cid)
                continue
            if cid:
                self._seen_ids.add(cid)
            cond = COMMAND_CONDITIONS[command]
            self.request(cond, f"operator command {command}"
                               + (f" ({rec['note']})" if rec.get("note")
                                  else ""))
            # operator-sourced bits report "operator", not "local"
            self._reasons.setdefault("_source_" + cond, "operator")
            self._ack(step, rec, "accepted")

    def _ack(self, step: int, rec: Mapping, status: str,
             note: Optional[str] = None) -> None:
        ack = {
            "id": rec.get("id"),
            "command": rec.get("command"),
            "step": int(step),
            "status": status,
        }
        if note or rec.get("note"):
            ack["note"] = note or rec.get("note")
        self.commands.append(ack)
        del self.commands[: max(0, len(self.commands) - self.cfg.max_trail)]
        logger.info("control: command %s (%s) %s at step %d",
                    ack["command"], ack["id"], status, step)
        self._write_trail()

    # -- the boundary -------------------------------------------------------

    def boundary(self, step: int) -> ControlDecision:
        """One deterministic logging boundary: poll the command channel
        (rank 0), fold every host's word through the one replicated
        collective, derive the decision all hosts share, record it in the
        trail, and clear this host's one-shot bits."""
        if self.poll:
            self._poll_commands(step)
        local = self._word
        word = local
        if self._peer_words is not None:
            try:
                word |= int(self._peer_words())
            except Exception as e:  # noqa: BLE001 — a drill seam must not kill
                logger.warning("control peer_words failed: %s", e)
        word = int(self._fold(word))
        decision = self._decide(step, word, local)
        # one-shot bits are consumed by this decision (locally; a remote
        # host's one-shot bit was cleared on ITS side the same boundary)
        self._word &= ~ONESHOT_MASK
        for cond in ("checkpoint_now", "dump"):
            self._reasons.pop(cond, None)
            self._reasons.pop("_source_" + cond, None)
        if decision.any:
            self.decisions.append(decision.to_dict())
            del self.decisions[
                : max(0, len(self.decisions) - self.cfg.max_trail)]
            self._write_trail()
        return decision

    def _decide(self, step: int, word: int, local: int) -> ControlDecision:
        conds = condition_names(word)
        decision = ControlDecision(step=int(step), word=int(word),
                                   conditions=conds)
        if not word:
            return decision
        decision.halt = bool(word & HALT_MASK)
        decision.stop = decision.halt or bool(word & STOP_MASK)
        decision.checkpoint_now = bool(
            word & CONDITION_BITS["checkpoint_now"])
        decision.dump = bool(word & CONDITION_BITS["dump"])
        # the deciding condition: highest-priority bit set; its reason is
        # host-local when this host requested it, an honest "fleet
        # consensus" marker when the bit arrived through the fold
        deciding = conds[0]
        if CONDITION_BITS[deciding] & local:
            src = self._reasons.get("_source_" + deciding, "local")
            reason = self._reasons.get(
                deciding, f"{deciding} requested on this host")
        else:
            src = "fleet"
            reason = (f"fleet consensus: {deciding} requested on another "
                      f"host")
        decision.source = src
        decision.reason = reason
        logger.warning(
            "control: boundary %d decided %s (word=0x%x, conditions=%s, "
            "source=%s): %s", step,
            "halt" if decision.halt else "stop" if decision.stop
            else "/".join(c for c in ("checkpoint_now", "dump")
                          if getattr(decision, c)) or "note",
            word, conds, src, reason)
        return decision

    def note_exit(self, condition: str, reason: str) -> None:
        """Record a terminal condition that never reaches a boundary fold
        (data stall raising out of the step path, the hang-escape exit) so
        the ``control`` trail still names the deciding condition."""
        self.decisions.append({
            "step": -1,
            "word": int(CONDITION_BITS.get(condition, 0)),
            "conditions": [condition],
            "reason": reason,
            "source": "local",
            "exit": True,
        })
        del self.decisions[: max(0, len(self.decisions) - self.cfg.max_trail)]
        self._write_trail()

    def _write_trail(self) -> None:
        if self._write_run_summary is None:
            return
        try:
            self._write_run_summary({"control": self.trail()})
        except Exception as e:  # noqa: BLE001 — observability must not kill
            logger.warning("control trail write failed: %s", e)

    def trail(self) -> dict:
        return {
            "enabled": True,
            "commands": list(self.commands),
            "decisions": list(self.decisions),
        }
