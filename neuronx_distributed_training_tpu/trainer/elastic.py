"""Elastic resume: world-size-agnostic checkpoints + restart-time replanning.

On real fleets chips disappear mid-run — preemption, ICI link flaps, host
failures.  This module closes the halt→resume loop (ROADMAP item 5) so a run
survives chip-count changes end to end:

- every checkpoint carries a **topology/plan manifest** (:func:`build_manifest`
  → ``Checkpointer.save(manifest=...)``): world size, mesh axes, the resolved
  parallelism plan, and the model identity — readable WITHOUT templates, so a
  restart can reason about the save before any model state exists;
- :func:`maybe_replan` detects that the live chip count differs from the
  manifest's world size and re-runs the autotune planner
  (:func:`~neuronx_distributed_training_tpu.autotune.plan_config`) on the NEW
  world size, filtered to plans whose parameter-tree layout matches the
  checkpoint (:func:`plan_layout_reason` — pipeline ``pp``/``vp`` pin the
  stacked-layer layout; tp/cp/ep/dp only reshard the same global arrays, so
  they are free to change).  The chosen plan is imposed on the config and the
  old-plan→new-plan record lands in ``run_summary.json``;
- :class:`FaultInjector` + ``tools/elastic_drill.py`` provide the preemption
  drill harness: kill or shrink a run at a configurable step (mid-step,
  mid-save, mid-restore) and prove loss-trajectory continuity after resume at
  the same or a different dp degree.

The knob block (validated at config load with did-you-mean hints):

.. code-block:: yaml

    exp_manager:
      elastic:
        enabled: true                    # replan-on-resume at nxdt-train start
        grace_period_seconds: 30.0       # SIGTERM → emergency-save budget
        save_retries: 3                  # transient-I/O retry (ENOSPC/EIO)
        save_retry_backoff_seconds: 0.5  # doubled per attempt
        replan_top_k: 5
        replan_audit: false              # true: AOT-audit candidates (slower)

See docs/elasticity.md.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from pathlib import Path
from typing import Any, Mapping, Optional

logger = logging.getLogger(__name__)

#: manifest schema version (bump on breaking layout changes)
MANIFEST_FORMAT = 1

#: knob name -> (default, type) — the single source of truth the validator,
#: ``from_config``, and docs/elasticity.md share
ELASTIC_KNOBS: dict[str, Any] = {
    "enabled": False,
    "grace_period_seconds": 30.0,
    "save_retries": 3,
    "save_retry_backoff_seconds": 0.5,
    "replan_top_k": 5,
    "replan_audit": False,
}


class ElasticResumeError(RuntimeError):
    """A resume that cannot proceed: the checkpoint's layout and the live
    world admit no legal plan (or the model identity changed)."""


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """``exp_manager.elastic`` — elastic-resume policy knobs."""

    enabled: bool = False
    grace_period_seconds: float = 30.0
    save_retries: int = 3
    save_retry_backoff_seconds: float = 0.5
    replan_top_k: int = 5
    replan_audit: bool = False

    @classmethod
    def from_config(cls, block: Any) -> "ElasticConfig":
        """Parse (and validate) an ``exp_manager.elastic`` block.  Accepts
        ``None``/``{}`` (defaults) or a mapping; a bare bool toggles
        ``enabled``.  Unknown keys and ill-typed values raise ``ValueError``
        with a did-you-mean hint — a typo'd knob must not silently run with
        defaults."""
        if block is None:
            return cls()
        if isinstance(block, bool):
            return cls(enabled=block)
        if not isinstance(block, Mapping):
            raise ValueError(
                f"exp_manager.elastic must be a mapping of "
                f"{sorted(ELASTIC_KNOBS)} (or a single bool), got "
                f"{type(block).__name__}"
            )
        unknown = set(block) - set(ELASTIC_KNOBS)
        if unknown:
            from neuronx_distributed_training_tpu.config.loader import (
                did_you_mean,
            )

            raise ValueError(
                f"unknown exp_manager.elastic keys {sorted(unknown)}; "
                f"supported: {sorted(ELASTIC_KNOBS)}"
                + did_you_mean(unknown, ELASTIC_KNOBS)
            )
        values: dict[str, Any] = {}
        for k, v in block.items():
            default = ELASTIC_KNOBS[k]
            if isinstance(default, bool):
                if not isinstance(v, bool):
                    raise ValueError(
                        f"exp_manager.elastic.{k} must be a boolean, got {v!r}"
                    )
                values[k] = v
            elif isinstance(default, int):
                if isinstance(v, bool) or not isinstance(v, int):
                    raise ValueError(
                        f"exp_manager.elastic.{k} must be an integer, "
                        f"got {v!r}"
                    )
                values[k] = int(v)
                if values[k] < 0:
                    raise ValueError(
                        f"exp_manager.elastic.{k} must be >= 0, got {v!r}"
                    )
            else:
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    raise ValueError(
                        f"exp_manager.elastic.{k} must be a number, got {v!r}"
                    )
                values[k] = float(v)
                if values[k] < 0.0:
                    raise ValueError(
                        f"exp_manager.elastic.{k} must be >= 0, got {v!r}"
                    )
        ec = cls(**values)
        if ec.replan_top_k < 1:
            raise ValueError(
                f"exp_manager.elastic.replan_top_k must be >= 1, got "
                f"{ec.replan_top_k}"
            )
        return ec


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------


def build_manifest(cfg: Mapping, mesh: Any, *, step: int,
                   schedule: Optional[str], model_family: str,
                   save_bf16: bool) -> dict[str, Any]:
    """The world-size-agnostic topology/plan manifest saved with every
    checkpoint.  Everything a cold restart needs to decide whether (and how)
    the save fits the live fleet — no arrays, no templates."""
    from neuronx_distributed_training_tpu.config.loader import batch_schedule

    ds = dict(cfg.get("distributed_strategy", {}) or {})
    data = dict(cfg.get("data", {}) or {})
    model = dict(cfg.get("model", {}) or {})
    world = int(mesh.devices.size)
    sched = batch_schedule(cfg, world)
    pp = int(ds.get("pipeline_model_parallel_size", 1) or 1)
    vp = int(ds.get("virtual_pipeline_model_parallel_size") or 1)
    remat = model.get("activations_checkpoint_granularity", "selective")
    return {
        "format": MANIFEST_FORMAT,
        "step": int(step),
        "world_size": world,
        "mesh_axes": {str(k): int(v) for k, v in dict(mesh.shape).items()},
        "plan": {
            "tp": int(ds.get("tensor_model_parallel_size", 1) or 1),
            "pp": pp,
            "cp": int(ds.get("context_parallel_size", 1) or 1),
            "ep": int(ds.get("expert_model_parallel_size", 1) or 1),
            "vp": vp,
            "dp": int(sched["dp_size"]),
            "micro_batch_size": int(sched["micro_batch_size"]),
            "num_microbatches": int(sched["num_microbatches"]),
            "remat": str(remat) if remat else "none",
            "schedule": schedule or "none",
        },
        "model": {
            "family": model_family,
            "num_layers": int(model.get("num_layers", 0) or 0),
            "hidden_size": int(model.get("hidden_size", 0) or 0),
            "vocab_size": int(model.get("vocab_size", 0) or 0),
        },
        "data": {
            "global_batch_size": int(sched["global_batch_size"]),
            "seq_length": int(data.get("seq_length", 0) or 0),
        },
        "zero1": bool(ds.get("zero1", True)),
        "save_bf16": bool(save_bf16),
        "layer_layout": "interleaved" if pp > 1 and vp > 1 else "flat",
    }


def discover_checkpoint_dir(cfg: Mapping) -> Optional[Path]:
    """The checkpoint dir a restart would resume from, WITHOUT building an
    :class:`~neuronx_distributed_training_tpu.trainer.exp_manager.ExpManager`
    (which creates directories).  This mirrors ``ExpManager``'s selection
    EXACTLY — ``resume_if_exists`` on, newest ``version_N`` (digit-suffixed
    only, same parse as ``exp_manager.py``), no fallback to older versions —
    because a replan keyed to a checkpoint the trainer will never restore
    would constrain a fresh run with a stale layout.  ``None`` when the
    restart would not resume anything."""
    from neuronx_distributed_training_tpu.trainer.exp_manager import (
        experiment_base_dir,
        latest_version,
    )

    em = dict(cfg.get("exp_manager", {}) or {})
    if not bool(em.get("resume_if_exists", False)):
        # ExpManager will open a FRESH version dir and restore nothing —
        # whatever checkpoints older versions hold do not bind this launch
        return None
    base = experiment_base_dir(dict(cfg))
    v = latest_version(base)
    if v is None:
        return None
    ck = base / f"version_{v}" / "checkpoints"
    return ck if ck.exists() else None


def read_latest_manifest(checkpoint_dir: Path, *,
                         integrity: Any = None,
                         trail: Optional[dict] = None) -> Optional[dict]:
    """Newest VERIFIED checkpoint's manifest under ``checkpoint_dir`` (None
    when no checkpoint, no manifest item, or orbax unavailable).

    ``integrity`` (an ``IntegrityConfig``; default: knob defaults, i.e.
    verification ON) selects the step the manifest is read from: the newest
    step whose integrity sidecar verifies — corrupt newer steps are
    quarantined here, at DISCOVERY time, so the replan keys off the step the
    trainer will actually restore and every later ``latest_step`` agrees.
    When every retained step is corrupt, the curated
    ``CheckpointIntegrityError`` PROPAGATES (an un-resumable store must stop
    the launch loudly, not silently start a fresh run).

    ``trail`` (a mutable dict) receives the discovery checkpointer's
    integrity trail — verified step, walk-back count, quarantined steps —
    so the caller can persist what happened here into ``run_summary.json``
    (the trainer's own restore then sees an already-cleaned chain and would
    otherwise report a walk-back of zero)."""
    from neuronx_distributed_training_tpu.checkpoint import (
        CheckpointIntegrityError,
    )

    try:
        from neuronx_distributed_training_tpu.checkpoint import (
            CheckpointConfig,
            Checkpointer,
            IntegrityConfig,
        )

        icfg = integrity if integrity is not None else IntegrityConfig()
        ck = Checkpointer(
            CheckpointConfig(dir=str(checkpoint_dir), save_top_k=0,
                             async_save=False, integrity=icfg))
        try:
            step = (ck.verified_latest_step()
                    if icfg.enabled and icfg.verify_restore
                    else ck.latest_step())
            return ck.read_manifest(step) if step is not None else None
        finally:
            if trail is not None and ck.integrity_trail:
                trail.update(ck.integrity_trail)
            ck.close()
    except CheckpointIntegrityError:
        raise
    except Exception as e:  # noqa: BLE001 — discovery must never kill a launch
        logger.warning("manifest discovery under %s failed: %s",
                       checkpoint_dir, e)
        return None


# ---------------------------------------------------------------------------
# replanning
# ---------------------------------------------------------------------------


def plan_layout_reason(manifest: Mapping, plan: Any) -> Optional[str]:
    """Why ``plan`` (an ``autotune.space.Plan`` or plan-shaped mapping) is
    INCOMPATIBLE with the checkpoint described by ``manifest`` — or ``None``
    when the restored tree reshards onto it cleanly.

    The parameter tree's GLOBAL shapes are what restore validates against:
    ``pp``/``vp`` change the stacked-layer layout (``[L]`` vs
    ``[vp, pp, Lc]`` leading dims), so both must match the save.  tp/cp/ep/dp
    and microbatching only reshard or re-chunk the same global arrays — free
    to change."""
    old = dict(manifest.get("plan", {}) or {})
    get = (plan.get if isinstance(plan, Mapping)
           else lambda k, d=None: getattr(plan, k, d))
    pp_old, vp_old = int(old.get("pp", 1)), int(old.get("vp", 1))
    pp_new, vp_new = int(get("pp", 1) or 1), int(get("vp", 1) or 1)
    if pp_new != pp_old:
        return (f"pipeline_model_parallel_size {pp_old} -> {pp_new}: the "
                f"layer stack was saved sliced into {pp_old} stages")
    if vp_new != vp_old and (pp_old > 1 or pp_new > 1):
        return (f"virtual_pipeline_model_parallel_size {vp_old} -> {vp_new}: "
                f"the checkpoint's layer layout is "
                f"{manifest.get('layer_layout', 'flat')}")
    return None


@dataclasses.dataclass
class ReplanResult:
    """What :func:`maybe_replan` decided.  ``record`` is ``None`` when no
    replanning happened (no checkpoint, no manifest, or the world matches)."""

    cfg: Any
    record: Optional[dict] = None
    manifest: Optional[dict] = None
    checkpoint_dir: Optional[Path] = None
    # the discovery checkpointer's integrity trail (verified step, walk-back
    # count, quarantined steps) — non-None when discovery verification ran;
    # the trainer merges it into run_summary.json's integrity section
    integrity_trail: Optional[dict] = None

    @property
    def replanned(self) -> bool:
        return self.record is not None


def maybe_replan(cfg: Any, chips: int, *,
                 elastic: Optional[ElasticConfig] = None,
                 force: bool = False) -> ReplanResult:
    """The restart-time replanning entry (``nxdt-train`` start, drill
    harness): if a resumable checkpoint's manifest names a DIFFERENT world
    size than ``chips``, re-run the autotune planner on the new world,
    filtered to checkpoint-layout-compatible plans, and return the config
    with the winner imposed plus the old-plan→new-plan record.

    Raises :class:`ElasticResumeError` when the checkpoint cannot legally
    resume on this fleet (model identity changed, or no layout-compatible
    plan exists) — a curated error beats an opaque restore-shape crash."""
    if elastic is None:
        elastic = ElasticConfig.from_config(
            dict(cfg.get("exp_manager", {}) or {}).get("elastic"))
    ck_dir = discover_checkpoint_dir(cfg)
    if ck_dir is None:
        return ReplanResult(cfg=cfg)
    # manifest discovery verifies integrity and walks back: a corrupt newest
    # step is quarantined HERE, so the replanned layout keys off the step
    # the trainer will actually restore (docs/elasticity.md)
    from neuronx_distributed_training_tpu.checkpoint.integrity import (
        parse_checkpoint_block,
    )

    icfg = parse_checkpoint_block(
        dict(cfg.get("exp_manager", {}) or {}).get("checkpoint"))
    itrail: dict[str, Any] = {}
    manifest = read_latest_manifest(ck_dir, integrity=icfg, trail=itrail)
    itrail_or_none = itrail or None
    if manifest is None:
        return ReplanResult(cfg=cfg, checkpoint_dir=ck_dir,
                            integrity_trail=itrail_or_none)
    old_world = int(manifest.get("world_size", 0) or 0)
    if old_world == int(chips) and not force:
        return ReplanResult(cfg=cfg, manifest=manifest, checkpoint_dir=ck_dir,
                            integrity_trail=itrail_or_none)

    # model identity: a different model cannot "resume", replan or not
    from neuronx_distributed_training_tpu.autotune import plan_config

    mf = dict(manifest.get("model", {}) or {})
    model = dict(cfg.get("model", {}) or {})
    for key, cfg_key in (("num_layers", "num_layers"),
                         ("hidden_size", "hidden_size"),
                         ("vocab_size", "vocab_size")):
        want = int(mf.get(key, 0) or 0)
        have = int(model.get(cfg_key, 0) or 0)
        if want and have and want != have:
            raise ElasticResumeError(
                f"checkpoint at {ck_dir} was saved with model.{key}={want} "
                f"but this config declares {have}: not the same model — "
                f"resume refused"
            )

    t0 = time.perf_counter()
    report = plan_config(
        cfg, chips=int(chips), top_k=elastic.replan_top_k,
        audit=elastic.replan_audit, max_devices=min(8, int(chips)),
    )
    if report.error:
        raise ElasticResumeError(
            f"replan for {chips} chips failed: {report.error}"
        )
    chosen = None
    skipped: list[str] = []
    for cand in report.candidates:
        if cand.discarded:
            continue
        reason = plan_layout_reason(manifest, cand.plan)
        if reason is None:
            chosen = cand
            break
        skipped.append(f"{cand.plan.describe()}: {reason}")
    if chosen is None and report.n_plans > len(report.candidates):
        # the ranked top-k had no layout match — walk the FULL lattice
        # (analytic-only; a layout-compatible plan deep in the ranking still
        # beats refusing to resume)
        full = plan_config(cfg, chips=int(chips), top_k=report.n_plans,
                           audit=False)
        for cand in full.candidates:
            if not cand.discarded and plan_layout_reason(
                    manifest, cand.plan) is None:
                chosen = cand
                report = full
                break
    if chosen is None:
        # the lattice is curated, not exhaustive (e.g. vp candidates are a
        # fixed set, so a pp=14 vp=3 save has no lattice representation):
        # before refusing, accept the config's OWN declared parallelism when
        # it is legal on the new world and keeps the checkpoint layout —
        # this is also what makes the error's --set remediation actionable
        # (a hand-forced mesh re-enters this function first)
        fb = _declared_plan_fallback(cfg, manifest, int(chips))
        if fb is not None:
            dt = time.perf_counter() - t0
            record = {
                "old_world": old_world,
                "new_world": int(chips),
                "checkpoint_step": manifest.get("step"),
                "old_plan": dict(manifest.get("plan", {}) or {}),
                "new_plan": fb,
                "fallback": "declared-config",
                "replan_seconds": round(dt, 3),
                "skipped_incompatible": len(skipped),
            }
            logger.warning(
                "elastic replan: no lattice plan keeps the checkpoint's "
                "layer layout; keeping the config's declared parallelism "
                "%s on %d chips", _plan_str(fb), chips,
            )
            return ReplanResult(cfg=cfg, record=record, manifest=manifest,
                                checkpoint_dir=ck_dir,
                                integrity_trail=itrail_or_none)
        old_plan = dict(manifest.get("plan", {}) or {})
        raise ElasticResumeError(
            f"no plan for {chips} chips keeps the checkpoint's layer layout "
            f"(pp={old_plan.get('pp')}, "
            f"vp={old_plan.get('vp')}); candidates rejected: "
            + ("; ".join(skipped) if skipped else "none enumerated")
            + " — relaunch on a chip count that admits this layout, or "
              "force a compatible mesh by hand (--set distributed_strategy."
              "pipeline_model_parallel_size=... etc.)"
        )
    from neuronx_distributed_training_tpu.config.loader import load_config

    new_cfg = load_config(cfg, chosen.plan.overrides(report.facts))
    dt = time.perf_counter() - t0
    record = {
        "old_world": old_world,
        "new_world": int(chips),
        "checkpoint_step": manifest.get("step"),
        "old_plan": dict(manifest.get("plan", {}) or {}),
        "new_plan": dataclasses.asdict(chosen.plan),
        "predicted_step_seconds": round(chosen.estimate.step_seconds, 6),
        "replan_seconds": round(dt, 3),
        "skipped_incompatible": len(skipped),
    }
    logger.warning(
        "elastic replan: world %d -> %d chips; %s -> %s (%.1fs, "
        "%d layout-incompatible candidates skipped)",
        old_world, chips, _plan_str(record["old_plan"]),
        chosen.plan.describe(), dt, len(skipped),
    )
    return ReplanResult(cfg=new_cfg, record=record, manifest=manifest,
                        checkpoint_dir=ck_dir, integrity_trail=itrail_or_none)


def _declared_plan_fallback(cfg: Any, manifest: Mapping,
                            chips: int) -> Optional[dict]:
    """The config's own declared parallelism as a replan candidate: legal on
    ``chips`` (``batch_schedule`` validates the mesh/batch arithmetic) and
    layout-compatible with the checkpoint.  The escape hatch for layouts the
    curated plan lattice cannot express.  ``None`` when the declared plan
    does not fit the new world or the saved layout."""
    from neuronx_distributed_training_tpu.config.loader import batch_schedule

    ds = dict(cfg.get("distributed_strategy", {}) or {})
    tp = int(ds.get("tensor_model_parallel_size", 1) or 1)
    pp = int(ds.get("pipeline_model_parallel_size", 1) or 1)
    cp = int(ds.get("context_parallel_size", 1) or 1)
    if int(chips) % (tp * pp * cp) != 0:
        # batch_schedule floors dp — an inexact fit would silently idle chips
        return None
    try:
        sched = batch_schedule(cfg, int(chips))
    except Exception:  # noqa: BLE001 — an unfit declared plan is just "no"
        return None
    plan = {
        "tp": tp,
        "pp": pp,
        "cp": cp,
        "ep": int(ds.get("expert_model_parallel_size", 1) or 1),
        "vp": int(ds.get("virtual_pipeline_model_parallel_size") or 1),
        "dp": int(sched["dp_size"]),
        "micro_batch_size": int(sched["micro_batch_size"]),
        "num_microbatches": int(sched["num_microbatches"]),
    }
    if plan_layout_reason(manifest, plan) is not None:
        return None
    return plan


def _plan_str(plan: Mapping) -> str:
    # tools/metrics_report.py carries a deliberate stdlib-only copy of this
    # formatter — keep the two in sync when the plan record grows a key
    keys = ("dp", "tp", "pp", "cp", "ep", "vp")
    parts = [f"{k}={plan[k]}" for k in keys if plan.get(k) is not None]
    if plan.get("micro_batch_size") is not None:
        parts.append(f"mbs={plan['micro_batch_size']}")
    if plan.get("schedule") not in (None, "none"):
        parts.append(f"sched={plan['schedule']}")
    return " ".join(parts) or "?"


# ---------------------------------------------------------------------------
# fault injection (the drill harness's kill switch)
# ---------------------------------------------------------------------------


class SimulatedPreemption(RuntimeError):
    """Raised by :class:`FaultInjector` in ``kill`` mode — stands in for the
    process dying (SIGKILL/power loss) at a chosen point.  The drill harness
    catches it where a real fleet would observe the process gone."""


class SimulatedOOM(RuntimeError):
    """Raised by :class:`FaultInjector` in ``oom`` mode — stands in for the
    backend's allocator exhaustion (``XlaRuntimeError: RESOURCE_EXHAUSTED``)
    escaping the step boundary.  The message carries the same
    ``RESOURCE_EXHAUSTED`` marker the real error does, so
    ``telemetry.memory.is_oom_error`` (and therefore the ``oom_<step>/``
    forensic path) treats drill and reality identically."""


@dataclasses.dataclass
class FaultInjector:
    """Kills (or gracefully preempts, or hangs) a run at a configurable point.

    Attach to a trainer (``trainer.fault_injector = FaultInjector(...)``);
    the fit loop and checkpoint paths call :meth:`maybe_fire` at their
    injection points:

    - ``phase="step"``    just before the train step at ``at_step`` runs;
    - ``phase="save"``    right after a checkpoint save is INITIATED (an
      async save is in flight when the fault hits — the drain-on-teardown
      contract is what keeps it from being orphaned);
    - ``phase="restore"`` mid-restore, after the checkpoint was read but
      before any state was applied (the save must survive untouched);
    - ``phase="sync"``    inside the boundary's hang-watchdog guard, just
      before the blocking metric fetch (the collective rendezvous point).

    ``mode="kill"`` raises :class:`SimulatedPreemption`; ``mode="sigterm"``
    returns True once so the caller requests the graceful-stop path (the
    grace-window emergency checkpoint); ``mode="hang"`` BLOCKS for
    ``hang_seconds`` — the stand-in for a dead peer mid-collective, whose
    boundary sync never returns.  A hung injection point is what the
    armed :class:`~neuronx_distributed_training_tpu.telemetry.
    flight_recorder.HangWatchdog` escape is drilled against: the watchdog
    must dump the ``hang_<step>/`` bundle, emit the dying beacon, and exit
    the process with ``EXIT_HANG_ESCAPE`` long before the sleep ends.
    ``mode="oom"`` raises :class:`SimulatedOOM` (message carrying the real
    backend's ``RESOURCE_EXHAUSTED`` marker) — the OOM-forensics drill:
    the fit loop must dump a complete ``oom_<step>/`` bundle
    (``telemetry.memory``) before the error propagates.
    """

    at_step: int
    mode: str = "kill"          # kill | sigterm | hang | oom
    phase: str = "step"         # step | save | restore | sync
    fired: bool = False
    #: how long mode="hang" blocks; the watchdog is expected to escape the
    #: process well before this elapses (bounded so a BROKEN watchdog fails
    #: the drill in minutes, not forever)
    hang_seconds: float = 60.0

    def __post_init__(self) -> None:
        if self.mode not in ("kill", "sigterm", "hang", "oom"):
            raise ValueError(
                f"FaultInjector.mode must be kill|sigterm|hang|oom, "
                f"got {self.mode!r}")
        if self.phase not in ("step", "save", "restore", "sync"):
            raise ValueError(
                f"FaultInjector.phase must be step|save|restore|sync, "
                f"got {self.phase!r}")

    def maybe_fire(self, phase: str, step: int) -> bool:
        """Called at each injection point; fires at most once."""
        if self.fired or phase != self.phase or int(step) < self.at_step:
            return False
        self.fired = True
        if self.mode == "kill":
            raise SimulatedPreemption(
                f"injected {self.phase} kill at step {step}")
        if self.mode == "oom":
            raise SimulatedOOM(
                f"RESOURCE_EXHAUSTED: injected allocator exhaustion at "
                f"step {step} (drill stand-in for the backend's OOM)")
        if self.mode == "hang":
            logger.warning("injected %s hang at step %d (%.0fs — the "
                           "watchdog should escape first)", self.phase, step,
                           self.hang_seconds)
            time.sleep(self.hang_seconds)
            return False
        return True
