"""Experiment management: log dirs, TensorBoard, throughput, resume detection.

Re-design of the reference's ``utils/exp_manager.py`` (579 LoC of NeMo
exp-manager glue): log-dir/version management (``exp_manager.py:81-200``),
TensorBoard logger creation (``:271-291``), step timing (``TimingCallback``,
``:64-78``), and auto-resume discovery (``check_resume``, ``:333-404``) —
without Lightning callbacks: the trainer calls ``log_metrics`` directly and
Orbax ``latest_step`` replaces newest-``*.ckpt`` scanning.
"""

from __future__ import annotations

import json
import logging
import time
from pathlib import Path
from typing import Any, Optional

from neuronx_distributed_training_tpu.telemetry import TelemetryConfig
from neuronx_distributed_training_tpu.utils.io import atomic_write_json
from neuronx_distributed_training_tpu.utils.perf import Throughput, mfu as _mfu

logger = logging.getLogger(__name__)


def _exp_base_path(exp_dir, name):
    """``<exp-root>/<name>`` with remote-store URIs (``gs://`` etc.) routed
    through epath — ``Path()`` would mangle the scheme into a local dir
    literally named ``gs:``."""
    if "://" in str(exp_dir):
        from etils import epath

        return epath.Path(str(exp_dir)) / str(name)
    return Path(str(exp_dir)) / str(name)


def exp_root_and_name(cfg: dict) -> tuple:
    """``(exp-root, name)`` for a config — THE key-fallback chain
    (``explicit_log_dir`` → ``exp_dir`` → default, ``name`` from the block or
    the config root), shared by :meth:`ExpManager.from_config`, the elastic
    replanner's checkpoint discovery (``trainer/elastic.py``), and the drill
    harness (``tools/elastic_drill.py``) so all of them resolve the directory
    ``ExpManager`` will actually open."""
    em = dict(cfg.get("exp_manager", {}) or {})
    return (
        em.get("explicit_log_dir") or em.get("exp_dir") or "nxdt_experiments",
        em.get("name", cfg.get("name", "default")),
    )


def experiment_base_dir(cfg: dict) -> Any:
    """``<exp-root>/<name>`` for a config (see :func:`exp_root_and_name`)."""
    return _exp_base_path(*exp_root_and_name(cfg))


def latest_version(base) -> Optional[int]:
    """Newest ``version_N`` index under ``base`` (digit-suffixed dirs only,
    an operator's ``version_backup_2`` is ignored) — THE version-dir parse,
    shared by :class:`ExpManager`, the elastic replanner's checkpoint
    discovery (``trainer/elastic.py``), and the drill harness
    (``tools/elastic_drill.py``), so all three always select the same
    directory.  ``None`` when no versions exist."""
    if not base.exists():
        return None
    versions = sorted(
        int(p.name.split("_")[1])
        for p in base.glob("version_*")
        if p.name.split("_")[1].isdigit()
    )
    return versions[-1] if versions else None


class ExpManager:
    """Owns the experiment directory and metric writers."""

    def __init__(
        self,
        exp_dir: str | Path = "nxdt_experiments",
        name: str = "default",
        *,
        version: Optional[str] = None,
        create_tensorboard_logger: bool = True,
        log_every_n_steps: int = 10,
        global_batch_size: int = 1,
        resume_if_exists: bool = False,
        profile_start_step: int = 0,  # 0 = profiling off
        profile_num_steps: int = 3,
        create_wandb_logger: bool = False,
        wandb_kwargs: Optional[dict] = None,
        create_mlflow_logger: bool = False,
        mlflow_kwargs: Optional[dict] = None,
        log_files: bool = True,
        log_local_rank_0_only: bool = False,
        log_global_rank_0_only: bool = False,
        seq_len: int = 0,
        telemetry: Optional[TelemetryConfig] = None,
    ):
        base = _exp_base_path(exp_dir, name)
        if version is None:
            if resume_if_exists and base.exists():
                v = latest_version(base)
                version = f"version_{v}" if v is not None else "version_0"
            else:
                n = 0
                while (base / f"version_{n}").exists():
                    n += 1
                version = f"version_{n}"
        self.log_dir = base / version
        self.log_dir.mkdir(parents=True, exist_ok=True)
        self.checkpoint_dir = self.log_dir / "checkpoints"
        self.log_every_n_steps = log_every_n_steps
        self.throughput = Throughput(global_batch_size, seq_len=seq_len)
        self.telemetry = telemetry if telemetry is not None else TelemetryConfig()
        self._last_tput: Optional[float] = None
        self._last_step_time: Optional[float] = None
        self._metrics_file = self.log_dir / "metrics.jsonl"
        # structured tensorstats records (histogram vectors — NOT scalars)
        # stream here, next to metrics.jsonl; see log_tensorstats
        self._tensorstats_file = self.log_dir / "tensorstats.jsonl"
        #: newest decoded tensorstats record — the loop teardown persists it
        #: as the run_summary.json "tensorstats" section
        self.last_tensorstats: Optional[dict] = None
        self._run_summary_file = self.log_dir / "run_summary.json"
        # run_summary.json is a read-modify-write merge reached from the main
        # thread (census, goodput teardown) AND, when the hang watchdog fires
        # without aborting, from its timer thread (anomaly trail) — serialize
        import threading

        self._summary_lock = threading.Lock()
        # set by set_mfu_reference: (train-step FLOPs/token, chips, peak TF/s)
        self._mfu_ref: Optional[tuple[float, int, float]] = None
        # metric keys already warned about as non-scalar (warn ONCE per key:
        # the sinks take scalars only, and silently dropping a value hides
        # an instrumentation bug — but warning every boundary is log spam)
        self._warned_nonscalar: set[str] = set()

        self.profile_start_step = profile_start_step
        self.profile_num_steps = profile_num_steps
        self._profiling = False
        # windowed device-time capture (telemetry.trace): summary lands in
        # trace_summary.json next to run_summary.json
        self._trace: Optional[Any] = None
        if self.telemetry.trace.enabled:
            from neuronx_distributed_training_tpu.telemetry.trace import (
                TraceCapture,
            )

            self._trace = TraceCapture(self.telemetry.trace, self.log_dir)

        self._tb = None
        if create_tensorboard_logger:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=str(self.log_dir / "tb"))
            except Exception as e:  # noqa: BLE001 — TB is optional observability
                logger.warning("TensorBoard logger unavailable: %s", e)
        self._wandb = None
        if create_wandb_logger:
            try:
                import wandb

                self._wandb = wandb.init(
                    dir=str(self.log_dir), name=name, **(wandb_kwargs or {})
                )
            except Exception as e:  # noqa: BLE001 — W&B is optional
                logger.warning("W&B logger unavailable: %s", e)
        self._mlflow = None
        if create_mlflow_logger:
            # reference create_mlflow_logger/mlflow_logger_kwargs
            # (utils/exp_manager.py:133-135, 223-228); soft-gated import
            try:
                import mlflow

                kw = dict(mlflow_kwargs or {})
                mlflow.set_tracking_uri(
                    kw.pop("tracking_uri", f"file:{self.log_dir / 'mlruns'}")
                )
                mlflow.set_experiment(kw.pop("experiment_name", name))
                self._mlflow = mlflow
                self._mlflow_run = mlflow.start_run(run_name=version)
            except Exception as e:  # noqa: BLE001 — MLflow is optional
                logger.warning("MLflow logger unavailable: %s", e)
        self._file_handler = None
        if log_files:
            self._file_handler = self._setup_rank_log_file(
                log_local_rank_0_only, log_global_rank_0_only
            )

    def _setup_rank_log_file(self, local_rank_0_only: bool,
                             global_rank_0_only: bool):
        """Per-rank log files (reference ``exp_manager.py:249-268``:
        ``nemo_log_globalrank-G_localrank-L.txt`` with rank-0-only gating)."""
        if local_rank_0_only and global_rank_0_only:
            raise ValueError(
                "Cannot set both log_local_rank_0_only and "
                "log_global_rank_0_only; pick one or neither."
            )
        import jax

        g = jax.process_index()
        # one process per host on TPU: local rank == 0 within its host
        local = 0
        if (global_rank_0_only and g != 0) or (local_rank_0_only and local != 0):
            return None
        # SLURM relaunches write under restart_N/ so earlier logs survive
        # (reference train_setup.sh:28-29 restart-count log pathing); the
        # version dir itself is shared so checkpoint auto-resume still works
        from pathlib import Path

        from neuronx_distributed_training_tpu.utils.launch import restart_log_dir

        log_dir = Path(restart_log_dir(str(self.log_dir)))
        log_dir.mkdir(parents=True, exist_ok=True)
        path = log_dir / f"nxdt_log_globalrank-{g}_localrank-{local}.txt"
        handler = logging.FileHandler(path)
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s [%(name)s] %(message)s"
        ))
        logging.getLogger().addHandler(handler)
        return handler

    @classmethod
    def from_config(cls, cfg: dict[str, Any], global_batch_size: int = 1) -> "ExpManager":
        """Build from the reference's ``exp_manager:`` block
        (``config_overview.rst:200-249``)."""
        em = dict(cfg.get("exp_manager", {}) or {})
        exp_dir, name = exp_root_and_name(cfg)
        return cls(
            exp_dir=exp_dir,
            name=name,
            create_tensorboard_logger=bool(em.get("create_tensorboard_logger", True)),
            log_every_n_steps=int(
                (cfg.get("trainer", {}) or {}).get("log_every_n_steps", 10)
            ),
            global_batch_size=global_batch_size,
            resume_if_exists=bool(em.get("resume_if_exists", False)),
            profile_start_step=int(em.get("profile_start_step", 0) or 0),
            profile_num_steps=int(em.get("profile_num_steps", 3)),
            create_wandb_logger=bool(em.get("create_wandb_logger", False)),
            wandb_kwargs=dict(em.get("wandb_logger_kwargs", {}) or {}),
            create_mlflow_logger=bool(em.get("create_mlflow_logger", False)),
            mlflow_kwargs=dict(em.get("mlflow_logger_kwargs", {}) or {}),
            log_files=bool(em.get("log_files", True)),
            log_local_rank_0_only=bool(em.get("log_local_rank_0_only", False)),
            log_global_rank_0_only=bool(em.get("log_global_rank_0_only", False)),
            seq_len=int((cfg.get("data", {}) or {}).get("seq_length", 0) or 0),
            telemetry=TelemetryConfig.from_config(em.get("telemetry")),
        )

    # -- profiling (jax.profiler -> TensorBoard profile plugin; the TPU-native
    # replacement for neuron-top/neuron-monitor, SURVEY.md §5.1) --------------

    _PROFILE_OWNER = "exp_manager.profile"

    def maybe_profile(self, step: int) -> None:
        """Start/stop a ``jax.profiler`` trace around the configured window.

        Start/stop go through the telemetry.trace session guard: the jax
        profiler session is process-global, and the unguarded window-end
        stop here vs the teardown stop in :meth:`close` could double-stop
        (raising out of teardown) — or stomp a live ``telemetry.trace``
        capture window."""
        if not self.profile_start_step:
            return
        from neuronx_distributed_training_tpu.telemetry.trace import (
            start_session,
            stop_session,
        )

        if step == self.profile_start_step and not self._profiling:
            self._profiling = start_session(
                str(self.log_dir / "profile"), self._PROFILE_OWNER)
        elif self._profiling and step >= self.profile_start_step + self.profile_num_steps:
            self._profiling = False
            stop_session(self._PROFILE_OWNER)

    def set_pipeline_facts(self, facts: Optional[dict[str, Any]]) -> None:
        """Arm the trace capture's pipeline-timeline reconstruction with the
        resolved schedule facts (``telemetry.step_timeline.pipeline_facts``).
        The trainer calls this once the schedule is known; with pp > 1 the
        next closed trace window carries the ``"pipeline"`` section and
        ``bubble_fraction_measured`` lands in ``run_summary.json`` next to
        the predicted fraction."""
        if self._trace is not None:
            self._trace.pipeline = dict(facts) if facts else None

    def set_comms_facts(self, facts: Optional[dict[str, Any]]) -> None:
        """Arm the trace capture's interconnect join with the cost model's
        per-axis byte volumes and the topology peak
        (``telemetry.comms.comms_section`` inputs).  The trainer calls this
        once the plan resolves; the next closed trace window then joins the
        MEASURED per-class wire seconds with the priced byte volumes into
        a ``"comms"`` section — per-class achieved_gbps and efficiency —
        in ``trace_summary.json`` / ``run_summary.json`` and through the
        metric sinks as ``comms/*`` scalars."""
        if self._trace is not None:
            self._trace.comms = dict(facts) if facts else None

    def maybe_trace(self, step: int) -> None:
        """Advance the ``telemetry.trace`` capture window (no-op when the
        knob is off).  When the window closes, the analyzed summary is in
        ``trace_summary.json`` and its headline numbers (achieved overlap,
        exposed collective seconds) are merged into ``run_summary.json``."""
        if self._trace is None:
            return
        summary = self._trace.maybe_update(step)
        if summary is not None:
            self._record_trace_summary(summary)

    @property
    def trace_active(self) -> bool:
        """Is a telemetry.trace capture window currently open?  The trainer
        keeps emitting ``StepTraceAnnotation``s while this is True even when
        ``spans`` is off, so per-step attribution always has windows."""
        return self._trace is not None and self._trace.active

    def _record_trace_summary(self, summary: dict[str, Any]) -> None:
        section: dict[str, Any] = {"trace": {
            "achieved_overlap": summary.get("achieved_overlap"),
            "exposed_collective_seconds": summary.get(
                "exposed_collective_seconds"),
            "collective_seconds": summary.get("collective_seconds"),
            "window": summary.get("window"),
            "summary_path": str(self._trace.summary_path),
        }}
        pipe = summary.get("pipeline")
        if isinstance(pipe, dict):
            # the MEASURED bubble fraction is a run fact: it lives at the
            # top level of run_summary.json beside bubble_fraction_predicted
            # (the compile-census run fact), plus a compact pipeline block
            section["bubble_fraction_measured"] = pipe.get(
                "bubble_fraction_measured")
            section["trace"]["pipeline"] = {
                k: pipe.get(k)
                for k in ("schedule", "bubble_fraction_measured",
                          "bubble_fraction_predicted", "bubble_residual",
                          "straggler_stage", "lane_resolution", "num_lanes")
                if pipe.get(k) is not None
            }
        comms = summary.get("comms")
        if isinstance(comms, dict):
            # the achieved-bandwidth join is a run fact too: per-class
            # achieved_gbps/efficiency at the top level for the perf
            # contract's PC204 extraction, and comms/* scalars through
            # every sink (and the fleet beacon's metric pick)
            section["comms"] = comms
            try:
                from neuronx_distributed_training_tpu.telemetry.comms import (
                    comms_metrics,
                )

                scalars = comms_metrics(comms)
                if scalars:
                    window = summary.get("window") or {}
                    step = int(window.get("start_step", 0) or 0) + int(
                        window.get("num_steps", 0) or 0)
                    self.log_metrics(step, scalars, force=True)
            except Exception as e:  # noqa: BLE001 — telemetry only
                logger.warning("comms metric emission failed: %s", e)
        self.write_run_summary(section)

    # -- per-step hooks -----------------------------------------------------

    def step_timed(self, num_steps: int = 1, exclude_seconds: float = 0.0) -> float:
        """Record a step boundary covering ``num_steps`` steps since the last
        call; returns per-step wall seconds (0.0 on first).

        ``exclude_seconds`` — wall time since the last call spent OUTSIDE
        steady-state training (validation, checkpointing, first-step compile;
        the trainer passes ``SpanTimer.take_excluded()``) — is subtracted
        before the per-step division, so the throughput window and
        ``throughput_peak`` reflect training only instead of silently folding
        a checkpoint stall into seq/s."""
        now = time.perf_counter()
        if self._last_step_time is None:
            dt = 0.0
        else:
            window = now - self._last_step_time - max(exclude_seconds, 0.0)
            dt = max(window, 0.0) / max(num_steps, 1)
        self._last_step_time = now
        if dt > 0:
            self._last_tput = self.throughput.update(dt, num_steps=num_steps)
        return dt

    def set_mfu_reference(
        self,
        *,
        train_step_flops_per_token: float,
        n_chips: int,
        peak_tflops_per_chip: float,
    ) -> None:
        """Arm MFU/tokens-per-sec-per-chip logging.  The trainer calls this
        once with the analytic per-family FLOPs estimate
        (``utils.perf.flops_for_model`` x3 for fwd+2xbwd); from then on every
        ``log_metrics`` derives ``mfu`` from the throughput window's
        ``tokens_per_sec`` — one source of truth, no second timer."""
        self._mfu_ref = (
            float(train_step_flops_per_token), max(int(n_chips), 1),
            float(peak_tflops_per_chip),
        )

    def write_run_summary(self, section: dict[str, Any]) -> None:
        """Merge ``section`` into ``run_summary.json`` (next to
        ``metrics.jsonl``): the one-shot facts of the run — compile census,
        goodput totals — that don't belong in the per-step stream.

        The write is atomic (serialize, temp file, rename): a SIGKILL
        mid-write — preemption, OOM-killer, the elastic drill's kill
        injector — must never leave a truncated document for resume or
        reporting to choke on, and an unserializable ``section`` raises
        with the previous contents intact."""
        with self._summary_lock:
            existing: dict[str, Any] = {}
            try:
                with open(self._run_summary_file) as f:
                    existing = json.load(f)
            except (OSError, ValueError):
                pass
            existing.update(section)
            atomic_write_json(self._run_summary_file, existing)

    def log_metrics(self, step: int, metrics: dict[str, Any], *, force: bool = False) -> None:
        """Write scalars (TB + jsonl) every ``log_every_n_steps``.

        Scalars logged mirror the reference's set: reduced_train_loss, lr,
        grad/param norm, throughput, throughput_peak, consumed_samples
        (``base.py:624-654``).  Non-scalar values are coerced when they hold
        exactly one element (0-d / size-1 arrays) and otherwise dropped with
        a once-per-key warning naming the offender — every sink (TB, W&B,
        MLflow, jsonl) takes scalars only, and a silent drop hides the
        instrumentation bug that produced the value."""
        if not force and step % self.log_every_n_steps != 0:
            return
        flat: dict[str, float] = {}
        stray_tensorstats: dict[str, Any] = {}
        for k, v in metrics.items():
            f = _coerce_scalar(v)
            if f is None:
                if k.startswith("tensorstats"):
                    # a tensorstats histogram vector that reached the scalar
                    # path (a caller that didn't pre-split the boundary
                    # fetch): route it to its own stream instead of the
                    # warn-once drop — the payload is structured BY DESIGN
                    stray_tensorstats[k] = v
                    continue
                if k not in self._warned_nonscalar:
                    self._warned_nonscalar.add(k)
                    shape = getattr(v, "shape", None)
                    logger.warning(
                        "log_metrics: dropping non-scalar metric %r "
                        "(%s%s) — the TB/W&B/MLflow/jsonl sinks take "
                        "scalars; log a reduction instead (warned once)",
                        k, type(v).__name__,
                        f", shape {tuple(shape)}" if shape is not None
                        else "",
                    )
                continue
            flat[k] = f
        if stray_tensorstats:
            self.log_tensorstats(step, stray_tensorstats)
        if self._last_tput is not None:
            flat["throughput_seqs_per_sec"] = self._last_tput
            flat["throughput_peak"] = self.throughput.peak
            tokens = self.throughput.tokens_per_sec
            if self.telemetry.mfu and self._mfu_ref is not None and tokens > 0:
                step_flops, n_chips, peak_tf = self._mfu_ref
                per_chip = tokens / n_chips
                flat["tokens_per_sec_per_chip"] = per_chip
                if peak_tf > 0:
                    flat["mfu"] = _mfu(per_chip, step_flops, peak_tf)
        if self._tb is not None:
            for k, v in flat.items():
                self._tb.add_scalar(k, v, step)
        if self._wandb is not None:
            self._wandb.log(flat, step=step)
        if self._mlflow is not None:
            self._mlflow.log_metrics(flat, step=step)
        with open(self._metrics_file, "a") as f:
            f.write(json.dumps({"step": step, **flat}) + "\n")

    def log_tensorstats(self, step: int, payload: dict[str, Any]) -> None:
        """Append one structured tensor-numerics-observatory record to
        ``tensorstats.jsonl``.

        ``payload`` maps ``tensorstats_hist/<phase>/<group>`` metric keys to
        the packed cumulative vectors fetched at the boundary (numpy arrays
        or float sequences — see ``telemetry.tensorstats.CUM_HEADER``).
        These are ARRAYS: they must never reach the scalar sinks, so they
        get their own strict-JSON stream (one decoded record per boundary)
        plus ``self.last_tensorstats`` for the run_summary teardown
        section.  Keys without the hist prefix are ignored (defensive: the
        caller may hand over a mixed dict)."""
        from neuronx_distributed_training_tpu.telemetry.tensorstats import (
            HIST_PREFIX,
            decode_cum,
        )

        cfg = self.telemetry.tensorstats
        groups: dict[str, Any] = {}
        for k, v in payload.items():
            if not k.startswith(HIST_PREFIX):
                continue
            try:
                groups[k[len(HIST_PREFIX):]] = decode_cum(v, cfg)
            except (TypeError, ValueError) as e:
                logger.warning(
                    "log_tensorstats: undecodable payload for %r: %s", k, e)
        if not groups:
            return
        rec = {
            "step": int(step),
            "hist_lo_exp": cfg.hist_lo_exp,
            "hist_hi_exp": cfg.hist_hi_exp,
            "groups": groups,
        }
        self.last_tensorstats = rec
        try:
            with open(self._tensorstats_file, "a") as f:
                f.write(json.dumps(rec, allow_nan=False) + "\n")
        except (OSError, ValueError, TypeError) as e:
            # observability must not kill training
            logger.warning("tensorstats.jsonl write failed: %s", e)

    def close(self) -> None:
        if self._profiling:
            # guarded: a window that already closed (or was stopped
            # out-of-band) makes this a logged no-op, not a teardown raise
            from neuronx_distributed_training_tpu.telemetry.trace import (
                stop_session,
            )

            self._profiling = False
            stop_session(self._PROFILE_OWNER)
        if self._trace is not None:
            summary = self._trace.close()
            if summary is not None:
                self._record_trace_summary(summary)
        if self._tb is not None:
            self._tb.flush()
            self._tb.close()
        if self._wandb is not None:
            self._wandb.finish()
        if self._mlflow is not None:
            self._mlflow.end_run()
        if self._file_handler is not None:
            logging.getLogger().removeHandler(self._file_handler)
            self._file_handler.close()
            self._file_handler = None


def _is_scalar(v: Any) -> bool:
    return _coerce_scalar(v) is not None


def _coerce_scalar(v: Any) -> Optional[float]:
    """Host float from a scalar-like value, else None.

    ``float()`` covers Python numbers and numpy/jax 0-d arrays / device
    scalars; size-1 arrays of higher rank (``np.array([3.0])``) go through
    ``item()`` (newer numpy deprecates ``float()`` on them).  Multi-element
    arrays (and anything else) return None — the caller decides whether to
    warn."""
    if getattr(v, "ndim", 0):
        if getattr(v, "size", 0) == 1:
            try:
                return float(v.item())
            except (TypeError, ValueError):
                return None
        return None
    try:
        return float(v)
    except (TypeError, ValueError):
        pass
    item = getattr(v, "item", None)
    if item is not None and getattr(v, "size", 0) == 1:
        try:
            return float(item())
        except (TypeError, ValueError):
            pass
    return None
