"""The training loop — `train(cfg)` replaces the reference's L1/L2 stack.

Where the reference assembles NLPTrainer + NLPDDPStrategy + Lightning fit loops
+ exp_manager (reference ``examples/training.py:41-94``,
``nlp_overrides.py:288-533``), this is one explicit loop:

    cfg -> mesh, dtype policy, model, data module, optimizer, checkpointer
    for step in range(max_steps):
        batch -> sharded device arrays -> jitted train step -> metrics
        periodic: validation, checkpoint (async), logging

Auto-resume restores params/opt-state/step/consumed-samples from the newest
checkpoint (the reference's ``resume_if_exists`` flow, ``exp_manager.py:333-404``).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_training_tpu.checkpoint import (
    CheckpointConfig,
    Checkpointer,
    TrainState,
)
from neuronx_distributed_training_tpu.config.loader import ConfigDict, batch_schedule
from neuronx_distributed_training_tpu.data import (
    DataModule,
    DataStallError,
    PrefetchIterator,
    SyntheticDataModule,
)
from neuronx_distributed_training_tpu.models import llama
from neuronx_distributed_training_tpu.optim.adamw import (
    AdamWConfig,
    EMAConfig,
    init_opt_state,
    opt_state_specs,
)
from neuronx_distributed_training_tpu.optim.lr import build_lr_schedule
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
from neuronx_distributed_training_tpu.trainer.exp_manager import ExpManager
from neuronx_distributed_training_tpu.trainer.step import (
    jit_train_step,
    make_eval_step,
    make_train_step,
)
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

logger = logging.getLogger(__name__)

#: base seed of the loop's per-step RNG derivation — each train step runs
#: with ``fold_in(PRNGKey(STEP_KEY_SEED), step)``, and the flight recorder's
#: bundles cite the same recipe for offline replay (one source of truth)
STEP_KEY_SEED = 0


def parse_max_time(value: Any) -> Optional[float]:
    """``trainer.max_time`` -> seconds.  Accepts NeMo's ``DD:HH:MM:SS`` string
    (reference ``StatelessTimer``, ``examples/training.py:65-69``) or a number
    of seconds.  "Stateless": each (re)start gets the full budget — elapsed
    time is deliberately NOT carried through checkpoints, so a requeued SLURM
    job trains for another ``max_time`` instead of exiting immediately."""
    if value in (None, "", 0):
        return None
    if isinstance(value, (int, float)):
        return float(value)
    parts = [int(p) for p in str(value).split(":")]
    if len(parts) != 4:
        raise ValueError(f"trainer.max_time must be DD:HH:MM:SS, got {value!r}")
    d, h, m, s = parts
    return float(((d * 24 + h) * 60 + m) * 60 + s)


def _local_mesh_devices(mesh) -> list:
    """This process's devices of the mesh (every device single-host)."""
    devices = list(getattr(mesh, "local_devices", None) or mesh.devices.flat)
    if not devices:
        devices = list(mesh.devices.flat)
    return devices


#: ``memory/`` metric -> its legacy ``device_*`` key (telemetry.
#: device_memory predates the memory plane; beacons and dashboards key on
#: these names)
_LEGACY_DEVICE_MEMORY_KEYS = (
    ("memory/bytes_in_use_max", "device_bytes_in_use"),
    ("memory/peak_bytes_max", "device_peak_bytes_in_use"),
    ("memory/bytes_limit_min", "device_bytes_limit"),
    ("memory/bytes_in_use_min", "device_bytes_in_use_min"),
    ("memory/bytes_in_use_p50", "device_bytes_in_use_p50"),
    ("memory/peak_device", "device_peak_device"),
)


def _legacy_device_memory_keys(mm: dict[str, float]) -> dict[str, float]:
    """``memory/`` metrics -> the legacy ``device_*`` names, so a boundary
    with BOTH ``device_memory`` and ``telemetry.memory`` on runs ONE
    allocator sweep (the two keys would otherwise come from two sweeps at
    slightly different instants and disagree within one record)."""
    return {dst: mm[src] for src, dst in _LEGACY_DEVICE_MEMORY_KEYS
            if src in mm}


def _device_memory_metrics(mesh) -> dict[str, float]:
    """Live allocator stats across ALL local mesh devices
    (telemetry.device_memory).

    ``memory_stats()`` is a local allocator query — no device sync — but
    some backends (CPU, older plugins) don't implement it; those log
    nothing.  The legacy ``device_*`` keys carry the WORST device (max
    in-use/peak, min limit) with min/p50 spread alongside and the peak
    device named by index — a skewed-stage pp run must not hide an
    OOM-bound device behind a roomy rank 0."""
    from neuronx_distributed_training_tpu.telemetry.memory import (
        device_memory_samples,
        memory_metrics,
    )

    samples = device_memory_samples(_local_mesh_devices(mesh))
    return _legacy_device_memory_keys(memory_metrics(samples))


def _sidecar_load(path, tag):
    """Read a reference-logp sidecar -> (done_upto, cols) or None.

    URI paths (gs://) read through epath; local reads tolerate a truncated
    file (crash mid-write predating the atomic spill) by recomputing."""
    if path is None:
        return None
    try:
        if "://" in str(path):
            import io

            from etils import epath

            p = epath.Path(path)
            if not p.exists():
                return None
            loaded = np.load(io.BytesIO(p.read_bytes()))
        else:
            import os

            if not os.path.exists(path):
                return None
            loaded = np.load(path)
    except Exception:
        logger.warning("%s sidecar %s unreadable; recomputing", tag, path)
        return None
    files = [k for k in loaded.files if k != "_done_upto"]
    done = int(loaded["_done_upto"]) if "_done_upto" in loaded.files else (
        len(loaded[files[0]]) if files else 0)
    return done, {k: np.array(loaded[k]) for k in files}


def _sidecar_store(path, done, cols):
    """Write the sidecar atomically: local tmp + rename, or a single remote
    object write (object stores commit whole objects)."""
    if "://" in str(path):
        import io

        from etils import epath

        buf = io.BytesIO()
        np.savez(buf, _done_upto=done, **cols)
        p = epath.Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_bytes(buf.getvalue())
        return
    import os

    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp.npz"
    np.savez(tmp, _done_upto=done, **cols)
    os.replace(tmp, path)


@dataclasses.dataclass
class StepProgram:
    """The config's train step as a PROGRAM, before any device state exists.

    Everything ``Trainer.from_config`` derives purely from the config — mesh,
    dtype policy, model, loss, specs, the jitted (but un-lowered) train step,
    abstract param/opt trees — with zero arrays materialized and no data files
    opened.  Two consumers:

    - ``Trainer.from_config`` materializes it (sharded-at-birth init, data
      modules, checkpointing) into a live session;
    - ``analysis.graph_audit`` AOT-lowers it on abstract inputs and checks the
      compiled artifact against the config's declared contracts (donation,
      collective census, precision) without spending a device-hour.

    ``build_data=False`` (the audit path) skips ``build_data_module`` entirely:
    no tokenizer download, no arrow/mmap open — ``shift_labels`` is derived
    statically (Megatron mmap data, the only pre-shifted source, is keyed on
    ``data.data_prefix``) and both data modules stay ``None``.
    """

    cfg: ConfigDict
    mesh: Any
    mesh_cfg: Any
    policy: DtypePolicy
    sched: dict
    seed: int
    alignment: str
    align_params: dict
    model_cfg: Any
    loss_fn: Callable
    eval_loss_fn: Callable
    forward_logits: Optional[Callable]
    param_builder: Callable
    init_key: Any
    abstract_params: Any
    pspecs: Any
    ospecs: Any
    opt_cfg: Any
    ema_cfg: Optional[Any]
    health_cfg: Any
    tensorstats_cfg: Any
    tensorstats_bucket_groups: tuple
    trainable: Any
    lora_block: dict
    jstep: Callable
    eval_fn: Optional[Callable]
    data_module: Optional[DataModule]
    val_data_module: Optional[DataModule]
    shift_labels: bool
    pipeline_schedule: Optional[str]
    num_micro_in_step: int
    max_steps: int
    donate: Any


@dataclasses.dataclass
class Trainer:
    """Assembled training session.  Build with ``Trainer.from_config``."""

    cfg: ConfigDict
    mesh: Any
    policy: DtypePolicy
    model_cfg: Any
    loss_fn: Callable
    params: Any
    opt_state: Any
    param_specs: Any
    opt_specs: Any
    train_step: Callable
    eval_step: Optional[Callable]
    data_module: DataModule
    val_data_module: Optional[DataModule]
    exp: ExpManager
    checkpointer: Optional[Checkpointer]
    max_steps: int
    step: int = 0
    pre_fit: Optional[Callable] = None  # runs once before the loop (DPO ref pass)
    ema_cfg: Optional[Any] = None  # optim.adamw.EMAConfig when EMA is enabled
    # resolved schedule under pp ("1f1b"/"1f1b-interleaved"/"1f1b-zb"/
    # "wavefront"), else None
    pipeline_schedule: Optional[str] = None
    # static facts of the run (model family, chips, seq len, analytic FLOPs)
    # persisted with the compile census into run_summary.json
    run_facts: dict = dataclasses.field(default_factory=dict)
    # donation mode the jitted step was built with (StepProgram.donate) —
    # the in-loop graph audit checks the SAME donated set, not a re-derived one
    donate: Any = True
    # elastic-resume policy (trainer.elastic.ElasticConfig; parsed from
    # exp_manager.elastic): SIGTERM grace window, save retry, replan knobs
    elastic: Optional[Any] = None
    # restart-time replan record (trainer.elastic.maybe_replan) — set by the
    # CLI / drill harness when the live world size differed from the
    # checkpoint manifest; fit() accounts its wall time as a "replan" span
    # and persists it in run_summary.json's elastic section
    replan_record: Optional[dict] = None
    # integrity trail of the DISCOVERY-time verification (trainer.elastic.
    # maybe_replan walked back / quarantined before this trainer existed);
    # merged with the checkpointer's own restore trail into the
    # run_summary.json integrity section at teardown
    discovery_integrity_trail: Optional[dict] = None
    # preemption drill hook (trainer.elastic.FaultInjector): fires at the
    # step/save/restore injection points; None outside drills
    fault_injector: Optional[Any] = None
    # sigterm-mode injection at the save/restore points happens outside the
    # fit loop's scope, so those call sites park the notice here and the loop
    # top converts it into a graceful-stop request (same path as SIGTERM)
    preemption_notice: Optional[str] = None
    # drill/test seam of the fleet control plane (trainer.control): extra
    # control-word bits standing in for other hosts' contributions on a
    # single-process mesh; the production path folds real processes through
    # the boundary collective
    control_peer_words: Optional[Callable[[], int]] = None
    # the deciding stop condition of the finished run ("health_halt",
    # "alert_halt", "data_stall", "preemption", "operator_stop",
    # "max_time"; None for a clean completion) — trainer.control's
    # exit_code_for_stop maps it to the orchestrator-facing exit code
    stop_class: Optional[str] = None

    # -- assembly -----------------------------------------------------------

    @classmethod
    def from_config(
        cls,
        cfg: ConfigDict,
        *,
        data_module: Optional[DataModule] = None,
        val_data_module: Optional[DataModule] = None,
        devices: Optional[list] = None,
        enable_checkpointing: bool = True,
    ) -> "Trainer":
        devices = devices if devices is not None else jax.devices()
        asm = cls.assemble(
            cfg, devices=devices, data_module=data_module,
            val_data_module=val_data_module,
        )
        return cls._materialize(
            asm, devices=devices, enable_checkpointing=enable_checkpointing
        )

    @staticmethod
    def assemble(
        cfg: ConfigDict,
        *,
        devices: Optional[list] = None,
        data_module: Optional[DataModule] = None,
        val_data_module: Optional[DataModule] = None,
        build_data: bool = True,
    ) -> StepProgram:
        """Derive the config's :class:`StepProgram` — everything up to (and
        including) the jitted train step — with zero arrays materialized.

        ``build_data=False`` (the graph-audit path) additionally skips the
        data-module build: no tokenizer fetch, no arrow/mmap open.
        ``shift_labels`` is then derived statically — the Megatron mmap
        module (keyed on ``data.data_prefix``, pretraining only) is the one
        pre-shifted source the dispatch can produce."""
        devices = devices if devices is not None else jax.devices()
        mesh_cfg = MeshConfig.from_config(cfg.get("distributed_strategy", {}))
        mesh = build_mesh(mesh_cfg, devices=devices)
        # engineered compute/comms overlap knobs (optim.overlap): bucketed
        # ZeRO-1 collectives + double-buffered pipeline hops, both opt-in
        from neuronx_distributed_training_tpu.optim.overlap import (
            OverlapConfig,
            build_bucket_plan,
        )

        overlap_cfg = OverlapConfig.from_config(
            (cfg.get("distributed_strategy", {}) or {}).get("overlap")
        )
        policy = DtypePolicy.from_precision_config(cfg.get("precision", {}))
        sched = batch_schedule(cfg, len(devices))
        seed = int(cfg.get("seed", 1234))

        # data first: the module's label convention decides shift_labels
        # (reference training.py:71-91 selects the DataModule the same way)
        from neuronx_distributed_training_tpu.data.build import (
            alignment_strategy,
            build_data_module,
        )

        alignment, align_params = alignment_strategy(cfg)
        if build_data:
            if data_module is None:
                data_module, cfg_val_dm = build_data_module(cfg, sched, seed=seed)
                if val_data_module is None:
                    val_data_module = cfg_val_dm
            # Megatron mmap data is pre-shifted on host (gpt_dataset_patch
            # convention); everything else relies on the in-model shift
            shift_labels = not getattr(data_module, "labels_pre_shifted", False)
        else:
            shift_labels = not (
                not alignment
                and (cfg.get("data", {}) or {}).get("data_prefix")
            )

        model_cfg, loss_fn, init_fn, specs_fn = build_model(
            cfg, policy, shift_labels=shift_labels
        )
        # params are NOT materialized here: param_builder composes init +
        # LoRA + pipeline-interleave as one pure function, jitted later with
        # out_shardings so every leaf is born sharded on its own devices —
        # the TPU-native form of the reference's meta-device init +
        # sequential_move_factor staged moves (base.py:147-152, 693-712);
        # a 405B-class config never materializes unsharded params anywhere
        init_key = jax.random.PRNGKey(seed)
        param_builder = init_fn

        # DPO/ORPO swap the loss for the preference objective; DPO's pre-fit
        # reference-logprob pass runs in fit() (reference base_dpo.py:23-66),
        # ORPO needs no reference model (reference base_orpo.py:26-46)
        forward_logits = None
        if alignment in ("dpo", "orpo", "kto"):
            dpo_cfg = dict((cfg.get("model", {}) or {}).get(alignment, {}) or {})
            forward_logits = _forward_logits_for(model_cfg, policy)

            # reference spells it kl_beta in the strategy block
            beta = float(align_params.get("kl_beta", dpo_cfg.get("beta", 0.1)))
            if alignment == "dpo":
                from neuronx_distributed_training_tpu.alignment.dpo import make_dpo_loss_fn

                loss_fn = make_dpo_loss_fn(forward_logits, beta=beta)
            elif alignment == "kto":
                # unpaired preference (extension; see alignment/kto.py)
                from neuronx_distributed_training_tpu.alignment.kto import make_kto_loss_fn

                loss_fn = make_kto_loss_fn(
                    forward_logits, beta=beta,
                    desirable_weight=float(
                        align_params.get("desirable_weight", 1.0)),
                    undesirable_weight=float(
                        align_params.get("undesirable_weight", 1.0)),
                    kl_estimator=str(
                        align_params.get("kl_estimator", "batch_mean")),
                )
            else:
                from neuronx_distributed_training_tpu.alignment.orpo import make_orpo_loss_fn

                loss_fn = make_orpo_loss_fn(forward_logits, beta=beta)

        # LoRA: inject adapters + freeze base weights (reference
        # llama_model.py:51-65 -> nxd lora_config)
        trainable = None
        lora_block = dict((cfg.get("model", {}) or {}).get("lora", {}) or {})
        if lora_block:
            from neuronx_distributed_training_tpu.peft import (
                LoraConfig as _LoraConfig,
                add_lora,
                lora_param_specs,
                trainable_mask,
            )

            lora_cfg = _LoraConfig.from_config(lora_block)
            lora_key = jax.random.PRNGKey(seed + 1)
            base_builder = param_builder
            param_builder = lambda key: add_lora(base_builder(key), lora_cfg, lora_key)
            # trainable mask is built later from the one shared eval_shape
            base_specs_fn = specs_fn
            specs_fn = lambda **kw: lora_param_specs(base_specs_fn(**kw), lora_cfg)

        pp = int(mesh.shape.get("pipe", 1))
        num_micro_in_step = sched["num_microbatches"]
        eval_loss_fn = loss_fn
        if pp > 1:
            # pipeline path: microbatching moves inside the pipelined loss
            # (reference base.py:374-383 run_train); layer stack sharded over
            # "pipe" IS the partitioning.  vp > 1 stores the stack in the
            # interleaved [vp, pp, Lc, ...] layout (reference VPP,
            # base.py:85,155) — note checkpoints then carry that layout.
            from jax.sharding import PartitionSpec as P

            from neuronx_distributed_training_tpu.parallel.pipeline import (
                MANUAL_VJP_SCHEDULES,
                pipeline_loss,
                pipeline_loss_and_grad,
                resolve_schedule,
                stage_layer_slice,
                to_interleaved,
            )
            from neuronx_distributed_training_tpu.trainer.step import microbatch_split

            vp = int(mesh_cfg.virtual_pipeline_model_parallel_size or 1)
            if getattr(model_cfg, "attention_impl", "") == "zigzag_ring":
                # the zig-zag batch/position transform lives in the non-PP
                # loss hook; pipeline stage hooks don't thread positions
                raise NotImplementedError(
                    "zigzag_ring_attention under pipeline parallelism; use "
                    "fusions.ring_attention for pp + cp configs"
                )
            # fail early with a clear message instead of an opaque GSPMD error
            moe_freq = int(getattr(model_cfg, "moe_frequency", 1) or 1)
            if moe_freq != 1:
                # pipe slices whole (MoE + dense) groups — with vp, every
                # chunk holds whole groups too (chunk layers = Gc*f, and
                # to_interleaved reshapes the [G]-leading moe/dense leaves
                # consistently with the flat [L] attn/norm leaves);
                # num_moe_layers is family-specific (mixtral wraps a llama
                # config, gpt is flat)
                from neuronx_distributed_training_tpu.models import gpt as _gpt
                from neuronx_distributed_training_tpu.models import mixtral as _mx

                if isinstance(model_cfg, _gpt.GPTConfig):
                    groups = _gpt.num_moe_layers(model_cfg)
                else:
                    groups = _mx.num_moe_layers(model_cfg)
                if groups % (pp * vp) != 0:
                    raise ValueError(
                        f"num_layers {model_cfg.num_layers} / moe frequency "
                        f"{moe_freq} = {groups} groups, not divisible by "
                        f"pp*vp = {pp}*{vp}"
                    )
            else:
                stage_layer_slice(
                    int(getattr(model_cfg, "num_layers", 0) or 0), pp, vp)
            nm = sched["num_microbatches"]
            if alignment in ("dpo", "orpo", "kto"):
                # preference losses pipeline via the concatenated forward
                # (reference base_dpo.py:68-88 runs chosen+rejected through
                # NxDPPModel as one doubled batch); every family pipelines —
                # the head_fn (final norm + lm head) is the only per-family bit
                from neuronx_distributed_training_tpu.alignment.dpo import (
                    preference_pipeline_hooks,
                )
                from neuronx_distributed_training_tpu.ops import norm as norm_ops

                if isinstance(model_cfg, llama.LlamaConfig):
                    base_embed, base_stage, _ = llama.pipeline_hooks(
                        model_cfg, policy
                    )
                    hook_opts: dict = {}

                    def head_fn(p, y):
                        h = norm_ops.apply_rms_norm(
                            p["final_norm"], y, eps=model_cfg.rms_norm_eps
                        )
                        return llama.logits_fn(p, h, model_cfg, policy)

                else:
                    (base_embed, base_stage, _), hook_opts = pipeline_hooks_for(
                        cfg, model_cfg, policy, shift_labels=shift_labels
                    )
                    from neuronx_distributed_training_tpu.models import (
                        gpt as _gptm,
                        mixtral as _mxm,
                    )

                    if isinstance(model_cfg, _mxm.MixtralConfig):
                        _lc = model_cfg.llama

                        def head_fn(p, y):
                            h = norm_ops.apply_rms_norm(
                                p["final_norm"], y, eps=_lc.rms_norm_eps
                            )
                            return llama.logits_fn(p, h, _lc, policy)

                    else:

                        def head_fn(p, y):
                            # post_ln layers end normalized; no final LN
                            # (gpt.py init_params omits the param)
                            h = (y if model_cfg.transformer_block_type
                                 == "post_ln"
                                 else _gptm._apply_norm(
                                     model_cfg, p["final_norm"], y))
                            return _gptm._logits_from_hidden(
                                p, h, model_cfg, policy
                            )

                    # reference parity: the HF models add the router aux loss
                    # only when ``labels`` is passed; the DPO/ORPO path
                    # computes logits without labels, so no aux term here
                    # (stage_aux stays — MoE stages return (x, aux) tuples)
                    hook_opts = dict(hook_opts, aux_inv_layers=0.0)
                if alignment == "kto":
                    # single-sequence batches: embed/stage pass through, only
                    # the loss hook changes (no chosen/rejected concat)
                    from neuronx_distributed_training_tpu.alignment.kto import (
                        kto_pipeline_hooks,
                    )

                    embed_fn, stage_fn, stage_loss_fn = kto_pipeline_hooks(
                        base_embed, base_stage, head_fn, beta=beta,
                        desirable_weight=float(
                            align_params.get("desirable_weight", 1.0)),
                        undesirable_weight=float(
                            align_params.get("undesirable_weight", 1.0)),
                    )
                else:
                    embed_fn, stage_fn, stage_loss_fn = preference_pipeline_hooks(
                        base_embed, base_stage, head_fn, mode=alignment, beta=beta
                    )
            else:
                (embed_fn, stage_fn, stage_loss_fn), hook_opts = pipeline_hooks_for(
                    cfg, model_cfg, policy, shift_labels=shift_labels
                )
            stage_aux = bool(hook_opts.get("stage_aux"))
            aux_scale = float(hook_opts.get("aux_inv_layers", 0.0)) / nm
            needs_rng = bool(hook_opts.get("needs_rng"))

            # schedule selection: the memory-bounded manual-vjp 1F1B is the
            # production default whenever the model/loss combination supports
            # it (reference run_train's 1F1B engine, base.py:374-383 — O(pp)
            # in-flight activations instead of the autodiff wavefront's
            # O(nm + pp) per-tick residuals); `pipeline.schedule` in the
            # distributed_strategy block forces either schedule explicitly
            pipe_knobs = dict(
                (cfg.get("distributed_strategy", {}) or {}).get("pipeline", {})
                or {}
            )
            pp_schedule = resolve_schedule(
                pipe_knobs.get("schedule", "auto"), model_cfg,
                {
                    "pipeline_model_parallel_size": pp,
                    "virtual_pipeline_model_parallel_size": vp,
                    "context_parallel_size": int(
                        mesh_cfg.context_parallel_size or 1),
                    "alignment": (alignment
                                  if alignment in ("dpo", "orpo", "kto")
                                  else None),
                    "lora": bool(lora_block),
                },
            )
            logger.info("pipeline schedule: %s (pp=%d, vp=%d)", pp_schedule, pp, vp)

            def loss_fn(p, batch, key):  # noqa: F811 — pipelined replacement
                mbs = microbatch_split(batch, nm)
                if needs_rng and key is not None:
                    mbs = dict(mbs)
                    mbs["_rng"] = jax.random.split(key, nm)
                loss = pipeline_loss(
                    p, p["layers"], mbs,
                    embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=stage_loss_fn,
                    mesh=mesh, num_microbatches=nm, virtual_pipeline_size=vp,
                    stage_aux=stage_aux, aux_scale=aux_scale,
                )
                return loss, {}

            # eval reuses the pipelined loss: under pp the layer stack lives in
            # the pipeline layout (interleaved when vp>1), so the plain forward
            # cannot run on it; val batches must be gbs-shaped to satisfy the
            # microbatch split — checked here instead of failing deep in
            # shard_map
            if val_data_module is not None:
                vgbs = getattr(val_data_module, "global_batch_size", None)
                if vgbs is not None and int(vgbs) != int(sched["global_batch_size"]):
                    raise ValueError(
                        f"under pipeline parallelism validation batches must "
                        f"match the train global_batch_size "
                        f"{sched['global_batch_size']} (val module has {vgbs}): "
                        f"the pipelined eval loss microbatches the same way"
                    )
            eval_loss_fn = loss_fn

            if pp_schedule in MANUAL_VJP_SCHEDULES:
                # train-step grads come from the manual-vjp tick loop (plain
                # 1F1B, the circular interleave when vp > 1, or the ZB-H1
                # dgrad/wgrad split); eval keeps the autodiff wavefront loss
                # above (it only needs the forward value).  Family head
                # dispatch: the gate currently admits llama/mistral only, but
                # route by config type so re-admitting mixtral (its
                # onef1b_head_hooks are already wired) needs nothing beyond
                # flipping supports_1f1b.
                from neuronx_distributed_training_tpu.models import (
                    mixtral as _mixtral_m,
                )

                if isinstance(model_cfg, _mixtral_m.MixtralConfig):
                    head_hooks = _mixtral_m.onef1b_head_hooks(model_cfg, policy)
                else:
                    head_hooks = llama.onef1b_head_hooks(model_cfg, policy)
                (head_hidden_fn, head_params_of, head_weight_of,
                 fold_head_grads) = head_hooks

                def pp_loss_and_grad(p, batch, key):
                    mbs = microbatch_split(batch, nm)
                    if needs_rng and key is not None:
                        mbs = dict(mbs)
                        mbs["_rng"] = jax.random.split(key, nm)
                    loss, g = pipeline_loss_and_grad(
                        p, p["layers"], mbs,
                        embed_fn=embed_fn, stage_fn=stage_fn,
                        head_hidden_fn=head_hidden_fn,
                        head_params=head_params_of(p),
                        head_weight=head_weight_of(p),
                        mesh=mesh, num_microbatches=nm,
                        virtual_pipeline_size=vp,
                        zero_bubble=(pp_schedule == "1f1b-zb"),
                        stage_aux=stage_aux, aux_scale=aux_scale,
                        shift_labels=shift_labels,
                        double_buffer=overlap_cfg.pp_double_buffer,
                    )
                    # assemble the params-shaped grad tree: start from the
                    # embed-path cotangent (zeros off the embed path), add
                    # the layer-stack grads, fold the head grads back in
                    grads = dict(g["params_from_embed"])
                    grads["layers"] = jax.tree_util.tree_map(
                        lambda a, d: a + d.astype(a.dtype),
                        grads["layers"], g["layers"],
                    )
                    grads = fold_head_grads(
                        grads, g["head_params"], g["head_weight"]
                    )
                    return loss, {}, grads
            else:
                pp_loss_and_grad = None
            pspecs = specs_fn(pipeline=True)
            if vp > 1:
                flat_builder = param_builder

                def param_builder(key):
                    p = flat_builder(key)
                    return {**p, "layers": to_interleaved(p["layers"], pp, vp)}

                # [L, ...] -> [vp, pp, Lc, ...]: spec grows (vp, pipe, Lc) dims
                pspecs["layers"] = jax.tree_util.tree_map(
                    lambda s: P(None, s[0], None, *tuple(s)[1:]), pspecs["layers"],
                    is_leaf=lambda x: isinstance(x, P),
                )
            num_micro_in_step = 1
        else:
            pp_schedule = None
            pp_loss_and_grad = None
            pspecs = specs_fn()
        opt_block = dict((cfg.get("model", {}) or {}).get("optim", {}) or {})
        opt_cfg = AdamWConfig.from_config(opt_block, cfg.get("trainer", {}))
        zero1 = bool(cfg.get("distributed_strategy", {}).get("zero1", True))
        # weight EMA (reference exp_manager.ema -> NeMo EMA callback,
        # utils/exp_manager.py:298-305); lives inside the optimizer state
        ema_block = dict((cfg.get("exp_manager", {}) or {}).get("ema", {}) or {})
        ema_cfg = (
            EMAConfig.from_config(ema_block) if ema_block.get("enable") else None
        )
        # numerics flight recorder (telemetry.health) + tensor numerics
        # observatory (telemetry.tensorstats): parsed here — before the
        # optimizer state exists — because enabling either adds its subtree
        # to opt_state (and therefore to its specs and checkpoints);
        # ExpManager re-parses the same block for the host-side knobs
        from neuronx_distributed_training_tpu.telemetry import TelemetryConfig

        _tel_cfg = TelemetryConfig.from_config(
            (cfg.get("exp_manager", {}) or {}).get("telemetry")
        )
        health_cfg = _tel_cfg.health
        tensorstats_cfg = _tel_cfg.tensorstats
        abstract_params = jax.eval_shape(param_builder, init_key)
        if trainable is None and lora_block:
            # path-derived 0/1 scalars; reuses the one abstract trace
            from neuronx_distributed_training_tpu.peft import trainable_mask

            trainable = trainable_mask(abstract_params)
        # full ZeRO-1 including the embedding: the pipeline embed hooks use the
        # one-hot matmul form (ops.linear.apply_embedding via_matmul) so no
        # gather-transpose scatter reaches the partitioner under manual pipe
        ospecs = opt_state_specs(
            abstract_params, pspecs, mesh, zero1=zero1, policy=policy,
            ema=ema_cfg is not None, health=health_cfg.enabled,
        )
        bucket_plan = None
        if zero1 and overlap_cfg.zero1_bucket_mb > 0:
            from neuronx_distributed_training_tpu.telemetry.health import (
                grad_group_of,
            )

            bucket_plan = build_bucket_plan(
                abstract_params, pspecs, ospecs["mu"], mesh,
                bucket_mb=overlap_cfg.zero1_bucket_mb,
                group_fn=grad_group_of,
            )
            if bucket_plan is not None:
                logger.info("engineered overlap: %s", bucket_plan.describe())

        # tensorstats slots join the opt-state specs AFTER bucket planning:
        # the bucket phase records the packed payload of each combined
        # all-gather, so its state slots are named by the plan's buckets
        ts_bucket_groups: tuple = ()
        if tensorstats_cfg.enabled:
            from neuronx_distributed_training_tpu.telemetry.tensorstats import (
                tensorstats_state_specs,
            )

            if tensorstats_cfg.buckets and bucket_plan is not None:
                ts_bucket_groups = tuple(
                    b.name for b in bucket_plan.buckets if b.ag)
            ospecs["tensorstats"] = tensorstats_state_specs(
                tensorstats_cfg, abstract_params,
                bucket_groups=ts_bucket_groups)

        max_steps = int((cfg.get("trainer", {}) or {}).get("max_steps", 100))
        lr_schedule = build_lr_schedule(opt_block, max_steps_default=max_steps)
        exp_block = dict(cfg.get("exp_manager", {}) or {})
        step_fn = make_train_step(
            loss_fn, opt_cfg, lr_schedule, policy,
            num_microbatches=num_micro_in_step,
            # reference log_parameter_norm / log_gradient_norm
            # (base.py:397-452): per-step norms in the metrics dict -> loggers
            log_param_norm=bool(exp_block.get("log_parameter_norm", False)),
            log_gradient_norm=bool(exp_block.get("log_gradient_norm", False)),
            trainable_mask=trainable,
            ema_cfg=ema_cfg,
            param_specs=pspecs,
            loss_and_grad_fn=pp_loss_and_grad,
            health_cfg=health_cfg,
            bucket_plan=bucket_plan,
            prefetch_ag=overlap_cfg.prefetch_ag,
            tensorstats_cfg=tensorstats_cfg,
        )
        # NARROWED EMA workaround (round 3): donating an opt state that
        # carries the EMA tree trips an INVALID_ARGUMENT in the (tunnelled)
        # TPU runtime (plain jit and donate=False both run clean; a CPU
        # repro attempt found no buffer aliasing between params and the EMA
        # tree, so the root cause sits in the TPU runtime's donation path).
        # Donating PARAMS only keeps the big aliasing win and avoids the
        # failing opt-state donation — the transient cost drops from
        # params+opt to opt-state-only.  Revisit donate="all" under EMA when
        # the backend can be exercised (tools/ema_donation_probe.py).
        donate = True if ema_cfg is None else "params"
        jstep = jit_train_step(step_fn, mesh, pspecs, ospecs, donate=donate)
        eval_fn = jax.jit(make_eval_step(eval_loss_fn)) if val_data_module else None

        return StepProgram(
            cfg=cfg, mesh=mesh, mesh_cfg=mesh_cfg, policy=policy, sched=sched,
            seed=seed, alignment=alignment, align_params=align_params,
            model_cfg=model_cfg, loss_fn=loss_fn, eval_loss_fn=eval_loss_fn,
            forward_logits=forward_logits, param_builder=param_builder,
            init_key=init_key, abstract_params=abstract_params,
            pspecs=pspecs, ospecs=ospecs, opt_cfg=opt_cfg, ema_cfg=ema_cfg,
            health_cfg=health_cfg, tensorstats_cfg=tensorstats_cfg,
            tensorstats_bucket_groups=ts_bucket_groups,
            trainable=trainable, lora_block=lora_block,
            jstep=jstep, eval_fn=eval_fn, data_module=data_module,
            val_data_module=val_data_module, shift_labels=shift_labels,
            pipeline_schedule=pp_schedule, num_micro_in_step=num_micro_in_step,
            max_steps=max_steps, donate=donate,
        )

    @classmethod
    def _materialize(
        cls, asm: StepProgram, *, devices: list, enable_checkpointing: bool
    ) -> "Trainer":
        """Turn a :class:`StepProgram` into a live session: sharded-at-birth
        param/opt-state init, warm start, sharding validation, exp manager,
        checkpointing, and the DPO/KTO reference-logprob pre-fit hook."""
        cfg, mesh, mesh_cfg = asm.cfg, asm.mesh, asm.mesh_cfg
        policy, sched, seed = asm.policy, asm.sched, asm.seed
        model_cfg, loss_fn = asm.model_cfg, asm.loss_fn
        pspecs, ospecs = asm.pspecs, asm.ospecs
        param_builder, init_key = asm.param_builder, asm.init_key
        ema_cfg, health_cfg = asm.ema_cfg, asm.health_cfg
        alignment, forward_logits = asm.alignment, asm.forward_logits
        data_module = asm.data_module
        val_data_module = asm.val_data_module
        jstep, eval_fn = asm.jstep, asm.eval_fn
        pp_schedule, max_steps = asm.pipeline_schedule, asm.max_steps
        pp = int(mesh.shape.get("pipe", 1))

        # materialize sharded-at-birth: jit with out_shardings creates every
        # leaf directly on its own devices — no full-model host/single-device
        # copy ever exists (cf. reference meta_device_init)
        import functools
        from jax.sharding import NamedSharding, PartitionSpec as P

        ns = functools.partial(NamedSharding, mesh)
        shardings = lambda specs: jax.tree_util.tree_map(
            ns, specs, is_leaf=lambda x: isinstance(x, P)
        )
        with mesh, shd.use_mesh(mesh):
            params = jax.jit(
                param_builder, out_shardings=shardings(pspecs)
            )(init_key)

        # warm start BEFORE the optimizer state is built: fp32 master weights
        # (and the EMA tree) must seed from the RESTORED params — the update
        # derives new params from opt_state["master"], so a master copied
        # from random init would silently void the warm start on step 1
        # (reference weight_init_only + resume_from_checkpoint,
        # nlp_overrides.py:541-568)
        warm_path = (cfg.get("exp_manager", {}) or {}).get("resume_from_checkpoint")
        if warm_path and bool((cfg.get("model", {}) or {}).get("weight_init_only")):
            warm_ck = Checkpointer(CheckpointConfig(dir=str(warm_path)))
            try:
                params = warm_ck.restore_params_only(
                    params, mesh=mesh, param_specs=pspecs
                )
            finally:
                warm_ck.close()
            logger.info("warm start: params restored from %s", warm_path)

        with mesh, shd.use_mesh(mesh):
            opt_state = jax.jit(
                functools.partial(
                    init_opt_state, policy=policy,
                    ema=ema_cfg is not None,
                    health=health_cfg.enabled,
                    tensorstats=asm.tensorstats_cfg,
                    tensorstats_bucket_groups=asm.tensorstats_bucket_groups),
                out_shardings=shardings(ospecs),
            )(params)

        # sharding sanity gate (SURVEY.md §5.2 "jit-time shape/sharding
        # assertions" — the TPU-native analogue of the reference's
        # HLO-consistency discipline): fail fast on silent replication or a
        # dropped constraint instead of discovering it as a perf mystery.
        # DEFAULT ON since round 3 — it is a pure metadata comparison (no
        # device work); set debug.validate_sharding: false to opt out.
        if bool((cfg.get("debug", {}) or {}).get("validate_sharding", True)):
            from neuronx_distributed_training_tpu.utils.debug import (
                assert_tree_sharding,
            )

            assert_tree_sharding(params, pspecs, mesh)
            assert_tree_sharding(opt_state, ospecs, mesh)
            logger.info("debug.validate_sharding: params + opt state verified")

        if data_module is None:
            # deferred ``data.synthetic: true`` (build_data_module had no vocab
            # hint before the model existed); any other source was built above
            seq = int((cfg.get("data", {}) or {}).get("seq_length", 2048))
            data_module = SyntheticDataModule(
                vocab_size=model_cfg.vocab_size,
                seq_len=seq,
                global_batch_size=sched["global_batch_size"],
                seed=seed,
            )

        # transient-read retry knobs (``data.io_retries`` /
        # ``data.io_retry_backoff_seconds``) imposed on whatever module the
        # build produced — attributes, not ctor args, so custom test doubles
        # keep working (without the attributes they simply don't retry)
        data_block = dict(cfg.get("data", {}) or {})
        for key, cast in (("io_retries", int),
                          ("io_retry_backoff_seconds", float)):
            if key in data_block and hasattr(data_module, key):
                setattr(data_module, key, cast(data_block[key]))

        exp = ExpManager.from_config(cfg, global_batch_size=sched["global_batch_size"])

        # -- telemetry wiring: MFU reference + the static run facts the
        # compile census persists to run_summary.json.  The analytic FLOPs
        # estimate (utils.perf, the reference's llama_perf_estimate role) is
        # per-family; throughput itself stays the one source of truth —
        # mfu derives from its tokens_per_sec at each logging boundary.
        from neuronx_distributed_training_tpu.utils import perf as _perf

        seq_len = int((cfg.get("data", {}) or {}).get("seq_length", 0) or 0) \
            or int(getattr(data_module, "seq_len", 0) or 0)
        if exp.throughput.seq_len == 0:
            exp.throughput.seq_len = seq_len
        n_chips = int(mesh.devices.size)
        from neuronx_distributed_training_tpu.parallel.pipeline import (
            MANUAL_VJP_SCHEDULES,
            predicted_bubble_fraction,
            work_table,
        )

        run_facts: dict = {
            "model_family": type(model_cfg).__name__,
            "n_chips": n_chips,
            "seq_len": seq_len,
            "global_batch_size": int(sched["global_batch_size"]),
            "pipeline_schedule": pp_schedule,
            "bubble_fraction_predicted": round(predicted_bubble_fraction(
                pp_schedule, pp, int(sched["num_microbatches"]),
                int(mesh_cfg.virtual_pipeline_model_parallel_size or 1)), 6),
        }
        # the manual-vjp schedules run the WORK-COMPACTED executor: record
        # its per-step tick counts (compacted span + per-kind active ticks
        # vs the old lockstep trip count) so the measured timelines are
        # interpretable from run_summary.json alone
        ticks_per_step = None
        if pp_schedule in MANUAL_VJP_SCHEDULES:
            ticks_per_step = work_table(
                pp_schedule, pp, int(sched["num_microbatches"]),
                int(mesh_cfg.virtual_pipeline_model_parallel_size or 1),
            ).tick_counts()
            run_facts["pipeline_ticks_per_step"] = ticks_per_step
        # arm the trace capture's pipeline-timeline reconstruction: with
        # pp > 1 a closed telemetry.trace window reconstructs the per-stage
        # tick Gantt and writes bubble_fraction_measured beside the
        # predicted run fact (telemetry.step_timeline)
        from neuronx_distributed_training_tpu.telemetry.step_timeline import (
            pipeline_facts,
        )

        exp.set_pipeline_facts(pipeline_facts(
            pp_schedule, pp, int(sched["num_microbatches"]),
            int(mesh_cfg.virtual_pipeline_model_parallel_size or 1),
            run_facts["bubble_fraction_predicted"],
            ticks_per_step=ticks_per_step))
        # arm the interconnect join (telemetry.comms): the cost model's
        # per-axis byte volumes + the topology's ICI prior let a closed
        # trace window turn per-class wire seconds into achieved_gbps /
        # efficiency — the "comms" section of trace_summary/run_summary
        try:
            from neuronx_distributed_training_tpu.autotune.cost_model import (
                ModelFacts,
                collective_byte_volumes,
            )
            from neuronx_distributed_training_tpu.autotune.topology import (
                resolve_topology,
            )
            from neuronx_distributed_training_tpu.telemetry.comms import (
                MESH_TO_AXIS,
            )

            plan_facts = ModelFacts.from_config(cfg)
            declared = plan_facts.declared_plan_for(n_chips)
            if declared is not None:
                topo = resolve_topology(device=devices[0])
                exp.set_comms_facts({
                    "byte_volumes": collective_byte_volumes(
                        plan_facts, declared),
                    "axis_sizes": {MESH_TO_AXIS[k]: int(v)
                                   for k, v in dict(mesh.shape).items()
                                   if k in MESH_TO_AXIS},
                    "peak_bandwidth_bytes": topo.ici_bandwidth_bytes,
                    "topology": topo.name,
                })
        except Exception as e:  # noqa: BLE001 — observability, not load-bearing
            logger.warning("comms telemetry arming unavailable: %s", e)
        try:
            fwd_flops = _perf.flops_for_model(model_cfg, seq_len)
            run_facts["fwd_flops_per_token"] = fwd_flops
            run_facts["peak_tflops_per_chip"] = _perf.detect_peak_tflops(
                devices[0])
            if exp.telemetry.mfu:
                exp.set_mfu_reference(
                    train_step_flops_per_token=(
                        _perf.train_step_flops_per_token(fwd_flops)),
                    n_chips=n_chips,
                    peak_tflops_per_chip=run_facts["peak_tflops_per_chip"],
                )
        except Exception as e:  # noqa: BLE001 — MFU is observability, not load-bearing
            logger.warning("MFU estimation unavailable for %s: %s",
                           type(model_cfg).__name__, e)

        checkpointer = None
        if enable_checkpointing:
            ck_cfg = CheckpointConfig.from_config(cfg)
            ck_cfg = dataclasses.replace(ck_cfg, dir=exp.checkpoint_dir)
            checkpointer = Checkpointer(ck_cfg)

        from neuronx_distributed_training_tpu.trainer.elastic import (
            ElasticConfig,
        )

        elastic = ElasticConfig.from_config(
            (cfg.get("exp_manager", {}) or {}).get("elastic"))

        pre_fit = None
        if alignment in ("dpo", "kto"):
            if alignment == "dpo":
                from neuronx_distributed_training_tpu.alignment.dpo import (
                    iter_reference_logprobs as _ref_iter,
                )

                _marker, _sidecar_name = (
                    "reference_chosen_logps", "dpo_reference_logps.npz")
            else:
                from neuronx_distributed_training_tpu.alignment.kto import (
                    iter_reference_logprobs_kto as _ref_iter,
                )

                _marker, _sidecar_name = (
                    "reference_logps", "kto_reference_logps.npz")

            def _attach_reference_columns(dm, ref_params, sidecar, tag):
                """Streamed frozen-policy pass over ONE data module: per-batch
                compute (single shared jit), progress logging, and periodic
                sidecar spill with a ``_done_upto`` cursor so a preempted
                100k-pair pass resumes where it stopped instead of restarting
                (VERDICT r2 item 10)."""
                import os

                if not hasattr(dm, "attach_reference_logprobs"):
                    return  # caller supplied reference columns already
                if _marker in getattr(dm, "arrays", {}):
                    return
                n = dm.sampler.total_samples
                bs = min(dm.global_batch_size, n)
                done = 0
                cols: dict[str, np.ndarray] = {}
                # column set the pass will produce for THIS data module —
                # a sidecar from a different config (e.g. written under
                # kto kl_estimator=batch_mean, resumed under mismatched)
                # must trigger recompute, not a KeyError in the jitted step
                expected = {_marker}
                if _marker == "reference_chosen_logps":
                    expected.add("reference_rejected_logps")
                if _marker == "reference_logps" and "kl_input_ids" in getattr(
                        dm, "arrays", {}):
                    expected.add("reference_kl_logps")
                loaded = _sidecar_load(sidecar, tag)
                if loaded is not None:
                    done, cols = loaded
                    if set(cols) != expected:
                        logger.warning(
                            "%s sidecar %s has columns %s but this config "
                            "needs %s; recomputing", tag, sidecar,
                            sorted(cols), sorted(expected),
                        )
                        done, cols = 0, {}
                    elif any(len(v) != n for v in cols.values()):
                        # dataset grew/shrank since the sidecar was written:
                        # stale columns would crash (or silently mis-attach)
                        logger.warning(
                            "%s sidecar %s has %d-sample columns but the "
                            "dataset has %d; recomputing", tag, sidecar,
                            len(next(iter(cols.values()))), n,
                        )
                        done, cols = 0, {}
                    elif done >= n:
                        dm.attach_reference_logprobs(cols)
                        logger.info("%s reference logps restored from %s", tag, sidecar)
                        return
                    else:
                        logger.info(
                            "%s reference pass resuming at %d/%d from %s",
                            tag, done, n, sidecar,
                        )
                # batches restart AT the cursor (not at cursor rounded to a
                # bs multiple): a resume with a different global_batch_size
                # must still recompute every remaining sample
                import time as _time

                from neuronx_distributed_training_tpu.data.loader import (
                    PrefetchIterator,
                )

                starts = list(range(done, n, bs))
                total = len(starts)
                log_every = max(1, total // 20)
                spill_every = max(1, total // 10)
                # same host/device overlap as the fit loop: row slicing
                # happens on the prefetch thread, not between dispatches
                batches = PrefetchIterator(
                    ({k: v[i:min(i + bs, n)] for k, v in dm.arrays.items()}
                     for i in starts)
                )
                start_done, t0 = done, _time.perf_counter()
                try:
                    for j, part in enumerate(_ref_iter(ref_params, batches,
                                                       forward_logits)):
                        if not cols:
                            cols = {k: np.empty((n,), v.dtype)
                                    for k, v in part.items()}
                        i = starts[j]
                        for k, v in part.items():
                            cols[k][i:i + len(v)] = v
                        done = min(i + bs, n)
                        if (j + 1) % log_every == 0 or done >= n:
                            rate = (done - start_done) / max(
                                _time.perf_counter() - t0, 1e-9)
                            logger.info(
                                "%s reference-logp pass: %d/%d samples "
                                "(%.0f samples/s, ETA %.0fs)",
                                tag, done, n, rate, (n - done) / max(rate, 1e-9),
                            )
                        if sidecar is not None and ((j + 1) % spill_every == 0
                                                    or done >= n):
                            _sidecar_store(sidecar, done, cols)
                finally:
                    batches.close()
                dm.attach_reference_logprobs(cols)

            def pre_fit(trainer: "Trainer") -> None:
                """Frozen-policy reference-logprob pass + column attach
                (reference base_dpo.py:23-66 on_train_start; same protocol
                for the KTO extension).

                Runs BEFORE checkpoint resume (fit() ordering): the reference
                logps must come from the frozen INITIAL policy, and at that
                point ``trainer.params`` still hold the deterministic initial
                (or warm-start) weights the original run started from.  The
                columns are cached to a sidecar so resumes skip the pass.
                Both the train AND val modules get columns — a val batch
                without them would KeyError inside the jitted eval step
                (ADVICE r2)."""
                import os

                ref_params = trainer.params
                # interleaving only happens when the pipeline branch ran
                # (pp > 1 AND vp > 1); gate on both or a flat stack would be
                # "de-interleaved" into garbage shapes
                vp_now = int(mesh_cfg.virtual_pipeline_model_parallel_size or 1)
                if pp > 1 and vp_now > 1:
                    # interleaved layout -> flat [L] for the plain forward
                    # (a reshape; the reference pass is compute-once)
                    from neuronx_distributed_training_tpu.parallel.pipeline import (
                        from_interleaved,
                    )

                    ref_params = dict(trainer.params)
                    ref_params["layers"] = from_interleaved(
                        trainer.params["layers"])
                ck_dir = (str(trainer.checkpointer.config.dir)
                          if trainer.checkpointer is not None else None)

                def _sidecar(suffix):
                    if ck_dir is None:
                        return None
                    stem, ext = os.path.splitext(_sidecar_name)
                    return os.path.join(ck_dir, stem + suffix + ext)

                _attach_reference_columns(
                    trainer.data_module, ref_params, _sidecar(""), "train")
                if trainer.val_data_module is not None:
                    _attach_reference_columns(
                        trainer.val_data_module, ref_params, _sidecar("_val"),
                        "val")

        return cls(
            cfg=cfg, mesh=mesh, policy=policy, model_cfg=model_cfg, loss_fn=loss_fn,
            params=params, opt_state=opt_state, param_specs=pspecs, opt_specs=ospecs,
            train_step=jstep, eval_step=eval_fn, data_module=data_module,
            val_data_module=val_data_module, exp=exp, checkpointer=checkpointer,
            max_steps=max_steps, pre_fit=pre_fit, ema_cfg=ema_cfg,
            pipeline_schedule=pp_schedule, run_facts=run_facts,
            donate=asm.donate, elastic=elastic,
        )

    # -- resume -------------------------------------------------------------

    @property
    def consumed_samples(self) -> int:
        """Derived from TRAINED steps (the reference's
        ``compute_consumed_samples``, ``data/base.py:33-47``) — NOT from the
        sampler's yield counter, which runs ahead of training by the prefetch
        queue depth."""
        return self.step * int(self.data_module.global_batch_size)

    def maybe_resume(self) -> bool:
        """Restore newest checkpoint if one exists (reference ``resume_if_exists``)."""
        if self.checkpointer is None or self.checkpointer.latest_step() is None:
            return False
        try:
            state = self.checkpointer.restore(
                self.params, self.opt_state,
                mesh=self.mesh, param_specs=self.param_specs,
                opt_specs=self.opt_specs,
            )
        except Exception as orig:
            # enabling telemetry.health or telemetry.tensorstats adds a
            # subtree to the opt state, so a checkpoint written BEFORE the
            # knob was turned on mismatches the template: retry without the
            # newer subtree(s) and keep the freshly initialized (already
            # correctly sharded) counters — an operator flipping a telemetry
            # knob on must not lose their run.  Candidates are tried
            # narrowest-first (newest feature alone, then each alone, then
            # both) so a checkpoint that DOES carry one subtree keeps it.  A
            # retry chain that fails too re-raises the ORIGINAL error (the
            # real root cause), not a retry's.
            telemetry_subtrees = [k for k in ("tensorstats", "health")
                                  if k in self.opt_state]
            if not telemetry_subtrees:
                raise
            candidates = [(k,) for k in telemetry_subtrees]
            if len(telemetry_subtrees) > 1:
                candidates.append(tuple(telemetry_subtrees))
            state = None
            stripped_of: tuple = ()
            for drop in candidates:
                logger.warning(
                    "resume: full restore failed (%s: %s); retrying without "
                    "the telemetry %s subtree(s) in case the checkpoint "
                    "predates them",
                    type(orig).__name__, orig, "/".join(drop),
                )
                stripped = {k: v for k, v in self.opt_state.items()
                            if k not in drop}
                stripped_specs = {k: v for k, v in self.opt_specs.items()
                                  if k not in drop}
                try:
                    state = self.checkpointer.restore(
                        self.params, stripped,
                        mesh=self.mesh, param_specs=self.param_specs,
                        opt_specs=stripped_specs,
                    )
                    stripped_of = drop
                    break
                except Exception:
                    continue
            if state is None:
                raise orig
            restored_opt = dict(state.opt_state)
            if "health" in stripped_of:
                # fresh counters, but steps_seen MUST align with the restored
                # trainer step: last_nonfinite_step derives from it, and a
                # misaligned value would name the wrong step (and RNG recipe)
                # in every future anomaly bundle
                health = dict(self.opt_state["health"])
                health["steps_seen"] = jnp.asarray(int(state.step), jnp.int32)
                restored_opt["health"] = health
            if "tensorstats" in stripped_of:
                # the cumulative observatory record simply starts fresh — the
                # stats are a streaming aggregate, not training state
                restored_opt["tensorstats"] = self.opt_state["tensorstats"]
            state.opt_state = restored_opt
            logger.info(
                "resume: checkpoint predates telemetry %s — restored without "
                "the subtree(s), counters start fresh at step %d",
                "/".join(stripped_of), int(state.step),
            )
        if self.fault_injector is not None:
            # drill injection point "restore": the checkpoint has been read
            # but nothing applied yet — a kill here must leave the save
            # intact and the next resume able to start over; sigterm mode is
            # a preemption notice landing mid-restore
            if self.fault_injector.maybe_fire("restore", int(state.step)):
                self.preemption_notice = (
                    "injected preemption notice (mid-restore)")
        self.params = state.params
        self.opt_state = state.opt_state
        self.step = state.step
        self.data_module.sampler.consumed_samples = state.consumed_samples
        logger.info(
            "resumed from step %d (consumed_samples=%d)", state.step, state.consumed_samples
        )
        return True

    # -- the loop -----------------------------------------------------------

    def fit(self) -> dict[str, float]:
        import contextlib
        import signal
        import time as _time

        from neuronx_distributed_training_tpu.telemetry import (
            HangWatchdog,
            HealthMonitor,
            RecompileDetector,
            SpanTimer,
        )
        from neuronx_distributed_training_tpu.telemetry.tensorstats import (
            HIST_PREFIX as _TS_HIST_PREFIX,
        )

        tel = self.exp.telemetry
        # spans power both the per-boundary decomposition AND goodput; the
        # timer is pure perf_counter bookkeeping, so either knob arms it
        spans = SpanTimer(enabled=tel.spans or tel.goodput)
        detector = RecompileDetector()
        # numerics flight recorder: ring-buffers per-step forensic context
        # (host references only — no device fetch on healthy steps) and
        # applies the anomaly policy at the loop's existing sync boundaries
        hc = tel.health
        monitor = (
            HealthMonitor(
                hc, dump_dir=self.exp.log_dir, run_facts=self.run_facts,
                write_run_summary=self.exp.write_run_summary,
                rng_seed=STEP_KEY_SEED,
            )
            if hc.enabled else None
        )
        # (the hang watchdog is built AFTER the fleet/alert/control blocks
        # below: a bundle-only monitor armed there must reach it, and the
        # control plane decides whether a fire escapes the process)
        # -- fleet observability plane + declarative alerts (telemetry.fleet
        # / telemetry.alerts — docs/observability.md "Fleet observability"):
        # this host appends a beacon to fleet/host_<id>.jsonl at every
        # logging boundary; rank 0 folds every host's stream into
        # fleet_summary.json (straggler attribution, quiet-host findings);
        # the alert rules evaluate over the streamed boundary metrics.
        # Everything is host-side bookkeeping on already-fetched values —
        # zero new host syncs between boundaries, no graph changes.
        fleet = None
        if tel.fleet.enabled:
            try:
                from neuronx_distributed_training_tpu.telemetry import (
                    FleetPlane,
                )

                host = int(jax.process_index())
                fleet = FleetPlane(
                    tel.fleet, self.exp.log_dir, host=host,
                    aggregate=(host == 0),
                    write_run_summary=self.exp.write_run_summary,
                )
            except Exception as e:  # noqa: BLE001 — observability must not
                logger.warning("fleet plane unavailable: %s", e)
        alerts = None
        if tel.alerts:
            from neuronx_distributed_training_tpu.telemetry import AlertEngine

            alerts = AlertEngine(
                tel.alerts, write_run_summary=self.exp.write_run_summary)
        # -- memory observability (telemetry.memory — docs/observability.md
        # "Memory observability"): per-device allocator stats across the
        # local mesh at every boundary (memory/ metrics through all sinks +
        # fleet beacons), ONE windowed device_memory_profile() capture
        # attributed to subsystems -> memory_summary.json, and OOM
        # forensics (a RESOURCE_EXHAUSTED escaping the step boundary dumps
        # oom_<step>/ with predicted-vs-actual in one artifact).  Host-side
        # only: zero graph changes, zero extra syncs between boundaries.
        memplane = None
        if tel.memory.enabled:
            try:
                from neuronx_distributed_training_tpu.autotune.cost_model import (  # noqa: E501
                    predicted_breakdown_for_config,
                )
                from neuronx_distributed_training_tpu.telemetry import (
                    MemoryPlane,
                )
                from neuronx_distributed_training_tpu.telemetry.memory import (  # noqa: E501
                    tree_bytes_by_subsystem,
                )

                memplane = MemoryPlane(
                    tel.memory, self.exp.log_dir,
                    devices=lambda: _local_mesh_devices(self.mesh),
                    tree_bytes_fn=lambda: tree_bytes_by_subsystem(
                        self.params, self.opt_state),
                    predicted=predicted_breakdown_for_config(
                        self.cfg, int(self.mesh.devices.size)),
                    run_facts=self.run_facts,
                    write_run_summary=self.exp.write_run_summary,
                )
            except Exception as e:  # noqa: BLE001 — observability must not
                logger.warning("memory plane unavailable: %s", e)
        # -- coordinated fleet control (trainer.control — docs/observability
        # .md "Fleet control"): every stop/checkpoint decision folds through
        # ONE tiny replicated collective at the deterministic boundary
        # cadence, so all hosts derive the SAME decision at the same step.
        # An alert halt, a health halt, a SIGTERM notice, or an operator
        # command on ONE host stops the whole fleet with a drained
        # emergency save instead of stalling the survivors at the next
        # collective rendezvous.
        ccfg = tel.control
        control = None
        if ccfg.enabled:
            try:
                from neuronx_distributed_training_tpu.trainer.control import (
                    ControlPlane,
                )

                chost = int(jax.process_index())
                control = ControlPlane(
                    ccfg, self.exp.log_dir, host=chost,
                    poll_commands=ccfg.poll_commands and chost == 0,
                    write_run_summary=self.exp.write_run_summary,
                    peer_words=self.control_peer_words,
                )
            except Exception as e:  # noqa: BLE001 — never kill the launch
                logger.warning("fleet control plane unavailable: %s", e)
        elif jax.process_count() > 1 and any(
                r.action == "halt" for r in tel.alerts):
            # without the control plane a halt decision is host-local: on a
            # metric that is not bit-identical across hosts, one host can
            # stop alone and stall the fleet at the next collective — the
            # consensus control word is the fix
            logger.warning(
                "multi-host run with action=halt alert rules and "
                "exp_manager.telemetry.control disabled: halt decisions "
                "are host-local; enable the control plane so stops are "
                "fleet-consistent (docs/observability.md 'Fleet control')")
        if monitor is None and (
                fleet is not None
                or control is not None
                or any(r.action == "dump" for r in tel.alerts)):
            # alert `action: dump` and the fleet's quiet-host findings both
            # reuse the flight recorder's bundle machinery; without the
            # health knob on, arm a bundle-only monitor (ring + forensic
            # writes — no in-graph probes, and with no health counters in
            # the metrics its boundary check is a no-op)
            monitor = HealthMonitor(
                hc, dump_dir=self.exp.log_dir, run_facts=self.run_facts,
                write_run_summary=self.exp.write_run_summary,
                rng_seed=STEP_KEY_SEED,
            )
        watchdog = (
            HangWatchdog(hc.watchdog_timeout_seconds, monitor,
                         abort=hc.watchdog_abort)
            if monitor is not None and hc.watchdog_timeout_seconds > 0
            else None
        )
        if watchdog is not None and control is not None and ccfg.hang_escape:
            # collective-hang escape (docs/observability.md "Fleet
            # control"): a boundary sync that exceeds the watchdog timeout
            # means a peer died mid-collective — after the hang_<step>/
            # bundle the survivor writes its final DYING beacon and the
            # control-trail exit note, then exits with the tagged
            # EXIT_HANG_ESCAPE code.  Survivors never hang forever; the
            # orchestrator restarts the incarnation and elastic resume +
            # integrity walk-back do the recovery.
            from neuronx_distributed_training_tpu.trainer.control import (
                EXIT_HANG_ESCAPE,
            )

            def _escape_note(what, step):
                control.note_exit(
                    "hang_escape",
                    f"boundary sync {what!r} exceeded "
                    f"{hc.watchdog_timeout_seconds:.0f}s at step {step}; "
                    f"exiting EXIT_HANG_ESCAPE")

            def _escape_beacon(what, step):
                if fleet is not None:
                    fleet.close(RuntimeError(
                        f"hang escape: {what} exceeded "
                        f"{hc.watchdog_timeout_seconds:.0f}s"), step=step)

            watchdog.arm_escape(EXIT_HANG_ESCAPE, _escape_note,
                                _escape_beacon)
        halted = False

        def _sync_guard(what):
            # arm the hung-device-sync watchdog around a blocking fetch
            return (watchdog.guard(what, self.step) if watchdog is not None
                    else contextlib.nullcontext())

        cfg_t = dict(self.cfg.get("trainer", {}) or {})
        val_interval = int(cfg_t.get("val_check_interval", 0) or 0)
        limit_val = int(cfg_t.get("limit_val_batches", 10) or 10)
        ck_every = (
            self.checkpointer.config.every_n_train_steps if self.checkpointer else 0
        )
        max_time = parse_max_time(cfg_t.get("max_time"))
        t_start = _time.monotonic()

        # preemption hook: SIGTERM (SLURM preemption / spot reclaim) requests a
        # graceful stop — checkpoint at the next step boundary, then exit clean
        # so resume_if_exists continues the run (reference: Lightning's
        # preemption plugin + SLURM requeue, train_setup.sh:28-29).  The
        # elastic grace window starts at the NOTICE, not at the boundary: the
        # emergency save's retry loop must give up before the fleet kills the
        # process (docs/elasticity.md "Grace window").
        from neuronx_distributed_training_tpu.trainer.elastic import (
            ElasticConfig,
        )

        el = self.elastic if self.elastic is not None else ElasticConfig()
        stop_requested: dict[str, Any] = {"reason": None, "deadline": None}

        def _request_stop(reason: str, condition: Optional[str] = None) -> None:
            # the grace deadline starts at the NOTICE (docs/elasticity.md);
            # `condition` additionally registers the control-word bit so the
            # next boundary fold shares the stop with the whole fleet —
            # without it (control disabled), the stop stays host-local
            stop_requested["reason"] = reason
            if stop_requested["deadline"] is None and el.grace_period_seconds > 0:
                stop_requested["deadline"] = (
                    _time.monotonic() + el.grace_period_seconds)
            if control is not None and condition is not None:
                control.request(condition, reason)

        def _on_sigterm(signum, frame):
            _request_stop("SIGTERM (preemption)", condition="preemption")

        old_handler = None
        try:
            old_handler = signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:
            pass  # not in the main thread (tests); preemption hook disabled

        resumed = False
        last_metrics: dict[str, float] = {}
        batches = None
        # the exception actually propagating out of THIS fit() — captured
        # explicitly because sys.exc_info() inside the finally would also
        # see an exception the CALLER is currently handling (fit() invoked
        # from an except block), mislabeling a clean run as a dying host in
        # the final fleet beacon
        fit_exc: Optional[BaseException] = None
        try:
            # the restart phase runs INSIDE the teardown scope: a restore
            # failure (corrupt checkpoint, drill restore-kill) must still
            # restore the SIGTERM handler, write the teardown summaries, and
            # close the exp manager — otherwise every faulted incarnation
            # leaks its log FileHandler and leaves a dead trainer's stop
            # closure bound to SIGTERM
            # restart-time replan (trainer.elastic.maybe_replan ran in the
            # CLI / drill harness BEFORE this trainer existed): account its
            # wall time as the "replan" span so goodput sees the full
            # restart cost
            if self.replan_record:
                spans.add_preexisting(
                    "replan",
                    float(self.replan_record.get("replan_seconds", 0.0) or 0.0))
            # pre_fit BEFORE resume: the DPO reference pass must see the
            # frozen initial policy, not resumed weights (see pre_fit
            # docstring).  Both are "restart" time for goodput: work a run
            # repeats after preemption that trains nothing.
            with spans.span("restart"):
                if self.pre_fit is not None:
                    self.pre_fit(self)
                resumed = self.maybe_resume()
                if resumed and monitor is not None and "health" in self.opt_state:
                    # align the boundary comparator with the RESTORED
                    # cumulative counter — otherwise the first boundary
                    # re-triggers the policy for an anomaly the previous
                    # incarnation handled (a permanent halt/restart loop
                    # under policy=halt)
                    monitor.seed_counters(
                        int(self.opt_state["health"]["nonfinite_count"]))
            # data-pipeline stats (telemetry.batch_stats): the accumulator
            # rides the prefetch thread — global_batches feeds it from the
            # host numpy batch before sharding, the boundary drains it into
            # the metric stream.  Attached before the iterator exists so the
            # first batch is already counted.
            batch_stats = None
            if tel.batch_stats and hasattr(self.data_module, "global_batches"):
                from neuronx_distributed_training_tpu.data.loader import (
                    BatchStats,
                )

                batch_stats = BatchStats(
                    pad_id=getattr(self.data_module, "pad_id", None))
                try:
                    self.data_module.batch_stats = batch_stats
                except AttributeError:  # a slotted test double: no hook
                    batch_stats = None
            # background prefetch: slow fetch_rows (arrow page-in, mmap
            # faults) must not stall dispatch (the reference's MpDeviceLoader
            # role); shard_batch uses an explicit NamedSharding, so it is
            # thread-safe.  AFTER resume: the sampler's consumed_samples
            # must be restored before the first fetch.
            batches = PrefetchIterator(
                self.data_module.sharded_batches(self.mesh),
                timeout_seconds=hc.data_wait_timeout_seconds,
                activity_fn=getattr(self.data_module, "last_io_activity",
                                    None))
            log_every = max(1, int(self.exp.log_every_n_steps))
            census_pending = tel.compile_census
            with self.mesh, shd.use_mesh(self.mesh):
                self.exp.step_timed()  # arm the step timer
                # restart time predates the window just armed: drop it from
                # the throughput exclusion (goodput still counts it)
                spans.take_excluded()
                first_dispatch = True
                last_fetch = self.step
                while self.step < self.max_steps:
                    self.exp.maybe_profile(self.step)
                    # device-time capture window (telemetry.trace): start/
                    # stop rides the same per-step cadence; steps outside
                    # the window are untouched (no syncs, no graph changes)
                    self.exp.maybe_trace(self.step)
                    if self.preemption_notice is not None:
                        # a sigterm-mode injection fired at the save/restore
                        # point (outside this loop's scope): honor it like a
                        # SIGTERM that landed there
                        _request_stop(self.preemption_notice,
                                      condition="preemption")
                        self.preemption_notice = None
                    if self.fault_injector is not None and \
                            self.fault_injector.maybe_fire("step", self.step):
                        # sigterm-mode injection: a preemption NOTICE — the
                        # step still runs, then the boundary takes the
                        # grace-window emergency checkpoint (kill mode raised
                        # out of maybe_fire instead)
                        _request_stop("injected preemption notice",
                                      condition="preemption")
                    with spans.span("data_wait"):
                        try:
                            batch = next(batches)
                        except DataStallError as stall:
                            # data-stall watchdog (telemetry.health.
                            # data_wait_timeout_seconds): feed the existing
                            # hang-watchdog bundle path — thread stacks + a
                            # device-safe forensic bundle — then let the
                            # curated error propagate instead of freezing.
                            # The transient-I/O retries already ran (and
                            # deferred this verdict) on the prefetch thread.
                            self.stop_class = "data_stall"
                            if control is not None:
                                control.note_exit("data_stall", str(stall))
                            if monitor is not None:
                                from neuronx_distributed_training_tpu.telemetry.flight_recorder import (  # noqa: E501
                                    _all_thread_stacks,
                                )

                                monitor.dump_hang(
                                    self.step, "data_wait",
                                    _all_thread_stacks())
                            raise
                    key = jax.random.fold_in(
                        jax.random.PRNGKey(STEP_KEY_SEED), self.step)
                    if census_pending:
                        census_pending = False
                        self._compile_census(batch, key, spans)
                    # host-side metadata check only (shapes/dtypes — never
                    # values): a mid-run signature change means a retrace
                    detector.check("train_step", batch)
                    # the step annotation also bounds the trace capture's
                    # per-step device-time attribution, so it stays on for
                    # an open trace window even when spans are off
                    annot = (
                        jax.profiler.StepTraceAnnotation(
                            "train", step_num=self.step)
                        if tel.spans or self.exp.trace_active
                        else contextlib.nullcontext()
                    )
                    # "dispatch" is host enqueue time: under dispatch-ahead
                    # the device runs behind and this span stays tiny; device
                    # time that outran the host surfaces in host_sync instead.
                    # The first call of a still-jitted step (census off or
                    # failed) traces+compiles inline — count that one as
                    # "compile" so it stays out of the throughput window and
                    # goodput either way.
                    dispatch_span = "dispatch"
                    if first_dispatch:
                        first_dispatch = False
                        if hasattr(self.train_step, "lower"):
                            dispatch_span = "compile"
                    with spans.span(dispatch_span), annot:
                        self.params, self.opt_state, metrics = self.train_step(
                            self.params, self.opt_state, batch, key
                        )
                    if monitor is not None:
                        # host references only (device arrays stay unfetched);
                        # the batch fingerprint is the retrace detector's —
                        # one abstract-signature source of truth
                        monitor.record(
                            self.step, metrics,
                            fingerprint=detector.signature("train_step"),
                            spans=spans.snapshot() if spans.enabled else None,
                        )
                    self.step += 1
                    if max_time is not None and stop_requested["reason"] is None:
                        if _time.monotonic() - t_start > max_time:
                            if control is not None:
                                # host clocks disagree at the margin: fold
                                # the budget stop through the control word
                                # so the fleet stops at the same step
                                control.request(
                                    "max_time",
                                    f"max_time {cfg_t.get('max_time')}")
                            else:
                                stop_requested["reason"] = (
                                    f"max_time {cfg_t.get('max_time')}")
                    # host sync ONLY at logging/validation/checkpoint
                    # boundaries: between them the loop keeps dispatching
                    # ahead of the device (the reference batches metric
                    # fetches the same way via xm.add_step_closure,
                    # base.py:235-250).  Under the control plane a stop
                    # NOTICE never makes its own boundary: the decision must
                    # land at a step every host computes identically, or the
                    # fold collective itself would rendezvous-mismatch — the
                    # notice waits for the next deterministic boundary (and
                    # on a real fleet the host keeps dispatching steps until
                    # then, staying inside every collective).
                    boundary = (
                        self.step % log_every == 0
                        or self.step == self.max_steps
                        or (control is None
                            and stop_requested["reason"] is not None)
                        or (val_interval and self.step % val_interval == 0)
                        or (ck_every and self.step % ck_every == 0)
                    )
                    if not boundary:
                        continue
                    n_since = self.step - last_fetch
                    last_fetch = self.step
                    # the boundary metric fetch is the loop's ONE host sync:
                    # any device time the host outran is absorbed here
                    with spans.span("host_sync"), _sync_guard("host_sync"):
                        if self.fault_injector is not None:
                            # drill injection point "sync": a dead peer mid-
                            # collective — the blocking fetch never returns
                            # and the armed watchdog must escape the process
                            # (mode="hang" blocks here)
                            self.fault_injector.maybe_fire("sync", self.step)
                        # the tensorstats packed vectors are ARRAYS — they
                        # ride the same boundary fetch (still the one host
                        # sync) but must bypass the float() coercion and the
                        # scalar sinks (-> ExpManager.log_tensorstats below)
                        last_metrics = {}
                        ts_payload = {}
                        for k, v in metrics.items():
                            if k.startswith(_TS_HIST_PREFIX):
                                ts_payload[k] = np.asarray(v)
                            else:
                                last_metrics[k] = float(v)
                    if monitor is not None:
                        # anomaly policy on the ALREADY-fetched scalars: a
                        # healthy boundary costs one int compare; an anomaly
                        # dumps the forensic bundle and applies the policy
                        action = monitor.check_boundary(self.step, last_metrics)
                        if action == "halt":
                            # do NOT checkpoint: under halt the poisoned
                            # update was applied, and auto-resume must find
                            # the last GOOD checkpoint, not this state
                            halt_reason = (
                                f"health policy=halt: non-finite step "
                                f"{int(last_metrics.get('health/last_nonfinite_step', -1))}"
                            )
                            if control is not None:
                                # folds through the boundary control word
                                # below — every host halts at this step even
                                # if a counter ever diverged across hosts
                                control.request("health_halt", halt_reason)
                            else:
                                logger.error(
                                    "%s (bundle in %s) — stopping without a "
                                    "checkpoint; resume restores the last "
                                    "good save", halt_reason,
                                    self.exp.log_dir,
                                )
                                halted = True
                                self.stop_class = "health_halt"
                    # throughput window excludes validation/checkpoint/compile
                    # wall time (the spans tagged non-productive) so seq/s and
                    # throughput_peak reflect steady-state training only
                    dt = self.exp.step_timed(
                        n_since, exclude_seconds=spans.take_excluded()
                    )
                    last_metrics["step_time"] = dt
                    last_metrics["consumed_samples"] = self.consumed_samples
                    ioc = int(getattr(self.data_module, "io_retry_count", 0)
                              or 0)
                    if ioc:
                        # cumulative transient-read retries the prefetch
                        # thread absorbed (data.io_retries backoff) — a
                        # flaky mount is visible before it becomes a stall
                        last_metrics["data/io_retries"] = float(ioc)
                    if tel.spans:
                        last_metrics.update(
                            {f"time/{k}": v for k, v in spans.drain().items()}
                        )
                    if tel.goodput:
                        last_metrics["goodput_fraction"] = (
                            spans.goodput_fraction())
                    if memplane is not None:
                        # memory/ metrics (worst-device in-use/peak/headroom
                        # + spread) ride the same boundary record into every
                        # sink, the fleet beacon, and the alert rules; the
                        # in-window boundary additionally captures the
                        # memory profile -> memory_summary.json.  With
                        # device_memory ALSO on, the legacy device_* keys
                        # derive from this same sweep — never a second one.
                        mem_metrics = memplane.boundary(self.step)
                        last_metrics.update(mem_metrics)
                        if tel.device_memory:
                            last_metrics.update(
                                _legacy_device_memory_keys(mem_metrics))
                    elif tel.device_memory:
                        last_metrics.update(_device_memory_metrics(self.mesh))
                    if batch_stats is not None and self.step % log_every == 0:
                        # data/ stats the prefetch thread accumulated since
                        # the last LOG boundary.  Drained only when
                        # log_metrics will actually write the record — a
                        # checkpoint/validation boundary off the log cadence
                        # would otherwise reset the accumulator into a
                        # record every sink drops
                        last_metrics.update(batch_stats.drain())
                    self.exp.log_metrics(self.step, last_metrics)
                    if ts_payload:
                        # structured observatory record -> tensorstats.jsonl
                        # (the per-step tensorstats/ SCALARS already rode
                        # last_metrics into every scalar sink above)
                        self.exp.log_tensorstats(self.step, ts_payload)
                    fleet_metrics: dict[str, float] = {}
                    if fleet is not None:
                        # this host's beacon + (rank 0) the fleet fold; a
                        # newly quiet host dumps a fleet_stall bundle through
                        # the flight recorder, and the returned fleet/*
                        # metrics feed the alert rules below
                        fleet_metrics = fleet.boundary(
                            self.step, last_metrics,
                            spans=(spans.snapshot() if spans.enabled
                                   else None),
                            monitor=monitor,
                        )
                    if alerts is not None:
                        for fire in alerts.observe(
                                self.step,
                                {**last_metrics, **fleet_metrics}):
                            if fire.action == "dump" and monitor is not None:
                                # same forensic machinery as an anomaly:
                                # alert_<step>/ bundle with the ring trail
                                monitor.dump(
                                    self.step, kind="alert",
                                    boundary_metrics=last_metrics,
                                    extra={"alert": fire.to_dict()},
                                )
                            elif fire.action == "halt":
                                # operational halt (state is NOT poisoned):
                                # the graceful-stop path checkpoints for
                                # resume and the reason lands in
                                # run_summary.json (elastic.stop_reason +
                                # the alerts trail)
                                reason = f"alert {fire.rule}: {fire.message}"
                                if control is not None:
                                    # fleet-consistent even on a host-local
                                    # metric: the stop folds through the
                                    # control word at THIS boundary
                                    control.request("alert_halt", reason)
                                else:
                                    _request_stop(reason)
                                    self.stop_class = "alert_halt"
                    ck_now = False
                    fold_stop = False
                    if control is not None:
                        # THE consensus fold (docs/observability.md "Fleet
                        # control"): rank 0 polls control/commands.jsonl,
                        # every host's condition word rides one tiny
                        # replicated collective, and all hosts apply the
                        # SAME decision at this step.  This is the
                        # boundary's only extra cross-host traffic — zero
                        # new syncs between boundaries.  The fold is itself
                        # a blocking rendezvous, so it rides the same hang
                        # guard as the metric fetch: a peer that died
                        # between its host_sync and its fold must not hang
                        # the survivors past the watchdog.
                        with _sync_guard("control_fold"):
                            decision = control.boundary(self.step)
                        if decision.dump and monitor is not None:
                            monitor.dump(
                                self.step, kind="control",
                                boundary_metrics=last_metrics,
                                extra={"control": decision.to_dict()},
                            )
                        ck_now = decision.checkpoint_now
                        if decision.halt:
                            halted = True
                            self.stop_class = "health_halt"
                            logger.error(
                                "control: fleet-consistent halt at step %d "
                                "(%s) — stopping WITHOUT a checkpoint; "
                                "resume restores the last good save",
                                self.step, decision.reason)
                        elif decision.stop:
                            fold_stop = True
                            self.stop_class = decision.conditions[0]
                            if stop_requested["reason"] is None:
                                _request_stop(decision.reason)

                    if halted:
                        break
                    if val_interval and self.step % val_interval == 0 and self.eval_step:
                        with spans.span("validate"):
                            last_metrics["val_loss"] = self.validate(
                                limit_val, detector=detector)
                        self.exp.log_metrics(
                            self.step, {"val_loss": last_metrics["val_loss"]}, force=True
                        )
                    # ONE snapshot of the stop decision for this boundary:
                    # the SIGTERM handler can run at any bytecode (including
                    # inside the cadence save below), and deciding the stop
                    # branch from a re-read would double-save this step —
                    # orbax raises StepAlreadyExistsError.  A notice landing
                    # mid-save stops at the NEXT boundary instead, still
                    # inside the grace window.  Under the control plane the
                    # snapshot is the FOLDED decision, not the raw local
                    # request: a SIGTERM landing after this boundary's fold
                    # must wait for the next fold, or this host would stop
                    # alone while its peers saw an empty word — exactly the
                    # rendezvous mismatch the plane exists to kill.
                    stopping = (fold_stop if control is not None
                                else stop_requested["reason"] is not None)
                    if stopping and self.stop_class is None:
                        r = str(stop_requested["reason"] or "")
                        self.stop_class = (
                            "alert_halt" if r.startswith("alert ")
                            else "max_time" if r.startswith("max_time")
                            else "preemption")
                    if ck_every and self.step % ck_every == 0 and not stopping:
                        with spans.span("checkpoint"):
                            self.save_checkpoint(last_metrics)
                    elif ck_now and not stopping:
                        # operator checkpoint_now (control decision): an
                        # off-cadence save at the deciding boundary — the
                        # cadence branch above already covered an on-cadence
                        # step, and a stop takes the emergency save below
                        with spans.span("checkpoint"):
                            self.save_checkpoint(last_metrics)
                    if stopping:
                        logger.warning(
                            "stopping at step %d: %s — checkpointing for resume",
                            self.step, stop_requested["reason"],
                        )
                        if self.checkpointer is not None:
                            # emergency save: drained inside the grace window
                            # so a background commit failure still counts as
                            # a failed save while retries are possible — it
                            # REPLACES the periodic save even when the stop
                            # step lands on the cadence (an async cadence
                            # save has no drain, no deadline, no guarantee)
                            with spans.span("checkpoint"):
                                self.save_checkpoint(
                                    last_metrics, emergency=True,
                                    deadline=stop_requested["deadline"])
                        break
                if (ck_every and self.checkpointer is not None
                        and stop_requested["reason"] is None and not halted):
                    with spans.span("checkpoint"):
                        self.save_checkpoint(last_metrics)  # final save
                if self.preemption_notice is not None:
                    # a notice that landed during the run's LAST save has no
                    # loop iteration left to convert it: the run is already
                    # complete and checkpointed, so record the fact in the
                    # elastic trail instead of silently dropping it
                    if stop_requested["reason"] is None:
                        stop_requested["reason"] = self.preemption_notice
                    logger.warning(
                        "preemption notice during the final save: run "
                        "already complete (%s)", self.preemption_notice)
                    self.preemption_notice = None
        except BaseException as e:
            fit_exc = e
            if memplane is not None:
                # OOM forensics (telemetry.memory): a RESOURCE_EXHAUSTED
                # escaping the step boundary dumps the oom_<step>/ bundle —
                # last allocator samples, live-buffer attribution, the
                # census's memory_analysis bytes, and the planner's
                # predicted breakdown — before the exception propagates.
                # dump_oom never raises.
                from neuronx_distributed_training_tpu.telemetry.memory import (  # noqa: E501
                    is_oom_error,
                )

                if is_oom_error(e):
                    memplane.dump_oom(
                        self.step, e, boundary_metrics=last_metrics,
                        memory_analysis=self._census_memory_analysis())
            raise
        finally:
            if memplane is not None:
                memplane.close()
            if fleet is not None:
                # final beacon FIRST (before the checkpoint drain can block):
                # clean exit -> closing:true, a raising fit() -> the
                # last_exception record, so the aggregator can tell a dead
                # host from a quiet one.  close() never raises.
                fleet.close(fit_exc, step=self.step)
            if batches is not None:
                batches.close()
            if old_handler is not None:
                import signal as _signal

                _signal.signal(_signal.SIGTERM, old_handler)
            try:
                if self.checkpointer is not None:
                    # the async-save drain: every exit path (clean, halt,
                    # SIGTERM, exception) waits the in-flight commit.  A drain
                    # failure still PROPAGATES (a lost save must be loud) —
                    # the nested finally below just keeps it from eating the
                    # goodput/elastic summaries and exp.close()
                    with spans.span("checkpoint"):
                        self.checkpointer.wait()
                        self.checkpointer.close()
            finally:
                self._write_teardown_summaries(
                    spans, detector, tel, resumed, stop_requested)
        return last_metrics

    def _write_teardown_summaries(self, spans, detector, tel, resumed,
                                  stop_requested) -> None:
        """fit() teardown after the checkpoint drain: persist the goodput and
        elastic sections of ``run_summary.json`` and close the exp manager.
        Runs even when the drain raised."""
        if tel.goodput:
            try:
                summary: dict[str, Any] = {
                    "goodput": spans.goodput_summary()}
                if detector.events:
                    summary["retrace_events"] = detector.events[-20:]
                self.exp.write_run_summary(summary)
            except Exception as e:  # noqa: BLE001 — teardown must finish
                logger.warning("goodput summary write failed: %s", e)
        last_ts = getattr(self.exp, "last_tensorstats", None)
        if last_ts:
            # the final cumulative observatory record — the snapshot
            # tools/quant_readiness.py prices compressed collectives from
            try:
                self.exp.write_run_summary({"tensorstats": last_ts})
            except Exception as e:  # noqa: BLE001 — teardown must finish
                logger.warning("tensorstats summary write failed: %s", e)
        itrail = self._merged_integrity_trail()
        if itrail:
            # the integrity trail (docs/elasticity.md "Integrity &
            # walk-back"): which step actually verified, how many corrupt
            # steps the restore walked past (including at discovery time,
            # before this trainer existed), what got quarantined, and what
            # the post-commit audit cost — metrics_report.py renders it
            try:
                self.exp.write_run_summary({"integrity": itrail})
            except Exception as e:  # noqa: BLE001 — teardown must finish
                logger.warning("integrity summary write failed: %s", e)
        if resumed or self.replan_record is not None \
                or stop_requested["reason"] is not None:
            # the elastic trail (docs/elasticity.md): what the restart
            # cost, whether a replan happened (old plan -> new plan), and
            # why this incarnation stopped — metrics_report.py renders it
            try:
                snap = spans.snapshot()
                section: dict[str, Any] = {
                    "resumed": bool(resumed),
                    "restart_seconds": round(snap.get("restart", 0.0), 3),
                    "replan_seconds": round(snap.get("replan", 0.0), 3),
                }
                if stop_requested["reason"] is not None:
                    section["stop_reason"] = stop_requested["reason"]
                if self.stop_class is not None:
                    # the deciding condition class — trainer.control's
                    # exit-code table maps it to the orchestrator-facing
                    # exit code
                    section["stop_class"] = self.stop_class
                if self.replan_record is not None:
                    section["replan"] = self.replan_record
                self.exp.write_run_summary({"elastic": section})
            except Exception as e:  # noqa: BLE001 — teardown must finish
                logger.warning("elastic summary write failed: %s", e)
        self.exp.close()

    def _merged_integrity_trail(self) -> dict:
        """Union of the discovery-time integrity trail (the replanner's
        walk-back, ``discovery_integrity_trail``) and the checkpointer's own
        restore/audit trail: walk-back counts add, quarantined steps union,
        the restore's verified step wins (it is the step actually used)."""
        disc = dict(self.discovery_integrity_trail or {})
        # getattr: fit() also runs against checkpointer test doubles
        own = dict(getattr(self.checkpointer, "integrity_trail", None) or {})
        if not disc:
            return own
        if not own:
            return disc
        merged = {**disc, **own}
        merged["walk_back_count"] = (int(disc.get("walk_back_count", 0))
                                     + int(own.get("walk_back_count", 0)))
        q = list(disc.get("quarantined_steps") or [])
        for s in own.get("quarantined_steps") or []:
            if s not in q:
                q.append(s)
        merged["quarantined_steps"] = q
        merged["verify_seconds"] = round(
            float(disc.get("verify_seconds", 0.0))
            + float(own.get("verify_seconds", 0.0)), 3)
        if disc.get("legacy_restore") or own.get("legacy_restore"):
            merged["legacy_restore"] = True
        return merged

    def _census_memory_analysis(self) -> Optional[dict]:
        """The compile census's ``memory_analysis`` bytes out of
        ``run_summary.json`` (for the OOM bundle's predicted-vs-actual);
        None when the census didn't run or the file is unreadable."""
        import json as _json
        from pathlib import Path

        try:
            with open(Path(self.exp.log_dir) / "run_summary.json") as f:
                ma = _json.load(f).get("memory_analysis")
            return dict(ma) if isinstance(ma, dict) else None
        except (OSError, ValueError, AttributeError, TypeError):
            return None

    def _compile_census(self, batch, key, spans) -> None:
        """First-compile census (telemetry.compile_census): AOT lower+compile
        the train step, harvest ``memory_analysis()`` bytes / HLO collective
        counts / the analytic FLOPs estimate into ``run_summary.json``, then
        swap the compiled executable into the loop — the census costs ZERO
        extra compiles because the loop runs the very executable it measured.
        Any failure degrades to the plain jit path (observability must never
        kill training)."""
        if not hasattr(self.train_step, "lower"):
            return  # already AOT-compiled, or a test double
        import time as _time

        from neuronx_distributed_training_tpu.telemetry import compile_census

        # deliberately NOT watchdog-guarded: a first compile legitimately runs
        # minutes on TPU, and a sync-tuned timeout would false-abort it
        try:
            t0 = _time.perf_counter()
            lowered = self.train_step.lower(
                self.params, self.opt_state, batch, key
            )
            compiled = lowered.compile()
            dt = _time.perf_counter() - t0
        except Exception as e:  # noqa: BLE001 — census is best-effort
            logger.warning(
                "compile census failed; continuing with the jit path: %s", e
            )
            return
        # the executable is in hand: swap it in BEFORE the fallible harvest/
        # write below — a full run_summary.json disk error must not discard a
        # multi-minute compile and force a second identical one
        self.train_step = compiled
        # compile is non-productive wall time: goodput + the throughput
        # window's exclusion both see it through the span
        spans.add("compile", dt)
        try:
            census = compile_census(
                compiled,
                compile_seconds=dt,
                flops_per_token=self.run_facts.get("fwd_flops_per_token"),
                extra={k: v for k, v in self.run_facts.items()
                       if k != "fwd_flops_per_token"},
            )
            self.exp.write_run_summary(census)
            logger.info(
                "compile census: %.1fs compile, collectives=%s",
                dt, census.get("collectives"),
            )
        except Exception as e:  # noqa: BLE001 — harvest is best-effort too
            logger.warning(
                "compile census harvest/write failed (the compiled step is "
                "still in use): %s", e
            )
        if self.exp.telemetry.graph_audit:
            self._graph_audit(compiled, lowered)

    def _graph_audit(self, compiled, lowered) -> None:
        """telemetry.graph_audit: run the static contract rules
        (analysis.graph_audit) against the very executable the loop is about
        to train with, attribute every collective to its declared source
        (analysis.graph_contract provenance — an unattributed collective is
        a GSPMD-inserted reshard and flips the verdict), log every finding,
        and persist the verdict to run_summary.json.  Pure host-side HLO
        inspection — no device work, no extra compiles; failures degrade to
        a warning (the audit gates pre-flight in tools/preflight_audit.py
        and tools/graph_contract.py; in-loop it only observes)."""
        try:
            from neuronx_distributed_training_tpu.analysis.graph_audit import (
                AuditContext,
                audit_executable,
            )
            from neuronx_distributed_training_tpu.analysis.graph_contract import (
                attribution_report,
                fingerprint_artifacts,
            )
            from neuronx_distributed_training_tpu.config.loader import (
                batch_schedule,
            )

            ctx = AuditContext(
                cfg=self.cfg, mesh=self.mesh, policy=self.policy,
                model_cfg=self.model_cfg,
                sched=batch_schedule(self.cfg, int(self.mesh.devices.size)),
                donate=self.donate,
                params_tree=self.params, opt_tree=self.opt_state,
                pspecs=self.param_specs, ospecs=self.opt_specs,
            )
            rep = audit_executable(ctx, compiled, lowered,
                                   log=logger.warning)
            summary: dict = {}
            try:
                stablehlo = ""
                if lowered is not None:
                    try:
                        stablehlo = lowered.as_text()
                    except Exception:  # noqa: BLE001 — dtype census degrades
                        pass
                fp = fingerprint_artifacts(ctx, compiled, stablehlo)
                prov = attribution_report(fp)
                for f in prov.findings:
                    logger.warning(f.format())
                rep.extend(prov)
                summary["contract"] = {
                    "collectives": {
                        k: {"count": v["count"], "source": v["source"]}
                        for k, v in fp["collectives"].items()},
                    "collectives_total":
                        prov.stats["collectives_total"],
                    "collectives_unattributed":
                        prov.stats["collectives_unattributed"],
                    "matmul_dtypes": (fp.get("matmul_dtypes") or {}).get(
                        "counts"),
                }
            except Exception as e:  # noqa: BLE001 — provenance is additive
                logger.warning("collective provenance failed: %s", e)
            summary = {**rep.to_dict(), **summary}
            self.exp.write_run_summary({"graph_audit": summary})
        except Exception as e:  # noqa: BLE001 — observability must not kill
            logger.warning("graph audit failed: %s", e)

    def validate(self, limit_batches: int, detector=None) -> float:
        params = self.params
        if (self.ema_cfg is not None
                and self.ema_cfg.evaluate_ema_weights_instead
                and "ema" in self.opt_state):
            # reference evaluate_ema_weights_instead: swap in the averaged
            # weights for validation only
            params = jax.tree_util.tree_map(
                lambda e, p: e.astype(p.dtype), self.opt_state["ema"], self.params
            )
        losses = []
        it = self.val_data_module.sharded_batches(self.mesh)
        for i, batch in enumerate(it):
            if i >= limit_batches:
                break
            if detector is not None:
                detector.check("eval_step", batch)
            m = self.eval_step(params, batch, jax.random.PRNGKey(0))
            losses.append(float(m["val_loss"]))
        return float(np.mean(losses)) if losses else float("nan")

    def save_checkpoint(
        self,
        metrics: Optional[dict[str, float]] = None,
        *,
        emergency: bool = False,
        deadline: Optional[float] = None,
    ) -> None:
        """One checkpoint save: the topology/plan manifest rides along
        (world-size-agnostic resume, trainer.elastic), transient I/O errors
        retry with backoff (``exp_manager.elastic.save_retries``), and
        ``emergency=True`` (the SIGTERM grace window) drains the async commit
        inside the retry loop bounded by ``deadline``."""
        if self.checkpointer is None:
            return
        from neuronx_distributed_training_tpu.trainer.elastic import (
            build_manifest,
        )

        ds = dict(self.cfg.get("distributed_strategy", {}) or {})
        pp = int(ds.get("pipeline_model_parallel_size", 1))
        vp = int(ds.get("virtual_pipeline_model_parallel_size") or 1)
        try:
            manifest = build_manifest(
                self.cfg, self.mesh, step=self.step,
                schedule=self.pipeline_schedule,
                model_family=self.run_facts.get(
                    "model_family", type(self.model_cfg).__name__),
                save_bf16=self.checkpointer.config.save_bf16,
            )
        except Exception as e:  # noqa: BLE001 — a manifest failure must not
            # block the save itself (the checkpoint stays resumable at the
            # SAME world size without one)
            logger.warning("manifest build failed (saving without): %s", e)
            manifest = None
        self.checkpointer.save_with_retry(
            TrainState(
                params=self.params,
                opt_state=self.opt_state,
                step=self.step,
                consumed_samples=self.consumed_samples,
                # authoritative layer layout for converters: VPP training
                # stores layers interleaved [vp, pp, Lc, ...] (ADVICE r2 —
                # converters branch on this, shape sniffing is the fallback)
                extra={"layer_layout": ("interleaved" if pp > 1 and vp > 1
                                        else "flat")},
            ),
            metrics=metrics,
            manifest=manifest,
            force=emergency,
            deadline=deadline,
            drain=emergency,
        )
        if self.fault_injector is not None:
            # drill injection point "save": the save was INITIATED (an async
            # save may be in flight) — the drain-on-teardown contract is what
            # keeps a kill here from orphaning it; sigterm mode is a
            # preemption notice landing mid-save
            if self.fault_injector.maybe_fire("save", self.step):
                self.preemption_notice = (
                    "injected preemption notice (mid-save)")


def build_model(cfg: ConfigDict, policy: DtypePolicy, *, shift_labels: bool = True):
    """Model dispatch by ``model_source`` + architecture (reference
    ``training.py:71-91`` selects Megatron vs HF modules the same way).

    ``shift_labels=False`` when the data path pre-shifts on host (the Megatron
    mmap convention).  Returns ``(model_cfg, loss_fn, init_fn, specs_fn)``.
    """
    source = str(cfg.get("model_source", "hf")).lower()
    if source not in ("hf", "megatron"):
        raise ValueError(f"unsupported model_source {source!r} (want 'hf' or 'megatron')")
    model_block = dict(cfg.get("model", {}) or {})
    ds_block = dict(cfg.get("distributed_strategy", {}) or {})
    arch = str(model_block.get("architecture", model_block.get("model_type", "llama"))).lower()

    if arch in ("llama", "mistral"):
        mc = llama.LlamaConfig.from_config(model_block, ds_block)

        if mc.attention_impl == "zigzag_ring":
            # zig-zag CP layout: the loss hook permutes the batch (labels
            # pre-shifted in ORIGINAL order — the in-model shift is
            # order-dependent) and feeds matching RoPE positions; cp == 1
            # makes both transforms the identity
            from neuronx_distributed_training_tpu.parallel.ring_attention import (
                zigzag_positions,
                zigzag_transform_batch,
            )

            zz_cp = int(ds_block.get("context_parallel_size", 1) or 1)
            if not shift_labels:
                raise NotImplementedError(
                    "zigzag_ring_attention with a pre-shifted data module "
                    "(the zig-zag transform owns the label shift)"
                )

            def loss_fn(p, batch, key):
                zb = zigzag_transform_batch(batch, zz_cp)
                s = zb["input_ids"].shape[1]
                pos = jnp.broadcast_to(
                    zigzag_positions(s, zz_cp)[None, :], zb["input_ids"].shape
                )
                return llama.forward(
                    p, zb, mc, policy, positions=pos, shift_labels=False
                )

        else:

            def loss_fn(p, batch, key):
                return llama.forward(p, batch, mc, policy, shift_labels=shift_labels)

        return (
            mc,
            loss_fn,
            lambda key: llama.init_params(key, mc, policy),
            lambda **kw: llama.param_specs(mc, **kw),
        )
    if arch == "mixtral":
        from neuronx_distributed_training_tpu.models import mixtral

        xc = mixtral.MixtralConfig.from_config(model_block, ds_block)
        if xc.llama.attention_impl == "zigzag_ring":
            # the zig-zag batch/position transform is wired for the llama
            # loss hook only; running the op on an unpermuted batch would
            # silently corrupt the causal structure
            raise NotImplementedError(
                "zigzag_ring_attention is llama/mistral-only; use "
                "fusions.ring_attention for mixtral"
            )

        def loss_fn(p, batch, key):
            return mixtral.forward(p, batch, xc, policy, shift_labels=shift_labels)

        return (
            xc,
            loss_fn,
            lambda key: mixtral.init_params(key, xc, policy),
            lambda **kw: mixtral.param_specs(xc, **kw),
        )
    if arch == "gpt" or source == "megatron":
        from neuronx_distributed_training_tpu.models import gpt

        gc = gpt.GPTConfig.from_config(model_block, ds_block)

        def loss_fn(p, batch, key):
            return gpt.forward(p, batch, gc, policy, rng=key, shift_labels=shift_labels)

        return (
            gc,
            loss_fn,
            lambda key: gpt.init_params(key, gc, policy),
            lambda **kw: gpt.param_specs(gc, **kw),
        )
    raise ValueError(f"unsupported model_source/architecture: {source}/{arch}")


def _forward_logits_for(model_cfg: Any, policy: DtypePolicy):
    """``(params, batch, rng=None) -> (logits, reg_loss)`` for any family —
    the preference losses' policy forward.

    ``reg_loss`` is the model's auxiliary regularizer (Mixtral/GPT-MoE router
    load-balancing term; 0.0 for dense models) so preference training keeps
    the same expert-balance pressure as the LM loss path.  ``rng`` threads
    dropout for GPT policy forwards (None during the frozen reference pass).
    """
    if isinstance(model_cfg, llama.LlamaConfig):
        if model_cfg.attention_impl == "zigzag_ring":
            # preference batches are chosen/rejected sequences, not the
            # zig-zag-permuted LM batches the layout expects
            raise NotImplementedError(
                "zigzag_ring_attention with preference alignment; use "
                "fusions.ring_attention"
            )

        def fwd(p, b, rng=None):
            logits, _ = llama.forward(
                p, {"input_ids": b["input_ids"]}, model_cfg, policy)
            return logits, 0.0

        return fwd
    from neuronx_distributed_training_tpu.models import gpt, mixtral

    if isinstance(model_cfg, mixtral.MixtralConfig):
        def fwd(p, b, rng=None):
            logits, aux = mixtral.forward(
                p, {"input_ids": b["input_ids"]}, model_cfg, policy)
            return logits, aux["router_aux_loss"]

        return fwd
    if isinstance(model_cfg, gpt.GPTConfig):
        def fwd(p, b, rng=None):
            logits, aux = gpt.forward(
                p, {"input_ids": b["input_ids"]}, model_cfg, policy, rng=rng)
            return logits, aux.get("router_aux_loss", 0.0)

        return fwd
    raise NotImplementedError(
        f"preference alignment not wired for {type(model_cfg).__name__}"
    )


def pipeline_hooks_for(cfg: ConfigDict, model_cfg: Any, policy: DtypePolicy,
                       *, shift_labels: bool = True):
    """Pipeline hooks dispatch -> ``((embed, stage, loss), opts)``.

    ``opts``: ``stage_aux`` (stage returns ``(x, aux)``), ``aux_inv_layers``
    (1/num_layers scale for the psum'd MoE router loss; the caller divides by
    num_microbatches), ``needs_rng`` (thread per-microbatch dropout keys).
    The reference pipelines every model source the same way
    (``megatron_gpt_model.py:67-77`` sets ``transformer_layer_cls``).
    """
    if isinstance(model_cfg, llama.LlamaConfig):
        return llama.pipeline_hooks(model_cfg, policy, shift_labels=shift_labels), {}
    from neuronx_distributed_training_tpu.models import gpt, mixtral

    if isinstance(model_cfg, mixtral.MixtralConfig):
        return (
            mixtral.pipeline_hooks(model_cfg, policy, shift_labels=shift_labels),
            # normalized over the layers that HAVE routers (moe_frequency)
            {"stage_aux": True,
             "aux_inv_layers": 1.0 / mixtral.num_moe_layers(model_cfg)},
        )
    if isinstance(model_cfg, gpt.GPTConfig):
        opts = {
            "stage_aux": True,
            # normalized over the layers that HAVE routers (moe_frequency)
            "aux_inv_layers": (
                1.0 / gpt.num_moe_layers(model_cfg)
                if model_cfg.moe is not None else 0.0
            ),
            "needs_rng": (
                model_cfg.hidden_dropout > 0.0 or model_cfg.embedding_dropout > 0.0
            ),
        }
        return gpt.pipeline_hooks(model_cfg, policy, shift_labels=shift_labels), opts
    raise NotImplementedError(
        f"pipeline parallelism not wired for {type(model_cfg).__name__} yet"
    )


def assemble_step_program(cfg: ConfigDict, **kw: Any) -> StepProgram:
    """Module-level alias of :meth:`Trainer.assemble` — the entry point the
    static graph auditor (``analysis.graph_audit``) builds on."""
    return Trainer.assemble(cfg, **kw)


def train(cfg: ConfigDict, **kw: Any) -> dict[str, float]:
    """The ``train(cfg)`` entry point (reference ``examples/training.py:41``)."""
    trainer = Trainer.from_config(cfg, **kw)
    return trainer.fit()
