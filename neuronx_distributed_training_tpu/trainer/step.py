"""The jitted training step.

Replaces the reference's ``BaseModelModule.training_step`` /
``forward_backward_step`` (``base.py:180-395``): zero-grad + microbatch loop +
``loss.backward()`` accumulation + optimizer step + loss reductions become ONE
compiled function:

- microbatch gradient accumulation is a ``lax.scan`` over a leading microbatch
  dim, accumulating in ``grad_accum_dtype`` (the reference's
  ``loss/num_microbatches`` scaling at ``base.py:364-373`` and fp32-grad-acc
  option at ``base.py:128-132``);
- the DP/CP loss all-reduces (``base.py:387-395``) are implicit — the loss is a
  global masked mean over a sharded batch, so GSPMD inserts them;
- the ZeRO-1 optimizer update runs on DP-sharded optimizer state
  (``optim/adamw.py``) with grad-norm clipping inside, exactly where the
  reference's wrapped optimizer does it (``nlp_overrides.py:203-216``).

There is no ``xm.mark_step`` anywhere: the jit boundary is the graph boundary.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.optim.adamw import AdamWConfig, adamw_update, global_norm
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.parallel.mesh import DATA_AXES
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

# loss_fn(params, batch, step_key) -> (loss, aux_dict)
LossFn = Callable[[Any, dict[str, jax.Array], jax.Array], tuple]


def microbatch_split(batch: dict[str, jax.Array], num_microbatches: int):
    """[gbs, ...] -> [num_micro, gbs/num_micro, ...] (the get_batch_iterator
    analogue, reference ``base.py:330-350``)."""
    def split(x):
        return x.reshape((num_microbatches, x.shape[0] // num_microbatches) + x.shape[1:])

    return jax.tree_util.tree_map(split, batch)


def make_train_step(
    loss_fn: LossFn,
    opt_cfg: AdamWConfig,
    lr_schedule: Callable,
    policy: DtypePolicy,
    *,
    num_microbatches: int = 1,
    log_param_norm: bool = False,
    log_gradient_norm: bool = False,
    trainable_mask: Any = None,  # peft.lora.trainable_mask for LoRA freeze
    ema_cfg: Any = None,  # optim.adamw.EMAConfig; state must carry an "ema" tree
    param_specs: Any = None,  # pin grads to the param sharding (see below)
    loss_and_grad_fn: Optional[Callable] = None,  # manual-grad schedules (1F1B)
    health_cfg: Any = None,  # telemetry.health.HealthConfig (numerics probes)
    bucket_plan: Any = None,  # optim.overlap.BucketPlan (engineered overlap)
    prefetch_ag: bool = True,
    tensorstats_cfg: Any = None,  # telemetry.tensorstats.TensorStatsConfig
) -> Callable:
    """Build the (un-jitted) train step:
    ``(params, opt_state, batch, step_key) -> (params, opt_state, metrics)``.

    ``loss_and_grad_fn`` — ``(params, batch, step_key) -> (loss, aux, grads)``
    — replaces the ``jax.value_and_grad`` of ``loss_fn`` when a schedule
    computes its own gradients (the manual-vjp 1F1B pipeline).  Everything
    downstream of the gradients — grad-accum dtype, the param-sharding pin,
    the AdamW/ZeRO-1 update, metrics — is the SAME code path, so the
    optimizer boundary is schedule-independent.

    ``health_cfg`` (enabled): the numerics flight recorder's in-graph probes —
    per-layer-group grad norms (sharing the clipping norm's reduction pass),
    loss finiteness, an ``updates_finite`` flag, cumulative anomaly counters
    threaded through ``opt_state["health"]`` (which ``init_opt_state(...,
    health=True)`` must have created), and — under ``policy: skip_update`` —
    the in-graph suppression of a non-finite update.  All of it rides the one
    jitted executable; the host sees the results only at the boundary metric
    fetch it already performs.

    ``tensorstats_cfg`` (enabled): the tensor numerics observatory
    (``telemetry.tensorstats``) — per layer-group dynamic-range stats of the
    optimizer-boundary grads, cumulated in ``opt_state["tensorstats"]`` and
    surfaced as ``tensorstats/...`` scalars plus ``tensorstats_hist/...``
    packed vectors in the boundary metrics.  Shares the health probes' layer
    grouping and the clipping norm's reduction pass; rides the same one
    executable."""
    health = health_cfg if (health_cfg is not None
                            and getattr(health_cfg, "enabled", False)) else None
    tstats = (tensorstats_cfg
              if tensorstats_cfg is not None
              and getattr(tensorstats_cfg, "enabled", False) else None)
    if health is not None or tstats is not None:
        from neuronx_distributed_training_tpu.telemetry.health import (
            grad_group_of,
        )

    def grad_one_microbatch(params, mb, step_key):
        def scalar_loss(p):
            loss, aux = loss_fn(p, mb, step_key)
            # scalar aux entries (DPO rewards, ORPO odds, MoE router loss)
            # surface as logged metrics — the reference's misc_metrics flow
            # (base_dpo.py:104-109); non-scalars (logits) stay internal
            scalars = {
                k: jnp.asarray(v, jnp.float32)
                for k, v in aux.items()
                if jnp.ndim(v) == 0
            }
            return loss.astype(jnp.float32), scalars

        return jax.value_and_grad(scalar_loss, has_aux=True)(params)

    def train_step(params, opt_state, batch, step_key):
        if loss_and_grad_fn is not None:
            loss, aux, grads = loss_and_grad_fn(params, batch, step_key)
            loss = loss.astype(jnp.float32)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(policy.grad_accum_dtype), grads
            )
        elif num_microbatches == 1:
            (loss, aux), grads = grad_one_microbatch(params, batch, step_key)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(policy.grad_accum_dtype), grads
            )
        else:
            mbs = microbatch_split(batch, num_microbatches)

            def body(carry, mb):
                loss_sum, grad_sum = carry
                (loss, aux), grads = grad_one_microbatch(params, mb, step_key)
                grad_sum = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(policy.grad_accum_dtype), grad_sum, grads
                )
                if param_specs is not None:
                    # Pin the accumulation carry to the param sharding, not
                    # just the post-loop grads (line ~161): the carry's
                    # layout is otherwise re-solved from its consumers, and
                    # extra read-only uses of the grads (the tensorstats
                    # reductions) can tip the partitioner into a different
                    # carry sharding that reshards the embedding-backward
                    # scatter-add INSIDE the loop on every microbatch
                    grad_sum = jax.tree_util.tree_map(
                        lambda s, g: shd.constrain(g, s), param_specs,
                        grad_sum, is_leaf=lambda x: isinstance(x, P),
                    )
                return (loss_sum + loss, grad_sum), aux

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, policy.grad_accum_dtype), params
            )
            (loss_sum, grad_sum), aux_stack = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), zeros), mbs
            )
            inv = 1.0 / num_microbatches
            loss = loss_sum * inv
            grads = jax.tree_util.tree_map(lambda g: g * inv, grad_sum)
            aux = {k: jnp.mean(v) for k, v in aux_stack.items()}

        if param_specs is not None:
            # Pin gradients to the PARAM sharding at the loss->optimizer
            # boundary.  ZeRO-1 moments can be sharded on a dim the param
            # spec leaves free (e.g. the embed table's hidden dim over
            # ``data`` when vocab is taken by ``model``); without this pin
            # the partitioner back-propagates that layout into the
            # activation-cotangent chain — observed as an "involuntary full
            # rematerialization" on the pp x cp mesh — instead of resharding
            # the small [vocab, h] grad right here.
            grads = jax.tree_util.tree_map(
                lambda s, g: shd.constrain(g, s), param_specs, grads,
                is_leaf=lambda x: isinstance(x, P),
            )

        lr = lr_schedule(opt_state["step"])
        new_params, new_opt_state, opt_metrics = adamw_update(
            params, grads, opt_state, lr, opt_cfg, policy,
            trainable_mask=trainable_mask, ema_cfg=ema_cfg,
            grad_group_fn=(grad_group_of
                           if (health is not None or tstats is not None)
                           else None),
            skip_nonfinite=(health is not None
                            and health.policy == "skip_update"),
            extra_finite=(jnp.isfinite(loss) if health is not None else None),
            bucket_plan=bucket_plan, prefetch_ag=prefetch_ag,
            tensorstats_cfg=tstats,
        )
        metrics = {
            "loss": loss,
            "lr": jnp.asarray(lr, jnp.float32),
            "grad_norm": opt_metrics["grad_norm"],
        }
        metrics.update({k: v for k, v in aux.items() if k not in metrics})
        if tstats is not None:
            # tensorstats/... per-step scalars + tensorstats_hist/... packed
            # cumulative vectors — the loop's boundary fetch splits them by
            # prefix (floats to the scalar sinks, vectors to tensorstats.jsonl)
            metrics.update(opt_metrics.get("tensorstats", {}))
        if health is not None:
            ok = opt_metrics["updates_finite"]
            bad = jnp.logical_not(ok).astype(jnp.int32)
            prev = opt_state["health"]
            # steps_seen counts train-step INVOCATIONS (unlike opt step, which
            # freezes on a skipped update) — steps_seen - 1 is the 0-based
            # trainer step just computed, the id the forensic bundle names
            seen = prev["steps_seen"] + 1
            hstate = {
                "steps_seen": seen,
                "nonfinite_count": prev["nonfinite_count"] + bad,
                "skipped_count": prev["skipped_count"] + (
                    bad if health.policy == "skip_update"
                    else jnp.zeros((), jnp.int32)),
                "last_nonfinite_step": jnp.where(
                    bad == 1, seen - 1, prev["last_nonfinite_step"]),
            }
            new_opt_state["health"] = hstate
            metrics["health/updates_finite"] = ok.astype(jnp.float32)
            metrics["health/loss_finite"] = jnp.isfinite(loss).astype(
                jnp.float32)
            metrics["health/nonfinite_count"] = hstate["nonfinite_count"]
            metrics["health/skipped_count"] = hstate["skipped_count"]
            metrics["health/last_nonfinite_step"] = (
                hstate["last_nonfinite_step"])
            for g, n in opt_metrics.get("group_norms", {}).items():
                metrics[f"health/grad_norm/{g}"] = n
            if health.param_norm:
                # post-update param norm: the host-side monitor diffs ring
                # entries to surface drift (a slow divergence the per-step
                # grad norm alone doesn't show)
                metrics["health/param_norm"] = global_norm(new_params)
        if log_param_norm:
            # reference log_parameter_norm (base.py:397-452): TP/CP/PP-group
            # all-reduced norm — here a plain global norm (params are one
            # global pytree under GSPMD).
            metrics["param_norm"] = global_norm(new_params)
        if log_gradient_norm:
            # reference log_gradient_norm (base.py:397-452): the pre-clip
            # grad norm under the reference's metric name (grad_norm is
            # always logged; this adds the explicit parity alias)
            metrics["gradient_norm"] = opt_metrics["grad_norm"]
        return new_params, new_opt_state, metrics

    return train_step


def make_eval_step(loss_fn: LossFn) -> Callable:
    def eval_step(params, batch, step_key=None):
        # key=None signals eval mode: models with dropout (GPT) must run
        # deterministically during validation
        loss, _aux = loss_fn(params, batch, None)
        return {"val_loss": loss.astype(jnp.float32)}

    return eval_step


def jit_train_step(
    train_step: Callable,
    mesh: Mesh,
    param_specs,
    opt_specs,
    *,
    batch_spec: Optional[P] = None,
    donate: bool | str = True,
):
    """jit with explicit in/out shardings; params/opt-state donated (in-place
    buffer reuse — the memory behavior the reference gets from in-place
    ``optimizer.step``).

    ``donate``: True/"all" donates params + opt state; "params" donates the
    params tree only (the narrowed EMA workaround — see Trainer.from_config);
    False/"none" disables donation."""
    if batch_spec is None:
        batch_spec = P(DATA_AXES)
    ns = functools.partial(NamedSharding, mesh)
    p_sh = jax.tree_util.tree_map(ns, param_specs, is_leaf=lambda x: isinstance(x, P))
    o_sh = jax.tree_util.tree_map(ns, opt_specs, is_leaf=lambda x: isinstance(x, P))
    donate_argnums = {
        True: (0, 1), "all": (0, 1), "params": (0,), False: (), "none": (),
    }[donate]
    return jax.jit(
        train_step,
        in_shardings=(p_sh, o_sh, ns(batch_spec), None),
        out_shardings=(p_sh, o_sh, None),
        donate_argnums=donate_argnums,
    )
