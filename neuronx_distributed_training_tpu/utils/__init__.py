"""Utilities: dtype policies, metrics, logging, PRNG discipline."""
