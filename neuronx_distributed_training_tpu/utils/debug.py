"""Sharding / HLO-consistency assertions — the SPMD sanity tooling.

The reference's correctness tooling is sanitizer-flavored (NCCL/compiler race
detection, SURVEY.md §5.2).  Under GSPMD the failure mode is different: a bad
or missing PartitionSpec never crashes — it silently replicates a tensor or
inserts surprise all-gathers, turning a sharding bug into a perf/memory
mystery.  These helpers make that failure mode ASSERTABLE:

- ``sharding_report(tree)``: path -> actual committed sharding of every leaf;
- ``assert_tree_sharding(tree, specs, mesh)``: every leaf's device layout
  matches the intended spec (catches silent replication after a bad
  ``device_put`` or a dropped ``with_sharding_constraint``);
- ``collective_counts(jitted, *args)``: HLO collective census of a compiled
  step (all-reduce / all-gather / reduce-scatter / collective-permute /
  all-to-all) so tests pin the expected communication pattern — a TP=2 matmul
  step that suddenly reports extra all-gathers has a sharding regression.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)


def sharding_report(tree: Any) -> dict[str, str]:
    """{leaf path: sharding spec string} for every array leaf."""
    out: dict[str, str] = {}

    def visit(path, x):
        sh = getattr(x, "sharding", None)
        if sh is None:
            out[_path_str(path)] = "<not a device array>"
        elif isinstance(sh, NamedSharding):
            out[_path_str(path)] = str(sh.spec)
        else:
            out[_path_str(path)] = repr(sh)
        return x

    jax.tree_util.tree_map_with_path(visit, tree)
    return out


def assert_tree_sharding(tree: Any, specs: Any, mesh: Mesh) -> None:
    """Every leaf of ``tree`` must be laid out as ``NamedSharding(mesh, spec)``.

    Comparison is by device layout (``Sharding.is_equivalent_to``), not spec
    string equality — ``P('data')`` on a 1-wide data axis and ``P()`` are the
    same layout and both pass.
    """
    errors: list[str] = []

    def visit(path, x, spec):
        want = NamedSharding(mesh, spec if spec is not None else P())
        got = getattr(x, "sharding", None)
        if got is None:
            errors.append(f"{_path_str(path)}: not a committed device array")
        elif not got.is_equivalent_to(want, x.ndim):
            errors.append(
                f"{_path_str(path)}: sharding {got} != expected "
                f"NamedSharding(spec={spec})"
            )
        return x

    jax.tree_util.tree_map_with_path(
        visit, tree, specs,
        is_leaf=lambda t: isinstance(t, P) or t is None,
    )
    if errors:
        raise AssertionError(
            "sharding mismatch (silent replication / dropped constraint?):\n  "
            + "\n  ".join(errors[:20])
            + (f"\n  ... and {len(errors) - 20} more" if len(errors) > 20 else "")
        )


#: the collective kinds the census (and everything downstream of it — the
#: graph auditor's GA101/GA102 classes, the autotune cost model's per-axis
#: byte volumes, the device-trace overlap analytics) classifies by
COLLECTIVE_KINDS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)
_COLLECTIVES = COLLECTIVE_KINDS

#: which collective kinds each comms AXIS (tp/dp/pp/cp/ep) legitimately
#: produces, in the order calibration prefers them.  The single source all
#: three static/measured surfaces share: the autotune cost model prices each
#: axis's bytes on these kinds, the trace analytics map measured
#: per-kind overlap back onto axes, and the graph-contract provenance
#: attributes compiled collectives to declared sources.  tp/dp under
#: SP+ZeRO-1 are AG/RS-shaped (plain variants fall back to all-reduce); pp
#: hops and cp ring passes lower to collective-permutes; ulysses-cp and ep
#: dispatch are all-to-alls.
AXIS_COLLECTIVE_KINDS: dict[str, tuple[str, ...]] = {
    "tp": ("all-gather", "reduce-scatter", "all-reduce"),
    "dp": ("reduce-scatter", "all-gather", "all-reduce"),
    "pp": ("collective-permute",),
    "cp": ("collective-permute", "all-to-all"),
    "ep": ("all-to-all",),
}

#: HLO op NAMES of collectives: plain and async ``-start`` forms count (the
#: ``-start`` op carries the wire time); ``-done`` halves are the completion
#: wait, deliberately NOT a collective so nothing double-counts — the same
#: convention as the text census below
_KIND_NAME_RE = re.compile(
    r"^%?(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(-start)?(\.\d+)?$"
)


def collective_kind_of(op_name: str) -> str | None:
    """Collective kind of one HLO op *name* (``all-reduce.3``,
    ``all-gather-start.1`` -> their kind; ``-done`` halves, fusions, and
    non-collectives -> ``None``).  The name-level twin of the text census:
    trace analytics classify device-timeline ops with the same kind set the
    compile census counts, so the two surfaces always line up."""
    m = _KIND_NAME_RE.match(op_name)
    return m.group(1) if m else None


def collective_counts(jitted_fn, *args, **kwargs) -> dict[str, int]:
    """Compile ``jitted_fn(*args)`` and count HLO collectives by kind.

    Works on anything with ``.lower()`` (a ``jax.jit`` result).  ``-start``
    variants (async collectives) count once, not twice.
    """
    return collective_counts_from_compiled(
        jitted_fn.lower(*args, **kwargs).compile()
    )


def collective_counts_from_compiled(compiled) -> dict[str, int]:
    """Collective census of an ALREADY-compiled executable (`.compile()`
    result) — the zero-extra-compile path the telemetry census uses on the
    train step it is about to run."""
    from neuronx_distributed_training_tpu.telemetry.census import (
        hlo_texts_from_compiled,
    )

    return collective_counts_from_texts(hlo_texts_from_compiled(compiled))


def collective_counts_from_texts(texts: list[str]) -> dict[str, int]:
    """Census over HLO texts already in hand — callers that walk the text
    for other rules too (the graph auditor) avoid a second multi-MB
    ``to_string`` per module."""
    counts = {k: 0 for k in _COLLECTIVES}
    # HLO line shapes: `%name = f32[4,8]{1,0} all-reduce(%dot), ...` and the
    # combined/async forms `%ar = (f32[..], f32[..]) all-reduce-start(...)`;
    # `-done` halves must NOT double-count.  op_name metadata is stripped so
    # source attributions can't fake a match.
    pattern = re.compile(
        r"\s(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
        r"(-start)?\("
    )
    for text in texts:
        for line in text.splitlines():
            if "=" not in line:
                continue
            m = pattern.search(line.split("metadata=")[0])
            if m:
                counts[m.group(1)] += 1
    return counts
