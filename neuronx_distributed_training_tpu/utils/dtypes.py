"""Explicit dtype policies — the TPU-native replacement for the reference's
env-var precision matrix.

The reference implements precision regimes as process-wide env vars set at launch
(``XLA_USE_BF16`` / ``XLA_DOWNCAST_BF16`` / ``NEURON_RT_STOCHASTIC_ROUNDING_EN``,
reference ``training_orchestrator.py:104-137``) and re-read lazily all over the
code (``base.py:368``, ``modeling_llama.py:242``, ``utils/utils.py:45-50``).
Here every regime is one explicit, local ``DtypePolicy`` value threaded through
model/optimizer construction — no global flags, no surprise downcasts.

Regime mapping (reference ``precision:`` YAML block → policy):

- ``mixed_precision`` (master-weights fp32 + fp32 grad accumulation + bf16
  compute; the reference's recommended regime): params stored fp32, cast to bf16
  for compute, gradients accumulated fp32, optimizer state fp32.
- ``bf16SR`` (pure bf16 with stochastic rounding — a Trainium hardware feature):
  on TPU this maps to bf16 params/compute with fp32 optimizer state; stochastic
  rounding has no XLA equivalent and fp32 master state is strictly more accurate.
- ``autocast``: bf16 compute, fp32 params.
- ``fp32``: everything fp32.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp

_DTYPES = {
    "float32": jnp.float32,
    "fp32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "bf16": jnp.bfloat16,
    "float16": jnp.float16,
    "fp16": jnp.float16,
    "float64": jnp.float64,
}


def canonical_dtype(d: Any) -> jnp.dtype:
    if isinstance(d, str):
        try:
            return jnp.dtype(_DTYPES[d.lower()])
        except KeyError as e:
            raise ValueError(f"unknown dtype name {d!r}") from e
    return jnp.dtype(d)


@dataclasses.dataclass(frozen=True)
class DtypePolicy:
    """Which dtype each role uses.

    - ``param_dtype``: storage dtype of the trainable parameter pytree.
    - ``compute_dtype``: dtype activations/matmuls run in (params are cast to
      this inside the forward pass).
    - ``reduce_dtype``: dtype for cross-device gradient/loss reductions
      (reference exposes this as ``reduce_dtype``, ``llama_model.py:67-74``).
    - ``grad_accum_dtype``: dtype microbatch gradients are accumulated in
      (reference ``fp32_grad_acc``, ``base.py:128-132``).
    - ``optimizer_dtype``: dtype of optimizer moments / master weights
      (reference ``adamw_fp32OptState``).
    """

    param_dtype: jnp.dtype = jnp.dtype(jnp.float32)
    compute_dtype: jnp.dtype = jnp.dtype(jnp.bfloat16)
    reduce_dtype: jnp.dtype = jnp.dtype(jnp.float32)
    grad_accum_dtype: jnp.dtype = jnp.dtype(jnp.float32)
    optimizer_dtype: jnp.dtype = jnp.dtype(jnp.float32)
    # softmax / norm internals
    softmax_dtype: jnp.dtype = jnp.dtype(jnp.float32)

    @classmethod
    def from_precision_config(cls, precision_cfg: Any) -> "DtypePolicy":
        """Map the reference ``precision:`` YAML block to a policy.

        Accepts either a string regime name or a mapping with a ``type`` key
        (reference ``config_overview.rst`` precision section, projected to env
        vars at ``training_orchestrator.py:104-137``).
        """
        if precision_cfg is None:
            return cls()  # mixed_precision default
        if isinstance(precision_cfg, str):
            regime, extra = precision_cfg, {}
        else:
            cfgd = dict(precision_cfg)
            regime = cfgd.get("type", "mixed_precision")
            extra = cfgd
        regime = str(regime).lower()
        if regime in ("mixed_precision", "mixed_precisionsr", "mixed"):
            pol = cls(
                param_dtype=jnp.dtype(jnp.float32),
                compute_dtype=jnp.dtype(jnp.bfloat16),
            )
        elif regime in ("bf16sr", "bf16"):
            pol = cls(
                param_dtype=jnp.dtype(jnp.bfloat16),
                compute_dtype=jnp.dtype(jnp.bfloat16),
                grad_accum_dtype=jnp.dtype(jnp.float32),
            )
        elif regime == "autocast":
            pol = cls(
                param_dtype=jnp.dtype(jnp.float32),
                compute_dtype=jnp.dtype(jnp.bfloat16),
            )
        elif regime in ("fp32", "32", "float32"):
            pol = cls(
                param_dtype=jnp.dtype(jnp.float32),
                compute_dtype=jnp.dtype(jnp.float32),
            )
        else:
            raise ValueError(f"unknown precision regime {regime!r}")
        overrides = {}
        for k in (
            "param_dtype",
            "compute_dtype",
            "reduce_dtype",
            "grad_accum_dtype",
            "optimizer_dtype",
            "softmax_dtype",
        ):
            if k in extra:
                overrides[k] = canonical_dtype(extra[k])
        # master_weights=False means optimizer state follows the param dtype
        if extra.get("master_weights") is False and "optimizer_dtype" not in overrides:
            overrides["optimizer_dtype"] = pol.param_dtype
        return dataclasses.replace(pol, **overrides) if overrides else pol

    def cast_to_compute(self, tree):
        """Cast a pytree of params/activations to the compute dtype."""
        import jax

        def _cast(x):
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(self.compute_dtype)
            return x

        return jax.tree_util.tree_map(_cast, tree)
