"""Crash-safe file I/O helpers.

``run_summary.json`` / ``trace_summary.json`` / ``fleet_summary.json`` are
read by resume paths, report tools, and the bench artifact chain — a
SIGKILL landing mid-write (preemption, OOM-killer, the elastic drill's kill
injector) must never leave a truncated JSON document for them to choke on.
``atomic_write_json`` serializes FIRST (an unserializable value raises
before the target is touched), writes a same-directory temp file, fsyncs,
and renames into place — the POSIX whole-file-or-nothing pattern.  Remote
object stores (``gs://`` …) commit whole objects by construction, so those
paths take a single ``epath`` write instead.
"""

from __future__ import annotations

import json
import os
from typing import Any


def atomic_write_json(path: Any, obj: Any, *, indent: int = 1,
                      sort_keys: bool = True) -> None:
    """Write ``obj`` as JSON to ``path`` atomically (temp + rename).

    The serialization happens up front: a non-serializable ``obj`` raises
    ``TypeError`` with the TARGET FILE UNTOUCHED — the old contents stay
    valid, which is the whole point.
    """
    data = json.dumps(obj, indent=indent, sort_keys=sort_keys) + "\n"
    spath = str(path)
    if "://" in spath:
        from etils import epath

        p = epath.Path(spath)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(data)  # object stores commit whole objects
        return
    tmp = f"{spath}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(data)
        f.flush()
        try:
            os.fsync(f.fileno())
        except OSError:  # pragma: no cover — some filesystems refuse
            pass
    os.replace(tmp, spath)
