"""Cluster detection / multi-host rendezvous — the ``train_setup.sh`` layer.

The reference's launch script (``examples/train_setup.sh:8-67``) cases on the
cluster environment: SLURM (``SLURM_NNODES``, nodelist -> ``MASTER_ADDR``),
MPI-on-EKS (``OMPI_COMM_WORLD_RANK``), else single node — then exports the
rendezvous env for torchrun.  The TPU-native equivalent derives an explicit
``(coordinator_address, num_processes, process_id)`` triple for
``jax.distributed.initialize`` from the same environments.

Everything here is a pure function of an env mapping (tests pass fake
environments); only ``initialize_distributed`` touches jax.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
from typing import Mapping, Optional

logger = logging.getLogger("nxdt.launch")

DEFAULT_COORDINATOR_PORT = 8476  # jax.distributed's own default


@dataclasses.dataclass(frozen=True)
class ClusterSpec:
    """Explicit rendezvous triple + bookkeeping for log paths."""

    coordinator_address: str  # host:port
    num_processes: int
    process_id: int
    managed_by: str  # "nxdt-env" | "slurm" | "ompi" | "single"
    restart_count: int = 0  # SLURM_RESTART_COUNT (reference train_setup.sh:28-29)

    @property
    def is_multiprocess(self) -> bool:
        return self.num_processes > 1


def expand_first_host(nodelist: str) -> str:
    """First hostname of a SLURM nodelist, without DNS.

    Handles the compressed forms scontrol emits: ``node[3-17,20]`` ->
    ``node3`` (zero-padding preserved: ``node[003-017]`` -> ``node003``),
    ``a1,b2`` -> ``a1``.  The reference resolves this with
    ``nslookup $(scontrol show hostnames ...)`` (``train_setup.sh:60-64``);
    a pure-string parse keeps it testable and dependency-free.
    """
    nodelist = nodelist.strip()
    m = re.match(r"^([^,\[]+)\[([^\]]+)\]", nodelist)
    if m:
        prefix, ranges = m.group(1), m.group(2)
        first = ranges.split(",")[0].split("-")[0]
        return prefix + first
    return nodelist.split(",")[0]


def detect_cluster(env: Optional[Mapping[str, str]] = None) -> ClusterSpec:
    """Case on the cluster environment (reference ``train_setup.sh:8-67``).

    Priority: explicit ``NXDT_*`` triple > SLURM > Open MPI > single process.
    """
    env = os.environ if env is None else env
    restart = int(env.get("SLURM_RESTART_COUNT", "0") or 0)

    if (env.get("NXDT_COORDINATOR") and env.get("NXDT_NUM_PROCESSES")
            and env.get("NXDT_PROCESS_ID")):
        return ClusterSpec(
            coordinator_address=env["NXDT_COORDINATOR"],
            num_processes=int(env["NXDT_NUM_PROCESSES"]),
            process_id=int(env["NXDT_PROCESS_ID"]),
            managed_by="nxdt-env",
            restart_count=restart,
        )

    ntasks = int(env.get("SLURM_NTASKS", env.get("SLURM_NPROCS", "0")) or 0)
    if ntasks > 1:
        nodelist = env.get("SLURM_STEP_NODELIST", env.get("SLURM_NODELIST", ""))
        if not nodelist:
            raise RuntimeError(
                "SLURM environment without SLURM_STEP_NODELIST/SLURM_NODELIST; "
                "set NXDT_COORDINATOR explicitly"
            )
        host = expand_first_host(nodelist)
        port = env.get("NXDT_COORDINATOR_PORT", str(DEFAULT_COORDINATOR_PORT))
        return ClusterSpec(
            coordinator_address=f"{host}:{port}",
            num_processes=ntasks,
            process_id=int(env.get("SLURM_PROCID", "0") or 0),
            managed_by="slurm",
            restart_count=restart,
        )

    world = int(env.get("OMPI_COMM_WORLD_SIZE", "0") or 0)
    if world > 1:
        # mpirun does not export a coordinator host; the EKS/MPI recipe
        # (reference train_setup.sh:41-52) provides MASTER_ADDR — honor it.
        # Without one, defer to jax's own Open MPI plugin (OmpiCluster reads
        # OMPI_MCA_orte_hnp_uri): empty coordinator -> no-arg initialize.
        host = env.get("MASTER_ADDR") or env.get("NXDT_COORDINATOR")
        if host:
            port = env.get("MASTER_PORT", str(DEFAULT_COORDINATOR_PORT))
            addr = host if ":" in host else f"{host}:{port}"
        else:
            addr = ""
        return ClusterSpec(
            coordinator_address=addr,
            num_processes=world,
            process_id=int(env.get("OMPI_COMM_WORLD_RANK", "0") or 0),
            managed_by="ompi" if addr else "ompi-auto",
            restart_count=restart,
        )

    return ClusterSpec(
        coordinator_address="", num_processes=1, process_id=0,
        managed_by="single", restart_count=restart,
    )


def restart_log_dir(base_dir: str, env: Optional[Mapping[str, str]] = None) -> str:
    """Per-restart log directory (reference ``train_setup.sh:28-29`` appends
    the SLURM restart count to the log path so relaunches don't clobber)."""
    env = os.environ if env is None else env
    restart = int(env.get("SLURM_RESTART_COUNT", "0") or 0)
    if restart > 0:
        return os.path.join(base_dir, f"restart_{restart}")
    return base_dir


def initialize_distributed(spec: Optional[ClusterSpec] = None) -> ClusterSpec:
    """``jax.distributed.initialize`` from the detected (or given) spec.

    Single-process specs are a no-op; multi-process specs pass the explicit
    triple (deterministic rendezvous even where jax's own auto-detection has
    no plugin for the cluster manager).
    """
    spec = spec or detect_cluster()
    if spec.is_multiprocess:
        import jax

        if spec.coordinator_address:
            jax.distributed.initialize(
                coordinator_address=spec.coordinator_address,
                num_processes=spec.num_processes,
                process_id=spec.process_id,
            )
        else:
            # the cluster manager's own jax plugin owns the handshake
            # (e.g. OmpiCluster deriving the coordinator from the HNP URI)
            jax.distributed.initialize()
        logger.info(
            "distributed via %s: process %d/%d coordinator=%s",
            spec.managed_by, spec.process_id, spec.num_processes,
            spec.coordinator_address or "(auto)",
        )
    return spec
