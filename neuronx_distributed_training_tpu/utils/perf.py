"""Analytic FLOPs / MFU estimation — the TPU-native analogue of the reference's
``utils/llama_perf_estimate.py`` (FLOPs model at reference
``llama_perf_estimate.py:48-69``, peak-FLOPs table at ``:89-97``).

FWD FLOPs = num_layers * (attention + mlp) + embedding/logits matmuls;
BWD = 2 x FWD (same convention as the reference).  Peak FLOPs come from a
per-TPU-generation table instead of the reference's trn1/trn2 numbers.
"""

from __future__ import annotations

from typing import Any

import jax

# Peak bf16 TFLOP/s per chip by TPU generation (public figures).
# Ordered most-specific-first: device_kind strings like "TPU v5 lite" must
# match their own entry before the bare-generation fallback.
PEAK_TFLOPS_PER_CHIP = {
    "v5 lite": 197.0,  # v5e device_kind spells it out
    "v5e": 197.0,
    "lite": 197.0,
    "v5p": 459.0,
    "v6e": 918.0,  # Trillium
    "v6": 918.0,
    "v4": 275.0,
    "v5": 459.0,
    "cpu": 0.5,  # nominal; keeps MFU finite in CPU smoke runs
}


def detect_peak_tflops(device: jax.Device | None = None) -> float:
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", device.platform).lower()
    for key, tf in PEAK_TFLOPS_PER_CHIP.items():
        if key in kind:
            return tf
    if device.platform == "tpu":
        return PEAK_TFLOPS_PER_CHIP["v5p"]
    return PEAK_TFLOPS_PER_CHIP["cpu"]


def llama_flops_per_token(
    *,
    num_layers: int,
    hidden_size: int,
    intermediate_size: int,
    num_attention_heads: int,
    num_kv_heads: int | None,
    vocab_size: int,
    seq_len: int,
    head_dim: int | None = None,
    include_causal_half: bool = True,
) -> float:
    """Forward FLOPs per token of a Llama-style decoder.

    Matches the reference's accounting (``llama_perf_estimate.py:48-69``):
    per-layer attention projections + score/context matmuls + SwiGLU MLP,
    plus the lm_head matmul.  ``include_causal_half`` halves the attention
    score/context term (causal masking skips half the work — flash kernels
    exploit this; the reference's estimate does the same).
    """
    h = hidden_size
    d = head_dim or h // num_attention_heads
    nh = num_attention_heads
    nkv = num_kv_heads or nh
    s = seq_len

    qkv = 2 * h * (nh + 2 * nkv) * d  # fused qkv proj
    o = 2 * nh * d * h
    attn_scores = 2 * s * nh * d  # q@k^T per token
    attn_context = 2 * s * nh * d  # softmax@v per token
    if include_causal_half:
        attn_scores /= 2
        attn_context /= 2
    mlp = 2 * h * (3 * intermediate_size)  # gate, up, down
    per_layer = qkv + o + attn_scores + attn_context + mlp
    logits = 2 * h * vocab_size
    return num_layers * per_layer + logits


def train_step_flops_per_token(fwd_flops_per_token: float) -> float:
    """fwd + bwd, bwd = 2x fwd (reference convention)."""
    return 3.0 * fwd_flops_per_token


def mfu(
    tokens_per_sec_per_chip: float,
    flops_per_token: float,
    peak_tflops_per_chip: float,
) -> float:
    """Model FLOPs utilization in [0, 1]."""
    achieved = tokens_per_sec_per_chip * flops_per_token
    return achieved / (peak_tflops_per_chip * 1e12)


class Throughput:
    """Moving-average sequences/sec with peak tracking, mirroring the
    reference's ``Throughput`` (``utils/utils.py:52-77``, window=10).

    ``peak`` is only recorded once the window holds at least
    ``min(window, 3)`` samples: the first one or two windows average over a
    partial history and a single fast boundary there would pin a phantom
    peak no steady-state window can ever reach again.

    ``seq_len`` (when given) makes ``tokens_per_sec`` the one source of
    truth tokens-based metrics (MFU, tokens/sec/chip) derive from.
    """

    def __init__(self, batch_size: int, window: int = 10, seq_len: int = 0):
        self.batch_size = batch_size
        self.window = window
        self.seq_len = int(seq_len or 0)
        self._times: list[float] = []
        self.peak = 0.0
        self.last = 0.0
        self.total_seqs = 0

    def update(self, step_seconds: float, num_steps: int = 1) -> float:
        self._times.append(step_seconds)
        if len(self._times) > self.window:
            self._times.pop(0)
        self.total_seqs += self.batch_size * num_steps
        tput = self.batch_size * len(self._times) / sum(self._times)
        self.last = tput
        if len(self._times) >= min(self.window, 3):
            self.peak = max(self.peak, tput)
        return tput

    @property
    def tokens_per_sec(self) -> float:
        """Windowed tokens/sec (seqs/s x seq_len); 0.0 when seq_len unset."""
        return self.last * self.seq_len


def flops_for_config(model_cfg: Any, seq_len: int) -> float:
    """fwd FLOPs/token from a LlamaConfig-like object."""
    return llama_flops_per_token(
        num_layers=model_cfg.num_layers,
        hidden_size=model_cfg.hidden_size,
        intermediate_size=model_cfg.intermediate_size,
        num_attention_heads=model_cfg.num_attention_heads,
        num_kv_heads=getattr(model_cfg, "num_kv_heads", None),
        vocab_size=model_cfg.vocab_size,
        seq_len=seq_len,
        head_dim=getattr(model_cfg, "head_dim", None),
    )


#: component keys of the per-token FLOPs breakdown, in reporting order
FLOPS_COMPONENTS = ("attention", "mlp", "router", "head")


def _attention_flops_per_token(
    *, hidden_size: int, num_attention_heads: int, num_kv_heads: int | None,
    seq_len: int, head_dim: int | None = None,
    include_causal_half: bool = True,
) -> float:
    """Per-layer attention FLOPs/token: qkv + o projections + the causal
    score/context matmuls — the Llama accounting with the MLP term removed."""
    h = hidden_size
    d = head_dim or h // num_attention_heads
    nh = num_attention_heads
    nkv = num_kv_heads or nh
    qkv = 2 * h * (nh + 2 * nkv) * d
    o = 2 * nh * d * h
    attn_scores = 2 * seq_len * nh * d
    attn_context = 2 * seq_len * nh * d
    if include_causal_half:
        attn_scores /= 2
        attn_context /= 2
    return qkv + o + attn_scores + attn_context


def flops_breakdown_for_model(model_cfg: Any, seq_len: int) -> dict[str, float]:
    """Per-component fwd FLOPs/token for ANY supported family:
    ``{attention, mlp, router, head}`` (``FLOPS_COMPONENTS``).

    The autotune cost model consumes this shape directly (each component
    scales differently under tp/cp/remat); ``flops_for_model`` is exactly its
    sum, so the scalar and the breakdown cannot drift apart.  Conventions are
    the MFU ones: mixtral/GPT-MoE count only ACTIVATED expert FLOPs + the
    router matmul; GPT honors its GLU-vs-plain activation; causal masking
    halves the score/context term.
    """
    from neuronx_distributed_training_tpu.models import gpt as _gpt
    from neuronx_distributed_training_tpu.models import mixtral as _mx

    if isinstance(model_cfg, _mx.MixtralConfig):
        lc = model_cfg.llama
        attn = lc.num_layers * _attention_flops_per_token(
            hidden_size=lc.hidden_size,
            num_attention_heads=lc.num_attention_heads,
            num_kv_heads=lc.num_kv_heads,
            seq_len=seq_len,
            head_dim=getattr(lc, "head_dim", None),
        )
        n_moe = _mx.num_moe_layers(model_cfg)
        n_dense = lc.num_layers - n_moe
        swiglu = 2 * lc.hidden_size * 3 * lc.intermediate_size
        router = 2 * lc.hidden_size * model_cfg.moe.num_experts
        return {
            "attention": attn,
            "mlp": n_dense * swiglu + n_moe * model_cfg.moe.top_k * swiglu,
            "router": float(n_moe * router),
            "head": 2.0 * lc.hidden_size * lc.vocab_size,
        }
    if isinstance(model_cfg, _gpt.GPTConfig):
        attn = model_cfg.num_layers * _attention_flops_per_token(
            hidden_size=model_cfg.hidden_size,
            num_attention_heads=model_cfg.num_attention_heads,
            num_kv_heads=model_cfg.kv_heads,
            seq_len=seq_len,
            head_dim=model_cfg.head_size,
        )
        matmuls = 3 if model_cfg.is_glu else 2  # (gate,) up, down
        mlp = 2 * model_cfg.hidden_size * matmuls * model_cfg.ffn_size
        head = 2.0 * model_cfg.hidden_size * model_cfg.vocab_size
        if model_cfg.moe is not None:
            n_moe = _gpt.num_moe_layers(model_cfg)
            n_dense = model_cfg.num_layers - n_moe
            router = 2 * model_cfg.hidden_size * model_cfg.moe.num_experts
            return {
                "attention": attn,
                "mlp": n_dense * mlp + n_moe * model_cfg.moe.top_k * mlp,
                "router": float(n_moe * router),
                "head": head,
            }
        return {
            "attention": attn,
            "mlp": float(model_cfg.num_layers * mlp),
            "router": 0.0,
            "head": head,
        }
    # llama/mistral (and anything exposing the same shape attributes)
    attn = model_cfg.num_layers * _attention_flops_per_token(
        hidden_size=model_cfg.hidden_size,
        num_attention_heads=model_cfg.num_attention_heads,
        num_kv_heads=getattr(model_cfg, "num_kv_heads", None),
        seq_len=seq_len,
        head_dim=getattr(model_cfg, "head_dim", None),
    )
    mlp = 2 * model_cfg.hidden_size * 3 * model_cfg.intermediate_size
    return {
        "attention": attn,
        "mlp": float(model_cfg.num_layers * mlp),
        "router": 0.0,
        "head": 2.0 * model_cfg.hidden_size * model_cfg.vocab_size,
    }


def flops_for_model(model_cfg: Any, seq_len: int) -> float:
    """fwd FLOPs/token for ANY supported model family — the MFU dispatch.

    llama/mistral use the Llama accounting directly; mixtral swaps the dense
    MLP term for top-k routed experts + the router matmul on its MoE layers;
    megatron GPT swaps SwiGLU for its configured activation (GLU: 3 matmuls,
    plain: 2) and honors optional MoE.  Only ACTIVATED expert FLOPs count —
    MFU measures useful work per token, and an unrouted expert does none.

    The scalar IS the sum of ``flops_breakdown_for_model`` — one accounting,
    two granularities.
    """
    return float(sum(flops_breakdown_for_model(model_cfg, seq_len).values()))
