"""PRNG discipline.

The reference seeds per-pipeline-stage RNGs with ``seed + 100 * pp_rank`` and keeps
a separate forked RNG tracker for sequence-parallel dropout so seq-sharded dropout
masks stay consistent (reference ``lightning_modules/model/megatron_init.py:72-82``,
``transformer.py:2529-2532``).

JAX's splittable threefry keys make this deterministic by construction: we derive
every random stream from a single base seed with ``jax.random.fold_in`` on stable
integer tags — no global RNG state, identical results regardless of device count
or sharding layout.
"""

from __future__ import annotations

import jax

# Stable stream tags (never renumber — checkpoint/reproducibility contract).
STREAM_PARAMS = 0
STREAM_DATA = 1
STREAM_DROPOUT = 2
STREAM_ROUTER = 3


def base_key(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)


def stream_key(seed_or_key, stream: int) -> jax.Array:
    """Key for a named stream (params / data / dropout / router)."""
    key = seed_or_key
    if not isinstance(seed_or_key, jax.Array):
        key = base_key(int(seed_or_key))
    return jax.random.fold_in(key, stream)


def step_key(key: jax.Array, step) -> jax.Array:
    """Per-training-step key (e.g. dropout); fold in the global step so resume
    from a checkpoint reproduces the exact same masks."""
    return jax.random.fold_in(key, step)


def stage_key(key: jax.Array, pp_stage: int) -> jax.Array:
    """Per-pipeline-stage key — the TPU analogue of the reference's
    ``seed + 100 * pp_rank`` convention (``megatron_init.py:72-82``)."""
    return jax.random.fold_in(key, 100 * pp_stage)
