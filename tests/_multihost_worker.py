"""Worker for the 2-process multi-host smoke test (test_launch.py).

Each process runs this with NXDT_COORDINATOR/NXDT_NUM_PROCESSES/
NXDT_PROCESS_ID set (the explicit rendezvous triple detect_cluster
prioritizes) and 4 virtual CPU devices, so the pair forms one 8-device
global mesh — the same topology class as two TPU hosts on DCN (SURVEY §4
plan item (b); reference rendezvous examples/train_setup.sh:8-67).

Exercises, across REAL processes:

- phase 1: jax.distributed rendezvous via utils.launch.initialize_distributed,
  a global dp=4 x tp=2 mesh spanning both processes, per-process device_put
  slices assembled with jax.make_array_from_single_device_arrays
  (data/loader.shard_batch), and two jitted train steps whose gradient
  all-reduces ride the inter-process channel;
- phase 2: the SAME workload on a mesh laid out by ``mesh.dcn_split`` with
  each process standing in for one DCN slice — the ``data`` axis's outer
  factor IS the process boundary, so gradient all-reduce crosses the
  DCN-class link while every ``model`` (TP) group stays inside one process
  (the multi-slice recipe build_mesh applies on real multi-slice TPU;
  reference multi-node path: examples/train_setup.sh:8-67).

Prints LOSS/PARAMSUM (phase 1) and LOSS2/PARAMSUM2 (phase 2) lines the
parent compares across ranks, plus DCN_SPAN_OK asserting the data-axis
groups really straddle the processes.
"""

import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _train_two_steps(mesh, cfg, policy, seed=11):
    """Init + two jitted train steps on ``mesh``; returns (loss, param_sum)."""
    import functools

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from neuronx_distributed_training_tpu.data import SyntheticDataModule
    from neuronx_distributed_training_tpu.models import llama
    from neuronx_distributed_training_tpu.optim.adamw import (
        AdamWConfig,
        init_opt_state,
        opt_state_specs,
    )
    from neuronx_distributed_training_tpu.parallel import sharding as shd
    from neuronx_distributed_training_tpu.trainer.step import (
        jit_train_step,
        make_train_step,
    )

    with mesh, shd.use_mesh(mesh):
        pspecs = llama.param_specs(cfg)
        ns = functools.partial(NamedSharding, mesh)
        p_sh = jax.tree_util.tree_map(
            ns, pspecs, is_leaf=lambda x: isinstance(x, P))
        params = jax.jit(
            lambda k: llama.init_params(k, cfg, policy), out_shardings=p_sh
        )(jax.random.PRNGKey(0))
        opt_cfg = AdamWConfig()
        ospecs = opt_state_specs(params, pspecs, mesh, zero1=True, policy=policy)
        o_sh = jax.tree_util.tree_map(
            ns, ospecs, is_leaf=lambda x: isinstance(x, P))
        opt_state = jax.jit(
            lambda p: init_opt_state(p, policy), out_shardings=o_sh
        )(params)

        def loss_fn(p, batch, key):
            loss, aux = llama.forward(p, batch, cfg, policy)
            return loss, aux

        step_fn = make_train_step(
            loss_fn, opt_cfg, lambda s: 1e-3, policy, num_microbatches=1)
        jstep = jit_train_step(step_fn, mesh, pspecs, ospecs)

        dm = SyntheticDataModule(vocab_size=128, seq_len=32,
                                 global_batch_size=8, seed=seed)
        it = dm.sharded_batches(mesh)
        loss = None
        for i, batch in enumerate(it):
            if i >= 2:
                break
            params, opt_state, metrics = jstep(
                params, opt_state, batch, jax.random.PRNGKey(i))
            loss = float(metrics["loss"])
        psum = float(sum(jnp.sum(x.astype(jnp.float64))
                         for x in jax.tree_util.tree_leaves(params)))
    return loss, psum


def main() -> None:
    import jax.numpy as jnp  # noqa: F401  (imported for helper parity)

    from neuronx_distributed_training_tpu.utils.launch import (
        detect_cluster,
        initialize_distributed,
    )

    spec = detect_cluster()
    assert spec.managed_by == "nxdt-env", spec
    initialize_distributed(spec)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    from neuronx_distributed_training_tpu.models import llama
    from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
    from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

    cfg = llama.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128, num_layers=2,
        num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
        activations_checkpoint_granularity=None,
    )
    policy = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                         softmax_dtype=jnp.float32)

    # ---- phase 1: flat global mesh (dp=4 x tp=2) -------------------------
    mesh = build_mesh(MeshConfig(tensor_model_parallel_size=2))
    loss, psum = _train_two_steps(mesh, cfg, policy)
    print(f"LOSS {loss:.8f}")
    print(f"PARAMSUM {psum:.6f}")

    # ---- phase 2: dcn_split layout — data axis spans the processes -------
    import numpy as np
    from jax.sharding import Mesh

    from neuronx_distributed_training_tpu.parallel.mesh import AXES, dcn_split

    mesh_cfg = MeshConfig(tensor_model_parallel_size=2)
    shape = mesh_cfg.shape(8)
    dims = tuple(shape[a] for a in AXES)
    split = dcn_split(dims, num_slices=2)
    assert split is not None, f"dcn_split refused {dims}"
    dcn_shape, ici_shape = split
    # data carries the slice factor (the least-frequent collective), every
    # other axis stays intra-slice — the build_mesh multi-slice invariant
    assert dcn_shape[AXES.index("data")] == 2 and sum(dcn_shape) == len(dims) + 1
    # realize the layout with process == slice: jax.devices() orders process
    # 0's devices first, so [slice, ici_data, model] -> AXES shape puts the
    # slice factor OUTERMOST on the data axis
    devs = np.array(jax.devices()).reshape(
        2, ici_shape[AXES.index("data")], dims[AXES.index("model")]
    )
    dev_array = devs.reshape(dims)
    mesh2 = Mesh(dev_array, AXES)
    # the point of the layout: data-axis groups straddle the process
    # boundary (gradient all-reduce crosses DCN)...
    data_col = dev_array[0, :, 0, 0, 0]
    assert {d.process_index for d in data_col} == {0, 1}, data_col
    # ...while every TP (model) group stays inside ONE process
    for di in range(dims[AXES.index("data")]):
        tp_group = dev_array[0, di, 0, 0, :]
        assert len({d.process_index for d in tp_group}) == 1, tp_group
    print("DCN_SPAN_OK")

    loss2, psum2 = _train_two_steps(mesh2, cfg, policy)
    print(f"LOSS2 {loss2:.8f}")
    print(f"PARAMSUM2 {psum2:.6f}")
    print("MULTIHOST_OK", jax.process_index())


if __name__ == "__main__":
    main()
