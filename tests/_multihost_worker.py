"""Worker for the 2-process multi-host smoke test (test_launch.py).

Each process runs this with NXDT_COORDINATOR/NXDT_NUM_PROCESSES/
NXDT_PROCESS_ID set (the explicit rendezvous triple detect_cluster
prioritizes) and 4 virtual CPU devices, so the pair forms one 8-device
global mesh — the same topology class as two TPU hosts on DCN (SURVEY §4
plan item (b); reference rendezvous examples/train_setup.sh:8-67).

Exercises, across REAL processes: jax.distributed rendezvous via
utils.launch.initialize_distributed, a global mesh spanning both processes,
per-process device_put slices assembled with
jax.make_array_from_single_device_arrays (data/loader.shard_batch), and two
jitted train steps whose gradient all-reduces ride the inter-process
channel.  Prints LOSS/PARAMSUM lines the parent compares across ranks.
"""

import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax.numpy as jnp

    from neuronx_distributed_training_tpu.utils.launch import (
        detect_cluster,
        initialize_distributed,
    )

    spec = detect_cluster()
    assert spec.managed_by == "nxdt-env", spec
    initialize_distributed(spec)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    from neuronx_distributed_training_tpu.data import SyntheticDataModule
    from neuronx_distributed_training_tpu.models import llama
    from neuronx_distributed_training_tpu.optim.adamw import (
        AdamWConfig,
        init_opt_state,
        opt_state_specs,
    )
    from neuronx_distributed_training_tpu.parallel import sharding as shd
    from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
    from neuronx_distributed_training_tpu.trainer.step import (
        jit_train_step,
        make_train_step,
    )
    from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

    cfg = llama.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128, num_layers=2,
        num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
        activations_checkpoint_granularity=None,
    )
    policy = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                         softmax_dtype=jnp.float32)
    mesh = build_mesh(MeshConfig(tensor_model_parallel_size=2))  # dp=4 x tp=2

    with mesh, shd.use_mesh(mesh):
        pspecs = llama.param_specs(cfg)
        import functools

        from jax.sharding import NamedSharding

        ns = functools.partial(NamedSharding, mesh)
        from jax.sharding import PartitionSpec as P

        p_sh = jax.tree_util.tree_map(
            ns, pspecs, is_leaf=lambda x: isinstance(x, P))
        params = jax.jit(
            lambda k: llama.init_params(k, cfg, policy), out_shardings=p_sh
        )(jax.random.PRNGKey(0))
        opt_cfg = AdamWConfig()
        ospecs = opt_state_specs(params, pspecs, mesh, zero1=True, policy=policy)
        o_sh = jax.tree_util.tree_map(
            ns, ospecs, is_leaf=lambda x: isinstance(x, P))
        opt_state = jax.jit(
            lambda p: init_opt_state(p, policy), out_shardings=o_sh
        )(params)

        def loss_fn(p, batch, key):
            loss, aux = llama.forward(p, batch, cfg, policy)
            return loss, aux

        step_fn = make_train_step(
            loss_fn, opt_cfg, lambda s: 1e-3, policy, num_microbatches=1)
        jstep = jit_train_step(step_fn, mesh, pspecs, ospecs)

        dm = SyntheticDataModule(vocab_size=128, seq_len=32,
                                 global_batch_size=8, seed=11)
        it = dm.sharded_batches(mesh)
        loss = None
        for i, batch in enumerate(it):
            if i >= 2:
                break
            params, opt_state, metrics = jstep(
                params, opt_state, batch, jax.random.PRNGKey(i))
            loss = float(metrics["loss"])
        psum = float(sum(jnp.sum(x.astype(jnp.float64))
                         for x in jax.tree_util.tree_leaves(params)))
    print(f"LOSS {loss:.8f}")
    print(f"PARAMSUM {psum:.6f}")
    print("MULTIHOST_OK", jax.process_index())


if __name__ == "__main__":
    main()
