"""Test harness: force an 8-device virtual CPU platform BEFORE jax initializes.

This is the TPU ecosystem's "fake backend" (SURVEY.md §4): all TP/PP/CP/EP mesh
logic runs on 8 virtual CPU devices, so the full parallel stack is exercised
without hardware."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The image's sitecustomize imports jax at interpreter start (axon TPU plugin),
# so JAX_PLATFORMS from the env above may be too late — force it post-import.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


def has_orbax_preservation() -> bool:
    """True when this image's orbax ships ``checkpoint_managers.
    preservation_policy`` — the retention API ``Checkpointer.__init__``
    builds on (``checkpoint/manager.py``).  Older orbax releases lack the
    module (and their ``CheckpointManagerOptions`` rejects the
    ``preservation_policy`` kwarg), so EVERY Checkpointer construction fails
    there; tests that construct one carry ``requires_orbax_preservation``."""
    try:
        import orbax.checkpoint.checkpoint_managers.preservation_policy  # noqa: F401
    except Exception:  # noqa: BLE001 — missing module OR import-time error
        return False
    return True


#: precise environment guard: skip (not fail) Checkpointer-constructing tests
#: on images whose orbax predates the preservation-policy retention API
requires_orbax_preservation = pytest.mark.skipif(
    not has_orbax_preservation(),
    reason="orbax-checkpoint too old: no checkpoint_managers."
           "preservation_policy (Checkpointer retention API)",
)


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def cpu_mesh(devices8):
    """Default 8-device mesh: dp=4 x tp=2."""
    from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh

    return build_mesh(MeshConfig(tensor_model_parallel_size=2), devices=devices8)


def lower_in_mesh(mesh, fn, *args):
    """Lower + compile ``fn(*args)`` INSIDE ``mesh``'s context — the shared
    guard for every test that inspects a compiled train/loss graph.

    Lowering outside ``with mesh, shd.use_mesh(mesh)`` silently drops every
    ``shd.constrain`` in the traced program (constrain no-ops without an
    active mesh), so a FLOPs/memory gate would pin a graph WITHOUT the
    sharding constraints it claims to measure (round-4 advisor finding on
    tests/test_pp_flops_parity.py).  The assert makes that mistake loud."""
    import jax as _jax

    from neuronx_distributed_training_tpu.parallel import sharding as shd

    with mesh, shd.use_mesh(mesh):
        assert shd.active_mesh() is mesh, (
            "lower_in_mesh: no active mesh at lower time — shd.constrain "
            "would silently no-op in the compiled graph"
        )
        lowered = (fn.lower(*args) if hasattr(fn, "lower")
                   else _jax.jit(fn).lower(*args))
        return lowered.compile()


def ragged_right_pad_mask(b, s, valid_lens):
    """[b, s] int32 attention_mask with row i real for its first valid_lens[i]
    positions (the HF right-padding convention) — shared by the masked
    flash/ring/ulysses parity tests."""
    import numpy as np
    import jax.numpy as jnp

    m = np.zeros((b, s), dtype=np.int32)
    for i, n in enumerate(valid_lens):
        m[i, :n] = 1
    return jnp.asarray(m)
