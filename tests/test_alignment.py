"""LoRA (inject/freeze/merge), DPO (losses + reference pass), ORPO."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import requires_orbax_preservation

from neuronx_distributed_training_tpu.alignment import (
    compute_reference_logprobs,
    dpo_loss,
    orpo_loss,
    sequence_logprobs,
)
from neuronx_distributed_training_tpu.alignment.dpo import make_dpo_loss_fn
from neuronx_distributed_training_tpu.models import llama
from neuronx_distributed_training_tpu.optim.adamw import AdamWConfig, adamw_update, init_opt_state
from neuronx_distributed_training_tpu.peft import (
    LoraConfig,
    add_lora,
    lora_param_specs,
    merge_lora,
    trainable_mask,
)
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

FP32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                   softmax_dtype=jnp.float32)
TINY = llama.LlamaConfig(
    vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
    num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
    activations_checkpoint_granularity=None,
)


class TestLora:
    def test_inject_preserves_forward(self):
        """Zero-init B => LoRA model == base model at t=0."""
        params = llama.init_params(jax.random.PRNGKey(0), TINY, FP32)
        batch = {"input_ids": jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)}
        base_logits, _ = llama.forward(params, batch, TINY, FP32)
        lparams = add_lora(params, LoraConfig(rank=4), jax.random.PRNGKey(2))
        lora_logits, _ = llama.forward(lparams, batch, TINY, FP32)
        np.testing.assert_allclose(np.asarray(base_logits), np.asarray(lora_logits),
                                   atol=1e-6)
        # adapters exist on targeted modules, stacked over layers
        assert lparams["layers"]["attn"]["qkv"]["lora_a"].shape == (2, 32, 4)

    def test_trainable_mask_freezes_base(self):
        params = llama.init_params(jax.random.PRNGKey(0), TINY, FP32)
        lparams = add_lora(params, LoraConfig(rank=4), jax.random.PRNGKey(2))
        mask = trainable_mask(lparams)
        assert mask["layers"]["attn"]["qkv"]["lora_a"] == 1.0
        assert mask["layers"]["attn"]["qkv"]["w"] == 0.0
        assert mask["embed"]["embedding"] == 0.0

    @pytest.mark.slow
    def test_frozen_params_do_not_move(self):
        params = llama.init_params(jax.random.PRNGKey(0), TINY, FP32)
        lparams = add_lora(params, LoraConfig(rank=4), jax.random.PRNGKey(2))
        batch = {"input_ids": jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)}
        batch["labels"] = batch["input_ids"]

        def loss_fn(p):
            return llama.forward(p, batch, TINY, FP32)[0]

        grads = jax.grad(loss_fn)(lparams)
        opt = init_opt_state(lparams, FP32)
        mask = trainable_mask(lparams)
        new_params, _, _ = adamw_update(
            lparams, grads, opt, 1e-2, AdamWConfig(), FP32, trainable_mask=mask
        )
        np.testing.assert_array_equal(
            np.asarray(new_params["layers"]["attn"]["qkv"]["w"]),
            np.asarray(lparams["layers"]["attn"]["qkv"]["w"]),
        )
        # adapters DO move
        assert not np.allclose(
            np.asarray(new_params["layers"]["attn"]["qkv"]["lora_b"]),
            np.asarray(lparams["layers"]["attn"]["qkv"]["lora_b"]),
        )

    def test_merge_matches_adapter_forward(self):
        params = llama.init_params(jax.random.PRNGKey(0), TINY, FP32)
        lparams = add_lora(params, LoraConfig(rank=4, alpha=8), jax.random.PRNGKey(2))
        # give B nonzero values so the adapter actually does something
        lparams["layers"]["attn"]["qkv"]["lora_b"] = (
            0.01 * jax.random.normal(jax.random.PRNGKey(3),
                                     lparams["layers"]["attn"]["qkv"]["lora_b"].shape)
        )
        batch = {"input_ids": jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)}
        adapter_logits, _ = llama.forward(lparams, batch, TINY, FP32)
        merged = merge_lora(lparams)
        merged_logits, _ = llama.forward(merged, batch, TINY, FP32)
        np.testing.assert_allclose(np.asarray(adapter_logits),
                                   np.asarray(merged_logits), atol=1e-5)
        assert "lora_a" not in merged["layers"]["attn"]["qkv"]

    def test_lora_specs_follow_base_layout(self):
        specs = llama.param_specs(TINY)
        lspecs = lora_param_specs(specs, LoraConfig(rank=4))
        qkv = lspecs["layers"]["attn"]["qkv"]
        assert qkv["lora_a"] == P(None, None, None)
        assert qkv["lora_b"] == P(None, None, "model")  # column layout preserved
        o = lspecs["layers"]["attn"]["o"]
        assert o["lora_a"] == P(None, "model", None)  # row layout preserved
        assert o["lora_b"] == P(None, None, None)


class TestDPO:
    def test_sequence_logprobs_masking(self):
        logits = jnp.zeros((1, 4, 8))  # uniform -> log p = -log 8 per token
        labels = jnp.array([[1, 2, 3, 4]])
        mask = jnp.array([[0, 0, 1, 1]])
        lp = sequence_logprobs(logits, labels, mask)
        # shift drops position 0; mask keeps labels at shifted positions 1,2
        np.testing.assert_allclose(float(lp[0]), -2 * np.log(8), rtol=1e-5)

    def test_dpo_loss_prefers_chosen(self):
        b = jnp.array([0.0, 0.0])
        loss_good, m_good = dpo_loss(b + 2.0, b - 2.0, b, b, beta=0.5)
        loss_bad, m_bad = dpo_loss(b - 2.0, b + 2.0, b, b, beta=0.5)
        assert float(loss_good) < float(loss_bad)
        assert float(m_good["reward_accuracy"]) == 1.0
        assert float(m_bad["reward_accuracy"]) == 0.0

    def test_reference_pass_and_loss_fn(self):
        params = llama.init_params(jax.random.PRNGKey(0), TINY, FP32)

        def fwd(p, batch):
            logits, _ = llama.forward(p, batch, TINY, FP32)
            return logits

        key = jax.random.PRNGKey(1)
        mk = lambda k: jax.random.randint(k, (2, 16), 0, 64)
        batches = [
            {
                "chosen_input_ids": mk(jax.random.fold_in(key, i)),
                "rejected_input_ids": mk(jax.random.fold_in(key, 100 + i)),
            }
            for i in range(2)
        ]
        cols = compute_reference_logprobs(params, batches, fwd)
        assert cols["reference_chosen_logps"].shape == (4,)
        assert np.all(np.isfinite(cols["reference_chosen_logps"]))

        # policy == reference at t=0 -> logits term 0 -> loss = -logsigmoid(0)
        batch = dict(batches[0])
        batch["reference_chosen_logps"] = jnp.asarray(cols["reference_chosen_logps"][:2])
        batch["reference_rejected_logps"] = jnp.asarray(cols["reference_rejected_logps"][:2])
        loss_fn = make_dpo_loss_fn(fwd, beta=0.1)
        loss, metrics = loss_fn(params, batch, None)
        np.testing.assert_allclose(float(loss), -np.log(0.5), rtol=1e-4)
        assert float(metrics["reward_margin"]) == pytest.approx(0.0, abs=1e-5)


class TestORPO:
    def test_orpo_prefers_chosen(self):
        chosen = jnp.array([-0.5, -0.5])
        rejected = jnp.array([-3.0, -3.0])
        nll = jnp.asarray(0.5)
        loss_good, m = orpo_loss(chosen, rejected, nll, beta=0.5)
        loss_bad, _ = orpo_loss(rejected, chosen, nll, beta=0.5)
        assert float(loss_good) < float(loss_bad)
        assert float(m["orpo_log_odds"]) > 0


class TestKTO:
    """KTO (unpaired preference, arXiv:2402.01306) — an extension beyond the
    reference's DPO/ORPO pair-only surface."""

    def test_kto_prefers_desirable(self):
        from neuronx_distributed_training_tpu.alignment.losses import kto_loss

        ref = jnp.zeros((4,))
        labels = jnp.asarray([1.0, 1.0, 0.0, 0.0])
        # policy already agrees with the labels -> lower loss
        good = jnp.asarray([2.0, 2.0, -2.0, -2.0])
        bad = jnp.asarray([-2.0, -2.0, 2.0, 2.0])
        l_good, m = kto_loss(good, ref, labels, beta=0.5)
        l_bad, _ = kto_loss(bad, ref, labels, beta=0.5)
        assert float(l_good) < float(l_bad)
        assert float(m["rewards_desirable"]) > float(m["rewards_undesirable"])

    def test_kto_gradient_directions(self):
        from neuronx_distributed_training_tpu.alignment.losses import kto_loss

        ref = jnp.zeros((2,))
        labels = jnp.asarray([1.0, 0.0])

        def loss(p):
            return kto_loss(p, ref, labels, beta=0.5)[0]

        g = jax.grad(loss)(jnp.zeros((2,)))
        assert float(g[0]) < 0  # desirable logp pushed UP
        assert float(g[1]) > 0  # undesirable logp pushed DOWN

    def test_class_weights(self):
        from neuronx_distributed_training_tpu.alignment.losses import kto_loss

        ref = jnp.zeros((2,))
        labels = jnp.asarray([1.0, 0.0])
        p = jnp.asarray([-1.0, 1.0])  # both wrong
        l1, _ = kto_loss(p, ref, labels, beta=0.5, undesirable_weight=1.0)
        l2, _ = kto_loss(p, ref, labels, beta=0.5, undesirable_weight=2.0)
        assert float(l2) > float(l1)


class TestKTOMismatchedKL:
    """kl_estimator: mismatched — the paper's off-policy z0 baseline from
    (prompt_i, completion_{i+1}) pairs (arXiv:2402.01306 / TRL semantics)."""

    class CharTok:
        eos_token_id = 1
        def encode(self, s):
            return [3 + (ord(c) % 60) for c in s]

    def _records(self, n=8):
        return [{"prompt": f"pr{i}", "completion": f"answer {i}",
                 "label": i % 2 == 0} for i in range(n)]

    @staticmethod
    def _paired_indices(a):
        """Recover which record each kl row borrowed its completion from by
        matching completion tokens (pairing is a seeded derangement now, not
        a fixed shift)."""
        n = a["input_ids"].shape[0]
        comps = [tuple(a["input_ids"][j][a["loss_mask"][j] > 0])
                 for j in range(n)]
        pairs = []
        for i in range(n):
            kl_comp = tuple(a["kl_input_ids"][i][a["kl_loss_mask"][i] > 0])
            pairs.append(comps.index(kl_comp))
        return pairs

    def test_kl_columns_are_spliced_pairs(self):
        from neuronx_distributed_training_tpu.data.modules import KTODataModule

        dm = KTODataModule(self._records(), self.CharTok(), seq_length=32,
                           global_batch_size=4, kl_estimator="mismatched")
        a = dm.arrays
        assert "kl_input_ids" in a and "kl_loss_mask" in a
        n, s = a["input_ids"].shape
        pairs = self._paired_indices(a)
        for i, j in enumerate(pairs):
            # kl row i = prompt of i (masked) + completion of some j!=i
            assert j != i, "mismatched pairing must be a derangement"
            prompt_len_i = int(np.argmax(a["loss_mask"][i] > 0))
            comp_j = a["input_ids"][j][a["loss_mask"][j] > 0]
            kl_comp = a["kl_input_ids"][i][a["kl_loss_mask"][i] > 0]
            np.testing.assert_array_equal(kl_comp, comp_j)
            np.testing.assert_array_equal(
                a["kl_input_ids"][i][:prompt_len_i],
                a["input_ids"][i][:prompt_len_i],
            )
        # every completion is used exactly once (cyclic derangement)
        assert sorted(pairs) == list(range(n))

    def test_pairing_is_seeded_and_deterministic(self):
        from neuronx_distributed_training_tpu.data.modules import KTODataModule

        mk = lambda seed: KTODataModule(
            self._records(16), self.CharTok(), seq_length=32,
            global_batch_size=4, kl_estimator="mismatched", seed=seed)
        a1, a2 = mk(7).arrays, mk(7).arrays
        np.testing.assert_array_equal(a1["kl_input_ids"], a2["kl_input_ids"])
        a3 = mk(8).arrays
        assert not np.array_equal(a1["kl_input_ids"], a3["kl_input_ids"])

    def test_repeated_prompts_never_pair_matched(self):
        """Several completions per prompt listed consecutively (the common
        KTO file layout) must not yield an effectively matched KL pair."""
        from neuronx_distributed_training_tpu.data.modules import KTODataModule

        recs = []
        for p in range(4):
            for c in range(4):  # 4 consecutive completions per prompt
                recs.append({"prompt": f"prompt {p}",
                             "completion": f"ans {p}-{c}", "label": c % 2})
        dm = KTODataModule(recs, self.CharTok(), seq_length=48,
                           global_batch_size=4, kl_estimator="mismatched")
        a = dm.arrays
        enc = self.CharTok().encode
        prompt_of = [tuple(enc(r["prompt"])) for r in recs]
        pairs = self._paired_indices(a)
        for i, j in enumerate(pairs):
            assert prompt_of[j] != prompt_of[i], (
                f"kl row {i} paired with token-identical prompt {j}")
        # largest group (4) fits in half the dataset (16) -> a bijection:
        # every completion weighs into the z0 baseline exactly once
        assert sorted(pairs) == list(range(len(recs)))

    def test_majority_prompt_falls_back_non_injective(self):
        """One prompt owning > n/2 records: no bijection avoiding matched
        pairs exists (Hall) — the pairing warns and stays matched-pair-free."""
        from neuronx_distributed_training_tpu.data.modules import KTODataModule

        recs = [{"prompt": "big", "completion": f"b{i}", "label": True}
                for i in range(6)]
        recs += [{"prompt": "other", "completion": f"o{i}", "label": False}
                 for i in range(2)]
        with pytest.warns(UserWarning, match="no one-to-one"):
            dm = KTODataModule(recs, self.CharTok(), seq_length=32,
                               global_batch_size=4, kl_estimator="mismatched")
        enc = self.CharTok().encode
        prompt_of = [tuple(enc(r["prompt"])) for r in recs]
        for i, j in enumerate(self._paired_indices(dm.arrays)):
            assert prompt_of[j] != prompt_of[i]

    def test_all_identical_prompts_warns(self):
        from neuronx_distributed_training_tpu.data.modules import KTODataModule

        recs = [{"prompt": "same", "completion": f"c{i}", "label": True}
                for i in range(4)]
        with pytest.warns(UserWarning, match="shares one prompt"):
            KTODataModule(recs, self.CharTok(), seq_length=32,
                          global_batch_size=2, kl_estimator="mismatched")

    def test_grouping_keys_on_raw_prompt_not_truncated_prefix(self):
        """Overlong rows trim the prompt by their own completion's length, so
        two records sharing a prompt can carry different row prefixes — the
        pairing must still see ONE prompt group (here: the all-identical
        degenerate warning), not distinct groups it could pair together."""
        from neuronx_distributed_training_tpu.data.modules import KTODataModule

        recs = [
            {"prompt": "p" * 60, "completion": "c" * 4, "label": True},
            {"prompt": "p" * 60, "completion": "d" * 12, "label": False},
        ]
        with pytest.warns(UserWarning, match="shares one prompt"):
            KTODataModule(recs, self.CharTok(), seq_length=24,
                          global_batch_size=2, kl_estimator="mismatched")

    def test_kl_rewards_change_z0(self):
        from neuronx_distributed_training_tpu.alignment.losses import kto_loss

        ref = jnp.zeros((4,))
        labels = jnp.asarray([1.0, 1.0, 0.0, 0.0])
        policy = jnp.asarray([2.0, 2.0, -2.0, -2.0])
        _, m_batch = kto_loss(policy, ref, labels, beta=0.5)
        kl = jnp.asarray([0.3, 0.3, 0.3, 0.3])
        _, m_mis = kto_loss(policy, ref, labels, beta=0.5, kl_rewards=kl)
        assert abs(float(m_mis["kto_kl"]) - 0.3) < 1e-6
        assert float(m_batch["kto_kl"]) != float(m_mis["kto_kl"])

    def test_trainer_end_to_end_mismatched(self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.config.loader import load_config
        from neuronx_distributed_training_tpu.data.modules import KTODataModule
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = load_config({
            "name": "ktomis", "model_source": "hf", "seed": 5,
            "trainer": {"max_steps": 2, "log_every_n_steps": 1},
            "exp_manager": {"exp_dir": str(tmp_path / "exp")},
            "model_alignment_strategy": {"kto": {"kl_beta": 0.2,
                                                 "kl_estimator": "mismatched"}},
            "distributed_strategy": {"tensor_model_parallel_size": 2},
            "data": {"global_batch_size": 8, "micro_batch_size": 1,
                     "seq_length": 32, "synthetic": True},
            "model": {
                "vocab_size": 128, "hidden_size": 64, "intermediate_size": 128,
                "num_layers": 2, "num_attention_heads": 4,
                "num_key_value_heads": 2, "max_position_embeddings": 32,
                "optim": {"lr": 1e-3,
                          "sched": {"name": "constant"}},
            },
            "precision": {"type": "mixed_precision"},
        })
        dm = KTODataModule(self._records(16), self.CharTok(), seq_length=32,
                           global_batch_size=8, kl_estimator="mismatched")
        t = Trainer.from_config(cfg, data_module=dm, enable_checkpointing=False)
        t.pre_fit(t)
        assert "reference_kl_logps" in dm.arrays  # pre-fit covered KL pairs
        m = t.fit()
        assert np.isfinite(m["loss"])
        assert "kto_kl" in m

    def test_mismatched_under_pp_rejected(self):
        from neuronx_distributed_training_tpu.config.loader import load_config

        with pytest.raises(ValueError, match="mismatched"):
            load_config({
                "model_alignment_strategy": {
                    "kto": {"kl_estimator": "mismatched"}},
                "distributed_strategy": {"pipeline_model_parallel_size": 2},
                "model": {"num_layers": 2},
                "data": {"global_batch_size": 4, "micro_batch_size": 1},
            })

    def test_single_record_mismatched_rejected(self):
        from neuronx_distributed_training_tpu.data.modules import KTODataModule

        with pytest.raises(ValueError, match="at least 2"):
            KTODataModule(self._records(1), self.CharTok(), seq_length=32,
                          global_batch_size=1, kl_estimator="mismatched")

    def test_overlong_splice_keeps_completion(self):
        from neuronx_distributed_training_tpu.data.modules import KTODataModule

        recs = [{"prompt": "p" * 60, "completion": f"c{i}" * 8,
                 "label": True} for i in range(4)]
        with pytest.warns(UserWarning, match="shares one prompt"):
            dm = KTODataModule(recs, self.CharTok(), seq_length=24,
                               global_batch_size=2, kl_estimator="mismatched")
        a = dm.arrays
        for i, j in enumerate(self._paired_indices(a)):
            comp_j = a["input_ids"][j][a["loss_mask"][j] > 0]
            kl_comp = a["kl_input_ids"][i][a["kl_loss_mask"][i] > 0]
            # the completion survives truncation intact (prompt is trimmed)
            np.testing.assert_array_equal(kl_comp, comp_j)
            assert kl_comp.size > 0

    @requires_orbax_preservation  # the sidecar lives next to the checkpoints,
    # so this path constructs a real Checkpointer (enable_checkpointing
    # defaults True)
    def test_stale_sidecar_column_set_recomputes(self, tmp_path, devices8):
        """A batch_mean sidecar resumed under mismatched must recompute, not
        KeyError in the jitted step."""
        from neuronx_distributed_training_tpu.config.loader import load_config
        from neuronx_distributed_training_tpu.data.modules import KTODataModule
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        def cfg_for(est):
            return load_config({
                "name": "ktostale", "model_source": "hf", "seed": 5,
                "trainer": {"max_steps": 1, "log_every_n_steps": 1},
                "exp_manager": {"exp_dir": str(tmp_path / "exp")},
                "model_alignment_strategy": {"kto": {"kl_beta": 0.2,
                                                     "kl_estimator": est}},
                "distributed_strategy": {"tensor_model_parallel_size": 2},
                "data": {"global_batch_size": 8, "micro_batch_size": 1,
                         "seq_length": 32, "synthetic": True},
                "model": {
                    "vocab_size": 128, "hidden_size": 64,
                    "intermediate_size": 128, "num_layers": 2,
                    "num_attention_heads": 4, "num_key_value_heads": 2,
                    "max_position_embeddings": 32,
                    "optim": {"lr": 1e-3, "sched": {"name": "constant"}},
                },
                "precision": {"type": "mixed_precision"},
            })

        dm1 = KTODataModule(self._records(8), self.CharTok(), seq_length=32,
                            global_batch_size=8)
        t1 = Trainer.from_config(cfg_for("batch_mean"), data_module=dm1)
        t1.pre_fit(t1)  # writes the batch_mean sidecar (reference_logps only)

        dm2 = KTODataModule(self._records(8), self.CharTok(), seq_length=32,
                            global_batch_size=8, kl_estimator="mismatched")
        t2 = Trainer.from_config(cfg_for("mismatched"), data_module=dm2)
        t2.pre_fit(t2)
        assert "reference_kl_logps" in dm2.arrays
