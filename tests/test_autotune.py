"""Autotune planner tests: lattice legality, cost-model calibration, golden
plans, and the planner end-to-end.

The memory-calibration class is the satellite contract: the analytic
per-device HBM estimate must stay within +-15% of compiled
``memory_analysis()`` bytes (arguments + temps) on tiny configs across
dp/tp/pp/ep meshes, so the planner's OOM pruning cannot silently drift from
XLA reality.  Known exclusions (documented in docs/autotuning.md "blind
spots"): mixtral under tp>1 (strided-mesh ragged-dot workspace) and extreme
vocab/width ratios outside the tiny-config envelope.
"""

import jax
import pytest

from neuronx_distributed_training_tpu.autotune import (
    ModelFacts,
    Plan,
    enumerate_plans,
    estimate_plan,
    kendall_tau,
    plan_config,
    resolve_topology,
)
from neuronx_distributed_training_tpu.autotune.cost_model import hbm_breakdown
from neuronx_distributed_training_tpu.autotune.space import REMAT_POLICIES
from neuronx_distributed_training_tpu.config.loader import load_config

EX = "examples/conf"


def tiny_raw(tp=1, pp=1, ep=1, remat="selective", gbs=8, mbs=1, seq=128,
             layers=4, h=64, ffn=176, vocab=512, heads=8, kv=4, arch="llama",
             sched=None, alignment=None, lora=False, fusions=None):
    m = {"architecture": arch, "vocab_size": vocab, "hidden_size": h,
         "intermediate_size": ffn, "num_layers": layers,
         "num_attention_heads": heads, "num_key_value_heads": kv,
         "max_position_embeddings": seq,
         "activations_checkpoint_granularity":
             None if remat == "none" else remat}
    if arch == "mixtral":
        m["moe"] = {"num_experts": 4, "top_k": 2, "dropless": True}
    if fusions:
        m["fusions"] = fusions
    if lora:
        m["lora"] = {"r": 4, "alpha": 8}
    ds = {"tensor_model_parallel_size": tp,
          "pipeline_model_parallel_size": pp,
          "expert_model_parallel_size": ep,
          "sequence_parallel": tp > 1, "zero1": True}
    if sched:
        ds["pipeline"] = {"schedule": sched}
    cfg = {"name": "tiny", "model_source": "hf", "seed": 0,
           "trainer": {"max_steps": 1},
           "distributed_strategy": ds,
           "data": {"seq_length": seq, "global_batch_size": gbs,
                    "micro_batch_size": mbs, "synthetic": True},
           "model": m, "precision": {"type": "mixed_precision"}}
    if alignment:
        cfg["model_alignment_strategy"] = alignment
    return cfg


# ---------------------------------------------------------------------------
# search space: legality properties
# ---------------------------------------------------------------------------


class TestSpaceLegality:
    @pytest.mark.parametrize("config,chips", [
        (f"{EX}/hf_llama3_8B_config.yaml", 256),
        (f"{EX}/hf_mixtral_8x7b_config.yaml", 32),
        (f"{EX}/megatron_gpt_config.yaml", 8),
        (f"{EX}/tiny_smoke_config.yaml", 8),
    ])
    def test_every_plan_is_legal(self, config, chips):
        facts = ModelFacts.from_config(load_config(config))
        plans = enumerate_plans(facts, chips)
        assert plans, f"{config} has no legal plan at {chips} chips"
        for p in plans:
            # world factorization is exact
            assert p.dp * p.tp * p.pp * p.cp == chips
            # heads shard cleanly; kv heads shard OR replicate (GQA)
            assert facts.num_heads % p.tp == 0
            assert (facts.num_kv_heads % p.tp == 0
                    or p.tp % facts.num_kv_heads == 0)
            # whole layer (or MoE+dense group) slices per stage
            if facts.moe_frequency > 1:
                assert facts.moe_groups % p.pp == 0
            else:
                assert facts.num_layers % p.pp == 0
            # experts shard over ep, ep carves dp (mesh.py contract)
            if facts.num_experts:
                assert facts.num_experts % p.ep == 0
            else:
                assert p.ep == 1
            assert p.dp % p.ep == 0
            # batch math: gbs = mbs * dp * nm exactly
            assert (facts.global_batch_size
                    == p.micro_batch_size * p.dp * p.num_microbatches)
            # cp requires a context-parallel fusion + seq divisibility
            if p.cp > 1:
                assert facts.cp_fusion is not None
                assert facts.seq % p.cp == 0
            assert p.remat in REMAT_POLICIES
            assert p.schedule == "none" if p.pp == 1 else p.schedule in (
                "1f1b", "1f1b-interleaved", "1f1b-zb", "wavefront")
            # the interleave carries the vp lattice dimension; everything
            # else runs vp == 1 (same invariants the runtime raises on)
            if p.schedule == "1f1b-interleaved":
                assert p.vp > 1
                assert p.num_microbatches >= p.pp
                if facts.moe_frequency > 1:
                    assert facts.moe_groups % (p.pp * p.vp) == 0
                else:
                    assert facts.num_layers % (p.pp * p.vp) == 0
            else:
                assert p.vp == 1

    def test_no_duplicates_and_deterministic_order(self):
        facts = ModelFacts.from_config(
            load_config(f"{EX}/hf_llama3_8B_config.yaml"))
        a = enumerate_plans(facts, 64)
        b = enumerate_plans(facts, 64)
        assert a == b, "enumeration must be deterministic"
        assert len(a) == len(set(a)), "plans must be unique"
        assert a == sorted(a, key=Plan.key), "plans must come sorted"

    def test_cp_requires_fusion(self):
        # no cp fusion configured -> no cp>1 plans, ever
        facts = ModelFacts.from_config(load_config(tiny_raw()))
        assert all(p.cp == 1 for p in enumerate_plans(facts, 8))
        # ring fusion -> cp plans appear
        facts_cp = ModelFacts.from_config(
            load_config(tiny_raw(fusions={"ring_attention": True})))
        assert any(p.cp > 1 for p in enumerate_plans(facts_cp, 8))

    def test_pp_collapses_remat(self):
        """The pipeline path ignores the remat policy (the stage loop's own
        buffering dominates — cost_model), so pp>1 plans carry exactly one
        remat value instead of three cost-identical clones."""
        facts = ModelFacts.from_config(load_config(tiny_raw()))
        plans = enumerate_plans(facts, 8)
        assert {p.remat for p in plans if p.pp > 1} == {"selective"}
        assert {p.remat for p in plans if p.pp == 1} == set(REMAT_POLICIES)


class TestScheduleGate:
    """supports_1f1b is the one source of truth the lattice honors."""

    def test_llama_gets_the_manual_vjp_family(self):
        facts = ModelFacts.from_config(load_config(tiny_raw()))
        pp_plans = [p for p in enumerate_plans(facts, 8) if p.pp > 1]
        scheds = {p.schedule for p in pp_plans}
        assert {"1f1b", "1f1b-zb", "1f1b-interleaved", "wavefront"} <= scheds

    def test_mixtral_is_wavefront_only(self):
        facts = ModelFacts.from_config(load_config(tiny_raw(arch="mixtral")))
        pp_plans = [p for p in enumerate_plans(facts, 8) if p.pp > 1]
        assert pp_plans, "mixtral should still get pp plans"
        assert {p.schedule for p in pp_plans} == {"wavefront"}

    def test_preference_alignment_is_wavefront_only(self):
        facts = ModelFacts.from_config(
            load_config(tiny_raw(alignment="orpo")))
        pp_plans = [p for p in enumerate_plans(facts, 8) if p.pp > 1]
        assert pp_plans
        assert {p.schedule for p in pp_plans} == {"wavefront"}

    def test_lora_is_wavefront_only(self):
        facts = ModelFacts.from_config(load_config(tiny_raw(lora=True)))
        pp_plans = [p for p in enumerate_plans(facts, 8) if p.pp > 1]
        assert pp_plans
        assert {p.schedule for p in pp_plans} == {"wavefront"}

    def test_zigzag_blocks_pp(self):
        facts = ModelFacts.from_config(
            load_config(tiny_raw(fusions={"zigzag_ring_attention": True})))
        assert all(p.pp == 1 for p in enumerate_plans(facts, 8))


# ---------------------------------------------------------------------------
# golden top-1 plans (representative configs; analytic ranking only)
# ---------------------------------------------------------------------------


class TestGoldenPlans:
    """Pinned winners: a cost-model change that reorders these must be a
    deliberate decision (update the snapshot in the same commit)."""

    @pytest.mark.parametrize("config,chips,topo,want", [
        # the work-compacted executor's interval-allocated chunk-input ring
        # is O(pp*vp) instead of the old lockstep O(vp*nm) store, so the
        # interleave now FITS at large nm and its smaller bubble wins the
        # same mesh (PR: cash the pipeline bubbles)
        (f"{EX}/hf_llama3_8B_config.yaml", 256, "v5e",
         Plan(tp=8, pp=4, cp=1, ep=1, dp=8, micro_batch_size=1,
              num_microbatches=128, remat="selective",
              schedule="1f1b-interleaved", vp=4)),
        # the 70B winner IS the shipped config's declared mesh layout
        (f"{EX}/hf_llama3_70B_config.yaml", 256, "v5e",
         Plan(tp=32, pp=8, cp=1, ep=1, dp=1, micro_batch_size=1,
              num_microbatches=1024, remat="selective",
              schedule="1f1b-interleaved", vp=2)),
        (f"{EX}/tiny_smoke_config.yaml", 8, "cpu",
         Plan(tp=2, pp=1, cp=1, ep=1, dp=4, micro_batch_size=2,
              num_microbatches=1, remat="none", schedule="none")),
    ])
    def test_top1(self, config, chips, topo, want):
        rep = plan_config(config, chips=chips, topology=topo, audit=False,
                          top_k=1)
        assert rep.error is None
        assert rep.candidates[0].plan == want


# ---------------------------------------------------------------------------
# cost model: structure + rank agreement helper
# ---------------------------------------------------------------------------


class TestCostModel:
    def setup_method(self):
        self.facts = ModelFacts.from_config(
            load_config(f"{EX}/hf_llama3_8B_config.yaml"))
        self.topo = resolve_topology("v5e")

    def plan(self, **kw):
        base = dict(tp=8, pp=1, cp=1, ep=1, dp=32, micro_batch_size=1,
                    num_microbatches=32, remat="selective", schedule="none")
        base.update(kw)
        return Plan(**base)

    def test_remat_trades_memory_for_compute(self):
        none = estimate_plan(self.facts, self.plan(remat="none"), self.topo)
        full = estimate_plan(self.facts, self.plan(remat="full"), self.topo)
        assert full.compute_seconds > none.compute_seconds
        assert full.hbm_breakdown["activations"] < \
            none.hbm_breakdown["activations"]

    def test_bubble_shrinks_with_microbatches(self):
        few = estimate_plan(
            self.facts, self.plan(pp=4, dp=8, num_microbatches=16,
                                  micro_batch_size=8, schedule="1f1b"),
            self.topo)
        many = estimate_plan(
            self.facts, self.plan(pp=4, dp=8, num_microbatches=128,
                                  micro_batch_size=1, schedule="1f1b"),
            self.topo)
        assert many.bubble_seconds < few.bubble_seconds

    def test_zb_bubble_strictly_below_1f1b(self):
        """ZB-H1 acceptance bar: at equal (pp, nm) the zero-bubble split's
        bubble term is strictly below plain 1f1b's (it prices only the
        warmup third the deferred wgrad tail cannot fill) — while its
        compute term is strictly above (the re-linearization forward)."""
        f1b = estimate_plan(
            self.facts, self.plan(pp=4, dp=8, num_microbatches=16,
                                  micro_batch_size=8, schedule="1f1b"),
            self.topo)
        zb = estimate_plan(
            self.facts, self.plan(pp=4, dp=8, num_microbatches=16,
                                  micro_batch_size=8, schedule="1f1b-zb"),
            self.topo)
        assert zb.bubble_seconds < f1b.bubble_seconds
        assert zb.compute_seconds > f1b.compute_seconds
        # at the multiplier level the ratio is exactly the warmup third
        from neuronx_distributed_training_tpu.parallel.pipeline import (
            bubble_multiplier,
        )

        assert bubble_multiplier("1f1b-zb", 4, 16) == pytest.approx(
            bubble_multiplier("1f1b", 4, 16) / 3.0)

    def test_wavefront_bubble_divides_by_vp(self):
        """The satellite fix: wavefront with a virtual pipeline runs the
        circular interleave (utilization nm*vp/(nm*vp + pp - 1)), so its
        bubble term divides by nm*vp — not the vp-blind (pp-1)/nm."""
        flat = estimate_plan(
            self.facts, self.plan(pp=4, dp=8, num_microbatches=16,
                                  micro_batch_size=8, schedule="wavefront"),
            self.topo)
        vp2 = estimate_plan(
            self.facts, self.plan(pp=4, dp=8, num_microbatches=16,
                                  micro_batch_size=8, schedule="wavefront",
                                  vp=2),
            self.topo)
        assert vp2.bubble_seconds == pytest.approx(flat.bubble_seconds / 2.0)

    def test_interleaved_bubble_and_ring_memory(self):
        """1f1b-interleaved divides the bubble by nm*vp and pays for it in
        chunk-input ring storage (priced as hbm_breakdown['pipeline_rings']),
        while staying far below the wavefront's per-layer residual class."""
        f1b = estimate_plan(
            self.facts, self.plan(pp=4, dp=8, num_microbatches=16,
                                  micro_batch_size=8, schedule="1f1b"),
            self.topo)
        il = estimate_plan(
            self.facts, self.plan(pp=4, dp=8, num_microbatches=16,
                                  micro_batch_size=8,
                                  schedule="1f1b-interleaved", vp=2),
            self.topo)
        wave = estimate_plan(
            self.facts, self.plan(pp=4, dp=8, num_microbatches=16,
                                  micro_batch_size=8, schedule="wavefront",
                                  vp=2),
            self.topo)
        assert il.bubble_seconds == pytest.approx(f1b.bubble_seconds / 2.0)
        assert il.hbm_breakdown["pipeline_rings"] > 0
        assert il.hbm_bytes > f1b.hbm_bytes
        assert il.hbm_bytes < wave.hbm_bytes

    def test_wavefront_costs_more_memory_at_depth(self):
        onef1b = estimate_plan(
            self.facts, self.plan(pp=8, dp=4, num_microbatches=256,
                                  schedule="1f1b"), self.topo)
        wave = estimate_plan(
            self.facts, self.plan(pp=8, dp=4, num_microbatches=256,
                                  schedule="wavefront"), self.topo)
        assert wave.hbm_bytes > onef1b.hbm_bytes

    def test_tp_shards_memory_but_adds_comms(self):
        tp1 = estimate_plan(self.facts, self.plan(tp=1, dp=256), self.topo)
        tp8 = estimate_plan(self.facts, self.plan(tp=8, dp=32), self.topo)
        assert tp8.hbm_breakdown["params"] < tp1.hbm_breakdown["params"]
        assert tp8.comms_breakdown.get("tp", 0) > \
            tp1.comms_breakdown.get("tp", 0)

    def test_kendall_tau(self):
        assert kendall_tau([1, 2, 3], [10, 20, 30]) == 1.0
        assert kendall_tau([1, 2, 3], [30, 20, 10]) == -1.0
        assert kendall_tau([1.0], [2.0]) is None
        assert kendall_tau([1, 2, 3, 4], [10, 20, 40, 30]) == pytest.approx(
            4 / 6)


# ---------------------------------------------------------------------------
# flops breakdown: one source of truth with flops_for_model
# ---------------------------------------------------------------------------


class TestFlopsBreakdown:
    def test_gpt_with_moe_breakdown_sums_to_total(self):
        from neuronx_distributed_training_tpu.models import gpt
        from neuronx_distributed_training_tpu.utils import perf

        gc = gpt.GPTConfig.from_config({
            "num_layers": 4, "hidden_size": 64, "ffn_hidden_size": 176,
            "num_attention_heads": 8, "num_query_groups": 4,
            "vocab_size": 512, "activation": "swiglu",
            "moe": {"num_experts": 4, "top_k": 2},
        }, {})
        bd = perf.flops_breakdown_for_model(gc, 128)
        assert set(bd) == set(perf.FLOPS_COMPONENTS)
        assert bd["router"] > 0, "MoE GPT must have a router term"
        assert sum(bd.values()) == pytest.approx(
            perf.flops_for_model(gc, 128), rel=1e-12)

    def test_llama_breakdown_matches_legacy_scalar(self):
        from neuronx_distributed_training_tpu.models import llama
        from neuronx_distributed_training_tpu.utils import perf

        lc = llama.LlamaConfig(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_layers=32, num_attention_heads=32, num_kv_heads=8)
        bd = perf.flops_breakdown_for_model(lc, 8192)
        legacy = perf.llama_flops_per_token(
            num_layers=32, hidden_size=4096, intermediate_size=14336,
            num_attention_heads=32, num_kv_heads=8, vocab_size=128256,
            seq_len=8192)
        assert sum(bd.values()) == pytest.approx(legacy, rel=1e-12)
        assert perf.flops_for_model(lc, 8192) == pytest.approx(legacy,
                                                              rel=1e-12)

    def test_mixtral_counts_activated_experts_only(self):
        from neuronx_distributed_training_tpu.models import mixtral
        from neuronx_distributed_training_tpu.utils import perf

        mc = mixtral.MixtralConfig.from_config({
            "vocab_size": 512, "hidden_size": 64, "intermediate_size": 176,
            "num_layers": 4, "num_attention_heads": 8,
            "num_key_value_heads": 4,
            "moe": {"num_experts": 8, "top_k": 2},
        }, {})
        bd = perf.flops_breakdown_for_model(mc, 128)
        # 2 activated of 8 experts: the mlp term prices top_k, not E
        swiglu = 2 * 64 * 3 * 176
        assert bd["mlp"] == pytest.approx(4 * 2 * swiglu)
        assert sum(bd.values()) == pytest.approx(
            perf.flops_for_model(mc, 128), rel=1e-12)


# ---------------------------------------------------------------------------
# memory-model calibration: analytic vs compiled memory_analysis()
# ---------------------------------------------------------------------------


def measured_bytes(raw, world):
    from neuronx_distributed_training_tpu.analysis.graph_audit import (
        lower_step_program,
    )
    from neuronx_distributed_training_tpu.telemetry.census import (
        memory_analysis_bytes,
    )
    from neuronx_distributed_training_tpu.trainer.loop import (
        assemble_step_program,
    )

    cfg = load_config(raw)
    asm = assemble_step_program(cfg, devices=jax.devices()[:world],
                                build_data=False)
    _, compiled = lower_step_program(asm)
    mem = memory_analysis_bytes(compiled)
    if mem is None:
        pytest.skip("backend has no memory_analysis()")
    return mem["argument_size_in_bytes"] + mem["temp_size_in_bytes"]


class TestMemoryCalibration:
    """The satellite contract: analytic HBM within +-15% of XLA across
    dp/tp/pp/ep meshes on tiny llama + mixtral."""

    TOLERANCE = 0.15

    @pytest.mark.parametrize("kw,world", [
        (dict(), 4),                                     # dp mesh
        (dict(layers=8), 4),                             # depth scaling
        (dict(seq=256), 4),                              # seq scaling
        (dict(remat="full"), 4),                         # remat policy
        (dict(remat="none"), 4),
        (dict(tp=2), 8),                                 # tp mesh
        (dict(tp=4), 8),
        (dict(pp=2, sched="1f1b"), 8),                   # pp mesh, 1f1b
        (dict(pp=2, sched="wavefront"), 8),              # pp mesh, wavefront
        (dict(tp=2, pp=2, sched="1f1b"), 8),             # tp x pp
        (dict(arch="mixtral"), 4),                       # moe, dense mesh
        (dict(arch="mixtral", ep=2), 8),                 # ep mesh
    ], ids=["dp", "L8", "s256", "full", "none", "tp2", "tp4", "pp2-1f1b",
            "pp2-wave", "tp2pp2", "moe", "moe-ep2"])
    def test_within_15pct(self, kw, world):
        raw = tiny_raw(**kw)
        measured = measured_bytes(raw, world)
        facts = ModelFacts.from_config(load_config(raw))
        plan = facts.declared_plan_for(world)
        assert plan is not None
        est = hbm_breakdown(facts, plan)["total"]
        ratio = est / measured
        assert abs(ratio - 1.0) <= self.TOLERANCE, (
            f"analytic {est / 1e6:.2f}M vs measured {measured / 1e6:.2f}M "
            f"(ratio {ratio:.3f}) — the cost model drifted from XLA; "
            f"recalibrate the constants in autotune/cost_model.py"
        )

    def test_state_bytes_are_exact(self):
        """Params + opt state + batch (the argument bytes) must match XLA to
        within 2% — that part is closed-form accounting, not calibration."""
        from neuronx_distributed_training_tpu.analysis.graph_audit import (
            lower_step_program,
        )
        from neuronx_distributed_training_tpu.autotune.cost_model import (
            _policy_for,
            params_per_device,
        )
        from neuronx_distributed_training_tpu.telemetry.census import (
            memory_analysis_bytes,
        )
        from neuronx_distributed_training_tpu.trainer.loop import (
            assemble_step_program,
        )

        raw = tiny_raw()
        cfg = load_config(raw)
        asm = assemble_step_program(cfg, devices=jax.devices()[:4],
                                    build_data=False)
        _, compiled = lower_step_program(asm)
        mem = memory_analysis_bytes(compiled)
        if mem is None:
            pytest.skip("backend has no memory_analysis()")
        facts = ModelFacts.from_config(cfg)
        plan = facts.declared_plan_for(4)
        bd = hbm_breakdown(facts, plan)
        policy = _policy_for(facts)
        n = params_per_device(facts, plan)
        state = bd["params"] + bd["opt_state"] + bd["batch"]
        # mixed precision: no master copy (params already f32)
        assert n > 0 and policy is not None
        assert state == pytest.approx(mem["argument_size_in_bytes"],
                                      rel=0.02)


# ---------------------------------------------------------------------------
# planner end-to-end (tiny, with the audit stage)
# ---------------------------------------------------------------------------


class TestPlanner:
    def test_plan_config_with_audit(self):
        rep = plan_config(tiny_raw(), chips=8, topology="cpu", top_k=3,
                          max_devices=8)
        assert rep.error is None
        assert rep.n_plans > 0 and rep.candidates
        w = rep.winner
        assert w is not None, "tiny config must produce a surviving plan"
        # every surviving candidate passed the graph audit
        for c in rep.candidates:
            if not c.discarded:
                assert c.audit_verdict in ("clean", "info", "warn")
                assert c.measured_collectives is not None
                assert c.measured_memory_bytes and c.measured_memory_bytes > 0

    def test_yaml_snippet_parses_and_round_trips(self, tmp_path):
        import yaml

        from neuronx_distributed_training_tpu.autotune.planner import (
            apply_plan,
        )

        rep = plan_config(tiny_raw(), chips=8, topology="cpu", top_k=1,
                          audit=False)
        snippet = yaml.safe_load(rep.yaml_snippet())
        ds = snippet["distributed_strategy"]
        assert ds["tensor_model_parallel_size"] == rep.winner.plan.tp
        # --apply writes a loadable config with the plan imposed
        src = tmp_path / "src.yaml"
        src.write_text(yaml.safe_dump(tiny_raw()))
        dst = tmp_path / "tuned.yaml"
        apply_plan(src, dst, rep.winner.plan, rep.facts)
        tuned = load_config(dst)
        assert int(tuned["distributed_strategy"][
            "tensor_model_parallel_size"]) == rep.winner.plan.tp
        facts2 = ModelFacts.from_config(tuned)
        assert facts2.declared_plan_for(8).mesh == rep.winner.plan.mesh

    def test_unplannable_chip_count_reports_not_raises(self):
        # 7 chips: no factorization divides heads/batch -> error field set
        rep = plan_config(tiny_raw(gbs=8), chips=7, topology="cpu",
                          audit=False)
        assert rep.winner is None or rep.n_plans >= 0  # never raises

    def test_hbm_budget_prunes(self):
        # an 8B model on one cpu-profile chip (8G) cannot fit: everything
        # ranks, nothing "fits"
        rep = plan_config(f"{EX}/hf_llama3_8B_config.yaml", chips=1,
                          topology="cpu", audit=False)
        assert rep.n_fit == 0
        assert rep.candidates  # still ranked, marked unfit
        assert not rep.candidates[0].estimate.fits


# ---------------------------------------------------------------------------
# config knob block
# ---------------------------------------------------------------------------


class TestAutotuneKnobBlock:
    def test_unknown_key_dies_with_did_you_mean(self):
        raw = tiny_raw()
        raw["autotune"] = {"topk": 3}
        with pytest.raises(ValueError, match="did you mean.*top_k"):
            load_config(raw)

    def test_bad_top_k(self):
        raw = tiny_raw()
        raw["autotune"] = {"top_k": 0}
        with pytest.raises(ValueError, match="top_k"):
            load_config(raw)

    def test_bad_topology(self):
        raw = tiny_raw()
        raw["autotune"] = {"topology": "v9z"}
        with pytest.raises(ValueError, match="unknown autotune.topology"):
            load_config(raw)

    def test_bad_headroom(self):
        raw = tiny_raw()
        raw["autotune"] = {"hbm_headroom": 1.5}
        with pytest.raises(ValueError, match="hbm_headroom"):
            load_config(raw)

    def test_non_mapping_rejected(self):
        raw = tiny_raw()
        raw["autotune"] = True
        with pytest.raises(ValueError, match="autotune must be a mapping"):
            load_config(raw)

    def test_valid_block_loads(self):
        raw = tiny_raw()
        raw["autotune"] = {"enabled": True, "top_k": 3, "topology": "v5e",
                           "hbm_headroom": 0.85, "max_micro_batch_size": 4}
        cfg = load_config(raw)
        assert cfg["autotune"]["top_k"] == 3
