"""Checkpoint: sharded round-trip, resume exactness, top-k retention, warm start."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.checkpoint import (
    CheckpointConfig,
    Checkpointer,
    TrainState,
)

from conftest import requires_orbax_preservation


def make_state(step=0, consumed=0, scale=1.0):
    params = {
        "w": jnp.full((8, 4), scale, jnp.float32),
        "b": jnp.arange(4, dtype=jnp.float32) * scale,
    }
    opt = {"mu": jax.tree_util.tree_map(jnp.zeros_like, params), "step": jnp.asarray(step)}
    return TrainState(params=params, opt_state=opt, step=step, consumed_samples=consumed,
                      extra={"lr": 0.1})


@requires_orbax_preservation
class TestRoundTrip:
    def test_save_restore(self, tmp_path):
        cfg = CheckpointConfig(dir=tmp_path, async_save=False, save_top_k=2)
        with Checkpointer(cfg) as ck:
            state = make_state(step=5, consumed=640, scale=2.5)
            assert ck.save(state, metrics={"loss": 1.0})
            ck.wait()
            restored = ck.restore(state.params, state.opt_state)
        np.testing.assert_array_equal(restored.params["w"], state.params["w"])
        np.testing.assert_array_equal(restored.opt_state["mu"]["b"], state.opt_state["mu"]["b"])
        assert restored.step == 5
        assert restored.consumed_samples == 640
        assert restored.extra["lr"] == 0.1

    def test_sharded_restore(self, tmp_path, cpu_mesh):
        cfg = CheckpointConfig(dir=tmp_path, async_save=False)
        sharding = NamedSharding(cpu_mesh, P("model", None))
        w = jax.device_put(jnp.arange(32.0).reshape(8, 4), sharding)
        params = {"w": w}
        opt = {"mu": {"w": jnp.zeros_like(w)}}
        with Checkpointer(cfg) as ck:
            ck.save(TrainState(params, opt, 1, 8))
            ck.wait()
            restored = ck.restore(
                params, opt, mesh=cpu_mesh,
                param_specs={"w": P("model", None)},
                opt_specs={"mu": {"w": P("model", None)}},
            )
        assert restored.params["w"].sharding.spec == P("model", None)
        np.testing.assert_array_equal(np.asarray(restored.params["w"]), np.asarray(w))

    def test_async_save(self, tmp_path):
        cfg = CheckpointConfig(dir=tmp_path, async_save=True)
        with Checkpointer(cfg) as ck:
            ck.save(make_state(step=1, consumed=8))
            ck.wait()
            assert ck.latest_step() == 1


@requires_orbax_preservation
class TestRetention:
    def test_topk_keeps_best_and_latest(self, tmp_path):
        cfg = CheckpointConfig(dir=tmp_path, async_save=False, save_top_k=2, monitor="loss")
        with Checkpointer(cfg) as ck:
            losses = {1: 5.0, 2: 1.0, 3: 4.0, 4: 2.0, 5: 3.0}
            for step, loss in losses.items():
                ck.save(make_state(step=step, consumed=step * 8), metrics={"loss": loss})
            ck.wait()
            kept = sorted(ck._mgr.all_steps())
        # best two by lowest loss = steps 2 (1.0) and 4 (2.0); latest = 5
        assert 2 in kept and 4 in kept, f"kept={kept}"
        assert 5 in kept, f"latest must survive eviction, kept={kept}"
        assert 1 not in kept and 3 not in kept, f"kept={kept}"

    def test_resume_latest(self, tmp_path):
        cfg = CheckpointConfig(dir=tmp_path, async_save=False, save_top_k=0)
        with Checkpointer(cfg) as ck:
            for step in (1, 2, 3):
                ck.save(make_state(step=step, consumed=step * 128, scale=step))
            ck.wait()
            assert ck.latest_step() == 3
            s = make_state()
            restored = ck.restore(s.params, s.opt_state)
        assert restored.consumed_samples == 384
        np.testing.assert_array_equal(
            restored.params["w"], jnp.full((8, 4), 3.0)
        )

    def test_restore_missing_raises(self, tmp_path):
        cfg = CheckpointConfig(dir=tmp_path, async_save=False)
        with Checkpointer(cfg) as ck:
            s = make_state()
            with pytest.raises(FileNotFoundError):
                ck.restore(s.params, s.opt_state)


@requires_orbax_preservation
class TestWarmStart:
    def test_params_only(self, tmp_path):
        cfg = CheckpointConfig(dir=tmp_path, async_save=False)
        with Checkpointer(cfg) as ck:
            ck.save(make_state(step=7, consumed=56, scale=7.0))
            ck.wait()
            s = make_state()
            params = ck.restore_params_only(s.params)
        np.testing.assert_array_equal(params["w"], jnp.full((8, 4), 7.0))


class TestConfig:
    def test_from_reference_schema(self):
        cfg = CheckpointConfig.from_config({
            "exp_manager": {
                "exp_dir": "/tmp/exp",
                "checkpoint_callback_params": {
                    "save_top_k": 5,
                    "every_n_train_steps": 50,
                    "monitor": "val_loss",
                },
            }
        })
        assert cfg.save_top_k == 5
        assert cfg.every_n_train_steps == 50
        assert cfg.monitor == "val_loss"  # passed through verbatim, never mangled
        assert str(cfg.dir) == "/tmp/exp"


class TestPrecisionKnobs:
    """save_bf16 + use_master_weights_in_ckpt (reference exp_manager.py:46,58,
    nlp_overrides.py:618-630) — VERDICT r2 item 7."""

    def _state(self):
        params = {"w": jnp.linspace(0, 1, 32, dtype=jnp.float32).reshape(8, 4)}
        opt = {
            "mu": jax.tree_util.tree_map(jnp.zeros_like, params),
            "master": jax.tree_util.tree_map(lambda x: x + 0.5, params),
            "step": jnp.asarray(3),
        }
        return TrainState(params=params, opt_state=opt, step=3,
                          consumed_samples=24)

    @requires_orbax_preservation
    def test_save_bf16_halves_and_restores_cast_up(self, tmp_path):
        cfg = CheckpointConfig(dir=tmp_path, async_save=False, save_bf16=True)
        st = self._state()
        with Checkpointer(cfg) as ck:
            ck.save(st)
            ck.wait()
            restored = ck.restore(st.params, st.opt_state)
        # restored at template dtype, values equal to a bf16 round-trip
        assert restored.params["w"].dtype == jnp.float32
        np.testing.assert_array_equal(
            np.asarray(restored.params["w"]),
            np.asarray(st.params["w"].astype(jnp.bfloat16).astype(jnp.float32)),
        )
        # integer leaves (opt step) untouched
        assert int(restored.opt_state["step"]) == 3

    @requires_orbax_preservation
    def test_drop_master_reseeds_from_params(self, tmp_path):
        cfg = CheckpointConfig(dir=tmp_path, async_save=False,
                               use_master_weights_in_ckpt=False)
        st = self._state()
        with Checkpointer(cfg) as ck:
            ck.save(st)
            ck.wait()
            # the master tree must not be on disk
            restored = ck.restore(st.params, st.opt_state)
        assert "master" in restored.opt_state
        # re-seeded from the SAVED PARAMS, not the old master (+0.5)
        np.testing.assert_array_equal(
            np.asarray(restored.opt_state["master"]["w"]),
            np.asarray(st.params["w"]),
        )

    def test_from_config_reads_knobs(self):
        cfg = CheckpointConfig.from_config({
            "exp_manager": {
                "exp_dir": "/tmp/x",
                "save_bf16": True,
                "checkpoint_callback_params": {
                    "use_master_weights_in_ckpt": False},
            }
        })
        assert cfg.save_bf16 and not cfg.use_master_weights_in_ckpt

    @requires_orbax_preservation
    def test_bitwise_default_unchanged(self, tmp_path):
        """Default knobs keep the bitwise round-trip (the resume-exactness
        contract other tests pin)."""
        cfg = CheckpointConfig(dir=tmp_path, async_save=False)
        st = self._state()
        with Checkpointer(cfg) as ck:
            ck.save(st)
            ck.wait()
            restored = ck.restore(st.params, st.opt_state)
        np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                      np.asarray(st.params["w"]))
        np.testing.assert_array_equal(
            np.asarray(restored.opt_state["master"]["w"]),
            np.asarray(st.opt_state["master"]["w"]))


class TestRemoteStylePath:
    """Remote-store path handling (reference saves to shared/remote stores;
    zero-egress CI cannot reach a real bucket, so the contract is pinned at
    the path-resolution seam)."""

    def test_gs_uri_not_mangled(self):
        from neuronx_distributed_training_tpu.checkpoint.manager import (
            resolve_checkpoint_dir,
        )

        p = resolve_checkpoint_dir("gs://bucket/ckpts")
        # keeps the scheme (an epath.Path) — Path().absolute() would turn it
        # into a local directory literally named "gs:"
        assert str(p).startswith("gs://bucket")

    def test_unknown_scheme_raises(self):
        from neuronx_distributed_training_tpu.checkpoint.manager import (
            resolve_checkpoint_dir,
        )

        with pytest.raises(ValueError, match="URI scheme"):
            resolve_checkpoint_dir("file:///tmp/x")

    @requires_orbax_preservation
    def test_epath_round_trip(self, tmp_path):
        """Full save/restore through etils epath.Path — the same class the
        gs:// path uses, exercising the TensorStore-facing path plumbing."""
        from etils import epath

        cfg = CheckpointConfig(dir=epath.Path(tmp_path) / "ckpt_epath",
                               async_save=False)
        with Checkpointer(cfg) as ck:
            st = make_state(step=2, consumed=16, scale=3.0)
            ck.save(st)
            ck.wait()
            restored = ck.restore(st.params, st.opt_state)
        np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                      np.asarray(st.params["w"]))
        assert restored.step == 2
