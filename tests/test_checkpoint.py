"""Checkpoint: sharded round-trip, resume exactness, top-k retention, warm start."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.checkpoint import (
    CheckpointConfig,
    Checkpointer,
    TrainState,
)


def make_state(step=0, consumed=0, scale=1.0):
    params = {
        "w": jnp.full((8, 4), scale, jnp.float32),
        "b": jnp.arange(4, dtype=jnp.float32) * scale,
    }
    opt = {"mu": jax.tree_util.tree_map(jnp.zeros_like, params), "step": jnp.asarray(step)}
    return TrainState(params=params, opt_state=opt, step=step, consumed_samples=consumed,
                      extra={"lr": 0.1})


class TestRoundTrip:
    def test_save_restore(self, tmp_path):
        cfg = CheckpointConfig(dir=tmp_path, async_save=False, save_top_k=2)
        with Checkpointer(cfg) as ck:
            state = make_state(step=5, consumed=640, scale=2.5)
            assert ck.save(state, metrics={"loss": 1.0})
            ck.wait()
            restored = ck.restore(state.params, state.opt_state)
        np.testing.assert_array_equal(restored.params["w"], state.params["w"])
        np.testing.assert_array_equal(restored.opt_state["mu"]["b"], state.opt_state["mu"]["b"])
        assert restored.step == 5
        assert restored.consumed_samples == 640
        assert restored.extra["lr"] == 0.1

    def test_sharded_restore(self, tmp_path, cpu_mesh):
        cfg = CheckpointConfig(dir=tmp_path, async_save=False)
        sharding = NamedSharding(cpu_mesh, P("model", None))
        w = jax.device_put(jnp.arange(32.0).reshape(8, 4), sharding)
        params = {"w": w}
        opt = {"mu": {"w": jnp.zeros_like(w)}}
        with Checkpointer(cfg) as ck:
            ck.save(TrainState(params, opt, 1, 8))
            ck.wait()
            restored = ck.restore(
                params, opt, mesh=cpu_mesh,
                param_specs={"w": P("model", None)},
                opt_specs={"mu": {"w": P("model", None)}},
            )
        assert restored.params["w"].sharding.spec == P("model", None)
        np.testing.assert_array_equal(np.asarray(restored.params["w"]), np.asarray(w))

    def test_async_save(self, tmp_path):
        cfg = CheckpointConfig(dir=tmp_path, async_save=True)
        with Checkpointer(cfg) as ck:
            ck.save(make_state(step=1, consumed=8))
            ck.wait()
            assert ck.latest_step() == 1


class TestRetention:
    def test_topk_keeps_best_and_latest(self, tmp_path):
        cfg = CheckpointConfig(dir=tmp_path, async_save=False, save_top_k=2, monitor="loss")
        with Checkpointer(cfg) as ck:
            losses = {1: 5.0, 2: 1.0, 3: 4.0, 4: 2.0, 5: 3.0}
            for step, loss in losses.items():
                ck.save(make_state(step=step, consumed=step * 8), metrics={"loss": loss})
            ck.wait()
            kept = sorted(ck._mgr.all_steps())
        # best two by lowest loss = steps 2 (1.0) and 4 (2.0); latest = 5
        assert 2 in kept and 4 in kept, f"kept={kept}"
        assert 5 in kept, f"latest must survive eviction, kept={kept}"
        assert 1 not in kept and 3 not in kept, f"kept={kept}"

    def test_resume_latest(self, tmp_path):
        cfg = CheckpointConfig(dir=tmp_path, async_save=False, save_top_k=0)
        with Checkpointer(cfg) as ck:
            for step in (1, 2, 3):
                ck.save(make_state(step=step, consumed=step * 128, scale=step))
            ck.wait()
            assert ck.latest_step() == 3
            s = make_state()
            restored = ck.restore(s.params, s.opt_state)
        assert restored.consumed_samples == 384
        np.testing.assert_array_equal(
            restored.params["w"], jnp.full((8, 4), 3.0)
        )

    def test_restore_missing_raises(self, tmp_path):
        cfg = CheckpointConfig(dir=tmp_path, async_save=False)
        with Checkpointer(cfg) as ck:
            s = make_state()
            with pytest.raises(FileNotFoundError):
                ck.restore(s.params, s.opt_state)


class TestWarmStart:
    def test_params_only(self, tmp_path):
        cfg = CheckpointConfig(dir=tmp_path, async_save=False)
        with Checkpointer(cfg) as ck:
            ck.save(make_state(step=7, consumed=56, scale=7.0))
            ck.wait()
            s = make_state()
            params = ck.restore_params_only(s.params)
        np.testing.assert_array_equal(params["w"], jnp.full((8, 4), 7.0))


class TestConfig:
    def test_from_reference_schema(self):
        cfg = CheckpointConfig.from_config({
            "exp_manager": {
                "exp_dir": "/tmp/exp",
                "checkpoint_callback_params": {
                    "save_top_k": 5,
                    "every_n_train_steps": 50,
                    "monitor": "val_loss",
                },
            }
        })
        assert cfg.save_top_k == 5
        assert cfg.every_n_train_steps == 50
        assert cfg.monitor == "val_loss"  # passed through verbatim, never mangled
        assert str(cfg.dir) == "/tmp/exp"
