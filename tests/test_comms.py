"""Interconnect observatory (telemetry.comms): measured collective bandwidth.

Covers the bus-bandwidth conventions against hand numbers, the in-loop
achieved-bandwidth join (cost-model byte volumes x traced wire seconds),
the per-axis bandwidth/latency fit recovering an exactly-planted plane, the
seeded-slow-device skew detector, the worked degraded-link alert rule
firing through the real alert engine, the committed hand-computable
``comms_summary`` fixture (byte-stable ratchet), the live CPU-mesh sweep on
virtual devices, the planner calibration round-trip (fixture AND
live-captured summary), PC204 fault injection + the committed ``cpu_comms``
baseline, quant-readiness savings provenance, fleet beacon/spread wiring,
and the CLI smokes (tools/comms_bench.py, tools/comms_report.py).

Run ``python tests/test_comms.py --regen-fixture`` to regenerate the
committed fixture after changing ``build_fixture()`` — the ratchet test
diffs bytes, so drift is loud.
"""

import importlib.util
import json
import sys
from pathlib import Path

import pytest

from neuronx_distributed_training_tpu.analysis import perf_contract as pc
from neuronx_distributed_training_tpu.telemetry import comms

FIXTURE = Path(__file__).parent / "data" / "comms_summary_fixture.json"


def _load_tool(name):
    path = Path(__file__).resolve().parents[1] / "tools" / f"{name}.py"
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# the committed fixture: two axes planted EXACTLY on t = B/bw + hops x lat
# planes, so the fit must recover the planted parameters to the digit
# ---------------------------------------------------------------------------

PRIOR_BW = 2e9  # the topology prior the fixture bench "saw" (cpu row)
PRIOR_LAT = 2e-5
#: device 3 is the seeded slow device: 0.0025s vs a 0.00105s fleet median
#: (ratio 2.381 > the 1.5x threshold); devices 0-2 are healthy
SKEW = {"0": 0.001, "1": 0.001, "2": 0.0011, "3": 0.0025}


def _plane_rows(points):
    """Sweep rows lying exactly on a planted (bw, lat) plane, shaped and
    rounded like ``run_comms_sweep`` emits them."""
    rows = []
    for kind, payload, bw, lat in points:
        n = 2
        bb = comms.bus_bytes(kind, payload, n)
        hops = comms.ring_hops(kind, n)
        t = bb / bw + hops * lat
        rows.append({
            "collective": kind, "payload_bytes": int(payload),
            "bus_bytes": round(bb, 1), "hops": hops,
            "seconds_median": round(t, 9), "seconds_min": round(t, 9),
            "reps": 3, "bus_gbps": round(bb / t / 1e9, 6),
        })
    return rows


def build_fixture() -> dict:
    # dp: 1 GB/s + 100us/hop (ratio 0.5 vs the 2 GB/s prior);
    # pp: 0.5 GB/s + 200us/hop (ratio 0.25)
    axis_results = {
        "dp": {"mesh_axis": "data", "size": 2, "sweep": _plane_rows([
            ("all-gather", 1 << 20, 1e9, 1e-4),
            ("all-gather", 4 << 20, 1e9, 1e-4),
            ("all-reduce", 1 << 20, 1e9, 1e-4),
        ])},
        "pp": {"mesh_axis": "pipe", "size": 2, "sweep": _plane_rows([
            ("collective-permute", 1 << 20, 5e8, 2e-4),
            ("collective-permute", 4 << 20, 5e8, 2e-4),
        ])},
    }
    return comms.build_comms_summary(
        axis_results, topology_name="cpu",
        prior_bandwidth_bytes=PRIOR_BW, prior_latency_seconds=PRIOR_LAT,
        device_skew=SKEW)


def build_fixture_bytes() -> bytes:
    # the exact serialization write_comms_summary uses
    return (json.dumps(build_fixture(), indent=1, sort_keys=True)
            + "\n").encode()


@pytest.fixture(scope="module")
def fixture_doc():
    return json.loads(FIXTURE.read_text())


# ---------------------------------------------------------------------------
# bus-bandwidth conventions (hand numbers)
# ---------------------------------------------------------------------------


class TestBusMath:
    def test_bus_bytes_ring_factors(self):
        # NCCL-tests vocabulary over n=4 ranks, 1000-byte payload
        assert comms.bus_bytes("all-reduce", 1000, 4) == 1500.0  # 2B(n-1)/n
        assert comms.bus_bytes("all-gather", 1000, 4) == 750.0  # B(n-1)/n
        assert comms.bus_bytes("reduce-scatter", 1000, 4) == 750.0
        assert comms.bus_bytes("all-to-all", 1000, 4) == 750.0
        assert comms.bus_bytes("collective-permute", 1000, 4) == 1000.0
        assert comms.bus_bytes("all-reduce", 1000, 1) == 0.0
        assert comms.bus_bytes("all-reduce", 0, 4) == 0.0

    def test_ring_hops(self):
        assert comms.ring_hops("all-reduce", 4) == 6  # 2(n-1)
        assert comms.ring_hops("all-gather", 4) == 3
        assert comms.ring_hops("reduce-scatter", 4) == 3
        assert comms.ring_hops("all-to-all", 4) == 3
        assert comms.ring_hops("collective-permute", 4) == 1
        assert comms.ring_hops("all-gather", 1) == 0

    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError, match="unknown collective kind"):
            comms.bus_bytes("all-scatter", 1000, 4)
        with pytest.raises(ValueError, match="unknown collective kind"):
            comms.ring_hops("all-scatter", 4)

    def test_kinds_match_debug_vocabulary(self):
        # COMMS_KINDS is duplicated so the module imports without jax —
        # it must never drift from the tracer's vocabulary
        from neuronx_distributed_training_tpu.utils.debug import (
            COLLECTIVE_KINDS,
        )

        assert tuple(comms.COMMS_KINDS) == tuple(COLLECTIVE_KINDS)

    def test_class_bus_bytes_per_step(self):
        per_class = comms.class_bus_bytes_per_step(
            {"tp": {"all-gather": 1000.0, "reduce-scatter": 1000.0},
             "dp": {"all-reduce": 2000.0},
             "pp": {"collective-permute": 500.0}},
            {"tp": 4, "dp": 2, "pp": 1})
        # pp degenerate (n=1) contributes nothing; the rest fold through
        # the ring factors
        assert per_class == {"all-gather": 750.0, "reduce-scatter": 750.0,
                             "all-reduce": 2000.0}

    def test_axes_summed_per_class(self):
        per_class = comms.class_bus_bytes_per_step(
            {"tp": {"all-gather": 1000.0}, "dp": {"all-gather": 1000.0}},
            {"tp": 2, "dp": 2})
        assert per_class == {"all-gather": 1000.0}  # 500 + 500


# ---------------------------------------------------------------------------
# the in-loop join (comms_section) with hand numbers
# ---------------------------------------------------------------------------


def _facts_block():
    return {"byte_volumes": {"tp": {"all-gather": float(1 << 20)}},
            "axis_sizes": {"tp": 2},
            "peak_bandwidth_bytes": 1e9, "topology": "cpu"}


class TestCommsSection:
    def test_hand_computed_join(self):
        # bus bytes/step = 1MiB/2 = 524288; wire = 2ms over 2 steps = 1ms
        # per step -> 524288000 B/s achieved = 0.524288 GB/s; efficiency
        # against the 1 GB/s peak is the same number
        section = comms.comms_section(
            _facts_block(),
            {"all-gather": {"wire_seconds": 0.002, "count": 10}},
            window_steps=2)
        e = section["classes"]["all-gather"]
        assert e["bus_bytes_per_step"] == 524288.0
        assert e["wire_seconds_per_step"] == pytest.approx(0.001)
        assert e["achieved_gbps"] == pytest.approx(0.524288)
        assert e["efficiency"] == pytest.approx(0.524288)
        assert e["count"] == 10
        assert section["window_steps"] == 2
        assert section["peak_bandwidth_gbps"] == 1.0
        assert section["topology"] == "cpu"

    def test_untraced_class_is_skipped(self):
        # volumes name all-gather but the trace saw only all-reduce: the
        # join never invents a wire time
        assert comms.comms_section(
            _facts_block(),
            {"all-reduce": {"wire_seconds": 0.1}}, window_steps=2) is None

    def test_nothing_to_say_returns_none(self):
        assert comms.comms_section({}, {}, window_steps=2) is None
        assert comms.comms_section(_facts_block(), {}, window_steps=0) is None
        assert comms.comms_section(
            {"byte_volumes": {}, "axis_sizes": {}}, {"all-gather":
                {"wire_seconds": 1.0}}, window_steps=2) is None

    def test_zero_wire_seconds_skipped(self):
        assert comms.comms_section(
            _facts_block(), {"all-gather": {"wire_seconds": 0.0}},
            window_steps=2) is None

    def test_no_peak_means_no_efficiency(self):
        facts = dict(_facts_block(), peak_bandwidth_bytes=0.0)
        section = comms.comms_section(
            facts, {"all-gather": {"wire_seconds": 0.002}}, window_steps=2)
        assert "efficiency" not in section["classes"]["all-gather"]
        assert "peak_bandwidth_gbps" not in section

    def test_metrics_flattening(self):
        section = comms.comms_section(
            _facts_block(),
            {"all-gather": {"wire_seconds": 0.002, "count": 1}},
            window_steps=2)
        scalars = comms.comms_metrics(section)
        assert scalars == {
            "comms/all-gather/achieved_gbps": pytest.approx(0.524288),
            "comms/all-gather/efficiency": pytest.approx(0.524288),
        }
        assert comms.comms_metrics(None) == {}


# ---------------------------------------------------------------------------
# the worked degraded-link alert rule, through the real engine
# ---------------------------------------------------------------------------


class TestDegradedLinkRule:
    def test_rule_validates(self):
        from neuronx_distributed_training_tpu.telemetry.alerts import (
            AlertRule,
        )

        r = AlertRule.from_config(comms.degraded_link_alert_rule())
        assert r.name == "comms_degraded_link"
        assert r.metric == "comms/all-gather/achieved_gbps"
        assert r.window == 3 and r.rel_drop == 0.5 and r.action == "log"
        r = AlertRule.from_config(comms.degraded_link_alert_rule(
            kind="reduce-scatter", window=1, rel_drop=0.3, action="halt"))
        assert r.metric == "comms/reduce-scatter/achieved_gbps"
        assert r.action == "halt"

    def test_fires_on_bandwidth_collapse(self):
        from neuronx_distributed_training_tpu.telemetry.alerts import (
            AlertEngine,
            AlertRule,
        )

        eng = AlertEngine([AlertRule.from_config(
            comms.degraded_link_alert_rule(window=1))])
        # healthy window establishes the peak; a boundary with no comms
        # metric (no trace window fired) is simply skipped
        assert eng.observe(1, {"comms/all-gather/achieved_gbps": 10.0}) == []
        assert eng.observe(2, {"loss": 2.0}) == []
        fired = eng.observe(3, {"comms/all-gather/achieved_gbps": 4.0})
        assert [f.rule for f in fired] == ["comms_degraded_link"]
        assert fired[0].value == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# the per-axis fit
# ---------------------------------------------------------------------------


class TestAxisFit:
    def test_exact_recovery_of_planted_plane(self):
        # three points exactly on t = B/1e9 + hops * 1e-4: the normal
        # equations must hand back the planted parameters
        fit = comms.fit_axis_bandwidth([
            {"bus_bytes": 524288.0, "hops": 1, "seconds": 0.000624288},
            {"bus_bytes": 2097152.0, "hops": 1, "seconds": 0.002197152},
            {"bus_bytes": 1048576.0, "hops": 2, "seconds": 0.001248576},
        ])
        assert fit == {"bandwidth_bytes_per_s": 1e9,
                       "latency_seconds": 1e-4, "n_points": 3}

    def test_slope_only_fallback_on_degenerate_system(self):
        # hops all zero: the 2-parameter system is singular; the fit falls
        # back to the latency-free slope
        fit = comms.fit_axis_bandwidth(
            [{"bus_bytes": 1e6, "hops": 0, "seconds": 0.001}])
        assert fit["bandwidth_bytes_per_s"] == pytest.approx(1e9)
        assert fit["latency_seconds"] == 0.0

    def test_negative_latency_rejected(self):
        # a plane whose exact solution has lat < 0 (timing noise shape)
        # must not be reported as-is: the fit degrades to slope-only
        fit = comms.fit_axis_bandwidth([
            {"bus_bytes": 1e6, "hops": 2, "seconds": 0.0009},
            {"bus_bytes": 4e6, "hops": 1, "seconds": 0.004},
        ])
        assert fit["latency_seconds"] == 0.0
        assert fit["bandwidth_bytes_per_s"] > 0

    def test_garbage_points_skipped(self):
        assert comms.fit_axis_bandwidth([]) is None
        assert comms.fit_axis_bandwidth(
            [{"bus_bytes": -1, "hops": 0, "seconds": 0.1},
             {"hops": 1}, {"bus_bytes": 1e6, "seconds": 0}]) is None


# ---------------------------------------------------------------------------
# skew detection (seeded slow device)
# ---------------------------------------------------------------------------


class TestSkew:
    def test_seeded_slow_device_named(self):
        findings = comms.skew_findings(SKEW)
        assert len(findings) == 1
        f = findings[0]
        assert f["kind"] == "degraded_link"
        assert f["device"] == "3"
        assert f["ratio"] == pytest.approx(0.0025 / 0.00105, abs=1e-3)
        assert "device 3" in f["message"]

    def test_uniform_fleet_is_clean(self):
        assert comms.skew_findings({"0": 0.001, "1": 0.001}) == []

    def test_threshold_respected(self):
        assert comms.skew_findings(SKEW, rel_threshold=3.0) == []

    def test_single_device_says_nothing(self):
        assert comms.skew_findings({"0": 99.0}) == []
        assert comms.skew_findings({}) == []


# ---------------------------------------------------------------------------
# the committed fixture (byte-stable ratchet) + artifact round trips
# ---------------------------------------------------------------------------


class TestFixture:
    def test_fixture_committed_and_current(self):
        """Bytes-equal ratchet: drift in the builder OR the serializer is
        loud; regenerate with ``python tests/test_comms.py
        --regen-fixture``."""
        assert FIXTURE.exists(), \
            "fixture missing: python tests/test_comms.py --regen-fixture"
        assert FIXTURE.read_bytes() == build_fixture_bytes()

    def test_fit_recovers_planted_planes(self, fixture_doc):
        dp = fixture_doc["axes"]["dp"]
        assert dp["fit"] == {"bandwidth_bytes_per_s": 1e9,
                             "latency_seconds": 1e-4, "n_points": 3}
        assert dp["bandwidth_ratio"] == 0.5  # 1 GB/s vs the 2 GB/s prior
        assert dp["latency_ratio"] == 5.0
        pp = fixture_doc["axes"]["pp"]
        assert pp["fit"] == {"bandwidth_bytes_per_s": 5e8,
                             "latency_seconds": 2e-4, "n_points": 2}
        assert pp["bandwidth_ratio"] == 0.25

    def test_degraded_link_finding(self, fixture_doc):
        assert [f["device"] for f in fixture_doc["findings"]] == ["3"]
        skew = fixture_doc["device_skew"]
        assert skew["median_seconds"] == 0.00105
        assert skew["findings"] == fixture_doc["findings"]

    def test_sniff_and_load(self, fixture_doc, tmp_path):
        assert comms.is_comms_summary(fixture_doc)
        # kind marker stripped: the axes+prior pair still identifies it
        anonymous = {k: v for k, v in fixture_doc.items() if k != "kind"}
        assert comms.is_comms_summary(anonymous)
        # things that must NOT sniff as a comms summary
        assert not comms.is_comms_summary({"overlap_by_class": {}})
        assert not comms.is_comms_summary(None)
        # a run dir resolves the canonical name
        comms.write_comms_summary(fixture_doc,
                                  tmp_path / comms.COMMS_SUMMARY_NAME)
        assert comms.load_comms_summary(tmp_path) == fixture_doc
        with pytest.raises(ValueError, match="no comms summary"):
            comms.load_comms_summary(tmp_path / "nope.json")

    def test_write_is_byte_stable(self, fixture_doc, tmp_path):
        out = tmp_path / "a.json"
        comms.write_comms_summary(fixture_doc, out)
        assert out.read_bytes() == FIXTURE.read_bytes()
        first = out.read_bytes()
        comms.write_comms_summary(json.loads(out.read_text()), out)
        assert out.read_bytes() == first


# ---------------------------------------------------------------------------
# live CPU-mesh sweep (virtual devices drive the real collectives)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def live_sweep(cpu_mesh):
    # dp=4 x tp=2; two kinds x two sizes keeps the compile bill small
    return comms.run_comms_sweep(
        cpu_mesh, sizes_bytes=(1 << 12, 1 << 14),
        kinds=("all-gather", "all-reduce"), warmup=1, reps=2)


@pytest.fixture(scope="module")
def live_summary(live_sweep, devices8):
    from neuronx_distributed_training_tpu.autotune.topology import (
        resolve_topology,
    )

    topo = resolve_topology(device=devices8[0])
    return comms.build_comms_summary(
        live_sweep, topology_name=topo.name,
        prior_bandwidth_bytes=topo.ici_bandwidth_bytes,
        prior_latency_seconds=topo.ici_latency_seconds,
        device_skew=comms.measure_device_skew(devices8, reps=1))


class TestLiveSweep:
    def test_axes_and_rows(self, live_sweep):
        assert set(live_sweep) == {"dp", "tp"}
        assert live_sweep["dp"]["mesh_axis"] == "data"
        assert live_sweep["dp"]["size"] == 4
        rows = live_sweep["dp"]["sweep"]
        assert {r["collective"] for r in rows} == {"all-gather",
                                                   "all-reduce"}
        for r in rows:
            assert r["seconds_median"] > 0 and r["bus_gbps"] > 0
            assert r["reps"] == 2
            assert r["hops"] == comms.ring_hops(r["collective"], 4)
            assert r["bus_bytes"] == pytest.approx(comms.bus_bytes(
                r["collective"], r["payload_bytes"], 4))

    def test_summary_fits_every_axis(self, live_summary, devices8):
        assert comms.is_comms_summary(live_summary)
        for axis in ("dp", "tp"):
            fit = live_summary["axes"][axis]["fit"]
            assert fit["bandwidth_bytes_per_s"] > 0
            assert fit["latency_seconds"] >= 0
            assert fit["n_points"] == 4
            assert live_summary["axes"][axis]["bandwidth_ratio"] > 0
        skew = live_summary["device_skew"]
        assert len(skew["per_device"]) == len(devices8)
        assert all(t > 0 for t in skew["per_device"].values())

    def test_round_trip_and_live_calibration(self, live_summary, tmp_path):
        """The satellite acceptance: a live-captured summary survives
        write -> load -> planner-calibration extraction."""
        from neuronx_distributed_training_tpu.autotune.cost_model import (
            _COMMS_RATIO_BOUNDS,
            comms_calibration_from_summary,
        )

        out = tmp_path / comms.COMMS_SUMMARY_NAME
        comms.write_comms_summary(live_summary, out)
        cal = comms_calibration_from_summary(str(out))
        assert set(cal) == {"dp", "tp"}
        lo, hi = _COMMS_RATIO_BOUNDS
        assert all(lo <= v <= hi for v in cal.values())


# ---------------------------------------------------------------------------
# planner calibration (fixture round trip, clamping, repricing)
# ---------------------------------------------------------------------------


class TestCalibration:
    def test_ratios_from_fixture(self, fixture_doc):
        from neuronx_distributed_training_tpu.autotune.cost_model import (
            comms_calibration_from_summary,
        )

        assert comms_calibration_from_summary(fixture_doc) == {
            "dp": 0.5, "pp": 0.25}
        # also from the committed file path (the CLI's shape)
        assert comms_calibration_from_summary(str(FIXTURE)) == {
            "dp": 0.5, "pp": 0.25}

    def test_ratio_clamped(self):
        from neuronx_distributed_training_tpu.autotune.cost_model import (
            _COMMS_RATIO_BOUNDS,
            comms_calibration_from_summary,
        )

        doc = {"kind": "comms_summary",
               "prior": {"ici_bandwidth_bytes": 1e9,
                         "ici_latency_seconds": 0.0},
               "axes": {"tp": {"fit": {"bandwidth_bytes_per_s": 1e3,
                                       "latency_seconds": 0.0,
                                       "n_points": 2}}}}
        assert comms_calibration_from_summary(doc) == {
            "tp": _COMMS_RATIO_BOUNDS[0]}

    def test_unusable_summary_raises(self):
        from neuronx_distributed_training_tpu.autotune.cost_model import (
            comms_calibration_from_summary,
        )

        with pytest.raises(ValueError, match="no fitted"):
            comms_calibration_from_summary(
                {"kind": "comms_summary", "prior": {}, "axes": {}})
        with pytest.raises(ValueError, match="must be a mapping"):
            comms_calibration_from_summary(
                {"kind": "comms_summary", "axes": [1, 2]})

    def test_estimate_reprices_comms(self):
        # halved measured bandwidth must make the priced comms term grow
        from neuronx_distributed_training_tpu.autotune.cost_model import (
            estimate_plan,
        )
        from neuronx_distributed_training_tpu.autotune.space import (
            ModelFacts,
            Plan,
        )
        from neuronx_distributed_training_tpu.autotune.topology import (
            resolve_topology,
        )
        from neuronx_distributed_training_tpu.config.loader import (
            load_config,
        )

        facts = ModelFacts.from_config(
            load_config("examples/conf/tiny_smoke_config.yaml"))
        plan = Plan(tp=2, pp=1, cp=1, ep=1, dp=4, micro_batch_size=2,
                    num_microbatches=1, remat="none", schedule="none")
        topo = resolve_topology("cpu")
        base = estimate_plan(facts, plan, topo)
        slow = estimate_plan(facts, plan, topo,
                             comms_calibration={"tp": 0.5, "dp": 0.5})
        assert slow.comms_seconds > base.comms_seconds
        assert slow.compute_seconds == base.compute_seconds

    def test_plan_config_sniffs_comms_summary(self):
        """The --calibrate-from loop: content-sniffed comms summary lands
        measured/prior ratios in the report header."""
        from neuronx_distributed_training_tpu.autotune import plan_config

        rep = plan_config("examples/conf/tiny_smoke_config.yaml", chips=8,
                          topology="cpu", audit=False, top_k=1,
                          calibration=str(FIXTURE))
        assert rep.error is None
        assert rep.comms_calibration == {"dp": 0.5, "pp": 0.25}
        text = rep.format()
        assert "comms bandwidth (measured/prior)" in text
        assert "dp=0.50" in text and "pp=0.25" in text


# ---------------------------------------------------------------------------
# perf contract: PC204 fault injection + the committed cpu_comms baseline
# ---------------------------------------------------------------------------


def _comms_line(**over):
    block = {
        "classes": {"all-gather": {"achieved_gbps": 0.8,
                                   "efficiency": 0.4}},
        "axes": {"dp": {"bandwidth_gbps": 0.5, "latency_us": 100.0,
                        "bandwidth_ratio": 0.25}},
        "peak_bandwidth_gbps": 2.0,
    }
    block.update(over)
    return {"metric": "comms_bench_sweep", "value": 0.25,
            "unit": "min_axis_bandwidth_measured_over_prior",
            "device": "cpu", "comms": block}


def _cfacts(**over):
    return pc.perf_facts_from_bench(_comms_line(**over))


def _rules(report):
    return {f.rule for f in report.findings}


class TestPerfContractComms:
    def test_extraction_normalizes_both_shapes(self):
        f = _cfacts()
        assert f["comms"]["classes"]["all-gather"]["achieved_gbps"] == 0.8
        assert f["comms"]["axes"]["dp"]["bandwidth_gbps"] == 0.5
        assert f["comms"]["peak_bandwidth_gbps"] == 2.0
        # the trainer's trace-summary shape rides the same key
        t = pc.perf_facts_from_trace_summary({
            "achieved_overlap": 0.5, "exposed_collective_seconds": 0.01,
            "overlap_by_class": {},
            "comms": {"classes": {"all-reduce": {"achieved_gbps": 1.5,
                                                 "efficiency": 0.75}}}})
        assert t["comms"]["classes"]["all-reduce"]["efficiency"] == 0.75

    def test_run_summary_fallback(self, tmp_path):
        # no trace window fired but the trainer still wrote the comms
        # section into run_summary.json: the facts must carry it
        (tmp_path / "run_summary.json").write_text(json.dumps({
            "n_chips": 8,
            "comms": {"classes": {"all-gather": {"achieved_gbps": 0.9}}}}))
        (tmp_path / "trace_summary.json").write_text(json.dumps({
            "achieved_overlap": 0.5, "exposed_collective_seconds": 0.01,
            "overlap_by_class": {}}))
        f = pc.perf_facts_from_run(tmp_path)
        assert f["comms"]["classes"]["all-gather"]["achieved_gbps"] == 0.9

    def test_default_key(self):
        assert pc.default_key(_cfacts()) == "cpu_comms"

    def test_in_band_drift_is_clean(self):
        new = _cfacts(classes={"all-gather": {"achieved_gbps": 0.7,
                                              "efficiency": 0.35}})
        assert not pc.diff_facts(_cfacts(), new).findings

    def test_pc204_per_class_drop_names_class(self):
        new = _cfacts(classes={"all-gather": {"achieved_gbps": 0.3,
                                              "efficiency": 0.15}})
        rep = pc.diff_facts(_cfacts(), new)
        assert _rules(rep) == {"PC204"}
        f = rep.findings[0]
        assert f.location == "all-gather" and f.severity == "error"
        assert "0.8" in f.message and "0.3" in f.message
        assert rep.failed("error")

    def test_pc204_per_axis_drop_names_axis(self):
        new = _cfacts(axes={"dp": {"bandwidth_gbps": 0.2,
                                   "latency_us": 100.0,
                                   "bandwidth_ratio": 0.1}})
        rep = pc.diff_facts(_cfacts(), new)
        assert _rules(rep) == {"PC204"}
        assert rep.findings[0].location == "dp"
        assert "dp-axis bandwidth" in rep.findings[0].message

    def test_pc110_improvement_is_info(self):
        new = _cfacts(classes={"all-gather": {"achieved_gbps": 1.6,
                                              "efficiency": 0.8}},
                      axes={"dp": {"bandwidth_gbps": 1.0,
                                   "latency_us": 50.0,
                                   "bandwidth_ratio": 0.5}})
        rep = pc.diff_facts(_cfacts(), new)
        assert _rules(rep) == {"PC110"}
        assert not rep.failed("error")

    def test_noise_band_respected(self):
        new = _cfacts(classes={"all-gather": {"achieved_gbps": 0.3,
                                              "efficiency": 0.15}})
        rep = pc.diff_facts(_cfacts(), new, noise={"comms_bw_frac": 0.9})
        assert not rep.findings

    def test_residual_report_comms_bandwidth_row(self):
        est = {"step_seconds": 0.10, "compute_seconds": 0.07,
               "comms_seconds": 0.02, "bubble_seconds": 0.01}
        r = pc.residual_report(est, _cfacts())
        row = r["comms_bandwidth"]
        assert row["peak_gbps"] == 2.0
        assert row["achieved_gbps_by_class"] == {"all-gather": 0.8}
        assert row["mean_efficiency"] == pytest.approx(0.4)
        # the row is always present; without comms it says so with Nones
        empty = pc.residual_report(est, {"step_seconds": 0.15})
        assert empty["comms_bandwidth"]["peak_gbps"] is None

    def test_bench_verdict_ratchets(self, tmp_path):
        pc.update_baseline("cpu_comms", _cfacts(), baselines_dir=tmp_path)
        assert pc.bench_verdict("cpu_comms", _cfacts(),
                                baselines_dir=tmp_path)["verdict"] == "clean"
        v = pc.bench_verdict(
            "cpu_comms",
            _cfacts(classes={"all-gather": {"achieved_gbps": 0.1}}),
            baselines_dir=tmp_path)
        assert v["verdict"] == "error"
        assert v["findings"][0]["rule"] == "PC204"

    def test_committed_cpu_comms_baseline(self):
        # the verify-gate baseline shipped with the repo: self-check must
        # land clean, and the noise band must stay CPU-jitter wide
        snap = pc.load_baseline("cpu_comms")
        assert snap is not None, \
            "missing committed baseline: python tools/comms_bench.py " \
            "--smoke then tools/perf_contract.py --update-baselines"
        facts = snap["facts"]
        assert facts["comms"]["axes"], "baseline carries no per-axis fit"
        assert facts["comms"]["classes"]
        assert snap["noise"]["comms_bw_frac"] >= 0.5
        assert pc.bench_verdict("cpu_comms", facts)["verdict"] == "clean"


# ---------------------------------------------------------------------------
# quant-readiness: savings provenance (measured wire rate vs static)
# ---------------------------------------------------------------------------


class TestQuantSavingsSource:
    def test_measured_wire_rate_wins_when_comms_present(self):
        from neuronx_distributed_training_tpu.telemetry.quant_readiness import (
            build_report,
            bytes_saved_fraction,
        )

        sf = bytes_saved_fraction(512, 4.0)
        report = build_report(
            None, block_sizes=(512,),
            byte_volumes={"all-gather": 1000.0},
            overlap_by_class={"all-gather": {"exposed_seconds": 0.5,
                                             "wire_seconds": 1.0}},
            comms={"classes": {"all-gather": {"achieved_gbps": 2.0,
                                              "bus_bytes_per_step": 2e6}}})
        e = report["classes"]["all-gather"]
        assert e["savings_source"] == "measured_wire_rate"
        assert e["predicted_seconds_saved"] == round(2e6 * sf / 2e9, 9)

    def test_static_fallback_names_itself(self):
        from neuronx_distributed_training_tpu.telemetry.quant_readiness import (
            build_report,
            bytes_saved_fraction,
        )

        sf = bytes_saved_fraction(512, 4.0)
        report = build_report(
            None, block_sizes=(512,),
            byte_volumes={"all-gather": 1000.0},
            overlap_by_class={"all-gather": {"exposed_seconds": 0.5,
                                             "wire_seconds": 1.0}})
        e = report["classes"]["all-gather"]
        assert e["savings_source"] == "static_exposed_fraction"
        assert e["predicted_seconds_saved"] == pytest.approx(0.5 * sf)


# ---------------------------------------------------------------------------
# fleet plane: beacons carry comms/*, the spread survives later beacons
# ---------------------------------------------------------------------------


class TestFleetComms:
    def test_beacon_picks_comms_metrics(self, tmp_path):
        from neuronx_distributed_training_tpu.telemetry.fleet import (
            FleetBeacon,
            beacon_path,
        )

        b = FleetBeacon(tmp_path, host=1)
        b.emit(10, {"comms/all-gather/achieved_gbps": 0.5,
                    "comms/all-gather/efficiency": 0.25,
                    "grad_norm": 1.0})
        b.close()
        rec = json.loads(
            beacon_path(tmp_path, 1).read_text().splitlines()[0])
        assert rec["metrics"]["comms/all-gather/achieved_gbps"] == 0.5
        assert rec["metrics"]["comms/all-gather/efficiency"] == 0.25
        assert "grad_norm" not in rec["metrics"]

    def test_spread_sticky_across_later_beacons(self, tmp_path):
        """The join fires once per trace window; regular beacons after it
        must not erase the per-host number before anyone reads the
        spread — that is how the aggregator names a degraded host."""
        from neuronx_distributed_training_tpu.telemetry.fleet import (
            FleetBeacon,
            aggregate_fleet,
        )

        for host, bw in ((0, 1.0), (1, 0.2)):
            b = FleetBeacon(tmp_path, host=host)
            b.emit(10, {"loss": 2.0,
                        "comms/all-gather/achieved_gbps": bw})
            b.emit(20, {"loss": 1.9})  # no comms metric on this boundary
            b.close()
        sp = aggregate_fleet(tmp_path)["spread"][
            "comms/all-gather/achieved_gbps"]
        assert sp["min"] == {"host": 1, "value": 0.2}
        assert sp["max"] == {"host": 0, "value": 1.0}


# ---------------------------------------------------------------------------
# CLI smokes
# ---------------------------------------------------------------------------


class TestCommsReportCLI:
    def test_renders_fixture_summary(self, tmp_path, capsys):
        mod = _load_tool("comms_report")
        assert mod.main([str(FIXTURE), "--json",
                         str(tmp_path / "r.json")]) == 0
        out = capsys.readouterr().out
        for needle in ("per-axis fit", "all-gather", "degraded",
                       "device 3"):
            assert needle in out, (needle, out)
        doc = json.loads((tmp_path / "r.json").read_text())
        assert doc["ok"] and doc["kind"] == "summary"

    def test_renders_run_dir_section(self, tmp_path, capsys):
        mod = _load_tool("comms_report")
        (tmp_path / "run_summary.json").write_text(json.dumps({
            "comms": {"classes": {"all-gather": {
                "achieved_gbps": 0.5, "efficiency": 0.25,
                "bus_bytes_per_step": 1000.0,
                "wire_seconds_per_step": 2e-6, "count": 4}},
                "window_steps": 2, "peak_bandwidth_gbps": 2.0,
                "topology": "cpu"}}))
        assert mod.main([str(tmp_path), "--json", "-"]) == 0
        out = capsys.readouterr().out
        assert "in-loop achieved bandwidth" in out
        payload = json.loads(out.strip().splitlines()[-1])
        assert payload["kind"] == "section"
        assert payload["payload"]["classes"]["all-gather"][
            "achieved_gbps"] == 0.5

    def test_rejects_garbage(self, tmp_path, capsys):
        mod = _load_tool("comms_report")
        p = tmp_path / "nothing.json"
        p.write_text(json.dumps({"loss": 1.0}))
        assert mod.main([str(p), "--json", "-"]) == 2
        payload = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert payload["ok"] is False and "comms" in payload["error"]

    def test_metrics_report_section(self):
        mod = _load_tool("metrics_report")
        out = mod.comms_section({"comms": {
            "classes": {"all-gather": {"achieved_gbps": 0.5,
                                       "efficiency": 0.25}},
            "peak_bandwidth_gbps": 2.0}})
        assert "all-gather" in out and "achieved=0.500" in out
        assert "efficiency=25.0%" in out
        assert mod.comms_section({}) == ""


class TestCommsBenchCLI:
    def test_sweep_writes_summary_and_contract_line(self, tmp_path, capsys):
        mod = _load_tool("comms_bench")
        rc = mod.main(["--sizes", "4096,16384", "--reps", "1",
                       "--warmup", "1", "--no-skew",
                       "--kinds", "all-gather,collective-permute",
                       "--out", str(tmp_path) + "/",
                       "--json", str(tmp_path / "bench.json")])
        assert rc == 0
        summary = comms.load_comms_summary(tmp_path)
        assert comms.is_comms_summary(summary)
        assert set(summary["axes"]) == {"dp", "pp", "tp"}
        payload = json.loads((tmp_path / "bench.json").read_text())
        assert payload["metric"] == "comms_bench_sweep"
        assert payload["value"] > 0
        assert payload["perf_contract"]["key"] == "cpu_comms"
        assert payload["comms"]["axes"]["dp"]["bandwidth_gbps"] > 0
        out = capsys.readouterr().out
        assert "interconnect sweep" in out and "perf contract" in out


if __name__ == "__main__":
    if "--regen-fixture" in sys.argv:
        FIXTURE.parent.mkdir(parents=True, exist_ok=True)
        comms.write_comms_summary(build_fixture(), FIXTURE)
        print(f"wrote {FIXTURE} ({FIXTURE.stat().st_size} bytes)")
    else:
        raise SystemExit(pytest.main([__file__, "-v"] + sys.argv[1:]))
