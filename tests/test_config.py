import pytest

from neuronx_distributed_training_tpu.config.loader import (
    batch_schedule,
    load_config,
    validate_config,
)

REFERENCE_STYLE_YAML = """
name: hf_llama
model_source: hf
seed: 1234

trainer:
  max_steps: 100
  log_every_n_steps: 10
  gradient_clip_val: 1.0

exp_manager:
  exp_dir: /tmp/exp
  resume_if_exists: True
  checkpoint_callback_params:
    save_top_k: 1
    every_n_train_steps: 10
    model_parallel_size: ${multiply:${distributed_strategy.tensor_model_parallel_size}, ${distributed_strategy.pipeline_model_parallel_size}}

distributed_strategy:
  tensor_model_parallel_size: 4
  pipeline_model_parallel_size: 2
  zero1: True
  sequence_parallel: True

data:
  micro_batch_size: 1
  global_batch_size: 8

model:
  num_layers: 4
  hidden_size: 64
  optim:
    name: adamw_fp32OptState
    lr: 1.5e-4
    sched:
      name: LinearAnnealingWithWarmUp
      warmup_steps: 10
      max_steps: ${trainer.max_steps}

precision:
  type: mixed_precision

compiler_flags: '--model-type transformer'
neuron_rt_exec_timeout: 100
"""


@pytest.fixture()
def cfg(tmp_path):
    p = tmp_path / "conf.yaml"
    p.write_text(REFERENCE_STYLE_YAML)
    return load_config(p)


def test_interpolation(cfg):
    assert cfg.exp_manager.checkpoint_callback_params.model_parallel_size == 8
    assert cfg.model.optim.sched.max_steps == 100


def test_attr_and_path_access(cfg):
    assert cfg.distributed_strategy.tensor_model_parallel_size == 4
    assert cfg.get_path("model.optim.lr") == 1.5e-4
    assert cfg.get_path("model.not.there", "dflt") == "dflt"


def test_neuron_keys_tolerated(cfg):
    # Neuron-only knobs accepted without error
    assert cfg.compiler_flags == "--model-type transformer"


def test_batch_schedule(cfg):
    # world 16: dp = 16/(4*2) = 2; num_micro = 8/(1*2) = 4  (reference base.py:54-57)
    sched = batch_schedule(cfg, 16)
    assert sched == {
        "dp_size": 2,
        "num_microbatches": 4,
        "micro_batch_size": 1,
        "global_batch_size": 8,
    }


def test_validation_rejects_bad_configs():
    with pytest.raises(ValueError):
        load_config(
            {
                "distributed_strategy": {"sequence_parallel": True, "tensor_model_parallel_size": 1},
            }
        )
    with pytest.raises(ValueError):
        load_config(
            {
                "distributed_strategy": {
                    "pipeline_model_parallel_size": 2,
                    "virtual_pipeline_model_parallel_size": 2,
                },
                "model": {"num_layers": 6},
            }
        )
    with pytest.raises(ValueError):
        load_config({"model": {"moe": {"dropless": True, "capacity_factor": 2.0}}})


def test_overrides(tmp_path):
    p = tmp_path / "conf.yaml"
    p.write_text(REFERENCE_STYLE_YAML)
    cfg = load_config(p, overrides={"model.num_layers": 2, "trainer.max_steps": 5})
    assert cfg.model.num_layers == 2
    assert cfg.model.optim.sched.max_steps == 5


def test_all_shipped_configs_load_and_build():
    """Every examples/conf YAML must load through the reference-schema loader
    and produce a valid model config + batch schedule (catches key drift)."""
    import glob

    from neuronx_distributed_training_tpu.config.loader import (
        batch_schedule,
        load_config,
    )
    from neuronx_distributed_training_tpu.trainer.loop import build_model
    from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

    configs = sorted(glob.glob("examples/conf/*.yaml"))
    assert len(configs) >= 20  # parity-class config pack
    for path in configs:
        cfg = load_config(path)
        model_cfg, loss_fn, init_fn, specs_fn = build_model(cfg, DtypePolicy())
        assert model_cfg.num_layers > 0, path
        ds = dict(cfg.get("distributed_strategy", {}) or {})
        n_needed = (int(ds.get("tensor_model_parallel_size", 1))
                    * int(ds.get("pipeline_model_parallel_size", 1))
                    * int(ds.get("context_parallel_size", 1)))
        sched = batch_schedule(cfg, n_needed)
        assert sched["num_microbatches"] >= 1, path
        # specs build without touching devices
        specs = specs_fn()
        assert "layers" in specs, path
