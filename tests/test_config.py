import pytest

from neuronx_distributed_training_tpu.config.loader import (
    batch_schedule,
    load_config,
    validate_config,
)

REFERENCE_STYLE_YAML = """
name: hf_llama
model_source: hf
seed: 1234

trainer:
  max_steps: 100
  log_every_n_steps: 10
  gradient_clip_val: 1.0

exp_manager:
  exp_dir: /tmp/exp
  resume_if_exists: True
  checkpoint_callback_params:
    save_top_k: 1
    every_n_train_steps: 10
    model_parallel_size: ${multiply:${distributed_strategy.tensor_model_parallel_size}, ${distributed_strategy.pipeline_model_parallel_size}}

distributed_strategy:
  tensor_model_parallel_size: 4
  pipeline_model_parallel_size: 2
  zero1: True
  sequence_parallel: True

data:
  micro_batch_size: 1
  global_batch_size: 8

model:
  num_layers: 4
  hidden_size: 64
  optim:
    name: adamw_fp32OptState
    lr: 1.5e-4
    sched:
      name: LinearAnnealingWithWarmUp
      warmup_steps: 10
      max_steps: ${trainer.max_steps}

precision:
  type: mixed_precision

compiler_flags: '--model-type transformer'
neuron_rt_exec_timeout: 100
"""


@pytest.fixture()
def cfg(tmp_path):
    p = tmp_path / "conf.yaml"
    p.write_text(REFERENCE_STYLE_YAML)
    return load_config(p)


def test_interpolation(cfg):
    assert cfg.exp_manager.checkpoint_callback_params.model_parallel_size == 8
    assert cfg.model.optim.sched.max_steps == 100


def test_attr_and_path_access(cfg):
    assert cfg.distributed_strategy.tensor_model_parallel_size == 4
    assert cfg.get_path("model.optim.lr") == 1.5e-4
    assert cfg.get_path("model.not.there", "dflt") == "dflt"


def test_neuron_keys_tolerated(cfg):
    # Neuron-only knobs accepted without error
    assert cfg.compiler_flags == "--model-type transformer"


def test_batch_schedule(cfg):
    # world 16: dp = 16/(4*2) = 2; num_micro = 8/(1*2) = 4  (reference base.py:54-57)
    sched = batch_schedule(cfg, 16)
    assert sched == {
        "dp_size": 2,
        "num_microbatches": 4,
        "micro_batch_size": 1,
        "global_batch_size": 8,
    }


def test_validation_rejects_bad_configs():
    with pytest.raises(ValueError):
        load_config(
            {
                "distributed_strategy": {"sequence_parallel": True, "tensor_model_parallel_size": 1},
            }
        )
    with pytest.raises(ValueError):
        load_config(
            {
                "distributed_strategy": {
                    "pipeline_model_parallel_size": 2,
                    "virtual_pipeline_model_parallel_size": 2,
                },
                "model": {"num_layers": 6},
            }
        )
    with pytest.raises(ValueError):
        load_config({"model": {"moe": {"dropless": True, "capacity_factor": 2.0}}})


def test_overrides(tmp_path):
    p = tmp_path / "conf.yaml"
    p.write_text(REFERENCE_STYLE_YAML)
    cfg = load_config(p, overrides={"model.num_layers": 2, "trainer.max_steps": 5})
    assert cfg.model.num_layers == 2
    assert cfg.model.optim.sched.max_steps == 5


def test_all_shipped_configs_load_and_build():
    """Every examples/conf YAML must load through the reference-schema loader
    and produce a valid model config + batch schedule (catches key drift)."""
    import glob

    from neuronx_distributed_training_tpu.config.loader import (
        batch_schedule,
        load_config,
    )
    from neuronx_distributed_training_tpu.trainer.loop import build_model
    from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

    configs = sorted(glob.glob("examples/conf/*.yaml"))
    assert len(configs) >= 20  # parity-class config pack
    for path in configs:
        cfg = load_config(path)
        model_cfg, loss_fn, init_fn, specs_fn = build_model(cfg, DtypePolicy())
        assert model_cfg.num_layers > 0, path
        ds = dict(cfg.get("distributed_strategy", {}) or {})
        n_needed = (int(ds.get("tensor_model_parallel_size", 1))
                    * int(ds.get("pipeline_model_parallel_size", 1))
                    * int(ds.get("context_parallel_size", 1)))
        sched = batch_schedule(cfg, n_needed)
        assert sched["num_microbatches"] >= 1, path
        # specs build without touching devices
        specs = specs_fn()
        assert "layers" in specs, path


class TestValidationCatalog:
    """The central unsupported-combination catalog (reference
    megatron_base_model.py:71-129) — every rejection carries a curated,
    actionable message and fires at load time, before any compilation."""

    def _base(self, **over):
        cfg = {
            "distributed_strategy": {"tensor_model_parallel_size": 1},
            "data": {"global_batch_size": 8, "micro_batch_size": 1,
                     "seq_length": 64},
            "model": {"num_layers": 4, "num_attention_heads": 4},
        }
        for dotted, v in over.items():
            cur = cfg
            parts = dotted.split(".")
            for p in parts[:-1]:
                cur = cur.setdefault(p, {})
            cur[parts[-1]] = v
        return cfg

    def _expect(self, match, **over):
        with pytest.raises(ValueError, match=match):
            load_config(self._base(**over))

    def test_sp_without_tp(self):
        self._expect("sequence_parallel requires",
                     **{"distributed_strategy.sequence_parallel": True})

    def test_vp_without_pp(self):
        self._expect("virtual pipeline requires",
                     **{"distributed_strategy.virtual_pipeline_model_parallel_size": 2})

    def test_layers_not_divisible_by_pp_vp(self):
        self._expect("divide evenly into pp",
                     **{"distributed_strategy.pipeline_model_parallel_size": 3})

    def test_gbs_not_divisible_by_mbs(self):
        self._expect("not divisible by micro_batch_size",
                     **{"data.micro_batch_size": 3})

    def test_moe_groups_vs_pp_vp(self):
        self._expect("MoE\\+dense groups",
                     **{"model.moe.moe_frequency": 2, "model.num_layers": 4,
                        "distributed_strategy.pipeline_model_parallel_size": 4,
                        "model.fusions.ring_attention": True})

    def test_moe_frequency_must_divide_layers(self):
        self._expect("multiple of\\s+moe.moe_frequency",
                     **{"model.moe.moe_frequency": 3, "model.num_layers": 4})

    def test_cp_without_cp_aware_attention(self):
        self._expect("context-parallel attention",
                     **{"distributed_strategy.context_parallel_size": 2,
                        "model.fusions.flash_attention": True})

    def test_cp_seq_divisibility(self):
        self._expect("divisible by\\s+context_parallel_size",
                     **{"distributed_strategy.context_parallel_size": 4,
                        "model.fusions.ring_attention": True,
                        "data.seq_length": 30})

    def test_zigzag_under_pp(self):
        self._expect("zigzag_ring_attention is not supported under pipeline",
                     **{"model.fusions.zigzag_ring_attention": True,
                        "distributed_strategy.pipeline_model_parallel_size": 2,
                        "model.num_layers": 4})

    def test_zigzag_with_sliding_window(self):
        self._expect("does not support sliding_window",
                     **{"model.fusions.zigzag_ring_attention": True,
                        "model.sliding_window": 1024})

    def test_zigzag_seq_two_cp(self):
        self._expect("divisible\\s+by 2\\*context_parallel_size",
                     **{"model.fusions.zigzag_ring_attention": True,
                        "distributed_strategy.context_parallel_size": 2,
                        "data.seq_length": 34})

    def test_ulysses_head_budget(self):
        self._expect("head budget",
                     **{"model.fusions.ulysses_attention": True,
                        "distributed_strategy.context_parallel_size": 8,
                        "model.num_attention_heads": 4})

    def test_unknown_precision_regime(self):
        self._expect("unknown precision.type",
                     **{"precision.type": "fp8_who_knows"})

    def test_two_alignment_strategies(self):
        self._expect("exactly one",
                     **{"model_alignment_strategy.dpo.beta": 0.1,
                        "model_alignment_strategy.kto.beta": 0.1})

    def test_moe_dropless_capacity_conflict(self):
        self._expect("dropless",
                     **{"model.moe.dropless": True,
                        "model.moe.capacity_factor": 1.5})

    def test_unknown_block_type(self):
        self._expect("transformer_block_type",
                     **{"model.transformer_block_type": "sandwich"})

    def test_normformer_moe_conflict(self):
        self._expect("dense-only",
                     **{"model.transformer_block_type": "normformer",
                        "model.moe.num_experts": 4})

    def test_typod_alignment_string(self):
        self._expect("unknown model_alignment_strategy",
                     **{"model_alignment_strategy": "dp0"})

    def test_alignment_block_without_known_name(self):
        self._expect("names none",
                     **{"model_alignment_strategy.ppo.beta": 0.1})

    def test_nested_alignment_rejected(self):
        self._expect("config ROOT",
                     **{"model.model_alignment_strategy": "dpo"})

    def test_segment_mask_under_cp_rejected(self):
        self._expect("segment_mask",
                     **{"model_alignment_strategy.sft.segment_mask": True,
                        "distributed_strategy.context_parallel_size": 2,
                        "model.fusions.ring_attention": True})

    def test_segment_mask_with_cp_fusion_rejected(self):
        # cp == 1 but a CP fusion enabled still trips the trace-time path
        self._expect("segment_mask",
                     **{"model_alignment_strategy.sft.segment_mask": True,
                        "model.fusions.ulysses_attention": True})

    def test_segment_mask_flash_only_passes(self):
        load_config(self._base(
            **{"model_alignment_strategy.sft.segment_mask": True,
               "model_alignment_strategy.sft.packing": True,
               "model.fusions.flash_attention": True}))

    def test_blockwise_cp_under_pp_nonsmooth_seq_rejected(self):
        # prime-ish seq len under CP x PP would degrade the blockwise body to
        # a tiny kv block and an s-step scan — must die at load time
        self._expect("smoother length",
                     **{"distributed_strategy.context_parallel_size": 2,
                        "distributed_strategy.pipeline_model_parallel_size": 2,
                        "model.fusions.ring_attention": True,
                        "model.num_layers": 4,
                        "data.seq_length": 2 * 1019})  # 2038 = 2 x prime

    def test_blockwise_cp_under_pp_smooth_seq_passes(self):
        load_config(self._base(
            **{"distributed_strategy.context_parallel_size": 2,
               "distributed_strategy.pipeline_model_parallel_size": 2,
               "model.fusions.ring_attention": True,
               "model.num_layers": 4,
               "data.seq_length": 2048}))



class TestPipelineScheduleKnob:
    """distributed_strategy.pipeline.schedule validation (the 1F1B knob)."""

    _base = TestValidationCatalog._base
    _expect = TestValidationCatalog._expect

    def test_unknown_schedule_rejected(self):
        self._expect("pipeline.schedule",
                     **{"distributed_strategy.pipeline.schedule": "gpipe",
                        "distributed_strategy.pipeline_model_parallel_size": 2})

    def test_unknown_pipeline_key_rejected(self):
        self._expect("unknown distributed_strategy.pipeline keys",
                     **{"distributed_strategy.pipeline.shedule": "1f1b",
                        "distributed_strategy.pipeline_model_parallel_size": 2})

    def test_1f1b_requires_pp(self):
        self._expect("requires",
                     **{"distributed_strategy.pipeline.schedule": "1f1b"})

    def test_1f1b_rejects_vp(self):
        self._expect("virtual",
                     **{"distributed_strategy.pipeline.schedule": "1f1b",
                        "distributed_strategy.pipeline_model_parallel_size": 2,
                        "distributed_strategy.virtual_pipeline_model_parallel_size": 2,
                        "model.num_layers": 4})

    def test_1f1b_rejects_cp(self):
        self._expect("context parallelism",
                     **{"distributed_strategy.pipeline.schedule": "1f1b",
                        "distributed_strategy.pipeline_model_parallel_size": 2,
                        "distributed_strategy.context_parallel_size": 2,
                        "model.fusions.ring_attention": True,
                        "data.seq_length": 1024})

    def test_1f1b_rejects_preference_alignment(self):
        self._expect("token-level CE",
                     **{"distributed_strategy.pipeline.schedule": "1f1b",
                        "distributed_strategy.pipeline_model_parallel_size": 2,
                        "model_alignment_strategy": "dpo"})

    def test_1f1b_rejects_lora(self):
        self._expect("LoRA",
                     **{"distributed_strategy.pipeline.schedule": "1f1b",
                        "distributed_strategy.pipeline_model_parallel_size": 2,
                        "model.lora.r": 8})

    def test_valid_schedules_load(self):
        for sched in ("auto", "1f1b", "1f1b-zb", "wavefront"):
            load_config(self._base(
                **{"distributed_strategy.pipeline.schedule": sched,
                   "distributed_strategy.pipeline_model_parallel_size": 2}))

    def test_interleaved_loads_with_vp(self):
        load_config(self._base(
            **{"distributed_strategy.pipeline.schedule": "1f1b-interleaved",
               "distributed_strategy.pipeline_model_parallel_size": 2,
               "distributed_strategy.virtual_pipeline_model_parallel_size": 2,
               "model.num_layers": 4}))

    def test_interleaved_rejects_vp1(self):
        self._expect("nothing to interleave",
                     **{"distributed_strategy.pipeline.schedule":
                        "1f1b-interleaved",
                        "distributed_strategy.pipeline_model_parallel_size": 2})

    def test_zb_rejects_vp(self):
        self._expect("1f1b-interleaved",
                     **{"distributed_strategy.pipeline.schedule": "1f1b-zb",
                        "distributed_strategy.pipeline_model_parallel_size": 2,
                        "distributed_strategy."
                        "virtual_pipeline_model_parallel_size": 2,
                        "model.num_layers": 4})

    def test_zb_rejects_cp(self):
        self._expect("context parallelism",
                     **{"distributed_strategy.pipeline.schedule": "1f1b-zb",
                        "distributed_strategy.pipeline_model_parallel_size": 2,
                        "distributed_strategy.context_parallel_size": 2,
                        "model.fusions.ring_attention": True,
                        "data.seq_length": 1024})


class TestUnknownKnobRejection:
    """Every validated knob block rejects unknown keys with a did-you-mean
    hint — a typo'd knob must die at load, corrected, not silently run with
    defaults."""

    _base = TestValidationCatalog._base
    _expect = TestValidationCatalog._expect

    def test_pipeline_typo_hint(self):
        self._expect(r"did you mean: 'schedul' -> 'schedule'",
                     **{"distributed_strategy.pipeline.schedul": "1f1b",
                        "distributed_strategy.pipeline_model_parallel_size": 2})

    def test_pipeline_non_mapping_block(self):
        self._expect("distributed_strategy.pipeline must be a mapping",
                     **{"distributed_strategy.pipeline": "1f1b"})

    def test_pipeline_unknown_without_close_match(self):
        # far-off keys still rejected, just without a suggestion
        self._expect("unknown distributed_strategy.pipeline keys",
                     **{"distributed_strategy.pipeline.zzz": 1,
                        "distributed_strategy.pipeline_model_parallel_size": 2})

    def test_telemetry_typo_hint(self):
        self._expect(r"did you mean: 'spanss' -> 'spans'",
                     **{"exp_manager.telemetry.spanss": True})

    def test_telemetry_non_mapping_block(self):
        self._expect("exp_manager.telemetry must be a mapping",
                     **{"exp_manager.telemetry": [1, 2]})

    def test_telemetry_non_bool_knob(self):
        self._expect("must be a boolean",
                     **{"exp_manager.telemetry.mfu": "yes"})

    def test_health_typo_hint(self):
        self._expect(r"did you mean: 'polcy' -> 'policy'",
                     **{"exp_manager.telemetry.health.polcy": "halt"})

    def test_health_unknown_policy_value(self):
        self._expect("policy must be one of",
                     **{"exp_manager.telemetry.health.policy": "explode"})

    def test_health_non_mapping_block(self):
        self._expect("telemetry.health must be a mapping",
                     **{"exp_manager.telemetry.health": [1]})

    def test_graph_audit_knob_accepted(self):
        from neuronx_distributed_training_tpu.config.loader import load_config

        cfg = load_config(self._base(
            **{"exp_manager.telemetry.graph_audit": True}))
        from neuronx_distributed_training_tpu.telemetry import TelemetryConfig

        tc = TelemetryConfig.from_config(cfg.exp_manager.telemetry)
        assert tc.graph_audit is True
