"""Coordinated fleet control: consensus control word, command channel,
hang escape, exit-code table (trainer.control — docs/observability.md
"Fleet control").

The unit half pins the control-word fold semantics (bit OR, decision
priority, local-vs-fleet reason attribution), the operator command
parse/dedupe/ack machinery, the knob validation, and the exit-code table.
The live half drives real tiny-llama ``fit()`` runs: the consensus
alert-halt drill (local AND simulated-peer hosts stop at the same step
with a drained emergency save), operator commands landing mid-run, the
AOT-once + dispatch-ahead contracts with control enabled, and the
hang-escape path through the armed watchdog (an injected hung boundary
sync — the in-process test stubs ``os._exit``; the subprocess leg lives
in ``tools/elastic_drill.py --control-smoke``).
"""

from __future__ import annotations

import json
import time
from pathlib import Path

import pytest

from neuronx_distributed_training_tpu.config.loader import load_config
from neuronx_distributed_training_tpu.telemetry import TelemetryConfig
from neuronx_distributed_training_tpu.trainer.control import (
    CONDITION_BITS,
    EXIT_ALERT_HALT,
    EXIT_ALL_CORRUPT,
    EXIT_CODES,
    EXIT_DATA_STALL,
    EXIT_ELASTIC_REFUSED,
    EXIT_HANG_ESCAPE,
    EXIT_HEALTH_HALT,
    EXIT_OK,
    ControlConfig,
    ControlPlane,
    append_command,
    commands_path,
    condition_names,
    exit_code_for_stop,
    exit_code_name,
    fold_word_fleet,
)


# ---------------------------------------------------------------------------
# knob validation
# ---------------------------------------------------------------------------


class TestControlConfig:
    def test_defaults_disabled(self):
        c = ControlConfig.from_config(None)
        assert not c.enabled and c.poll_commands and c.hang_escape
        assert c.max_trail == 64

    def test_bool_form(self):
        assert ControlConfig.from_config(True).enabled
        assert not ControlConfig.from_config(False).enabled

    def test_unknown_key_did_you_mean(self):
        with pytest.raises(ValueError, match="hang_escape"):
            ControlConfig.from_config({"hang_escap": True})

    def test_bad_values(self):
        with pytest.raises(ValueError, match="boolean"):
            ControlConfig.from_config({"enabled": "yes"})
        with pytest.raises(ValueError, match="integer"):
            ControlConfig.from_config({"max_trail": "many"})
        with pytest.raises(ValueError, match="integer"):
            ControlConfig.from_config({"max_trail": True})
        with pytest.raises(ValueError, match=">= 1"):
            ControlConfig.from_config({"max_trail": 0})
        with pytest.raises(ValueError, match="mapping"):
            ControlConfig.from_config([1, 2])

    def test_nested_in_telemetry(self):
        t = TelemetryConfig.from_config({"control": {"enabled": True}})
        assert t.control.enabled
        assert not TelemetryConfig.from_config({}).control.enabled

    def test_telemetry_bool_keeps_control_disabled(self):
        assert not TelemetryConfig.from_config(True).control.enabled

    def test_validated_at_config_load(self):
        with pytest.raises(ValueError, match="control"):
            load_config({
                "exp_manager": {"telemetry": {"control": {"enbled": True}}},
                "model": {"vocab_size": 8, "hidden_size": 8, "num_layers": 1,
                          "num_attention_heads": 1},
            })


# ---------------------------------------------------------------------------
# control-word semantics
# ---------------------------------------------------------------------------


class TestControlWord:
    def test_bits_distinct(self):
        bits = list(CONDITION_BITS.values())
        assert len(set(bits)) == len(bits)
        assert all(b and (b & (b - 1)) == 0 for b in bits)  # powers of two

    def test_condition_names_priority(self):
        w = CONDITION_BITS["alert_halt"] | CONDITION_BITS["health_halt"]
        assert condition_names(w) == ["health_halt", "alert_halt"]

    def test_fold_single_process_is_identity(self):
        # tier-1 runs single-process: the fold must be exact with zero
        # collective traffic
        w = CONDITION_BITS["preemption"] | CONDITION_BITS["dump"]
        assert fold_word_fleet(w) == w
        assert fold_word_fleet(0) == 0


def _plane(tmp_path, **kw):
    writes: list[dict] = []
    plane = ControlPlane(
        ControlConfig(enabled=True), tmp_path,
        write_run_summary=writes.append, **kw)
    return plane, writes


class TestDecisions:
    def test_no_conditions_no_decision(self, tmp_path):
        plane, writes = _plane(tmp_path)
        d = plane.boundary(4)
        assert not d.any and not d.stop and not d.halt
        assert writes == []  # an empty boundary writes nothing

    def test_local_stop_reason_wins(self, tmp_path):
        plane, writes = _plane(tmp_path)
        plane.request("preemption", "SIGTERM (preemption)")
        d = plane.boundary(6)
        assert d.stop and not d.halt
        assert d.conditions == ["preemption"]
        assert d.reason == "SIGTERM (preemption)" and d.source == "local"
        assert writes and writes[-1]["control"]["decisions"][-1]["stop"]

    def test_halt_beats_stop_and_suppresses_nothing(self, tmp_path):
        plane, _ = _plane(tmp_path)
        plane.request("alert_halt", "alert x")
        plane.request("health_halt", "nonfinite step 3")
        d = plane.boundary(8)
        assert d.halt and d.stop
        assert d.conditions[0] == "health_halt"
        assert d.reason == "nonfinite step 3"

    def test_remote_bit_reports_fleet_consensus(self, tmp_path):
        plane, _ = _plane(
            tmp_path, peer_words=lambda: CONDITION_BITS["alert_halt"])
        d = plane.boundary(2)
        assert d.stop and d.source == "fleet"
        assert d.reason.startswith("fleet consensus: alert_halt")

    def test_peer_words_failure_never_kills(self, tmp_path):
        def boom():
            raise RuntimeError("seam broke")

        plane, _ = _plane(tmp_path, peer_words=boom)
        plane.request("preemption", "notice")
        assert plane.boundary(1).stop  # local word still decides

    def test_oneshot_bits_clear_after_decision(self, tmp_path):
        plane, _ = _plane(tmp_path)
        plane.request("checkpoint_now", "operator")
        plane.request("dump", "operator")
        d = plane.boundary(2)
        assert d.checkpoint_now and d.dump and not d.stop
        d2 = plane.boundary(4)
        assert not d2.any  # consumed — no re-fire at the next boundary

    def test_stop_bits_persist(self, tmp_path):
        plane, _ = _plane(tmp_path)
        plane.request("operator_stop", "operator command stop")
        assert plane.boundary(2).stop
        assert plane.boundary(4).stop  # a stop never un-requests itself

    def test_trail_capped(self, tmp_path):
        plane = ControlPlane(ControlConfig(enabled=True, max_trail=3),
                             tmp_path)
        plane.request("preemption", "notice")
        for s in range(10):
            plane.boundary(s)
        assert len(plane.decisions) == 3

    def test_note_exit_names_condition(self, tmp_path):
        plane, writes = _plane(tmp_path)
        plane.note_exit("data_stall", "data_wait exceeded 30s")
        rec = writes[-1]["control"]["decisions"][-1]
        assert rec["conditions"] == ["data_stall"] and rec["exit"]


# ---------------------------------------------------------------------------
# operator command channel
# ---------------------------------------------------------------------------


class TestCommands:
    def test_append_and_accept(self, tmp_path):
        rec = append_command(tmp_path, "checkpoint_now", note="pre-maint")
        assert commands_path(tmp_path).exists()
        plane, writes = _plane(tmp_path)
        d = plane.boundary(2)
        assert d.checkpoint_now and not d.stop
        (ack,) = plane.commands
        assert ack["id"] == rec["id"] and ack["status"] == "accepted"
        assert ack["step"] == 2
        assert writes[-1]["control"]["commands"][-1]["status"] == "accepted"

    def test_unknown_command_refused_at_enqueue(self, tmp_path):
        with pytest.raises(ValueError, match="unknown control command"):
            append_command(tmp_path, "reboot")

    def test_stop_command_reason_and_source(self, tmp_path):
        append_command(tmp_path, "stop", note="maintenance window")
        plane, _ = _plane(tmp_path)
        d = plane.boundary(4)
        assert d.stop and d.source == "operator"
        assert "operator command stop" in d.reason
        assert "maintenance window" in d.reason

    def test_dedupe_by_id(self, tmp_path):
        rec = append_command(tmp_path, "dump")
        # replay the same line (an operator double-paste / a retried write)
        with open(commands_path(tmp_path), "a") as f:
            f.write(json.dumps(rec) + "\n")
        plane, _ = _plane(tmp_path)
        d = plane.boundary(2)
        assert d.dump
        statuses = [a["status"] for a in plane.commands]
        assert statuses == ["accepted", "duplicate"]

    def test_unknown_command_in_file_acked_unknown(self, tmp_path):
        with open_commands(tmp_path) as f:
            f.write(json.dumps({"id": "zz1", "command": "reboot"}) + "\n")
        plane, _ = _plane(tmp_path)
        d = plane.boundary(2)
        assert not d.any
        (ack,) = plane.commands
        assert ack["status"] == "unknown" and ack["command"] == "reboot"

    def test_malformed_line_acked_not_dropped(self, tmp_path):
        with open_commands(tmp_path) as f:
            f.write("{not json}\n")
        plane, _ = _plane(tmp_path)
        plane.boundary(2)
        (ack,) = plane.commands
        assert ack["status"] == "malformed"

    def test_torn_tail_line_waits_for_next_poll(self, tmp_path):
        append_command(tmp_path, "dump")
        with open_commands(tmp_path) as f:
            f.write('{"id": "abc", "command": "st')  # no newline: torn
        plane, _ = _plane(tmp_path)
        d = plane.boundary(2)
        assert d.dump and len(plane.commands) == 1
        with open_commands(tmp_path) as f:
            f.write('op"}\n')  # the writer finished the line
        d2 = plane.boundary(4)
        assert d2.stop  # the completed command lands at the NEXT poll

    def test_incremental_offsets(self, tmp_path):
        append_command(tmp_path, "dump")
        plane, _ = _plane(tmp_path)
        plane.boundary(2)
        append_command(tmp_path, "checkpoint_now")
        d = plane.boundary(4)
        assert d.checkpoint_now and not d.dump  # only the NEW command
        assert [a["command"] for a in plane.commands] == [
            "dump", "checkpoint_now"]

    def test_restarted_incarnation_never_replays_acted_commands(
            self, tmp_path):
        """A restarted run re-reads commands.jsonl from offset 0: a stop
        the previous incarnation already obeyed must come back as a
        `duplicate`, not re-stop the run into a stop/restart loop — the
        dedupe set re-seeds from the ack trail in run_summary.json."""
        append_command(tmp_path, "stop")
        plane1, _ = _plane(tmp_path)
        assert plane1.boundary(2).stop
        # persist the trail the way the trainer does
        (tmp_path / "run_summary.json").write_text(
            json.dumps({"control": plane1.trail()}))
        plane2, _ = _plane(tmp_path)
        d = plane2.boundary(1)
        assert not d.stop
        (ack,) = plane2.commands
        assert ack["status"] == "duplicate"

    def test_poll_disabled_ignores_commands(self, tmp_path):
        append_command(tmp_path, "stop")
        plane = ControlPlane(ControlConfig(enabled=True), tmp_path,
                             poll_commands=False)
        assert not plane.boundary(2).any  # non-rank-0 hosts never poll


def open_commands(run_dir):
    path = commands_path(run_dir)
    path.parent.mkdir(parents=True, exist_ok=True)
    return open(path, "a")


# ---------------------------------------------------------------------------
# exit-code table
# ---------------------------------------------------------------------------


class TestExitCodes:
    def test_codes_distinct_and_out_of_signal_range(self):
        codes = list(EXIT_CODES.values())
        assert len(set(codes)) == len(codes)
        tagged = [c for c in codes if c not in (0, 1)]
        # 128+signum is what a signal death reports; stay clear of it
        assert all(64 <= c < 128 for c in tagged), tagged

    def test_stop_class_mapping(self):
        assert exit_code_for_stop(None) == EXIT_OK
        assert exit_code_for_stop("preemption") == EXIT_OK
        assert exit_code_for_stop("operator_stop") == EXIT_OK
        assert exit_code_for_stop("max_time") == EXIT_OK
        assert exit_code_for_stop("health_halt") == EXIT_HEALTH_HALT
        assert exit_code_for_stop("alert_halt") == EXIT_ALERT_HALT
        assert exit_code_for_stop("data_stall") == EXIT_DATA_STALL

    def test_names_round_trip(self):
        assert exit_code_name(EXIT_HANG_ESCAPE) == "hang_escape"
        assert exit_code_name(EXIT_ALL_CORRUPT) == "all_corrupt"
        assert exit_code_name(EXIT_ELASTIC_REFUSED) == "elastic_refused"
        assert exit_code_name(7) == "7"


# ---------------------------------------------------------------------------
# hang-escape machinery (unit)
# ---------------------------------------------------------------------------


class TestHangEscapeUnit:
    def test_escape_runs_hooks_then_exits(self, tmp_path):
        from neuronx_distributed_training_tpu.telemetry import HangWatchdog

        events: list = []
        wd = HangWatchdog(0.05, None, abort=False)
        wd.arm_escape(EXIT_HANG_ESCAPE,
                      lambda what, step: events.append(("note", what, step)))
        wd._exit_fn = lambda code: events.append(("exit", code))
        with wd.guard("host_sync", 7):
            time.sleep(0.3)
        assert ("note", "host_sync", 7) in events
        assert ("exit", EXIT_HANG_ESCAPE) in events

    def test_hook_failure_never_blocks_exit(self):
        from neuronx_distributed_training_tpu.telemetry import HangWatchdog

        events: list = []

        def bad_hook(what, step):
            raise RuntimeError("hook broke")

        wd = HangWatchdog(0.05, None, abort=False)
        wd.arm_escape(EXIT_HANG_ESCAPE, bad_hook)
        wd._exit_fn = lambda code: events.append(code)
        with wd.guard("host_sync", 1):
            time.sleep(0.3)
        assert events == [EXIT_HANG_ESCAPE]

    def test_unarmed_watchdog_keeps_legacy_behavior(self):
        from neuronx_distributed_training_tpu.telemetry import HangWatchdog

        wd = HangWatchdog(0.05, None, abort=False)
        with wd.guard("host_sync", 1):
            time.sleep(0.3)
        assert wd.fired and wd.escape_code is None  # no exit attempted


# ---------------------------------------------------------------------------
# data transient-I/O retry (satellite)
# ---------------------------------------------------------------------------


class TestDataIoRetry:
    def test_classifier_walks_cause_chain(self):
        import errno

        from neuronx_distributed_training_tpu.data.loader import (
            is_transient_io_error,
        )

        inner = OSError(errno.ESTALE, "stale NFS handle")
        outer = RuntimeError("arrow read failed")
        outer.__cause__ = inner
        assert is_transient_io_error(outer)
        assert is_transient_io_error(TimeoutError("slow store"))
        assert not is_transient_io_error(KeyError("bad column"))
        assert not is_transient_io_error(OSError(errno.ENOENT, "gone"))

    def test_fetch_retries_then_succeeds(self):
        import errno

        import numpy as np

        from neuronx_distributed_training_tpu.data.loader import (
            SyntheticDataModule,
        )

        dm = SyntheticDataModule(vocab_size=16, seq_len=8,
                                 global_batch_size=2,
                                 io_retry_backoff_seconds=0.01)
        real = dm.fetch_rows
        fails = {"n": 2}

        def flaky(idx):
            if fails["n"] > 0:
                fails["n"] -= 1
                raise OSError(errno.EIO, "flaky mount")
            return real(idx)

        dm.fetch_rows = flaky
        batch = next(dm.global_batches())
        assert isinstance(batch["input_ids"], np.ndarray)
        assert dm.io_retry_count == 2
        assert dm.last_io_activity() > 0

    def test_non_transient_raises_immediately(self):
        from neuronx_distributed_training_tpu.data.loader import (
            SyntheticDataModule,
        )

        dm = SyntheticDataModule(vocab_size=16, seq_len=8,
                                 global_batch_size=2)

        def broken(idx):
            raise KeyError("missing column")

        dm.fetch_rows = broken
        with pytest.raises(KeyError):
            next(dm.global_batches())
        assert dm.io_retry_count == 0

    def test_retries_exhausted_reraises_the_real_error(self):
        import errno

        from neuronx_distributed_training_tpu.data.loader import (
            SyntheticDataModule,
        )

        dm = SyntheticDataModule(vocab_size=16, seq_len=8,
                                 global_batch_size=2, io_retries=2,
                                 io_retry_backoff_seconds=0.01)

        def always(idx):
            raise OSError(errno.EIO, "dead mount")

        dm.fetch_rows = always
        with pytest.raises(OSError, match="dead mount"):
            next(dm.global_batches())
        assert dm.io_retry_count == 2  # bounded — not infinite

    def test_stall_deferred_while_retrying(self):
        """DataStallError fires only after retries are exhausted: a fresh
        activity timestamp from the retry loop defers the stall verdict."""
        import threading

        from neuronx_distributed_training_tpu.data.loader import (
            DataStallError,
            PrefetchIterator,
        )

        activity = {"t": 0.0}
        release = threading.Event()

        def slow():
            release.wait(10.0)
            yield {"x": 1}

        it = PrefetchIterator(slow(), timeout_seconds=0.3,
                              activity_fn=lambda: activity["t"])

        def keep_active():
            for _ in range(8):
                activity["t"] = time.monotonic()
                time.sleep(0.1)
            release.set()

        t = threading.Thread(target=keep_active)
        t.start()
        try:
            assert next(it) == {"x": 1}  # survived ~0.8s > timeout 0.3s
        finally:
            t.join()
            it.close()

    def test_stall_deferred_through_backoff_longer_than_timeout(self):
        """A single backoff delay LONGER than the stall timeout must still
        defer the verdict: the retry loop refreshes its activity timestamp
        in sub-timeout slices while sleeping."""
        import errno

        from neuronx_distributed_training_tpu.data.loader import (
            PrefetchIterator,
            SyntheticDataModule,
        )

        dm = SyntheticDataModule(vocab_size=16, seq_len=8,
                                 global_batch_size=2, io_retries=1,
                                 io_retry_backoff_seconds=0.8)
        real = dm.fetch_rows
        fails = {"n": 1}

        def flaky(idx):
            if fails["n"] > 0:
                fails["n"] -= 1
                raise OSError(errno.EIO, "flaky mount")
            return real(idx)

        dm.fetch_rows = flaky
        it = PrefetchIterator(dm.global_batches(), timeout_seconds=0.3,
                              activity_fn=dm.last_io_activity)
        try:
            batch = next(it)  # 0.8s backoff > 0.3s timeout: no stall
            assert batch["input_ids"].shape == (2, 8)
        finally:
            it.close()

    def test_stall_fires_when_activity_goes_silent(self):
        from neuronx_distributed_training_tpu.data.loader import (
            DataStallError,
            PrefetchIterator,
        )

        def never():
            time.sleep(30)
            yield {}

        it = PrefetchIterator(never(), timeout_seconds=0.2,
                              activity_fn=lambda: 0.0)
        with pytest.raises(DataStallError):
            next(it)
        it.close()


# ---------------------------------------------------------------------------
# live fit() integration
# ---------------------------------------------------------------------------


def _ctl_cfg(tmp_path, **over):
    cfg = {
        "name": "ctl",
        "trainer": {"max_steps": 6, "log_every_n_steps": 2},
        "exp_manager": {"exp_dir": str(tmp_path / "exp"),
                        "create_tensorboard_logger": False,
                        "log_files": False,
                        "telemetry": {"control": {"enabled": True}}},
        "distributed_strategy": {"tensor_model_parallel_size": 1},
        "data": {"global_batch_size": 8, "micro_batch_size": 1,
                 "seq_length": 32, "synthetic": True},
        "model": {"vocab_size": 128, "hidden_size": 64,
                  "intermediate_size": 128, "num_layers": 2,
                  "num_attention_heads": 4, "num_key_value_heads": 2,
                  "max_position_embeddings": 32,
                  "optim": {"name": "adamw_fp32OptState", "lr": 1e-3}},
        "precision": {"type": "mixed_precision"},
    }
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(cfg.get(k), dict):
            cfg[k] = {**cfg[k], **v}
        else:
            cfg[k] = v
    return load_config(cfg)


def _summary(t):
    return json.loads(
        (Path(str(t.exp.log_dir)) / "run_summary.json").read_text())


class TestControlLive:
    def test_consensus_alert_halt_same_step_and_emergency_save(
            self, tmp_path, devices8):
        """The acceptance scenario: an action:halt alert on a
        NON-replicated metric (data_wait — a host-local span) stops the
        deciding host at a deterministic boundary WITH a drained emergency
        save, and a second simulated host that sees only the folded
        control word stops at the SAME step."""
        from neuronx_distributed_training_tpu.trainer.control import (
            CONDITION_BITS,
        )
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        # leg 1: the deciding host (alert fires locally)
        cfg = _ctl_cfg(
            tmp_path / "local",
            exp_manager={
                "exp_dir": str(tmp_path / "local"),
                "create_tensorboard_logger": False, "log_files": False,
                "checkpoint_callback_params": {
                    "every_n_train_steps": 10, "save_top_k": 2,
                    "async_checkpointing": False},
                "telemetry": {
                    "control": {"enabled": True},
                    "alerts": [{"metric": "data_wait", "threshold": 1e-12,
                                "action": "halt", "name": "dw"}],
                }})
        t = Trainer.from_config(cfg)
        t.fit()
        assert t.step == 2  # the first deterministic boundary
        assert t.stop_class == "alert_halt"
        rs = _summary(t)
        assert rs["elastic"]["stop_reason"].startswith("alert dw:")
        assert rs["elastic"]["stop_class"] == "alert_halt"
        dec = rs["control"]["decisions"][-1]
        assert dec["step"] == 2 and dec["stop"]
        assert dec["conditions"] == ["alert_halt"]
        assert dec["source"] == "local"
        # the drained emergency save exists at the stop step even though
        # the cadence (every 10) never reached it
        ck = Path(str(t.exp.log_dir)) / "checkpoints"
        assert "2" in {p.name for p in ck.iterdir()}

        # leg 2: a simulated OTHER host — no local condition, only the
        # folded word — stops at the SAME boundary, honestly attributed
        cfg2 = _ctl_cfg(tmp_path / "peer")
        t2 = Trainer.from_config(cfg2, enable_checkpointing=False)
        t2.control_peer_words = lambda: CONDITION_BITS["alert_halt"]
        t2.fit()
        assert t2.step == 2  # SAME deciding step
        rs2 = _summary(t2)
        assert rs2["elastic"]["stop_reason"].startswith("fleet consensus:")
        dec2 = rs2["control"]["decisions"][-1]
        assert dec2["source"] == "fleet" and dec2["step"] == 2

    def test_operator_stop_command(self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _ctl_cfg(tmp_path)
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        append_command(Path(str(t.exp.log_dir)), "stop", note="maint")
        t.fit()
        assert t.step == 2 and t.stop_class == "operator_stop"
        rs = _summary(t)
        assert "operator command stop" in rs["elastic"]["stop_reason"]
        (ack,) = rs["control"]["commands"]
        assert ack["status"] == "accepted" and ack["step"] == 2

    def test_operator_checkpoint_now_off_cadence(self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _ctl_cfg(
            tmp_path,
            exp_manager={
                "exp_dir": str(tmp_path / "exp"),
                "create_tensorboard_logger": False, "log_files": False,
                "checkpoint_callback_params": {
                    "every_n_train_steps": 10, "save_top_k": 3,
                    "async_checkpointing": False},
                "telemetry": {"control": {"enabled": True}}})
        t = Trainer.from_config(cfg)
        append_command(Path(str(t.exp.log_dir)), "checkpoint_now")
        t.fit()
        assert t.step == 6  # run completes — checkpoint_now never stops
        ck = Path(str(t.exp.log_dir)) / "checkpoints"
        steps = {p.name for p in ck.iterdir() if p.name.isdigit()}
        assert "2" in steps  # the off-cadence operator save
        rs = _summary(t)
        dec = [d for d in rs["control"]["decisions"]
               if d.get("checkpoint_now")]
        assert dec and dec[0]["step"] == 2

    def test_operator_dump_writes_control_bundle(self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _ctl_cfg(tmp_path)
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        append_command(Path(str(t.exp.log_dir)), "dump")
        t.fit()
        assert t.step == 6
        d = Path(str(t.exp.log_dir))
        bundles = sorted(p.name for p in d.glob("control_*"))
        assert bundles == ["control_00000002"]
        payload = json.loads((d / bundles[0] / "anomaly.json").read_text())
        assert payload["kind"] == "control"
        assert payload["control"]["conditions"] == ["dump"]

    def test_health_halt_folds_through_consensus(self, tmp_path, devices8):
        """health policy=halt with control enabled: the halt bit rides the
        word, the decision halts WITHOUT a checkpoint, and the exit class
        maps to EXIT_HEALTH_HALT."""
        from neuronx_distributed_training_tpu.trainer.control import (
            CONDITION_BITS,
        )
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        # simulate the halt arriving from ANOTHER host (replicated health
        # counters make the local path identical; the peer form also pins
        # the no-checkpoint semantics for a remote-only halt)
        cfg = _ctl_cfg(
            tmp_path,
            exp_manager={
                "exp_dir": str(tmp_path / "exp"),
                "create_tensorboard_logger": False, "log_files": False,
                "checkpoint_callback_params": {
                    "every_n_train_steps": 10, "save_top_k": 2,
                    "async_checkpointing": False},
                "telemetry": {"control": {"enabled": True}}})
        t = Trainer.from_config(cfg)
        t.control_peer_words = lambda: CONDITION_BITS["health_halt"]
        t.fit()
        assert t.step == 2 and t.stop_class == "health_halt"
        assert exit_code_for_stop(t.stop_class) == EXIT_HEALTH_HALT
        ck = Path(str(t.exp.log_dir)) / "checkpoints"
        steps = ({p.name for p in ck.iterdir() if p.name.isdigit()}
                 if ck.exists() else set())
        assert "2" not in steps  # halt NEVER checkpoints the poisoned state

    def test_aot_once_and_dispatch_ahead_with_control(self, tmp_path,
                                                      devices8):
        """Control enabled must add ZERO host syncs between boundaries and
        keep the AOT-once contract — the same instrumented-step proof the
        fleet layer pins, with the control plane on."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _ctl_cfg(
            tmp_path,
            trainer={"max_steps": 6, "log_every_n_steps": 3},
            exp_manager={
                "exp_dir": str(tmp_path / "exp"),
                "create_tensorboard_logger": False, "log_files": False,
                "telemetry": {"control": {"enabled": True},
                              "fleet": {"enabled": True},
                              "alerts": [{"metric": "loss",
                                          "threshold": 1e9}]}})
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        assert not hasattr(t.train_step, "lower") or True  # pre-census

        conversions: list[int] = []

        class _Scalar:
            def __init__(self, step):
                self.step = step

            def __float__(self):
                conversions.append(self.step)
                return 1.0

        real_params, real_opt = t.params, t.opt_state

        def fake_step(params, opt_state, batch, key):
            return real_params, real_opt, {"loss": _Scalar(t.step),
                                           "grad_norm": _Scalar(t.step)}

        t.train_step = fake_step
        t.fit()
        assert conversions, "boundaries must fetch metrics"
        assert set(conversions) == {2, 5}, conversions

    def test_aot_once_with_control_enabled(self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _ctl_cfg(
            tmp_path,
            exp_manager={
                "exp_dir": str(tmp_path / "exp"),
                "create_tensorboard_logger": False, "log_files": False,
                "telemetry": {"control": {"enabled": True},
                              "compile_census": True}})
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        t.fit()
        # the census swapped in the AOT executable; the control plane
        # added no recompile (the retrace detector would have logged)
        assert not hasattr(t.train_step, "lower")
        assert t.step == 6

    def test_hang_escape_through_real_fit(self, tmp_path, devices8):
        """An injected hung boundary sync (the dead-peer stand-in): the
        armed watchdog dumps the hang bundle, writes the dying beacon +
        control exit note, and calls the exit fn with EXIT_HANG_ESCAPE.
        The exit fn is stubbed (the real ``os._exit`` leg lives in
        ``elastic_drill.py --control-smoke``); the injected hang then
        unblocks and the run finishes, letting us assert the artifacts."""
        from neuronx_distributed_training_tpu.telemetry import (
            flight_recorder,
        )
        from neuronx_distributed_training_tpu.trainer.elastic import (
            FaultInjector,
        )
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _ctl_cfg(
            tmp_path,
            trainer={"max_steps": 4, "log_every_n_steps": 2},
            exp_manager={
                "exp_dir": str(tmp_path / "exp"),
                "create_tensorboard_logger": False, "log_files": False,
                "telemetry": {
                    "control": {"enabled": True},
                    "fleet": {"enabled": True},
                    "health": {"watchdog_timeout_seconds": 0.5,
                               "watchdog_abort": False},
                }})
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        t.fault_injector = FaultInjector(at_step=2, mode="hang",
                                         phase="sync", hang_seconds=2.0)
        exits: list[int] = []
        orig_init = flight_recorder.HangWatchdog.arm_escape

        def spy_arm(self, code, *hooks):
            orig_init(self, code, *hooks)
            self._exit_fn = exits.append

        try:
            flight_recorder.HangWatchdog.arm_escape = spy_arm
            t.fit()
        finally:
            flight_recorder.HangWatchdog.arm_escape = orig_init
        assert exits == [EXIT_HANG_ESCAPE]
        d = Path(str(t.exp.log_dir))
        assert sorted(p.name for p in d.glob("hang_*")) == ["hang_00000002"]
        beacons = [json.loads(l) for l in
                   (d / "fleet" / "host_0.jsonl").read_text().splitlines()]
        dying = [b for b in beacons if b.get("last_exception")]
        assert dying and "hang escape" in dying[0]["last_exception"]
        rs = json.loads((d / "run_summary.json").read_text())
        note = [x for x in rs["control"]["decisions"] if x.get("exit")]
        assert note and note[0]["conditions"] == ["hang_escape"]

    def test_io_retries_surface_as_boundary_metric(self, tmp_path,
                                                   devices8):
        import errno

        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _ctl_cfg(
            tmp_path,
            data={"global_batch_size": 8, "micro_batch_size": 1,
                  "seq_length": 32, "synthetic": True,
                  "io_retry_backoff_seconds": 0.01})
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        assert t.data_module.io_retry_backoff_seconds == 0.01  # knob landed
        real = t.data_module.fetch_rows
        fails = {"n": 2}

        def flaky(idx):
            if fails["n"] > 0:
                fails["n"] -= 1
                raise OSError(errno.EIO, "flaky mount")
            return real(idx)

        t.data_module.fetch_rows = flaky
        t.fit()
        assert t.step == 6
        recs = [json.loads(l) for l in
                (Path(str(t.exp.log_dir)) / "metrics.jsonl")
                .read_text().splitlines()]
        vals = [r.get("data/io_retries") for r in recs
                if "data/io_retries" in r]
        assert vals and vals[-1] == 2.0


# ---------------------------------------------------------------------------
# CLI surfaces
# ---------------------------------------------------------------------------


class TestControlDrill:
    @pytest.mark.slow
    def test_control_smoke_matrix(self, tmp_path, devices8):
        """The full acceptance matrix through real tiny-llama fit()s (the
        ``elastic_drill.py --control-smoke`` CI gate): consensus stop on
        both simulated hosts at the same step, subprocess hang escape with
        the real ``os._exit`` and the tagged code, bitwise resume."""
        import os
        import sys

        sys.path.insert(0, os.path.join(
            str(Path(__file__).parent.parent), "tools"))
        from elastic_drill import run_control_drill

        report = run_control_drill(tmp_path)
        assert report["ok"]
        assert report["hang_escape_code"] == EXIT_HANG_ESCAPE
        assert report["max_loss_diff"] == 0.0


class TestRunCtlCLI:
    def _load(self):
        import importlib.util
        import sys

        path = (Path(__file__).parent.parent / "tools" / "run_ctl.py")
        spec = importlib.util.spec_from_file_location("_run_ctl", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["_run_ctl"] = mod
        spec.loader.exec_module(mod)
        return mod

    def test_enqueue_json_last_line(self, tmp_path, capsys):
        mod = self._load()
        rc = mod.main([str(tmp_path), "checkpoint_now", "--note", "x",
                       "--json", "-"])
        assert rc == 0
        last = capsys.readouterr().out.strip().splitlines()[-1]
        payload = json.loads(last)
        assert payload["ok"] and payload["command"] == "checkpoint_now"
        # the enqueued line is on disk, parseable, with the same id
        (line,) = commands_path(tmp_path).read_text().splitlines()
        assert json.loads(line)["id"] == payload["id"]

    def test_list_joins_acks(self, tmp_path, capsys):
        mod = self._load()
        rec = append_command(tmp_path, "stop")
        # a run recorded the ack in run_summary.json
        (tmp_path / "run_summary.json").write_text(json.dumps({
            "control": {"commands": [{"id": rec["id"], "command": "stop",
                                      "step": 4, "status": "accepted"}],
                        "decisions": []}}))
        rc = mod.main([str(tmp_path), "list", "--json", "-"])
        assert rc == 0
        last = capsys.readouterr().out.strip().splitlines()[-1]
        payload = json.loads(last)
        (cmd,) = payload["commands"]
        assert cmd["status"] == "accepted" and cmd["acked_step"] == 4

    def test_missing_run_dir(self, tmp_path):
        mod = self._load()
        assert mod.main([str(tmp_path / "nope"), "stop"]) == 2


class TestReportRendering:
    def test_metrics_report_control_section(self, tmp_path, capsys):
        import importlib.util
        import sys

        path = (Path(__file__).parent.parent / "tools" / "metrics_report.py")
        spec = importlib.util.spec_from_file_location("_mr_ctl", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["_mr_ctl"] = mod
        spec.loader.exec_module(mod)
        (tmp_path / "metrics.jsonl").write_text(
            '{"step": 2, "loss": 1.0}\n')
        (tmp_path / "run_summary.json").write_text(json.dumps({
            "alerts": [{"step": 2, "rule": "dw", "action": "halt",
                        "message": "data_wait high"}],
            "control": {
                "commands": [{"id": "abc", "command": "stop", "step": 2,
                              "status": "accepted"}],
                "decisions": [{"step": 2, "stop": True,
                               "conditions": ["alert_halt"],
                               "source": "local",
                               "reason": "alert dw: data_wait high"}]},
        }))
        assert mod.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "fleet control" in out
        assert "command stop" in out and "accepted" in out
        assert "[alert_halt]" in out and "alert dw" in out

    def test_fleet_monitor_renders_control_next_to_findings(
            self, tmp_path, capsys):
        import importlib.util
        import sys

        path = (Path(__file__).parent.parent / "tools" / "fleet_monitor.py")
        spec = importlib.util.spec_from_file_location("_fm_ctl", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["_fm_ctl"] = mod
        spec.loader.exec_module(mod)
        fleet = tmp_path / "fleet"
        fleet.mkdir()
        (fleet / "host_0.jsonl").write_text(json.dumps({
            "host": 0, "step": 2, "t_mono": 1.0, "t_wall": 1.0,
            "metrics": {"loss": 1.0}}) + "\n")
        (tmp_path / "run_summary.json").write_text(json.dumps({
            "control": {"commands": [],
                        "decisions": [{"step": 2, "stop": True,
                                       "conditions": ["preemption"],
                                       "source": "fleet",
                                       "reason": "fleet consensus"}]}}))
        mod.main([str(tmp_path)])
        out = capsys.readouterr().out
        assert "fleet control" in out and "[preemption]" in out
