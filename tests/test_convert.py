"""Checkpoint converters: HF <-> native round-trip + forward parity vs HF transformers."""

import os
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_training_tpu.models import llama, mixtral
from neuronx_distributed_training_tpu.ops import moe as moe_ops
from neuronx_distributed_training_tpu.tools.convert import (
    hf_llama_to_native,
    hf_mixtral_to_native,
    native_to_hf_llama,
)
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

import pytest as _pytest_mark

pytestmark = _pytest_mark.mark.slow  # multi-minute parity tests; CI fast tier deselects

FP32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                   softmax_dtype=jnp.float32)

CFG = llama.LlamaConfig(
    vocab_size=96, hidden_size=32, intermediate_size=64, num_layers=2,
    num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
    activations_checkpoint_granularity=None,
)


def tree_equal(a, b, path=""):
    if isinstance(a, dict):
        assert set(a) == set(b), f"{path}: {set(a)} != {set(b)}"
        for k in a:
            tree_equal(a[k], b[k], f"{path}/{k}")
    else:
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6,
                                   err_msg=path)


class TestLlamaRoundTrip:
    def test_native_hf_native(self):
        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        hf = native_to_hf_llama(params, CFG)
        back = hf_llama_to_native(hf, CFG)
        tree_equal(jax.tree_util.tree_map(np.asarray, params), back)

    def test_forward_parity_with_hf_transformers(self):
        """Converted weights must produce the same logits as HF transformers."""
        torch = pytest.importorskip("torch")
        from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

        hf_cfg = HFConfig(
            vocab_size=CFG.vocab_size, hidden_size=CFG.hidden_size,
            intermediate_size=CFG.intermediate_size, num_hidden_layers=CFG.num_layers,
            num_attention_heads=CFG.num_attention_heads,
            num_key_value_heads=CFG.kv_heads,
            max_position_embeddings=CFG.max_position_embeddings,
            rope_theta=CFG.rope_theta, rms_norm_eps=CFG.rms_norm_eps,
            attention_bias=False, tie_word_embeddings=False,
        )
        torch.manual_seed(0)
        hf_model = LlamaForCausalLM(hf_cfg).eval()
        state = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
        params = hf_llama_to_native(state, CFG)

        ids = np.arange(16, dtype=np.int64)[None, :] % CFG.vocab_size
        with torch.no_grad():
            hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
        our_logits, _ = llama.forward(
            jax.tree_util.tree_map(jnp.asarray, params),
            {"input_ids": jnp.asarray(ids, jnp.int32)}, CFG, FP32,
        )
        np.testing.assert_allclose(np.asarray(our_logits), hf_logits,
                                   atol=2e-4, rtol=1e-3)


class TestMixtralConvert:
    def test_expert_stacking_shapes(self):
        xcfg = mixtral.MixtralConfig(
            llama=CFG, moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True)
        )
        params = mixtral.init_params(jax.random.PRNGKey(0), xcfg, FP32)
        # fabricate an HF-style state dict from the native one, then convert
        state = {}
        state["model.embed_tokens.weight"] = np.asarray(params["embed"]["embedding"])
        state["model.norm.weight"] = np.asarray(params["final_norm"]["scale"])
        state["lm_head.weight"] = np.asarray(params["lm_head"]["w"]).T
        nh, nkv, d = CFG.num_attention_heads, CFG.kv_heads, CFG.head_size
        F = CFG.intermediate_size
        for i in range(CFG.num_layers):
            pre = f"model.layers.{i}."
            qkv = np.asarray(params["layers"]["attn"]["qkv"]["w"][i])
            q, k, v = np.split(qkv, [nh * d, (nh + nkv) * d], axis=1)
            state[pre + "self_attn.q_proj.weight"] = q.T
            state[pre + "self_attn.k_proj.weight"] = k.T
            state[pre + "self_attn.v_proj.weight"] = v.T
            state[pre + "self_attn.o_proj.weight"] = np.asarray(
                params["layers"]["attn"]["o"]["w"][i]).T
            state[pre + "input_layernorm.weight"] = np.asarray(
                params["layers"]["input_norm"]["scale"][i])
            state[pre + "post_attention_layernorm.weight"] = np.asarray(
                params["layers"]["post_attn_norm"]["scale"][i])
            state[pre + "block_sparse_moe.gate.weight"] = np.asarray(
                params["layers"]["mlp"]["router"]["w"][i]).T
            for j in range(4):
                gu = np.asarray(params["layers"]["mlp"]["experts"]["gate_up"][i, j])
                state[pre + f"block_sparse_moe.experts.{j}.w1.weight"] = gu[:, :F].T
                state[pre + f"block_sparse_moe.experts.{j}.w3.weight"] = gu[:, F:].T
                state[pre + f"block_sparse_moe.experts.{j}.w2.weight"] = np.asarray(
                    params["layers"]["mlp"]["experts"]["down"][i, j]).T
        back = hf_mixtral_to_native(state, xcfg)
        tree_equal(jax.tree_util.tree_map(np.asarray, params), back)


def test_mixtral_native_hf_round_trip():
    """native -> HF -> native is exact (the nxdt->HF converter direction)."""
    from neuronx_distributed_training_tpu.models import mixtral
    from neuronx_distributed_training_tpu.ops import moe as moe_ops
    from neuronx_distributed_training_tpu.tools.convert import (
        hf_mixtral_to_native,
        native_to_hf_mixtral,
    )
    from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

    fp32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                       softmax_dtype=jnp.float32)
    cfg = mixtral.MixtralConfig(
        llama=llama.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
            num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
            activations_checkpoint_granularity=None,
        ),
        moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True),
    )
    params = mixtral.init_params(jax.random.PRNGKey(0), cfg, fp32)
    hf = native_to_hf_mixtral(params, cfg)
    assert "model.layers.0.block_sparse_moe.experts.3.w2.weight" in hf
    back = hf_mixtral_to_native(hf, cfg)

    def eq(a, b, path=""):
        if isinstance(a, dict):
            assert set(a) == set(b), path
            for k in a:
                eq(a[k], b[k], path + "/" + k)
        else:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=path)

    eq(jax.tree_util.tree_map(np.asarray, params), back)


def test_mixtral_interleaved_round_trip():
    """moe_frequency=2 (grouped dense/MoE layout): native -> HF -> native is
    exact; dense layers emit Llama mlp.* names, MoE layers block_sparse_moe.*."""
    from neuronx_distributed_training_tpu.models import mixtral
    from neuronx_distributed_training_tpu.ops import moe as moe_ops
    from neuronx_distributed_training_tpu.tools.convert import (
        hf_mixtral_to_native,
        native_to_hf_mixtral,
    )
    from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

    fp32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                       softmax_dtype=jnp.float32)
    cfg = mixtral.MixtralConfig(
        llama=llama.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=4,
            num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
            activations_checkpoint_granularity=None,
        ),
        moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True),
        moe_frequency=2,
    )
    params = mixtral.init_params(jax.random.PRNGKey(0), cfg, fp32)
    hf = native_to_hf_mixtral(params, cfg)
    # layers 0, 2 are MoE; layers 1, 3 dense with llama mlp names
    assert "model.layers.0.block_sparse_moe.gate.weight" in hf
    assert "model.layers.2.block_sparse_moe.experts.3.w2.weight" in hf
    assert "model.layers.1.mlp.gate_proj.weight" in hf
    assert "model.layers.3.mlp.down_proj.weight" in hf
    assert "model.layers.1.block_sparse_moe.gate.weight" not in hf
    back = hf_mixtral_to_native(hf, cfg)

    def eq(a, b, path=""):
        if isinstance(a, dict):
            assert set(a) == set(b), path
            for k in a:
                eq(a[k], b[k], path + "/" + k)
        else:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=path)

    eq(jax.tree_util.tree_map(np.asarray, params), back)

    # converted-back params drive the forward identically
    ids = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, 64)
    ref, _ = mixtral.forward(params, {"input_ids": ids}, cfg, fp32)
    got, _ = mixtral.forward(
        jax.tree_util.tree_map(jnp.asarray, back), {"input_ids": ids}, cfg, fp32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)


def test_vpp_interleaved_checkpoint_converts():
    """A VPP-trained checkpoint (interleaved [vp, pp, Lc, ...] layer layout)
    converts to HF identically to its flat-layout equivalent."""
    from neuronx_distributed_training_tpu.parallel.pipeline import to_interleaved
    from neuronx_distributed_training_tpu.tools.convert import native_to_hf_llama
    from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

    fp32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                       softmax_dtype=jnp.float32)
    cfg = llama.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=8,
        num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
        activations_checkpoint_granularity=None,
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg, fp32)
    ref = native_to_hf_llama(params, cfg)
    inter = dict(params)
    inter["layers"] = to_interleaved(params["layers"], pp=2, vp=2)
    got = native_to_hf_llama(inter, cfg)
    assert set(got) == set(ref)
    for k in ref:
        np.testing.assert_array_equal(np.asarray(got[k]), np.asarray(ref[k]),
                                      err_msg=k)


@pytest.mark.parametrize("freq", [2, 4])
def test_vpp_interleaved_mixtral_grouped_converts(freq):
    """Interleaved + grouped mixtral checkpoint converts identically to the
    flat layout.  freq=4 exercises the case where group count (L/f) differs
    from the dense-layer count (L - L/f): the dense stack LEADS with the
    group count, so the same expect applies to both moe and dense leaves."""
    from neuronx_distributed_training_tpu.models import mixtral
    from neuronx_distributed_training_tpu.ops import moe as moe_ops
    from neuronx_distributed_training_tpu.parallel.pipeline import to_interleaved
    from neuronx_distributed_training_tpu.tools.convert import native_to_hf_mixtral
    from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

    fp32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                       softmax_dtype=jnp.float32)
    cfg = mixtral.MixtralConfig(
        llama=llama.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=8,
            num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
            activations_checkpoint_granularity=None,
        ),
        moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True),
        moe_frequency=freq,
    )
    params = mixtral.init_params(jax.random.PRNGKey(0), cfg, fp32)
    ref = native_to_hf_mixtral(params, cfg)
    inter = dict(params)
    # pp*vp must divide the group count L/freq: (2,2) for G=4, (2,1) for G=2
    pp, vp = (2, 2) if freq == 2 else (2, 1)
    inter["layers"] = to_interleaved(params["layers"], pp=pp, vp=vp)
    got = native_to_hf_mixtral(inter, cfg)
    assert set(got) == set(ref)
    for k in ref:
        np.testing.assert_array_equal(np.asarray(got[k]), np.asarray(ref[k]),
                                      err_msg=k)


class TestConverterCLI:
    """examples/checkpoint_converter.py end to end: hf2native writes an Orbax
    checkpoint, native2hf reads it back (meta-less checkpoint -> layout
    heuristic fallback) and the tensors round-trip exactly."""

    @pytest.mark.slow
    def test_cli_hf_roundtrip(self, tmp_path):
        import subprocess
        import sys

        import torch

        cfg_yaml = tmp_path / "conf.yaml"
        cfg_yaml.write_text("""
model_source: hf
model:
  vocab_size: 64
  hidden_size: 32
  intermediate_size: 64
  num_layers: 2
  num_attention_heads: 4
  num_key_value_heads: 2
  max_position_embeddings: 32
  tie_word_embeddings: false
data: {global_batch_size: 8, micro_batch_size: 1}
""")
        # synthesize a tiny HF llama state dict
        from neuronx_distributed_training_tpu.models import llama as llama_mod
        from neuronx_distributed_training_tpu.tools import convert as conv
        from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

        lc = llama_mod.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
            num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
            tie_word_embeddings=False,
            activations_checkpoint_granularity=None,
        )
        params = llama_mod.init_params(
            jax.random.PRNGKey(0), lc,
            DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                        softmax_dtype=jnp.float32))
        sd = conv.native_to_hf_llama(params, lc)
        pt = tmp_path / "hf_model.pt"
        torch.save({k: torch.from_numpy(np.asarray(v).copy()) for k, v in sd.items()}, pt)

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        script = str(Path(__file__).parent.parent / "examples" /
                     "checkpoint_converter.py")
        ck = tmp_path / "native_ckpt"
        r = subprocess.run(
            [sys.executable, script, "--model", "llama",
             "--direction", "hf2native", "--config", str(cfg_yaml),
             "--input", str(pt), "--output", str(ck), "--step", "0"],
            capture_output=True, text=True, env=env, timeout=300,
        )
        assert r.returncode == 0, r.stdout + r.stderr

        out = tmp_path / "hf_out"
        r = subprocess.run(
            [sys.executable, script, "--model", "llama",
             "--direction", "native2hf", "--config", str(cfg_yaml),
             "--input", str(ck), "--output", str(out)],
            capture_output=True, text=True, env=env, timeout=300,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        files = list(out.glob("model.*"))
        assert files, list(out.iterdir())
        if files[0].suffix == ".npz":
            back = dict(np.load(files[0]))
        else:
            from safetensors.numpy import load_file

            back = load_file(str(files[0]))
        assert set(back) == set(sd)
        for k in sd:
            np.testing.assert_array_equal(back[k], np.asarray(sd[k]),
                                          err_msg=k)
