"""NeMo-Megatron checkpoint converter: round-trip + TP/PP shard merge."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_training_tpu.models import gpt
from neuronx_distributed_training_tpu.tools.convert_megatron import (
    megatron_gpt_to_native,
    merge_nnm_shards,
    native_to_megatron_gpt,
)
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

FP32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                   softmax_dtype=jnp.float32)


def make_cfg(**over):
    base = dict(
        vocab_size=64, hidden_size=32, num_layers=2, num_attention_heads=4,
        num_query_groups=2, max_position_embeddings=16,
        position_embedding_type="learned_absolute", normalization="layernorm",
        bias=True, share_embeddings_and_output_weights=True,
        activations_checkpoint_granularity=None,
    )
    base.update(over)
    return gpt.GPTConfig(**base)


def tree_equal(a, b, path=""):
    if isinstance(a, dict):
        assert set(a) == set(b), f"keys differ at {path}: {set(a)} vs {set(b)}"
        for k in a:
            tree_equal(a[k], b[k], f"{path}/{k}")
    else:
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"mismatch at {path}"
        )


class TestRoundTrip:
    @pytest.mark.parametrize("cfg", [
        make_cfg(),
        make_cfg(num_query_groups=None, normalization="rmsnorm", bias=False,
                 position_embedding_type="rope",
                 share_embeddings_and_output_weights=False),
        make_cfg(transformer_block_type="normformer", num_tokentypes=2),
        make_cfg(transformer_block_type="post_ln"),
        make_cfg(transformer_block_type="gpt_j"),
    ], ids=["gqa-learned-ln-tied", "mha-rope-rms-untied",
            "normformer-tokentype", "post_ln", "gpt_j"])
    def test_native_megatron_native(self, cfg):
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        meg = native_to_megatron_gpt(params, cfg)
        back = megatron_gpt_to_native(meg, cfg)
        tree_equal(jax.tree_util.tree_map(np.asarray, params), back)

    def test_qkv_interleave_is_head_grouped(self):
        """Megatron row order per kv group: q..q, k, v — verify against a
        hand-built pattern."""
        cfg = make_cfg(num_layers=1)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        nh, nkv, d, h = 4, 2, 8, 32
        # paint recognizable values into the native fused qkv [H, (nh+2kv)d]
        w = np.zeros((h, (nh + 2 * nkv) * d), np.float32)
        for head in range(nh):
            w[:, head * d:(head + 1) * d] = 100 + head  # Q heads
        for kv in range(nkv):
            w[:, (nh + kv) * d:(nh + kv + 1) * d] = 200 + kv  # K heads
            w[:, (nh + nkv + kv) * d:(nh + nkv + kv + 1) * d] = 300 + kv  # V
        params["layers"]["attn"]["qkv"]["w"] = jnp.asarray(w[None])
        meg = native_to_megatron_gpt(params, cfg)
        fused = meg["language_model.encoder.layers.0.self_attention.query_key_value.weight"]
        # group 0 rows: q0, q1, k0, v0; group 1 rows: q2, q3, k1, v1
        rows = fused.reshape(nkv, (nh // nkv + 2), d, h)
        assert np.all(rows[0, 0] == 100) and np.all(rows[0, 1] == 101)
        assert np.all(rows[0, 2] == 200) and np.all(rows[0, 3] == 300)
        assert np.all(rows[1, 0] == 102) and np.all(rows[1, 1] == 103)
        assert np.all(rows[1, 2] == 201) and np.all(rows[1, 3] == 301)


class TestShardMerge:
    def test_tp_pp_merge_reconstructs_full(self):
        """Split a full Megatron dict into tp=2 x pp=2 shards the way Megatron
        shards (column dim 0 in head groups, row dim 1, vocab dim 0, local
        layer indices), then merge and compare."""
        cfg = make_cfg(num_layers=4)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        full = native_to_megatron_gpt(params, cfg)
        tp, pp = 2, 2
        per_stage = cfg.num_layers // pp
        nh, nkv, d = 4, 2, 8

        def tp_slice(key, v, r):
            if "word_embeddings" in key or "output_layer" in key:
                return np.split(v, tp, axis=0)[r]
            if "query_key_value" in key:
                # shard by kv group: [nkv, q_per+2, d, ...] over dim 0
                g = v.reshape((nkv, nh // nkv + 2, d) + v.shape[1:])
                return np.split(g, tp, axis=0)[r].reshape(
                    (-1,) + v.shape[1:]
                )
            if key.endswith("dense.weight") or "4h_to_h.weight" in key:
                return np.split(v, tp, axis=1)[r]
            if "h_to_4h" in key:
                return np.split(v, tp, axis=0)[r]
            return v  # replicated

        shards = {}
        for r in range(tp):
            for p in range(pp):
                sd = {}
                for key, v in full.items():
                    import re

                    m = re.search(r"\.layers\.(\d+)\.", key)
                    if m:
                        li = int(m.group(1))
                        if not (p * per_stage <= li < (p + 1) * per_stage):
                            continue
                        key_local = key.replace(
                            f".layers.{li}.", f".layers.{li - p * per_stage}."
                        )
                    else:
                        key_local = key
                    sd["model." + key_local] = tp_slice(key, v, r)
                shards[(r, p)] = sd

        merged = merge_nnm_shards(shards, tp=tp, pp=pp, num_layers=cfg.num_layers)
        assert set(merged) == set(full)
        for k in full:
            np.testing.assert_array_equal(merged[k], full[k], err_msg=k)
        # and the merged dict loads into a native pytree that matches
        back = megatron_gpt_to_native(merged, cfg)
        tree_equal(jax.tree_util.tree_map(np.asarray, params), back)
