"""Data layer: samplers (determinism + resume), packing/padding, DataModules."""

import numpy as np
import pytest

from neuronx_distributed_training_tpu.data import (
    HFDataModule,
    PretrainingSampler,
    RandomSampler,
    SyntheticDataModule,
    pack_sequences,
    pad_sequences,
    process_global_batch,
)
from neuronx_distributed_training_tpu.data.packing import IGNORE_INDEX, mask_prompt_labels
from neuronx_distributed_training_tpu.data.sampler import (
    consumed_samples_from_name,
    dp_shard,
)
from neuronx_distributed_training_tpu.data.loader import PrefetchIterator


def take(it, n):
    out = []
    for _ in range(n):
        out.append(next(it))
    return out


class TestSamplers:
    def test_sequential_wraps_and_resumes(self):
        s = PretrainingSampler(total_samples=10, global_batch_size=4)
        batches = take(iter(s), 3)
        assert batches[0].tolist() == [0, 1, 2, 3]
        assert batches[2].tolist() == [8, 9, 0, 1]  # wraps around
        assert s.consumed_samples == 12
        # resume from consumed_samples reproduces the continuation
        s2 = PretrainingSampler(total_samples=10, global_batch_size=4, consumed_samples=8)
        assert next(iter(s2)).tolist() == batches[2].tolist()

    def test_random_deterministic_and_resumable(self):
        a = take(iter(RandomSampler(100, 8, seed=7)), 5)
        b = take(iter(RandomSampler(100, 8, seed=7)), 5)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        # resume mid-epoch
        s = RandomSampler(100, 8, seed=7)
        take(iter(s), 3)
        resumed = RandomSampler(100, 8, seed=7, consumed_samples=s.consumed_samples)
        np.testing.assert_array_equal(next(iter(resumed)), a[3])

    def test_random_epoch_reshuffles(self):
        s = RandomSampler(16, 8, seed=3)
        batches = take(iter(s), 4)  # 2 epochs
        epoch0 = np.concatenate(batches[:2])
        epoch1 = np.concatenate(batches[2:])
        assert sorted(epoch0.tolist()) == list(range(16))
        assert sorted(epoch1.tolist()) == list(range(16))
        assert epoch0.tolist() != epoch1.tolist()

    def test_dp_shard(self):
        batch = np.arange(8)
        assert dp_shard(batch, 0, 4).tolist() == [0, 1]
        assert dp_shard(batch, 3, 4).tolist() == [6, 7]
        with pytest.raises(ValueError):
            dp_shard(np.arange(6), 0, 4)

    def test_consumed_samples_from_name(self):
        assert consumed_samples_from_name("x-step=10-consumed_samples=128000.0.ckpt") == 128000
        assert consumed_samples_from_name("step_5_consumed_samples=64") == 64
        assert consumed_samples_from_name("nothing") is None


class TestPacking:
    def test_pack_basic(self):
        out = pack_sequences([[1, 2, 3], [4, 5], [6, 7, 8, 9]], chunk_size=8, eos_id=99)
        # [1,2,3,99,4,5,99,pad] then [6,7,8,9,99,...]
        assert out["input_ids"].shape == (2, 8)
        assert out["input_ids"][0].tolist() == [1, 2, 3, 99, 4, 5, 99, 0]
        assert out["loss_mask"][0].tolist() == [1, 1, 1, 1, 1, 1, 1, 0]
        assert out["input_ids"][1, :5].tolist() == [6, 7, 8, 9, 99]

    def test_pack_drops_overflow(self):
        out = pack_sequences([[1] * 20, [2, 3]], chunk_size=8, eos_id=9)
        assert out["input_ids"].shape[0] == 1
        assert out["input_ids"][0, :3].tolist() == [2, 3, 9]

    def test_pack_with_labels(self):
        ids, lbl = mask_prompt_labels([1, 2], [3, 4])
        out = pack_sequences([ids], chunk_size=8, eos_id=9, label_lists=[lbl])
        assert out["labels"][0, :5].tolist() == [IGNORE_INDEX, IGNORE_INDEX, 3, 4, 9]
        assert out["loss_mask"][0, :5].tolist() == [0, 0, 1, 1, 1]

    def test_pad_right_and_left(self):
        r = pad_sequences([[1, 2, 3]], max_length=5, pad_id=0)
        assert r["input_ids"][0].tolist() == [1, 2, 3, 0, 0]
        assert r["attention_mask"][0].tolist() == [1, 1, 1, 0, 0]
        l = pad_sequences([[1, 2, 3]], max_length=5, pad_id=0, left_pad=True)
        assert l["input_ids"][0].tolist() == [0, 0, 1, 2, 3]
        assert l["loss_mask"][0].tolist() == [0, 0, 1, 1, 1]

    def test_pad_truncates(self):
        r = pad_sequences([[1, 2, 3, 4, 5, 6]], max_length=4, pad_id=0)
        assert r["input_ids"][0].tolist() == [1, 2, 3, 4]


class TestDataModules:
    def test_process_global_batch_derives_labels_and_mask(self):
        ids = np.array([[1, 2, 0, 0]], dtype=np.int32)
        out = process_global_batch({"input_ids": ids}, pad_id=0)
        np.testing.assert_array_equal(out["labels"], ids)
        assert out["loss_mask"][0].tolist() == [1, 1, 0, 0]

    def test_synthetic_deterministic(self):
        dm1 = SyntheticDataModule(vocab_size=100, seq_len=16, global_batch_size=4, seed=5)
        dm2 = SyntheticDataModule(vocab_size=100, seq_len=16, global_batch_size=4, seed=5)
        b1 = take(dm1.global_batches(), 2)
        b2 = take(dm2.global_batches(), 2)
        for x, y in zip(b1, b2):
            np.testing.assert_array_equal(x["input_ids"], y["input_ids"])
        assert b1[0]["input_ids"].shape == (4, 16)
        assert dm1.consumed_samples == 8

    def test_synthetic_resume_exactness(self):
        dm = SyntheticDataModule(vocab_size=50, seq_len=8, global_batch_size=2)
        take(dm.global_batches(), 3)
        resumed = SyntheticDataModule(
            vocab_size=50, seq_len=8, global_batch_size=2,
            consumed_samples=dm.consumed_samples,
        )
        a = next(dm.global_batches())
        b = next(resumed.global_batches())
        np.testing.assert_array_equal(a["input_ids"], b["input_ids"])

    def test_hf_datamodule_from_dict_dataset(self):
        import datasets

        ds = datasets.Dataset.from_dict(
            {"input_ids": np.arange(32).reshape(8, 4).tolist()}
        )
        dm = HFDataModule(ds, global_batch_size=4)
        b = next(dm.global_batches())
        assert b["input_ids"].shape == (4, 4)
        assert b["input_ids"][0].tolist() == [0, 1, 2, 3]

    def test_sharded_batches(self, cpu_mesh):
        dm = SyntheticDataModule(vocab_size=10, seq_len=8, global_batch_size=8)
        b = next(dm.sharded_batches(cpu_mesh))
        assert b["input_ids"].shape == (8, 8)
        import jax

        assert isinstance(b["input_ids"], jax.Array)


class TestNativePacker:
    """C++ packer must be bit-identical to the numpy path."""

    def _python_pack(self, toks, chunk, eos, lbls=None):
        import unittest.mock as mock

        from neuronx_distributed_training_tpu.data import packing

        with mock.patch.object(packing, "_pack_sequences_native",
                               lambda *a: None):
            return packing.pack_sequences(toks, chunk, eos, label_lists=lbls)

    def test_parity_with_python(self):
        from neuronx_distributed_training_tpu.data import packing

        if packing._load_native() is None:
            pytest.skip("no native toolchain")
        rng = np.random.default_rng(0)
        toks = [list(rng.integers(3, 100, rng.integers(1, 40)))
                for _ in range(200)]
        toks.append(list(range(3, 3 + 50)))  # an overflow record (dropped)
        lbls = [[t if i % 3 else -100 for i, t in enumerate(ts)] for ts in toks]
        got = packing.pack_sequences(toks, 32, eos_id=2, label_lists=lbls)
        ref = self._python_pack(toks, 32, 2, lbls)
        for k in ("input_ids", "labels", "loss_mask"):
            np.testing.assert_array_equal(got[k], ref[k], err_msg=k)

    def test_parity_default_labels_and_empty(self):
        from neuronx_distributed_training_tpu.data import packing

        if packing._load_native() is None:
            pytest.skip("no native toolchain")
        toks = [[5, 6, 7], [8, 9], [10, 11, 12, 13]]
        got = packing.pack_sequences(toks, 8, eos_id=2)
        ref = self._python_pack(toks, 8, 2)
        for k in ("input_ids", "labels", "loss_mask"):
            np.testing.assert_array_equal(got[k], ref[k], err_msg=k)
        # all-overflow -> zero chunks, correct shapes
        got0 = packing.pack_sequences([[1] * 50], 8, eos_id=2)
        assert got0["input_ids"].shape == (0, 8)

    def test_ragged_labels_fall_back_loudly(self):
        """Over-long per-record labels must NOT silently shift (fromiter
        truncation); native falls back and python raises/misaligns visibly."""
        from neuronx_distributed_training_tpu.data import packing

        res = packing._pack_sequences_native(
            [[1, 2, 3], [4, 5]], 8, 2, [[1, 2, 3, 99], [4, 5]], 0)
        assert res is None  # native refuses; caller takes the python path


class TestPrefetchIterator:
    def test_order_preserved(self):
        it = PrefetchIterator(iter(range(50)), depth=4)
        assert list(it) == list(range(50))

    def test_exception_propagates(self):
        def gen():
            yield 1
            raise RuntimeError("boom")

        it = PrefetchIterator(gen())
        assert next(it) == 1
        with pytest.raises(RuntimeError, match="boom"):
            next(it)

    def test_close_stops_producer(self):
        import itertools
        import time

        produced = []

        def gen():
            for i in itertools.count():
                produced.append(i)
                yield i

        it = PrefetchIterator(gen(), depth=2)
        next(it)
        it.close()
        time.sleep(0.3)
        n = len(produced)
        time.sleep(0.3)
        assert len(produced) == n  # producer stopped

    def test_runs_ahead(self):
        import time

        produced = []

        def gen():
            for i in range(10):
                produced.append(i)
                yield i

        it = PrefetchIterator(gen(), depth=3)
        time.sleep(0.3)
        # the producer filled the queue before the consumer asked for anything
        assert len(produced) >= 3
        assert list(it) == list(range(10))


def test_prefetch_close_with_full_queue_unblocks_producer():
    """Terminal puts honor close(): producer thread exits even when the queue
    is full at exhaustion time, and a late consumer wakes instead of hanging."""
    import time

    it = PrefetchIterator(iter(range(3)), depth=1)  # queue full immediately
    time.sleep(0.2)
    it.close()
    time.sleep(0.3)
    assert not it._thread.is_alive()
    # post-close consumption terminates (drains then StopIteration) — no hang
    list(it)


def test_prefetch_repeat_next_after_exhaustion_raises():
    """Iterator protocol: next() after StopIteration keeps raising (no hang)."""
    it = PrefetchIterator(iter([1, 2]), depth=1)
    assert list(it) == [1, 2]
    with pytest.raises(StopIteration):
        next(it)
    with pytest.raises(StopIteration):
        next(it)


def test_prefetch_next_after_exception_terminates():
    """After a propagated producer error, further next() raises StopIteration
    instead of polling forever."""
    def gen():
        yield 1
        raise RuntimeError("boom")

    it = PrefetchIterator(gen(), depth=1)
    assert next(it) == 1
    with pytest.raises(RuntimeError):
        next(it)
    with pytest.raises(StopIteration):
        next(it)
