"""Config -> DataModule wiring (data/build.py): the reference's
``training.py:71-91`` dispatch.  Covers the HF pretokenized-arrow pretraining
path end-to-end (BASELINE configs[0] scenario), the Megatron mmap path with
label-shift correctness, alignment paths from YAML, and the no-silent-synthetic
rule."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_training_tpu.config.loader import load_config
from neuronx_distributed_training_tpu.data.build import (
    alignment_strategy,
    build_data_module,
)
from neuronx_distributed_training_tpu.trainer.loop import Trainer, train

import pytest as _pytest_mark

pytestmark = _pytest_mark.mark.slow  # fit()-based integration tests; CI fast tier deselects


def base_cfg(tmp_path, **data):
    return load_config({
        "name": "wired",
        "model_source": "hf",
        "seed": 3,
        "trainer": {"max_steps": 6, "log_every_n_steps": 1},
        "exp_manager": {"exp_dir": str(tmp_path / "exp"), "resume_if_exists": True,
                        "checkpoint_callback_params": {"save_top_k": 1,
                                                       "every_n_train_steps": 3}},
        "distributed_strategy": {"tensor_model_parallel_size": 2},
        "data": {"global_batch_size": 8, "micro_batch_size": 1, "seq_length": 32,
                 **data},
        "model": {
            "vocab_size": 64, "hidden_size": 64, "intermediate_size": 128,
            "num_layers": 2, "num_attention_heads": 4, "num_key_value_heads": 2,
            "max_position_embeddings": 32,
            "optim": {"name": "adamw_fp32OptState", "lr": 5e-3,
                      "sched": {"name": "LinearAnnealingWithWarmUp",
                                "warmup_steps": 1, "max_steps": 6}},
        },
        "precision": {"type": "mixed_precision"},
    })


def make_arrow_dataset(path, n_rows=64, seq=32, vocab=64, period=4, seed=0):
    """Fixed-length pretokenized rows with a learnable periodic pattern."""
    import datasets

    rng = np.random.default_rng(seed)
    base = rng.integers(3, vocab, period)
    rows = np.tile(base, (n_rows, seq // period + 1))[:, :seq]
    ds = datasets.Dataset.from_dict({"input_ids": rows.tolist()})
    ds.save_to_disk(str(path))
    return rows


class TestAlignmentStrategyParsing:
    def test_dict_form(self):
        cfg = load_config({"model_alignment_strategy": {"dpo": {"kl_beta": 0.2}}})
        name, params = alignment_strategy(cfg)
        assert name == "dpo" and params["kl_beta"] == 0.2

    def test_string_form(self):
        cfg = load_config({"model_alignment_strategy": "SFT"})
        assert alignment_strategy(cfg) == ("sft", {})

    def test_absent(self):
        assert alignment_strategy(load_config({})) == ("", {})


class TestNoSilentSynthetic:
    def test_missing_source_raises(self, tmp_path, devices8):
        cfg = base_cfg(tmp_path)  # no train_dir/data_prefix/synthetic
        with pytest.raises(ValueError, match="no data source"):
            Trainer.from_config(cfg, enable_checkpointing=False)

    def test_explicit_synthetic_ok(self, tmp_path, devices8):
        cfg = base_cfg(tmp_path, synthetic=True)
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        from neuronx_distributed_training_tpu.data import SyntheticDataModule

        assert isinstance(t.data_module, SyntheticDataModule)


class TestHFArrowPretraining:
    def test_end_to_end_falling_loss_and_resume(self, tmp_path, devices8):
        """BASELINE configs[0]: flagship-schema config + pretokenized arrow dir
        trains with falling loss and exact consumed-samples resume."""
        make_arrow_dataset(tmp_path / "corpus")
        cfg = base_cfg(tmp_path, train_dir=str(tmp_path / "corpus"))
        t = Trainer.from_config(cfg)
        from neuronx_distributed_training_tpu.data.loader import HFDataModule

        assert isinstance(t.data_module, HFDataModule)
        m = t.fit()
        assert np.isfinite(m["loss"])
        # periodic data is highly learnable: loss must fall well below init
        lines = [json.loads(l) for l in
                 (tmp_path / "exp" / "wired" / "version_0" / "metrics.jsonl")
                 .read_text().strip().splitlines()]
        assert lines[-1]["loss"] < lines[0]["loss"] * 0.7
        assert m["consumed_samples"] == 48  # 6 steps x gbs 8

        # resume: restart with longer horizon from the step-6 checkpoint
        cfg2 = base_cfg(tmp_path, train_dir=str(tmp_path / "corpus"))
        cfg2["trainer"]["max_steps"] = 8
        t2 = Trainer.from_config(cfg2)
        assert t2.maybe_resume() and t2.step == 6
        assert t2.data_module.consumed_samples == 48
        m2 = t2.fit()
        assert m2["consumed_samples"] == 64


class TestMegatronWiring:
    def test_preshifted_labels_no_double_shift(self, tmp_path, devices8):
        """Trainer + MegatronDataModule: the mmap data is pre-shifted on host,
        so the trainer must run the model with shift_labels=False — training
        with the default in-model shift would optimize predicting t+2."""
        import jax

        from neuronx_distributed_training_tpu.data.megatron.dataset import (
            write_indexed_dataset,
        )
        from neuronx_distributed_training_tpu.models import gpt

        rng = np.random.default_rng(1)
        docs = [rng.integers(3, 64, size=200).astype(np.int32) for _ in range(4)]
        write_indexed_dataset(tmp_path / "corpus_text_document", docs)

        cfg = base_cfg(tmp_path, data_prefix=str(tmp_path / "corpus_text_document"))
        cfg["model_source"] = "megatron"
        cfg["model"]["architecture"] = "gpt"
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        assert t.data_module.labels_pre_shifted

        batch = next(t.data_module.global_batches())
        # the module's convention: labels[t] == input_ids[t+1]
        np.testing.assert_array_equal(batch["labels"][:, :-1], batch["input_ids"][:, 1:])

        from neuronx_distributed_training_tpu.parallel import sharding as shd

        jb = {k: np.asarray(v) for k, v in batch.items()}
        key = jax.random.PRNGKey(0)
        with t.mesh, shd.use_mesh(t.mesh):
            loss_trainer, _ = t.loss_fn(t.params, jb, key)
            loss_noshift, _ = gpt.forward(
                t.params, jb, t.model_cfg, t.policy, rng=key, shift_labels=False
            )
            loss_doubleshift, _ = gpt.forward(
                t.params, jb, t.model_cfg, t.policy, rng=key, shift_labels=True
            )
        np.testing.assert_allclose(
            float(loss_trainer), float(loss_noshift), rtol=1e-6
        )
        assert abs(float(loss_trainer) - float(loss_doubleshift)) > 1e-4


class TestAlignmentFromConfig:
    def test_sft_char_tokenizer_jsonl(self, tmp_path, devices8):
        recs = [{"input": f"question {i}", "output": "the answer is yes"}
                for i in range(64)]
        p = tmp_path / "train.jsonl"
        p.write_text("\n".join(json.dumps(r) for r in recs))
        cfg = base_cfg(tmp_path, train_dir=str(p),
                       tokenizer={"library": "char", "vocab_size": 64})
        cfg["model_alignment_strategy"] = {"sft": {"packing": True}}
        cfg = load_config(dict(cfg))
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        from neuronx_distributed_training_tpu.data.modules import SFTDataModule

        assert isinstance(t.data_module, SFTDataModule)
        m = t.fit()
        assert np.isfinite(m["loss"])

    def test_dpo_resume_restores_reference_logps(self, tmp_path, devices8):
        """Auto-resume mid-DPO: the frozen-policy reference logps must come
        back (sidecar cache), not be recomputed from resumed weights or
        crash on a missing column."""
        recs = [{"prompt": f"q{i}", "chosen": "fine answer", "rejected": "meh"}
                for i in range(16)]
        p = tmp_path / "prefs.jsonl"
        p.write_text("\n".join(json.dumps(r) for r in recs))

        def cfg_for(steps):
            cfg = base_cfg(tmp_path, train_dir=str(p),
                           tokenizer={"library": "char", "vocab_size": 64})
            cfg["model_alignment_strategy"] = {"dpo": {"kl_beta": 0.1}}
            cfg["trainer"]["max_steps"] = steps
            cfg["exp_manager"]["checkpoint_callback_params"] = {
                "save_top_k": 1, "every_n_train_steps": 2}
            return load_config(dict(cfg))

        t1 = Trainer.from_config(cfg_for(2))
        t1.fit()
        ref1 = np.array(t1.data_module.arrays["reference_chosen_logps"])

        t2 = Trainer.from_config(cfg_for(4))
        m = t2.fit()  # resumes from step 2; pre_fit must load the sidecar
        assert np.isfinite(m["loss"])
        ref2 = np.array(t2.data_module.arrays["reference_chosen_logps"])
        np.testing.assert_array_equal(ref1, ref2)

    def test_dpo_from_config(self, tmp_path, devices8):
        recs = [{"prompt": f"q{i}", "chosen": "good long answer",
                 "rejected": "bad"} for i in range(16)]
        p = tmp_path / "prefs.jsonl"
        p.write_text("\n".join(json.dumps(r) for r in recs))
        cfg = base_cfg(tmp_path, train_dir=str(p),
                       tokenizer={"library": "char", "vocab_size": 64})
        cfg["model_alignment_strategy"] = {
            "dpo": {"kl_beta": 0.1, "max_prompt_length": 8,
                    "truncation_mode": "keep_start"}}
        cfg["trainer"]["max_steps"] = 2
        cfg = load_config(dict(cfg))
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        m = t.fit()
        assert np.isfinite(m["loss"])
        assert "reference_chosen_logps" in t.data_module.arrays


def test_prepare_dataset_tool(tmp_path):
    """tools/prepare_dataset.py produces both formats loadable by the modules."""
    import subprocess
    import sys

    corpus = tmp_path / "corpus.jsonl"
    corpus.write_text("\n".join(
        json.dumps({"text": f"document number {i} with some text"})
        for i in range(40)))
    out_arrow = tmp_path / "arrow_ds"
    r = subprocess.run(
        [sys.executable, "tools/prepare_dataset.py", "--input", str(corpus),
         "--tokenizer", "char", "--seq-length", "16", "--output", str(out_arrow)],
        capture_output=True, text=True, cwd="/root/repo")
    assert r.returncode == 0, r.stderr
    import datasets

    ds = datasets.load_from_disk(str(out_arrow))
    assert len(ds[0]["input_ids"]) == 16

    out_meg = tmp_path / "meg_text_document"
    r = subprocess.run(
        [sys.executable, "tools/prepare_dataset.py", "--input", str(corpus),
         "--tokenizer", "char", "--format", "megatron", "--output", str(out_meg)],
        capture_output=True, text=True, cwd="/root/repo")
    assert r.returncode == 0, r.stderr
    from neuronx_distributed_training_tpu.data.megatron.dataset import IndexedDataset

    idx = IndexedDataset(out_meg)
    assert len(idx) == 40


class TestPromptTemplates:
    """Reference model_alignment_data_module.py:94-121 prompt_datasets."""

    def test_format_template(self):
        from neuronx_distributed_training_tpu.data.templates import build_template

        t = build_template({"prompt_template": {
            "input": "Question: {question}\nAnswer:", "output": " {answer}"}})
        rec = t({"question": "why", "answer": "because"})
        assert rec["input"] == "Question: why\nAnswer:"
        assert rec["output"] == " because"

    def test_no_template_is_none(self):
        from neuronx_distributed_training_tpu.data.templates import build_template

        assert build_template({}) is None

    def test_sft_module_applies_template(self):
        from neuronx_distributed_training_tpu.data.modules import SFTDataModule
        from neuronx_distributed_training_tpu.data.templates import FormatTemplate

        class CharTok:
            eos_token_id = 1

            def encode(self, s):
                return [3 + (ord(c) % 60) for c in s]

        tok = CharTok()
        records = [{"question": f"q{i}", "answer": "a" * 8} for i in range(8)]
        tmpl = FormatTemplate("Q: {question}", "{answer}")
        dm = SFTDataModule(records, tok, seq_length=32, global_batch_size=4,
                           packing=False, template=tmpl)
        # prompt tokens are label-masked; the 8-char answer is not
        assert dm.arrays["loss_mask"].sum() > 0
        templated = tmpl(records[0])
        n_resp = len(tok.encode(templated["output"]))
        assert dm.arrays["loss_mask"][0].sum() == n_resp

    def test_chat_template_extracts_last_assistant_turn(self):
        from neuronx_distributed_training_tpu.data.templates import ChatTemplate

        class FakeTok:
            def apply_chat_template(self, msgs, tokenize=False,
                                    add_generation_prompt=True):
                return "".join(f"<{m['role']}>{m['content']}" for m in msgs) + "<assistant>"

        t = ChatTemplate(FakeTok())
        rec = t({"messages": [
            {"role": "user", "content": "hi"},
            {"role": "assistant", "content": "hello"}]})
        assert rec["input"] == "<user>hi<assistant>"
        assert rec["output"] == "hello"


class TestSegmentMaskedPacking:
    """sft segment_mask: packed chunks get block-diagonal attention segments
    (beyond the reference — ConcatDataset packs without masking)."""

    class CharTok:
        bos_token_id = 1
        eos_token_id = 2
        def encode(self, s):
            return [3 + (ord(c) % 60) for c in s]

    def _records(self, n=12):
        return [{"input": f"q{i}" * (1 + i % 3), "output": f"a{i}"}
                for i in range(n)]

    def test_module_emits_segments_matching_pack_layout(self):
        from neuronx_distributed_training_tpu.data.modules import SFTDataModule

        dm = SFTDataModule(self._records(), self.CharTok(), seq_length=24,
                           global_batch_size=2, packing=True, segment_mask=True)
        a = dm.arrays
        assert a["segment_ids"].shape == a["input_ids"].shape
        # segments tile the real region exactly: nonzero where labels real
        # OR prompt (everything before the pad tail), zero on padding
        for r in range(len(a["input_ids"])):
            seg = a["segment_ids"][r]
            # the real extent ends where segments end; within it, ids are
            # non-decreasing starting at 1
            nz = seg[seg > 0]
            assert nz.size > 0 and nz[0] == 1
            assert (np.diff(nz) >= 0).all() and (np.diff(nz) <= 1).all()
            # eos of each record is the last token of its segment
            ends = np.where(np.diff(seg[seg > 0]) == 1)[0]
            for e in ends:
                assert a["input_ids"][r][e] == self.CharTok.eos_token_id

    def test_segment_mask_without_packing_rejected(self):
        from neuronx_distributed_training_tpu.data.modules import SFTDataModule

        with pytest.raises(ValueError, match="packing"):
            SFTDataModule(self._records(), self.CharTok(), seq_length=24,
                          global_batch_size=2, packing=False,
                          segment_mask=True)

    def test_positions_reset_per_segment(self):
        from neuronx_distributed_training_tpu.models.llama import positions_for

        seg = jnp.asarray([[1, 1, 1, 2, 2, 3, 0, 0]])
        ids = jnp.zeros_like(seg)
        pos = positions_for(ids, segment_ids=seg)
        np.testing.assert_array_equal(
            np.asarray(pos[0]), [0, 1, 2, 0, 1, 0, 0, 1])

    def test_sft_trainer_with_segment_mask(self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.config.loader import load_config
        from neuronx_distributed_training_tpu.data.modules import SFTDataModule
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = load_config({
            "name": "sftseg", "model_source": "hf", "seed": 5,
            "trainer": {"max_steps": 2, "log_every_n_steps": 1},
            "exp_manager": {"exp_dir": str(tmp_path / "exp")},
            "model_alignment_strategy": {"sft": {"packing": True,
                                                 "segment_mask": True}},
            "distributed_strategy": {"tensor_model_parallel_size": 2},
            "data": {"global_batch_size": 4, "micro_batch_size": 1,
                     "seq_length": 32, "synthetic": True},
            "model": {
                "vocab_size": 128, "hidden_size": 64, "intermediate_size": 128,
                "num_layers": 2, "num_attention_heads": 4,
                "num_key_value_heads": 2, "max_position_embeddings": 32,
                "optim": {"lr": 1e-3, "sched": {"name": "constant"}},
            },
            "precision": {"type": "mixed_precision"},
        })
        dm = SFTDataModule(self._records(40), self.CharTok(), seq_length=32,
                           global_batch_size=4, packing=True,
                           segment_mask=True)
        # the LOADER path must carry segment_ids (input_names filters batches;
        # a missing name silently no-ops the whole feature)
        assert "segment_ids" in next(dm.global_batches())
        t = Trainer.from_config(cfg, data_module=dm, enable_checkpointing=False)
        m = t.fit()
        assert np.isfinite(m["loss"])

    def test_no_cross_record_leak_at_model_level(self):
        """Changing record 1's tokens must not move record 2's logits when
        segment masking is on (and must move them when it's off — the
        reference ConcatDataset behavior)."""
        from neuronx_distributed_training_tpu.models import llama
        from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

        fp32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                           softmax_dtype=jnp.float32)
        cfg = llama.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
            num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
            activations_checkpoint_granularity=None,
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg, fp32)
        ids = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 3, 64)
        ids2 = ids.at[:, :8].add(1)  # perturb record 1 only (same length)
        seg = jnp.asarray([[1] * 8 + [2] * 8])

        def logits(i, s):
            batch = {"input_ids": i}
            if s is not None:
                batch["segment_ids"] = s
            out, _ = llama.forward(params, batch, cfg, fp32)
            return np.asarray(out)

        masked_a = logits(ids, seg)[:, 8:]
        masked_b = logits(ids2, seg)[:, 8:]
        np.testing.assert_array_equal(masked_a, masked_b)
        unmasked_a = logits(ids, None)[:, 8:]
        unmasked_b = logits(ids2, None)[:, 8:]
        assert not np.allclose(unmasked_a, unmasked_b)

    @pytest.mark.parametrize("family", ["gpt", "mixtral"])
    def test_no_cross_record_leak_gpt_and_mixtral(self, family):
        """segment masking is wired through every model family, not just
        llama (each was initially llama-only and silently unmasked)."""
        from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

        fp32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                           softmax_dtype=jnp.float32)
        if family == "gpt":
            from neuronx_distributed_training_tpu.models import gpt as mod

            cfg = mod.GPTConfig(
                vocab_size=64, hidden_size=32, num_layers=2,
                num_attention_heads=4, max_position_embeddings=32,
                activations_checkpoint_granularity=None,
            )
        else:
            from neuronx_distributed_training_tpu.models import mixtral as mod
            from neuronx_distributed_training_tpu.ops import moe as moe_ops

            cfg = mod.MixtralConfig.from_config({
                "vocab_size": 64, "hidden_size": 32, "intermediate_size": 64,
                "num_layers": 2, "num_attention_heads": 4,
                "num_key_value_heads": 2, "max_position_embeddings": 32,
                "moe": {"num_experts": 2, "top_k": 1, "dropless": True},
                "activations_checkpoint_granularity": None,
            })
        params = mod.init_params(jax.random.PRNGKey(0), cfg, fp32)
        ids = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 3, 64)
        ids2 = ids.at[:, :8].add(1)
        seg = jnp.asarray([[1] * 8 + [2] * 8])

        def logits(i):
            out, _ = mod.forward(params, {"input_ids": i, "segment_ids": seg},
                                 cfg, fp32)
            return np.asarray(out)

        np.testing.assert_array_equal(logits(ids)[:, 8:], logits(ids2)[:, 8:])
