"""Sharding/HLO-consistency assertions (utils.debug) — SURVEY §5.2 tooling."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
from neuronx_distributed_training_tpu.utils.debug import (
    assert_tree_sharding,
    collective_counts,
    sharding_report,
)


@pytest.fixture(scope="module")
def tp_mesh():
    return build_mesh(MeshConfig(tensor_model_parallel_size=2))


class TestShardingAssertions:
    def test_matching_sharding_passes(self, tp_mesh):
        tree = {"w": jnp.zeros((8, 16)), "b": jnp.zeros((16,))}
        specs = {"w": P(None, "model"), "b": P()}
        tree = jax.device_put(tree, {
            "w": NamedSharding(tp_mesh, P(None, "model")),
            "b": NamedSharding(tp_mesh, P()),
        })
        assert_tree_sharding(tree, specs, tp_mesh)  # no raise

    def test_silent_replication_caught(self, tp_mesh):
        """The classic GSPMD failure: a tensor that SHOULD be TP-sharded got
        replicated (e.g. device_put with the wrong spec)."""
        tree = {"w": jax.device_put(jnp.zeros((8, 16)),
                                    NamedSharding(tp_mesh, P()))}
        with pytest.raises(AssertionError, match="sharding mismatch"):
            assert_tree_sharding(tree, {"w": P(None, "model")}, tp_mesh)

    def test_equivalent_layouts_pass(self, tp_mesh):
        """P('data') on a trivial axis == P(): layout equality, not string."""
        one_wide = jax.device_put(
            jnp.zeros((8,)), NamedSharding(tp_mesh, P()))
        assert_tree_sharding({"x": one_wide}, {"x": P("pipe")}, tp_mesh)

    def test_report_lists_specs(self, tp_mesh):
        tree = {"w": jax.device_put(jnp.zeros((8, 16)),
                                    NamedSharding(tp_mesh, P(None, "model")))}
        rep = sharding_report(tree)
        assert "model" in rep["w"]


class TestCollectiveCensus:
    def test_tp_matmul_reduces_once(self, tp_mesh):
        """A row-parallel matmul must produce exactly one all-reduce-class
        collective; a regression to replicated weights would show zero, a
        dropped constraint extra all-gathers."""
        w = jax.device_put(
            jnp.ones((16, 8)), NamedSharding(tp_mesh, P("model", None)))
        x = jax.device_put(
            jnp.ones((4, 16)), NamedSharding(tp_mesh, P(None, "model")))

        @jax.jit
        def f(x, w):
            return x @ w

        with tp_mesh:
            counts = collective_counts(f, x, w)
        assert (counts["all-reduce"] + counts["reduce-scatter"]) >= 1, counts
        # and the result is correct
        with tp_mesh:
            np.testing.assert_allclose(np.asarray(f(x, w)), 16.0)

    def test_replicated_matmul_has_no_collectives(self, tp_mesh):
        x = jnp.ones((4, 16))
        w = jnp.ones((16, 8))

        @jax.jit
        def f(x, w):
            return x @ w

        counts = collective_counts(f, x, w)
        assert all(v == 0 for v in counts.values()), counts


class TestTrainStepCollectives:
    @pytest.mark.slow  # 16 s full-train-step compile; keeps the fast tier < 5 min
    def test_tp_zero1_train_step_pattern(self, tp_mesh):
        """The compiled TP=2 + ZeRO-1 train step must contain reduction
        collectives (grad sync) and gather collectives (ZeRO-1 param
        all-gather) — zeros would mean the mesh sharding silently degraded
        to replication."""
        import jax.numpy as jnp

        from neuronx_distributed_training_tpu.models import llama
        from neuronx_distributed_training_tpu.optim.adamw import (
            AdamWConfig,
            init_opt_state,
            opt_state_specs,
        )
        from neuronx_distributed_training_tpu.parallel import sharding as shd
        from neuronx_distributed_training_tpu.trainer.step import (
            jit_train_step,
            make_train_step,
        )
        from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

        policy = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                             softmax_dtype=jnp.float32)
        cfg = llama.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
            num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
            activations_checkpoint_granularity=None, sequence_parallel=True,
        )
        with tp_mesh, shd.use_mesh(tp_mesh):
            params = llama.init_params(jax.random.PRNGKey(0), cfg, policy)
            pspecs = llama.param_specs(cfg)
            ns = lambda spec: NamedSharding(tp_mesh, spec)
            params = jax.device_put(params, jax.tree_util.tree_map(
                ns, pspecs, is_leaf=lambda x: isinstance(x, P)))
            opt = init_opt_state(params, policy)
            ospecs = opt_state_specs(params, pspecs, tp_mesh, zero1=True,
                                     policy=policy)
            opt = jax.device_put(opt, jax.tree_util.tree_map(
                ns, ospecs, is_leaf=lambda x: isinstance(x, P)))

            def loss_fn(p, batch, key):
                return llama.forward(p, batch, cfg, policy)

            step = make_train_step(loss_fn, AdamWConfig(), lambda s: 1e-3, policy)
            jstep = jit_train_step(step, tp_mesh, pspecs, ospecs)
            ids = jnp.zeros((8, 16), jnp.int32)
            batch = {"input_ids": ids, "labels": ids}
            counts = collective_counts(
                jstep, params, opt, batch, jax.random.PRNGKey(0))
        reductions = counts["all-reduce"] + counts["reduce-scatter"]
        gathers = counts["all-gather"]
        assert reductions >= 1, counts   # TP grad/activation reductions
        assert gathers >= 1, counts      # ZeRO-1 sharded-update re-gather


@pytest.mark.slow
def test_trainer_validate_sharding_gate(tmp_path, devices8):
    """debug.validate_sharding: the trainer asserts param/opt-state layouts at
    build time (and passes on a correct config)."""
    from neuronx_distributed_training_tpu.config.loader import load_config
    from neuronx_distributed_training_tpu.trainer.loop import Trainer

    cfg = load_config({
        "name": "dbg", "model_source": "hf", "seed": 1,
        "trainer": {"max_steps": 1},
        "exp_manager": {"exp_dir": str(tmp_path / "exp")},
        "debug": {"validate_sharding": True},
        "distributed_strategy": {"tensor_model_parallel_size": 2},
        "data": {"global_batch_size": 4, "micro_batch_size": 1,
                 "seq_length": 16, "synthetic": True},
        "model": {"vocab_size": 64, "hidden_size": 32, "intermediate_size": 64,
                  "num_layers": 2, "num_attention_heads": 4,
                  "num_key_value_heads": 2, "max_position_embeddings": 16,
                  "optim": {"lr": 1e-3}},
        "precision": {"type": "mixed_precision"},
    })
    t = Trainer.from_config(cfg, enable_checkpointing=False)  # no raise
    assert t.params is not None
