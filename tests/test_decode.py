"""KV-cache decode: greedy parity with the full-prefix generate path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_training_tpu.models import decode, llama
from neuronx_distributed_training_tpu.models.generate import generate, pad_prompts
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

FP32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                   softmax_dtype=jnp.float32)
CFG = llama.LlamaConfig(
    vocab_size=97, hidden_size=32, intermediate_size=64, num_layers=2,
    num_attention_heads=4, num_kv_heads=2, max_position_embeddings=64,
    activations_checkpoint_granularity=None,
)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(jax.random.PRNGKey(0), CFG, FP32)


class TestCachedDecode:
    def test_prefill_logits_match_forward(self, params):
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 3, 97)
        ref, _ = llama.forward(params, {"input_ids": ids}, CFG, FP32)
        h, cache = decode.prefill(params, ids, CFG, FP32, max_len=20)
        logits = llama.logits_fn(params, h, CFG, FP32)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        assert cache["k"].shape == (2, 2, 20, 2, 8)

    def test_zero_new_tokens_is_noop(self, params):
        prompts = [[5, 6, 7], [10, 11]]
        from neuronx_distributed_training_tpu.models.generate import pad_prompts as pp
        ids, lens = pp(prompts, pad_id=0)
        out = decode.generate_cached(params, CFG, FP32, ids, lens,
                                     max_new_tokens=0, eos_id=96, pad_id=0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ids))

    def test_decode_step_matches_full_forward(self, params):
        """Token t+1 logits from the cache must equal a fresh full forward."""
        ids = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 3, 97)
        _h, cache = decode.prefill(params, ids, CFG, FP32, max_len=16)
        nxt = jnp.asarray([11, 23], jnp.int32)
        pos = jnp.asarray([8, 8], jnp.int32)
        step_logits, _ = decode.decode_step(params, cache, nxt, pos, CFG, FP32)
        full = jnp.concatenate([ids, nxt[:, None]], axis=1)
        ref, _ = llama.forward(params, {"input_ids": full}, CFG, FP32)
        np.testing.assert_allclose(np.asarray(step_logits),
                                   np.asarray(ref[:, -1]), rtol=2e-5, atol=2e-5)

    def test_greedy_parity_with_uncached_generate(self, params):
        """Variable-length right-padded prompts: cached greedy == uncached."""
        prompts = [[5, 6, 7, 8, 9], [10, 11, 12]]
        ids, lens = pad_prompts(prompts, pad_id=0)

        def logits_of(p, buf):
            return llama.forward(p, {"input_ids": buf}, CFG, FP32)[0]

        ref = generate(params, ids, lens, logits_of, max_new_tokens=10,
                       eos_id=96, pad_id=0)
        out = decode.generate_cached(params, CFG, FP32, ids, lens,
                                     max_new_tokens=10, eos_id=96, pad_id=0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_sampled_decode_runs(self, params):
        prompts = [[5, 6, 7], [10, 11, 12]]
        ids, lens = pad_prompts(prompts, pad_id=0)
        out = decode.generate_cached(
            params, CFG, FP32, ids, lens, max_new_tokens=6, eos_id=96,
            temperature=0.8, top_k=20, key=jax.random.PRNGKey(3))
        gen = np.asarray(out)
        assert gen.shape == (2, 3 + 6)
        assert np.all(gen < 97)

    def test_sliding_window_decode(self, params):
        import dataclasses

        cfg = dataclasses.replace(CFG, sliding_window=4)
        prompts = [[5, 6, 7, 8, 9, 10, 11, 12]]
        ids, lens = pad_prompts(prompts, pad_id=0)

        def logits_of(p, buf):
            return llama.forward(p, {"input_ids": buf}, cfg, FP32)[0]

        ref = generate(params, ids, lens, logits_of, max_new_tokens=6,
                       eos_id=96, pad_id=0)
        out = decode.generate_cached(params, cfg, FP32, ids, lens,
                                     max_new_tokens=6, eos_id=96, pad_id=0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


class TestFamilyDecode:
    """Cached decode parity for the Mixtral and Megatron-GPT families."""

    def test_mixtral_greedy_parity(self):
        from neuronx_distributed_training_tpu.models import mixtral
        from neuronx_distributed_training_tpu.ops import moe as moe_ops

        cfg = mixtral.MixtralConfig(
            llama=CFG, moe=moe_ops.MoEConfig(num_experts=4, top_k=2,
                                             dropless=True),
        )
        params = mixtral.init_params(jax.random.PRNGKey(0), cfg, FP32)
        prompts = [[5, 6, 7, 8], [10, 11]]
        ids, lens = pad_prompts(prompts, pad_id=0)

        def logits_of(p, buf):
            return mixtral.forward(p, {"input_ids": buf}, cfg, FP32)[0]

        ref = generate(params, ids, lens, logits_of, max_new_tokens=8,
                       eos_id=96, pad_id=0)
        out = decode.generate_cached(params, cfg, FP32, ids, lens,
                                     max_new_tokens=8, eos_id=96, pad_id=0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    @pytest.mark.parametrize("pe", ["rope", "learned_absolute"])
    def test_gpt_greedy_parity(self, pe):
        from neuronx_distributed_training_tpu.models import gpt

        cfg = gpt.GPTConfig(
            vocab_size=97, hidden_size=32, num_layers=2, num_attention_heads=4,
            num_query_groups=2, max_position_embeddings=64,
            position_embedding_type=pe,
            activations_checkpoint_granularity=None,
        )
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        prompts = [[5, 6, 7, 8, 9], [10, 11, 12]]
        ids, lens = pad_prompts(prompts, pad_id=0)

        def logits_of(p, buf):
            return gpt.forward(p, {"input_ids": buf}, cfg, FP32)[0]

        ref = generate(params, ids, lens, logits_of, max_new_tokens=8,
                       eos_id=96, pad_id=0)
        out = decode.generate_cached(params, cfg, FP32, ids, lens,
                                     max_new_tokens=8, eos_id=96, pad_id=0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


class TestInterleavedDecode:
    """moe_frequency > 1: grouped prefill/decode, flat [L] cache layout."""

    def test_mixtral_interleaved_greedy_parity(self):
        import dataclasses

        from neuronx_distributed_training_tpu.models import mixtral
        from neuronx_distributed_training_tpu.ops import moe as moe_ops

        cfg = mixtral.MixtralConfig(
            llama=dataclasses.replace(CFG, num_layers=4),
            moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True),
            moe_frequency=2,
        )
        params = mixtral.init_params(jax.random.PRNGKey(0), cfg, FP32)
        prompts = [[5, 6, 7, 8], [10, 11]]
        ids, lens = pad_prompts(prompts, pad_id=0)

        def logits_of(p, buf):
            return mixtral.forward(p, {"input_ids": buf}, cfg, FP32)[0]

        ref = generate(params, ids, lens, logits_of, max_new_tokens=8,
                       eos_id=96, pad_id=0)
        out = decode.generate_cached(params, cfg, FP32, ids, lens,
                                     max_new_tokens=8, eos_id=96, pad_id=0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_gpt_interleaved_greedy_parity(self):
        from neuronx_distributed_training_tpu.models import gpt
        from neuronx_distributed_training_tpu.ops import moe as moe_ops

        cfg = gpt.GPTConfig(
            vocab_size=97, hidden_size=32, num_layers=4, num_attention_heads=4,
            num_query_groups=2, max_position_embeddings=64,
            activations_checkpoint_granularity=None,
            moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True),
            moe_frequency=2,
        )
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        prompts = [[5, 6, 7, 8, 9], [10, 11, 12]]
        ids, lens = pad_prompts(prompts, pad_id=0)

        def logits_of(p, buf):
            return gpt.forward(p, {"input_ids": buf}, cfg, FP32)[0]

        ref = generate(params, ids, lens, logits_of, max_new_tokens=8,
                       eos_id=96, pad_id=0)
        out = decode.generate_cached(params, cfg, FP32, ids, lens,
                                     max_new_tokens=8, eos_id=96, pad_id=0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


class TestBlockTypeDecode:
    """Cached decode matches forward() for every transformer block layout
    (the decode path previously hardcoded pre_ln)."""

    @pytest.mark.parametrize("bt", ["post_ln", "normformer", "gpt_j"])
    def test_gpt_block_type_greedy_parity(self, bt):
        from neuronx_distributed_training_tpu.models import gpt

        cfg = gpt.GPTConfig(
            vocab_size=97, hidden_size=32, num_layers=2, num_attention_heads=4,
            max_position_embeddings=64, transformer_block_type=bt,
            activations_checkpoint_granularity=None,
        )
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        prompts = [[5, 6, 7, 8, 9], [10, 11, 12]]
        ids, lens = pad_prompts(prompts, pad_id=0)

        def logits_of(p, buf):
            return gpt.forward(p, {"input_ids": buf}, cfg, FP32)[0]

        ref = generate(params, ids, lens, logits_of, max_new_tokens=6,
                       eos_id=96, pad_id=0)
        out = decode.generate_cached(params, cfg, FP32, ids, lens,
                                     max_new_tokens=6, eos_id=96, pad_id=0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
