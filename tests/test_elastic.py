"""Elastic resume: world-size-agnostic checkpoints, save retry/backoff,
restart-time replanning, the SIGTERM grace window, and the preemption drill.

The headline acceptance test (``TestDrill.test_kill_and_resume_at_smaller_dp``)
is the automated form of the fleet story: a tiny-llama run killed at step k
resumes on a DIFFERENT dp degree, the autotune replanner re-meshes it, and the
loss trajectory matches an uninterrupted control run at pinned tolerance with
the restart cost visible in goodput accounting (docs/elasticity.md).
"""

import errno
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from neuronx_distributed_training_tpu.checkpoint import (
    CheckpointConfig,
    Checkpointer,
    TrainState,
    is_transient_save_error,
)
from neuronx_distributed_training_tpu.config.loader import (
    batch_schedule,
    load_config,
)
from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
from neuronx_distributed_training_tpu.trainer.elastic import (
    ElasticConfig,
    ElasticResumeError,
    FaultInjector,
    SimulatedPreemption,
    build_manifest,
    discover_checkpoint_dir,
    maybe_replan,
    plan_layout_reason,
    read_latest_manifest,
)

from elastic_drill import read_losses, run_drill, tiny_llama_config


# ---------------------------------------------------------------------------
# knob block
# ---------------------------------------------------------------------------


class TestElasticConfig:
    def test_defaults(self):
        ec = ElasticConfig.from_config(None)
        assert not ec.enabled
        assert ec.grace_period_seconds == 30.0
        assert ec.save_retries == 3

    def test_bare_bool_toggles_enabled(self):
        assert ElasticConfig.from_config(True).enabled
        assert not ElasticConfig.from_config(False).enabled

    def test_unknown_key_has_did_you_mean(self):
        with pytest.raises(ValueError, match="grace_period_seconds"):
            ElasticConfig.from_config({"grace_perid_seconds": 5})

    def test_ill_typed_and_negative_rejected(self):
        with pytest.raises(ValueError, match="boolean"):
            ElasticConfig.from_config({"enabled": "yes"})
        with pytest.raises(ValueError, match=">= 0"):
            ElasticConfig.from_config({"save_retries": -1})
        with pytest.raises(ValueError, match="replan_top_k"):
            ElasticConfig.from_config({"replan_top_k": 0})

    def test_int_knobs_reject_bool_float_and_bad_strings(self):
        # int(True) == 1 and int(2.9) == 2 would silently run a misconfigured
        # knob — the contract says ill-typed values raise, with the knob name
        with pytest.raises(ValueError, match="replan_top_k.*integer"):
            ElasticConfig.from_config({"replan_top_k": True})
        with pytest.raises(ValueError, match="save_retries.*integer"):
            ElasticConfig.from_config({"save_retries": 2.9})
        with pytest.raises(ValueError, match="save_retries.*integer"):
            ElasticConfig.from_config({"save_retries": "lots"})
        with pytest.raises(ValueError, match="grace_period_seconds.*number"):
            ElasticConfig.from_config({"grace_period_seconds": "fast"})
        with pytest.raises(ValueError, match="grace_period_seconds.*number"):
            ElasticConfig.from_config({"grace_period_seconds": True})
        # ints are fine for float knobs; floats are not for int knobs
        assert ElasticConfig.from_config(
            {"grace_period_seconds": 5}).grace_period_seconds == 5.0

    def test_checkpoint_config_knobs_flow_through_elastic_config(self):
        # one source of truth: the checkpointer's retry knobs parse via the
        # validated ElasticConfig block, not re-read with literal defaults
        cc = CheckpointConfig.from_config({"exp_manager": {"elastic": {
            "save_retries": 7, "save_retry_backoff_seconds": 0.25}}})
        assert cc.save_retries == 7
        assert cc.save_retry_backoff_seconds == 0.25
        default = ElasticConfig()
        cc = CheckpointConfig.from_config({})
        assert cc.save_retries == default.save_retries
        assert cc.save_retry_backoff_seconds == \
            default.save_retry_backoff_seconds
        with pytest.raises(ValueError, match="save_retries"):
            CheckpointConfig.from_config(
                {"exp_manager": {"elastic": {"save_retries": "lots"}}})

    def test_loader_validates_the_block(self):
        # a typo'd knob must die at config load, not silently run defaults
        with pytest.raises(ValueError, match="grace_period_seconds"):
            load_config({"exp_manager": {"elastic": {"grace_perid_seconds": 5}}})
        cfg = load_config({"exp_manager": {"elastic": {"enabled": True}}})
        assert cfg.exp_manager.elastic.enabled


# ---------------------------------------------------------------------------
# transient-error classification + save retry
# ---------------------------------------------------------------------------


class TestTransientClassification:
    def test_direct_oserrors(self):
        assert is_transient_save_error(OSError(errno.ENOSPC, "disk full"))
        assert is_transient_save_error(OSError(errno.EIO, "io"))
        assert not is_transient_save_error(
            OSError(errno.EACCES, "permission"))
        assert not is_transient_save_error(ValueError("bad tree"))

    def test_wrapped_cause_chain(self):
        # orbax wraps the underlying OSError in its own exception types
        try:
            try:
                raise OSError(errno.ENOSPC, "disk full")
            except OSError as inner:
                raise RuntimeError("commit failed") from inner
        except RuntimeError as outer:
            assert is_transient_save_error(outer)

    def test_timeout_is_transient(self):
        assert is_transient_save_error(TimeoutError("slow store"))


def _small_state(step=1, scale=1.0):
    params = {"w": jnp.full((8, 4), scale, jnp.float32)}
    opt = {"mu": {"w": jnp.zeros((8, 4), jnp.float32)},
           "step": jnp.asarray(step)}
    return TrainState(params=params, opt_state=opt, step=step,
                      consumed_samples=step * 8)


class TestSaveRetry:
    def test_transient_failures_retry_then_succeed(self, tmp_path,
                                                   monkeypatch):
        ck = Checkpointer(CheckpointConfig(dir=tmp_path, async_save=False,
                                           save_top_k=0))
        real_save = ck.save
        calls = {"n": 0}

        def flaky(state, **kw):
            calls["n"] += 1
            if calls["n"] <= 2:
                raise OSError(errno.ENOSPC, "injected disk full")
            return real_save(state, **kw)

        monkeypatch.setattr(ck, "save", flaky)
        slept = []
        monkeypatch.setattr(
            "neuronx_distributed_training_tpu.checkpoint.manager.time.sleep",
            slept.append)
        assert ck.save_with_retry(_small_state(step=3), retries=3,
                                  backoff_seconds=0.25)
        assert calls["n"] == 3
        assert slept == [0.25, 0.5]  # exponential backoff, doubled per retry
        assert ck.latest_step() == 3
        ck.close()

    def test_non_transient_raises_immediately(self, tmp_path, monkeypatch):
        ck = Checkpointer(CheckpointConfig(dir=tmp_path, async_save=False,
                                           save_top_k=0))
        calls = {"n": 0}

        def bad(state, **kw):
            calls["n"] += 1
            raise ValueError("programming error")

        monkeypatch.setattr(ck, "save", bad)
        with pytest.raises(ValueError, match="programming error"):
            ck.save_with_retry(_small_state(), retries=5, backoff_seconds=0.0)
        assert calls["n"] == 1
        ck.close()

    def test_exhausted_retries_reraise_last_transient(self, tmp_path,
                                                      monkeypatch):
        ck = Checkpointer(CheckpointConfig(dir=tmp_path, async_save=False,
                                           save_top_k=0))
        calls = {"n": 0}

        def always_enospc(state, **kw):
            calls["n"] += 1
            raise OSError(errno.ENOSPC, "injected")

        monkeypatch.setattr(ck, "save", always_enospc)
        with pytest.raises(OSError, match="injected"):
            ck.save_with_retry(_small_state(), retries=2, backoff_seconds=0.0)
        assert calls["n"] == 3  # first attempt + 2 retries
        ck.close()

    def test_deadline_bounds_the_grace_window(self, tmp_path, monkeypatch):
        import time as _time

        ck = Checkpointer(CheckpointConfig(dir=tmp_path, async_save=False,
                                           save_top_k=0))
        calls = {"n": 0}

        def always_enospc(state, **kw):
            calls["n"] += 1
            raise OSError(errno.ENOSPC, "injected")

        monkeypatch.setattr(ck, "save", always_enospc)
        with pytest.raises(OSError):
            ck.save_with_retry(_small_state(), retries=10,
                               backoff_seconds=60.0,
                               deadline=_time.monotonic() + 0.1)
        assert calls["n"] == 1  # no 60 s sleep past the expired notice
        ck.close()

    def test_failed_save_never_shadows_last_good(self, tmp_path, monkeypatch):
        """Regression: a failed step-5 save must leave step 3 restorable —
        no stale staging dirs, latest_step still the committed one."""
        ck = Checkpointer(CheckpointConfig(dir=tmp_path, async_save=False,
                                           save_top_k=0))
        good = _small_state(step=3, scale=2.0)
        assert ck.save(good)
        ck.wait()

        real_save = ck.save

        def fails_midway(state, **kw):
            # simulate a crash mid-write: orbax leaves a staging dir behind
            (ck.directory / "5.orbax-checkpoint-tmp-99").mkdir()
            raise OSError(errno.ENOSPC, "injected mid-write")

        monkeypatch.setattr(ck, "save", fails_midway)
        with pytest.raises(OSError):
            ck.save_with_retry(_small_state(step=5), retries=1,
                               backoff_seconds=0.0)
        monkeypatch.setattr(ck, "save", real_save)
        assert not list(ck.directory.glob("5.orbax-checkpoint-tmp-*")), \
            "partial-save staging dir survived cleanup"
        assert ck.latest_step() == 3
        restored = ck.restore(good.params, good.opt_state)
        np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                      np.asarray(good.params["w"]))
        ck.close()

    def test_cleanup_sweeps_previous_steps_staging_dirs(self, tmp_path,
                                                        monkeypatch):
        """An async commit failure surfaces at the NEXT save() call — i.e.
        for a later step.  The cleanup must sweep the earlier step's
        staging leftovers too, not just the step it was called for."""
        ck = Checkpointer(CheckpointConfig(dir=tmp_path, async_save=False,
                                           save_top_k=0))
        # step 10's background commit died mid-write and left its staging
        # tree; the error will surface at the step-20 save below
        (ck.directory / "10.orbax-checkpoint-tmp-7").mkdir()

        def fails(state, **kw):
            raise OSError(errno.ENOSPC, "surfaced stale async failure")

        monkeypatch.setattr(ck, "save", fails)
        with pytest.raises(OSError):
            ck.save_with_retry(_small_state(step=20), retries=0,
                               backoff_seconds=0.0)
        assert not list(ck.directory.glob("*.orbax-checkpoint-tmp-*")), \
            "previous step's staging dir survived the sweep"
        ck.close()


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------


def _tiny_raw(tmp_path, **over):
    raw = tiny_llama_config(tmp_path, max_steps=4, save_every=2)
    raw.update(over)
    return raw


class TestManifest:
    def test_build_manifest_fields(self, tmp_path, cpu_mesh):
        cfg = load_config(_tiny_raw(tmp_path))
        mf = build_manifest(cfg, cpu_mesh, step=7, schedule=None,
                            model_family="LlamaConfig", save_bf16=False)
        assert mf["world_size"] == 8
        assert mf["plan"]["dp"] == batch_schedule(cfg, 8)["dp_size"]
        assert mf["plan"]["pp"] == 1 and mf["layer_layout"] == "flat"
        assert mf["model"]["num_layers"] == 2
        assert mf["step"] == 7 and not mf["save_bf16"]

    def test_manifest_round_trip_and_absence(self, tmp_path):
        ck = Checkpointer(CheckpointConfig(dir=tmp_path, async_save=False,
                                           save_top_k=0))
        st = _small_state(step=2)
        ck.save(st, manifest={"format": 1, "world_size": 4,
                              "plan": {"dp": 4}})
        ck.wait()
        assert ck.read_manifest()["world_size"] == 4
        ck.save(_small_state(step=4))  # no manifest on this one
        ck.wait()
        assert ck.read_manifest(step=4) is None  # pre-elastic save: None
        ck.close()

    def test_discover_checkpoint_dir(self, tmp_path):
        raw = _tiny_raw(tmp_path / "exp")
        cfg = load_config(raw)
        assert discover_checkpoint_dir(cfg) is None  # nothing yet
        name = raw["name"]
        for v in (0, 2):  # newest version_N wins
            (tmp_path / "exp" / name / f"version_{v}" / "checkpoints").mkdir(
                parents=True)
        # an operator's stray non-numeric dir must be ignored, not crash
        (tmp_path / "exp" / name / "version_backup_7").mkdir()
        got = discover_checkpoint_dir(cfg)
        assert got is not None and got.parts[-2] == "version_2"

    def test_discover_mirrors_exp_manager_selection(self, tmp_path):
        """Discovery must key the replan to the dir ExpManager will ACTUALLY
        resume from: its selection is newest version_N with NO
        has-checkpoints fallback, and no resume at all when
        ``resume_if_exists`` is off."""
        raw = _tiny_raw(tmp_path / "exp")
        name = raw["name"]
        (tmp_path / "exp" / name / "version_0" / "checkpoints").mkdir(
            parents=True)
        # a later run crashed before any save: version_1 has no checkpoints/
        # — ExpManager resumes version_1 (fresh), so discovery finds nothing
        (tmp_path / "exp" / name / "version_1").mkdir()
        assert discover_checkpoint_dir(load_config(raw)) is None
        # resume_if_exists off: a fresh version dir is opened, nothing binds
        raw2 = dict(raw)
        raw2["exp_manager"] = dict(raw["exp_manager"],
                                   resume_if_exists=False)
        (tmp_path / "exp" / name / "version_1").rmdir()
        assert discover_checkpoint_dir(load_config(raw2)) is None


# ---------------------------------------------------------------------------
# layout compatibility + replanning
# ---------------------------------------------------------------------------


class TestPlanLayout:
    MANIFEST = {"plan": {"pp": 1, "vp": 1, "tp": 2, "dp": 4},
                "layer_layout": "flat"}

    def test_tp_dp_changes_are_free(self):
        assert plan_layout_reason(self.MANIFEST,
                                  {"pp": 1, "vp": 1, "tp": 4, "dp": 2}) is None

    def test_pp_change_pins_layout(self):
        reason = plan_layout_reason(self.MANIFEST, {"pp": 2, "vp": 1})
        assert reason is not None and "pipeline" in reason

    def test_vp_change_under_pp_pins_layout(self):
        mf = {"plan": {"pp": 2, "vp": 2}, "layer_layout": "interleaved"}
        assert plan_layout_reason(mf, {"pp": 2, "vp": 1}) is not None
        assert plan_layout_reason(mf, {"pp": 2, "vp": 2}) is None


def _seed_checkpoint_with_manifest(tmp_path, raw, world, plan_over=None):
    """Lay down exp/<name>/version_0/checkpoints with one tiny save carrying
    a manifest for ``world`` chips — the replanner's discovery target."""
    cfg = load_config(raw)
    mesh = build_mesh(MeshConfig(), devices=jax.devices()[:world])
    manifest = build_manifest(cfg, mesh, step=2, schedule=None,
                              model_family="LlamaConfig", save_bf16=False)
    if plan_over:
        manifest["plan"].update(plan_over)
    em = raw["exp_manager"]
    ck_dir = (os.path.join(str(em["exp_dir"]), raw["name"], "version_0",
                           "checkpoints"))
    os.makedirs(ck_dir, exist_ok=True)
    ck = Checkpointer(CheckpointConfig(dir=ck_dir, async_save=False,
                                       save_top_k=0))
    ck.save(_small_state(step=2), manifest=manifest)
    ck.wait()
    ck.close()
    return cfg


class TestMaybeReplan:
    def test_no_checkpoint_is_a_noop(self, tmp_path):
        cfg = load_config(_tiny_raw(tmp_path))
        result = maybe_replan(cfg, 8)
        assert not result.replanned and result.cfg is cfg

    def test_same_world_skips_replanning(self, tmp_path):
        cfg = _seed_checkpoint_with_manifest(tmp_path, _tiny_raw(tmp_path), 4)
        result = maybe_replan(cfg, 4)
        assert not result.replanned
        assert result.manifest is not None  # but the manifest WAS read

    def test_changed_world_replans_and_records(self, tmp_path):
        cfg = _seed_checkpoint_with_manifest(tmp_path, _tiny_raw(tmp_path), 4)
        result = maybe_replan(cfg, 2)
        assert result.replanned
        rec = result.record
        assert rec["old_world"] == 4 and rec["new_world"] == 2
        assert rec["old_plan"]["dp"] == 4
        assert rec["new_plan"]["dp"] != rec["old_plan"]["dp"]
        # the imposed config is legal on the new world
        sched = batch_schedule(result.cfg, 2)
        assert sched["dp_size"] == rec["new_plan"]["dp"]

    def test_model_identity_mismatch_refuses_resume(self, tmp_path):
        raw = _tiny_raw(tmp_path)
        _seed_checkpoint_with_manifest(tmp_path, raw, 4)
        raw["model"]["num_layers"] = 4  # not the model that was saved
        with pytest.raises(ElasticResumeError, match="num_layers"):
            maybe_replan(load_config(raw), 2)

    def test_impossible_layout_is_a_curated_error(self, tmp_path):
        # manifest claims pp=5: no 2-chip plan can keep that layer layout
        cfg = _seed_checkpoint_with_manifest(tmp_path, _tiny_raw(tmp_path), 4,
                                             plan_over={"pp": 5})
        with pytest.raises(ElasticResumeError, match="layer layout"):
            maybe_replan(cfg, 2)

    def test_lattice_miss_falls_back_to_declared_config(self, tmp_path):
        """vp=3 has no representation in the planner's curated vp lattice;
        the config's OWN declared parallelism (legal on the new world,
        layout-matching) must be accepted instead of refusing the resume —
        this is also what makes a hand-forced --set mesh actionable."""
        raw = _tiny_raw(tmp_path)
        raw["model"]["num_layers"] = 6
        raw["distributed_strategy"].update(
            pipeline_model_parallel_size=2,
            virtual_pipeline_model_parallel_size=3)
        cfg = _seed_checkpoint_with_manifest(tmp_path, raw, 8)
        result = maybe_replan(cfg, 4)
        assert result.replanned
        assert result.record["fallback"] == "declared-config"
        assert result.record["new_plan"]["pp"] == 2
        assert result.record["new_plan"]["vp"] == 3
        assert result.record["new_plan"]["dp"] == 2
        assert result.cfg is cfg  # the declared config IS the plan


# ---------------------------------------------------------------------------
# fault injector + drain-on-teardown
# ---------------------------------------------------------------------------


class TestFaultInjector:
    def test_validation(self):
        with pytest.raises(ValueError, match="kill|sigterm"):
            FaultInjector(at_step=1, mode="explode")
        with pytest.raises(ValueError, match="step|save|restore"):
            FaultInjector(at_step=1, phase="nowhere")

    def test_fires_once_at_phase_and_step(self):
        fi = FaultInjector(at_step=3, mode="sigterm", phase="save")
        assert not fi.maybe_fire("step", 3)    # wrong phase
        assert not fi.maybe_fire("save", 2)    # too early
        assert fi.maybe_fire("save", 3)
        assert fi.fired and not fi.maybe_fire("save", 4)  # once only

    def test_kill_mode_raises(self):
        fi = FaultInjector(at_step=1, mode="kill", phase="step")
        with pytest.raises(SimulatedPreemption):
            fi.maybe_fire("step", 1)


class TestDrainOnTeardown:
    def test_kill_mid_async_save_is_not_orphaned(self, tmp_path, devices8):
        """fit() dies right after an ASYNC save was initiated; the teardown
        drain (wait_until_finished on every exit path) must still commit it —
        the next incarnation resumes from step 2, not step 0."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        raw = tiny_llama_config(tmp_path, max_steps=6, save_every=2)
        cfg = load_config(raw)
        t = Trainer.from_config(cfg, devices=devices8[:4])
        t.fault_injector = FaultInjector(at_step=2, mode="kill", phase="save")
        with pytest.raises(SimulatedPreemption):
            t.fit()
        ck_dir = discover_checkpoint_dir(cfg)
        assert ck_dir is not None
        ck = Checkpointer(CheckpointConfig(dir=str(ck_dir), async_save=False,
                                           save_top_k=0))
        try:
            assert ck.latest_step() == 2, (
                "async save orphaned by the injected kill")
            assert ck.read_manifest()["world_size"] == 4
        finally:
            ck.close()


class TestGraceWindowStopPath:
    def test_stop_on_cadence_step_takes_drained_emergency_save(
            self, tmp_path, devices8):
        """A preemption stop landing exactly on the checkpoint cadence must
        still take the drained, deadline-bounded emergency save — a plain
        async cadence save has no drain, no retry deadline, and therefore no
        grace-window guarantee."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        raw = tiny_llama_config(tmp_path, max_steps=6, save_every=2)
        cfg = load_config(raw)
        t = Trainer.from_config(cfg, devices=devices8[:4])
        # notice before the step at counter 1 -> that step still runs -> the
        # stop boundary is step 2, which IS the save_every=2 cadence
        t.fault_injector = FaultInjector(at_step=1, mode="sigterm",
                                         phase="step")
        calls = []
        real = t.checkpointer.save_with_retry

        def spy(state, **kw):
            calls.append({"step": state.step, "force": kw.get("force"),
                          "drain": kw.get("drain"),
                          "deadline": kw.get("deadline")})
            return real(state, **kw)

        t.checkpointer.save_with_retry = spy
        t.fit()
        at_stop = [c for c in calls if c["step"] == 2]
        assert len(at_stop) == 1, (
            f"expected exactly the emergency save at the stop step, "
            f"got {calls}")
        assert at_stop[0]["force"] and at_stop[0]["drain"], (
            "the stop-step save was the undrained cadence save — the "
            "grace-window guarantee is lost")
        assert at_stop[0]["deadline"] is not None

    def test_sigterm_during_cadence_save_does_not_double_save(
            self, tmp_path, devices8):
        """The SIGTERM handler can run at any bytecode — including inside
        the cadence save itself.  The stop decision must be snapshotted
        before that save, or the stop branch re-saves the same step and
        orbax raises StepAlreadyExistsError, turning a graceful preemption
        into a crash.  The notice landing mid-save stops at the NEXT
        boundary instead."""
        import signal as _sig

        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        raw = tiny_llama_config(tmp_path, max_steps=6, save_every=2)
        t = Trainer.from_config(load_config(raw), devices=devices8[:4])
        real = t.checkpointer.save_with_retry
        fired = {"done": False}

        def racy(state, **kw):
            out = real(state, **kw)
            if state.step == 2 and not fired["done"]:
                # synchronous delivery: the fit loop's handler sets the stop
                # reason "mid-save", after this save already ran
                fired["done"] = True
                _sig.raise_signal(_sig.SIGTERM)
            return out

        t.checkpointer.save_with_retry = racy
        t.fit()  # must not raise StepAlreadyExistsError
        # the notice was honored one boundary later, with the emergency save
        assert t.step == 3

    def test_notice_during_final_save_is_recorded(self, tmp_path, devices8):
        """A sigterm-mode notice landing during the run's LAST save has no
        loop iteration left to convert it — it must land in the elastic
        trail's stop_reason, not vanish."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        raw = tiny_llama_config(tmp_path, max_steps=2, save_every=2)
        t = Trainer.from_config(load_config(raw), devices=devices8[:4])
        t.fault_injector = FaultInjector(at_step=2, mode="sigterm",
                                         phase="save")
        t.fit()
        assert t.fault_injector.fired
        with open(os.path.join(_run_dir_of(raw), "run_summary.json")) as f:
            summary = json.load(f)
        assert "mid-save" in summary["elastic"]["stop_reason"]

    def test_restore_failure_still_tears_down(self, tmp_path, devices8):
        """A restore-phase kill (or any corrupt-checkpoint restore failure)
        happens before the fit loop proper — it must still restore the
        SIGTERM handler and close the exp manager (log FileHandler), or
        every faulted incarnation leaks both."""
        import logging as _logging
        import signal as _sig

        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        raw = tiny_llama_config(tmp_path, max_steps=4, save_every=2)
        t1 = Trainer.from_config(load_config(raw), devices=devices8[:4])
        t1.fit()  # leaves a resumable checkpoint
        before_handler = _sig.getsignal(_sig.SIGTERM)
        n_log_handlers = len(_logging.getLogger().handlers)
        t2 = Trainer.from_config(load_config(raw), devices=devices8[:4])
        t2.fault_injector = FaultInjector(at_step=0, mode="kill",
                                          phase="restore")
        with pytest.raises(SimulatedPreemption):
            t2.fit()
        assert _sig.getsignal(_sig.SIGTERM) is before_handler, (
            "SIGTERM handler leaked by the faulted restore")
        assert len(_logging.getLogger().handlers) == n_log_handlers, (
            "exp manager log handler leaked by the faulted restore")

    def test_sigterm_mid_save_notice_stops_the_run(self, tmp_path, devices8):
        """FaultInjector(mode=sigterm, phase=save): the notice fired during a
        cadence save must stop the run with an emergency checkpoint — not be
        silently swallowed (the run completing all steps would mean the
        injection exercised nothing)."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        raw = tiny_llama_config(tmp_path, max_steps=6, save_every=2)
        t = Trainer.from_config(load_config(raw), devices=devices8[:4])
        t.fault_injector = FaultInjector(at_step=2, mode="sigterm",
                                         phase="save")
        t.fit()
        assert t.fault_injector.fired
        # notice during the step-2 cadence save -> one more step runs ->
        # emergency stop at step 3, well short of max_steps
        assert t.step == 3
        with open(os.path.join(_run_dir_of(raw), "run_summary.json")) as f:
            summary = json.load(f)
        assert "mid-save" in summary["elastic"]["stop_reason"]


# ---------------------------------------------------------------------------
# resharding restore across dp changes (the ZeRO-1 regrouping)
# ---------------------------------------------------------------------------


def _llama_trees(tied: bool, mesh):
    """Tiny REAL llama params + full opt state (mu/nu/master/ema/health) with
    the production ZeRO-1 specs on ``mesh`` — global shapes are mesh-free, so
    the same call serves the save and the (differently sized) restore mesh."""
    from neuronx_distributed_training_tpu.models import llama
    from neuronx_distributed_training_tpu.optim.adamw import (
        init_opt_state,
        opt_state_specs,
    )
    from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

    mc = llama.LlamaConfig.from_config(
        {"vocab_size": 64, "hidden_size": 32, "intermediate_size": 64,
         "num_layers": 2, "num_attention_heads": 4, "num_key_value_heads": 2,
         "max_position_embeddings": 32, "tie_word_embeddings": tied}, {})
    # bf16 params + f32 optimizer: the ONLY regime with a distinct fp32
    # master tree (mixed_precision keeps params in f32 and skips it)
    policy = DtypePolicy.from_precision_config({"type": "bf16"})
    params = llama.init_params(jax.random.PRNGKey(0), mc, policy)
    pspecs = llama.param_specs(mc)
    opt = init_opt_state(params, policy=policy, ema=True, health=True)
    ospecs = opt_state_specs(params, pspecs, mesh, zero1=True, policy=policy,
                             ema=True, health=True)
    place = lambda tree, specs: jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs,
        is_leaf=lambda x: isinstance(x, P))
    return place(params, pspecs), place(opt, ospecs), pspecs, mc, policy


@pytest.mark.parametrize("dp_from,dp_to", [(4, 2), (2, 4)])
@pytest.mark.parametrize("tied", [True, False])
@pytest.mark.parametrize("save_bf16", [False, True])
def test_restore_reshards_across_dp_change(tmp_path, devices8, dp_from,
                                           dp_to, tied, save_bf16):
    """Params, ZeRO-1 moments, fp32 master, EMA, and health counters saved at
    dp_from restore direct-to-sharded at dp_to — the dp-shard regrouping is
    orbax's sharding-aware read against the NEW mesh's specs."""
    from neuronx_distributed_training_tpu.optim.adamw import opt_state_specs

    mesh_from = build_mesh(MeshConfig(), devices=devices8[:dp_from])
    mesh_to = build_mesh(MeshConfig(), devices=devices8[:dp_to])
    params, opt, pspecs, mc, policy = _llama_trees(tied, mesh_from)
    assert "master" in opt and "ema" in opt and "health" in opt
    assert tied == ("lm_head" not in params)

    ck = Checkpointer(CheckpointConfig(dir=tmp_path, async_save=False,
                                       save_top_k=0, save_bf16=save_bf16))
    ck.save(TrainState(params, opt, 5, 40))
    ck.wait()
    ospecs_to = opt_state_specs(params, pspecs, mesh_to, zero1=True,
                                policy=policy, ema=True, health=True)
    restored = ck.restore(params, opt, mesh=mesh_to, param_specs=pspecs,
                          opt_specs=ospecs_to)
    ck.close()
    assert restored.step == 5 and restored.consumed_samples == 40

    def assert_on_new_mesh(tree, specs):
        def one(x, s):
            assert x.sharding.mesh.devices.size == dp_to, (
                f"leaf not resharded onto the {dp_to}-device mesh")
            assert x.sharding.spec == s
        jax.tree_util.tree_map(one, tree, specs,
                               is_leaf=lambda x: isinstance(x, P))

    assert_on_new_mesh(restored.params, pspecs)
    assert_on_new_mesh(restored.opt_state, ospecs_to)
    for key in ("mu", "nu", "master", "ema", "health"):
        assert key in restored.opt_state
    tol = dict(rtol=1e-2, atol=1e-2) if save_bf16 else dict(rtol=0, atol=0)
    np.testing.assert_allclose(
        np.asarray(restored.params["embed"]["embedding"], np.float32),
        np.asarray(params["embed"]["embedding"], np.float32), **tol)
    # the fp32 master + EMA trees are exact either way (save_bf16 only
    # downcasts the PARAMS item; opt state keeps full precision)
    np.testing.assert_array_equal(
        np.asarray(restored.opt_state["master"]["layers"]["attn"]["qkv"]["w"]),
        np.asarray(opt["master"]["layers"]["attn"]["qkv"]["w"]))
    np.testing.assert_array_equal(
        np.asarray(restored.opt_state["ema"]["embed"]["embedding"]),
        np.asarray(opt["ema"]["embed"]["embedding"]))
    if not tied:
        np.testing.assert_allclose(
            np.asarray(restored.params["lm_head"]["w"], np.float32),
            np.asarray(params["lm_head"]["w"], np.float32), **tol)


# ---------------------------------------------------------------------------
# the drill (the PR's acceptance criterion, automated)
# ---------------------------------------------------------------------------


class TestDrill:
    def test_kill_and_resume_at_smaller_dp(self, tmp_path, devices8):
        """Tiny-llama killed at step 3, resumed on dp 2 (was 4): replanned
        mesh recorded, loss trajectory continuous at pinned tolerance,
        restart cost in goodput accounting."""
        report = run_drill(tmp_path, at_step=3, phase="step", mode="kill",
                           world=4, resume_world=2, total_steps=6)
        assert report["ok"] and report["replanned"]
        assert report["old_plan"]["dp"] == 4
        assert report["new_plan"]["dp"] == 2
        assert report["max_loss_diff"] <= report["loss_tol"]
        assert report["goodput_fraction"] is not None
        assert report["restart_cost_seconds"] >= 0.0
        # the replanned mesh is durably recorded in run_summary.json
        with open(os.path.join(report["run_dir"], "run_summary.json")) as f:
            summary = json.load(f)
        assert summary["elastic"]["replan"]["new_plan"]["dp"] == 2

    @pytest.mark.slow
    def test_sigterm_grace_window_same_world(self, tmp_path, devices8):
        """Graceful preemption notice: the emergency checkpoint inside the
        grace window makes the same-world resume bitwise."""
        report = run_drill(tmp_path, at_step=2, phase="step", mode="sigterm",
                           world=4, resume_world=4, total_steps=6)
        assert report["ok"] and not report["replanned"]
        assert report["max_param_diff"] == 0.0  # bitwise at same world
        # the notice lands before the step at counter 2; that step still
        # runs, then the boundary takes the EMERGENCY save at step 3 — an
        # odd step, so the save_every=2 periodic cadence cannot have taken it
        assert report["resume_step"] == 3

    @pytest.mark.slow
    def test_kill_and_resume_at_larger_dp(self, tmp_path, devices8):
        report = run_drill(tmp_path, at_step=3, phase="step", mode="kill",
                           world=2, resume_world=4, total_steps=6)
        assert report["ok"] and report["replanned"]
        assert report["old_plan"]["dp"] == 2

    @pytest.mark.slow
    def test_restore_phase_drill_kill(self, tmp_path, devices8):
        """The CLI restore drill (--phase restore --mode kill): the fault
        rides the first RESUME incarnation (a fresh start never restores),
        dies mid-restore leaving the save intact, and the second resume
        completes the run bitwise at the same world."""
        report = run_drill(tmp_path, at_step=3, phase="restore", mode="kill",
                           world=2, resume_world=2, total_steps=6)
        assert report["ok"] and not report["replanned"]
        assert report["max_param_diff"] == 0.0

    @pytest.mark.slow
    def test_restore_phase_drill_sigterm_cross_world(self, tmp_path,
                                                     devices8):
        """--phase restore --mode sigterm across a shrink: the notice lands
        mid-restore on the replanned incarnation, which emergency-saves and
        hands off to a clean resume — continuity still holds."""
        report = run_drill(tmp_path, at_step=3, phase="restore",
                           mode="sigterm", world=4, resume_world=2,
                           total_steps=6)
        assert report["ok"] and report["replanned"]
        assert report["new_plan"]["dp"] == 2

    @pytest.mark.slow
    def test_kill_mid_restore_leaves_save_intact(self, tmp_path, devices8):
        """A kill DURING restore (checkpoint read, state not yet applied)
        must leave the save untouched — the next attempt succeeds."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        raw = tiny_llama_config(tmp_path, max_steps=6, save_every=2)
        cfg = load_config(raw)
        t1 = Trainer.from_config(cfg, devices=devices8[:4])
        t1.fault_injector = FaultInjector(at_step=4, mode="kill",
                                          phase="step")
        with pytest.raises(SimulatedPreemption):
            t1.fit()
        # incarnation 2 dies mid-restore
        t2 = Trainer.from_config(load_config(raw), devices=devices8[:4])
        t2.fault_injector = FaultInjector(at_step=0, mode="kill",
                                          phase="restore")
        with pytest.raises(SimulatedPreemption):
            t2.fit()
        # incarnation 3 resumes cleanly from the same save
        t3 = Trainer.from_config(load_config(raw), devices=devices8[:4])
        m = t3.fit()
        assert np.isfinite(m["loss"])
        losses = read_losses(_run_dir_of(raw))
        assert max(losses) == 6


def _run_dir_of(raw):
    em = raw["exp_manager"]
    return os.path.join(str(em["exp_dir"]), raw["name"], "version_0")


@pytest.mark.slow
def test_same_world_autotune_respects_checkpoint_layout(tmp_path, devices8,
                                                        monkeypatch):
    """``--autotune`` on a SAME-world resume must not impose a mesh that
    breaks the resumable checkpoint's layer layout: the planner's winner is
    filtered to layout-compatible candidates (or the launch refuses with a
    curated exit) — never an opaque restore-shape crash."""
    import yaml

    from neuronx_distributed_training_tpu.trainer import cli

    raw = tiny_llama_config(tmp_path / "exp", max_steps=4, save_every=2)
    raw["distributed_strategy"]["pipeline_model_parallel_size"] = 2
    p = tmp_path / "cfg.yaml"
    p.write_text(yaml.safe_dump(raw))
    monkeypatch.setattr(sys, "argv", ["nxdt-train", "--config", str(p)])
    cli.main()  # run 1: saves a pp=2 checkpoint
    monkeypatch.setattr(
        sys, "argv", ["nxdt-train", "--config", str(p), "--autotune"])
    try:
        cli.main()  # same world, planner on: pp=1 winner must be filtered
    except SystemExit as e:
        assert "layer layout" in str(e)
    else:
        # resumed without a restore-shape crash; run 1's trajectory intact
        losses = read_losses(_run_dir_of(raw))
        assert max(losses) == 4 and np.isfinite(losses[4])


# ---------------------------------------------------------------------------
# report surfaces: metrics_report elastic trail + bench drill pickup
# ---------------------------------------------------------------------------


_SUMMARY_WITH_TRAIL = {
    "goodput": {"goodput_fraction": 0.91},
    "elastic": {
        "resumed": True,
        "restart_seconds": 4.312,
        "replan_seconds": 1.807,
        "stop_reason": "SIGTERM (preemption)",
        "replan": {
            "old_world": 4, "new_world": 2, "checkpoint_step": 2,
            "old_plan": {"dp": 4, "tp": 1, "pp": 1, "micro_batch_size": 1},
            "new_plan": {"dp": 2, "tp": 1, "pp": 1, "micro_batch_size": 1},
            "predicted_step_seconds": 0.125,
            "skipped_incompatible": 1,
        },
    },
}


class TestReportSurfaces:
    def test_metrics_report_renders_elastic_trail(self, tmp_path):
        import metrics_report

        out = metrics_report.elastic_section(_SUMMARY_WITH_TRAIL)
        assert "restart/replan trail" in out
        assert "world 4 -> 2 chips" in out
        assert "dp=4" in out and "dp=2" in out
        assert "SIGTERM (preemption)" in out
        assert "1 layout-incompatible" in out
        # and through the full render() path from a run dir on disk
        (tmp_path / "run_summary.json").write_text(
            json.dumps(_SUMMARY_WITH_TRAIL))
        rendered = metrics_report.render(
            None, str(tmp_path / "run_summary.json"))
        assert "restart/replan trail" in rendered

    def test_metrics_report_no_trail_no_section(self):
        import metrics_report

        assert metrics_report.elastic_section({}) == ""
        assert metrics_report.elastic_section({"elastic": {}}) == ""

    def test_bench_picks_up_last_drill(self, tmp_path, monkeypatch):
        """bench.py's JSON line carries restart_cost_seconds +
        goodput_fraction from the last completed drill."""
        import bench

        monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
        assert bench.load_last_drill() == {}  # no drill ran: empty
        (tmp_path / "bench_results").mkdir()
        (tmp_path / "bench_results" / "last_drill.json").write_text(
            json.dumps({"ok": True, "restart_cost_seconds": 0.07,
                        "goodput_fraction": 0.11, "mode": "kill"}))
        drill = bench.load_last_drill()
        assert drill["ok"] and drill["restart_cost_seconds"] == 0.07
