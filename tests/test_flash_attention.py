"""Numerics gate for the Pallas flash-attention kernel: forward and gradients
must match core_attention (the reference-numerics implementation) in
interpreter mode on CPU (SURVEY.md §4 plan item (a))."""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from neuronx_distributed_training_tpu.ops.attention import core_attention
from neuronx_distributed_training_tpu.ops.flash_attention import flash_attention


def _make_qkv(key, b, sq, skv, nh, nkv, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, sq, nh, d), dtype)
    k = jax.random.normal(kk, (b, skv, nkv, d), dtype)
    v = jax.random.normal(kv, (b, skv, nkv, d), dtype)
    return q, k, v


CASES = [
    # (sq, skv, nh, nkv, window, causal)
    (256, 256, 2, 2, None, True),     # MHA causal
    (256, 256, 4, 2, None, True),     # GQA
    (256, 512, 2, 1, None, False),    # cross-length, non-causal, MQA
    (256, 256, 2, 2, 128, True),      # sliding window
]


@pytest.mark.parametrize("sq,skv,nh,nkv,window,causal", CASES)
def test_flash_matches_core_fwd_and_grad(sq, skv, nh, nkv, window, causal):
    q, k, v = _make_qkv(jax.random.PRNGKey(0), 2, sq, skv, nh, nkv, 128)

    def loss_flash(q, k, v):
        o = flash_attention(
            q, k, v, causal=causal, sliding_window=window,
            block_q=128, block_kv=128, interpret=True,
        )
        return jnp.sum(o * o)

    def loss_core(q, k, v):
        o = core_attention(q, k, v, causal=causal, sliding_window=window)
        return jnp.sum(o * o)

    (lf, gf) = jax.value_and_grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    (lc, gc) = jax.value_and_grad(loss_core, argnums=(0, 1, 2))(q, k, v)
    assert jnp.allclose(lf, lc, rtol=2e-4), (lf, lc)
    for a, b_, name in zip(gf, gc, "qkv"):
        err = jnp.max(jnp.abs(a - b_)) / (jnp.max(jnp.abs(b_)) + 1e-9)
        assert err < 2e-3, f"d{name} rel err {err}"


def test_flash_untileable_falls_back():
    # head_dim 64 is not lane-aligned -> silently uses core attention
    q, k, v = _make_qkv(jax.random.PRNGKey(1), 1, 64, 64, 2, 2, 64)
    o = flash_attention(q, k, v, causal=True, interpret=True)
    ref = core_attention(q, k, v, causal=True)
    assert jnp.allclose(o, ref, rtol=1e-5, atol=1e-5)


def test_flash_q_offset_matches_core():
    # context-parallel shard: queries are rows 128..255 of a 256-long sequence
    q, k, v = _make_qkv(jax.random.PRNGKey(2), 1, 128, 256, 2, 2, 128)
    o = flash_attention(
        q, k, v, causal=True, q_offset=128, block_q=128, block_kv=128, interpret=True
    )
    ref = core_attention(q, k, v, causal=True, q_offset=128)
    err = jnp.max(jnp.abs(o - ref))
    assert err < 1e-4, err


def test_flash_bf16_grad_tolerance():
    """Pin bf16 gradient accuracy (dq uses the same fp32 ds accumulation as
    dk/dv — a downcast there showed up as dq-only error growth)."""
    q, k, v = _make_qkv(jax.random.PRNGKey(3), 1, 256, 256, 4, 2, 128, jnp.bfloat16)

    def lf(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=128, block_kv=128,
                            interpret=True)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def lc(q, k, v):
        o = core_attention(q, k, v, causal=True)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    gf = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    gc = jax.grad(lc, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gc, "qkv"):
        a32, b32 = a.astype(jnp.float32), b.astype(jnp.float32)
        err = jnp.max(jnp.abs(a32 - b32)) / (jnp.max(jnp.abs(b32)) + 1e-9)
        assert err < 0.05, f"d{name} bf16 rel err {err}"


def _pad_mask(b, skv, valid_lens):
    from tests.conftest import ragged_right_pad_mask

    return ragged_right_pad_mask(b, skv, valid_lens)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_masked_matches_core_fwd_and_grad(causal):
    """Padded-batch (attention_mask) support inside the Pallas kernel: the
    flash path with a key padding mask must match core attention with the
    equivalent additive bias — fwd and all three grads (VERDICT r2 item 2)."""
    from neuronx_distributed_training_tpu.ops.attention import padding_mask_bias

    b, s = 2, 256
    q, k, v = _make_qkv(jax.random.PRNGKey(7), b, s, s, 4, 2, 128)
    mask = _pad_mask(b, s, [s - 37, 129])  # ragged right-padding

    def loss_flash(q, k, v):
        o = flash_attention(
            q, k, v, causal=causal, attention_mask=mask,
            block_q=128, block_kv=128, interpret=True,
        )
        return jnp.sum(o * o)

    def loss_core(q, k, v):
        o = core_attention(q, k, v, causal=causal, bias=padding_mask_bias(mask))
        return jnp.sum(o * o)

    lf, gf = jax.value_and_grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    lc, gc = jax.value_and_grad(loss_core, argnums=(0, 1, 2))(q, k, v)
    assert jnp.allclose(lf, lc, rtol=2e-4), (lf, lc)
    for a, b_, name in zip(gf, gc, "qkv"):
        err = jnp.max(jnp.abs(a - b_)) / (jnp.max(jnp.abs(b_)) + 1e-9)
        assert err < 2e-3, f"d{name} rel err {err}"


def test_flash_masked_no_grad_leak_to_padded_keys():
    """dk/dv on padded key positions must be exactly zero — the backward
    kernels re-apply the padding mask when recomputing p."""
    b, s, valid = 1, 256, 100
    q, k, v = _make_qkv(jax.random.PRNGKey(8), b, s, s, 2, 2, 128)
    mask = _pad_mask(b, s, [valid])

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, attention_mask=mask,
                            block_q=128, block_kv=128, interpret=True)
        return jnp.sum(o * o)

    _, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert jnp.all(dk[:, valid:] == 0), "dk leaks into padded keys"
    assert jnp.all(dv[:, valid:] == 0), "dv leaks into padded keys"


def test_flash_masked_with_lse_matches_core():
    """The lse-exposing variant (ring building block) honors the mask too."""
    from neuronx_distributed_training_tpu.ops.attention import padding_mask_bias
    from neuronx_distributed_training_tpu.ops.flash_attention import (
        flash_attention_with_lse,
    )

    b, s = 2, 256
    q, k, v = _make_qkv(jax.random.PRNGKey(9), b, s, s, 2, 2, 128)
    mask = _pad_mask(b, s, [200, 130])
    o, lse = flash_attention_with_lse(
        q, k, v, causal=True, attention_mask=mask,
        block_q=128, block_kv=128, interpret=True,
    )
    ref = core_attention(q, k, v, causal=True, bias=padding_mask_bias(mask))
    assert jnp.max(jnp.abs(o - ref)) < 1e-4
    # lse finite on real rows, NEG_INF convention respected on any fully
    # masked row (none here — row i always sees key i when i < valid)
    assert jnp.all(jnp.isfinite(lse[:, :, :130]))


class TestSegmentedFlash:
    """segment_ids: block-diagonal packed-sequence masking inside the kernel
    (a correctness upgrade over the reference's ConcatDataset, whose packed
    records causally attend ACROSS record boundaries)."""

    def _seg(self, b, s, bounds):
        import numpy as np

        seg = np.zeros((b, s), np.int32)
        for bi in range(b):
            sid = 1
            prev = 0
            for cut in bounds[bi] + [s]:
                seg[bi, prev:cut] = sid
                sid += 1
                prev = cut
        return jnp.asarray(seg)

    @pytest.mark.parametrize("causal", [True, False])
    def test_segmented_matches_core_fwd_and_grad(self, causal):
        from neuronx_distributed_training_tpu.ops.attention import (
            segment_mask_bias,
        )

        b, s = 2, 256
        q, k, v = _make_qkv(jax.random.PRNGKey(20), b, s, s, 4, 2, 128)
        seg = self._seg(b, s, [[100, 180], [37]])

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal, segment_ids=seg,
                                block_q=128, block_kv=128, interpret=True)
            return jnp.sum(o * o)

        def loss_core(q, k, v):
            o = core_attention(q, k, v, causal=causal,
                               bias=segment_mask_bias(seg))
            return jnp.sum(o * o)

        lf, gf = jax.value_and_grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        lc, gc = jax.value_and_grad(loss_core, argnums=(0, 1, 2))(q, k, v)
        assert jnp.allclose(lf, lc, rtol=2e-4), (lf, lc)
        for a, b_, name in zip(gf, gc, "qkv"):
            err = jnp.max(jnp.abs(a - b_)) / (jnp.max(jnp.abs(b_)) + 1e-9)
            assert err < 2e-3, f"d{name} rel err {err}"

    def test_no_cross_segment_leak(self):
        """Changing record 1's tokens must not move record 2's outputs."""
        b, s = 1, 256
        q, k, v = _make_qkv(jax.random.PRNGKey(21), b, s, s, 2, 2, 128)
        seg = self._seg(b, s, [[128]])
        o1 = flash_attention(q, k, v, causal=True, segment_ids=seg,
                             block_q=128, block_kv=128, interpret=True)
        # perturb segment 1 (first 128 positions) of k/v
        k2 = k.at[:, :128].add(1.0)
        v2 = v.at[:, :128].add(-1.0)
        o2 = flash_attention(q, k2, v2, causal=True, segment_ids=seg,
                             block_q=128, block_kv=128, interpret=True)
        np.testing.assert_array_equal(np.asarray(o1[:, 128:]),
                                      np.asarray(o2[:, 128:]))
        assert not np.allclose(np.asarray(o1[:, :128]), np.asarray(o2[:, :128]))

    def test_segments_compose_with_padding_mask(self):
        from neuronx_distributed_training_tpu.ops.attention import (
            padding_mask_bias,
            segment_mask_bias,
        )

        b, s = 1, 256
        q, k, v = _make_qkv(jax.random.PRNGKey(22), b, s, s, 2, 2, 128)
        seg = self._seg(b, s, [[90]])
        mask = _pad_mask(b, s, [200])
        o = flash_attention(q, k, v, causal=True, segment_ids=seg,
                            attention_mask=mask, block_q=128, block_kv=128,
                            interpret=True)
        ref = core_attention(
            q, k, v, causal=True,
            bias=padding_mask_bias(mask) + segment_mask_bias(seg))
        assert jnp.max(jnp.abs(o - ref)) < 1e-4

    def test_cross_attention_segments_rejected(self):
        q, k, v = _make_qkv(jax.random.PRNGKey(23), 1, 128, 256, 2, 2, 128)
        with pytest.raises(ValueError, match="self-attention"):
            flash_attention(q, k, v, causal=False,
                            segment_ids=jnp.zeros((1, 128), jnp.int32),
                            interpret=True)
