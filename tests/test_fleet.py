"""Fleet observability plane: beacons, aggregator, alert engine, wiring.

Simulated-fleet harness: :func:`write_sim_fleet` writes N host beacon
streams with seeded skew / stalls / deaths, and the tests assert the
aggregator names the right host AND the right cause class — off hardware,
off multiprocessing.  The live half drives real tiny-llama ``fit()`` runs
(alert halt, beacon continuity across incarnations, the dispatch-ahead
contract with fleet + alerts enabled).

``python tests/test_fleet.py --regen-fixture`` regenerates the committed
``tests/data/fleet_fixture/`` streams the verify SKILL's
``fleet_monitor --json`` smoke reads.
"""

from __future__ import annotations

import importlib.util
import json
import os
import time
from pathlib import Path

import numpy as np
import pytest

from neuronx_distributed_training_tpu.config.loader import load_config
from neuronx_distributed_training_tpu.telemetry import TelemetryConfig
from neuronx_distributed_training_tpu.telemetry.alerts import (
    AlertEngine,
    AlertRule,
    parse_alerts,
)
from neuronx_distributed_training_tpu.telemetry.fleet import (
    FleetAggregator,
    FleetBeacon,
    FleetConfig,
    aggregate_fleet,
    beacon_path,
)

FIXTURE = Path(__file__).parent / "data" / "fleet_fixture"


# ---------------------------------------------------------------------------
# the simulated-fleet harness
# ---------------------------------------------------------------------------

#: wall seconds per boundary window in simulated streams
SIM_WINDOW = 300.0
SIM_T0 = 1_700_000_000.0


def write_sim_fleet(
    fleet_dir: str | Path,
    *,
    n_hosts: int = 4,
    n_steps: int = 8,
    straggler: int | None = 2,
    cause: str = "data_stall",
    quiet_host: int | None = None,
    quiet_after: int = 4,
    die_host: int | None = None,
    die_after: int = 6,
    close_clean: bool = True,
    window: float = SIM_WINDOW,
) -> Path:
    """Write ``n_hosts`` beacon streams with seeded behavior.

    The fleet is lockstep (every host reaches step ``s`` at nearly the same
    wall instant) — the straggler signature is in the SPANS: the seeded
    straggler accumulates its cause span (data_wait / checkpoint / plain
    busy time) while every other host accumulates ``host_sync`` (waiting at
    the rendezvous).  Per-host monotonic origins deliberately differ: the
    aggregator must never compare them across hosts.
    """
    fleet_dir = Path(fleet_dir)
    fleet_dir.mkdir(parents=True, exist_ok=True)
    for h in range(n_hosts):
        spans = {"data_wait": 0.0, "host_sync": 0.0, "checkpoint": 0.0}
        mono0 = 1000.0 + 7.77 * h  # incomparable origins, on purpose
        lines = []
        last_step = n_steps
        for s in range(1, n_steps + 1):
            if quiet_host == h and s > quiet_after:
                last_step = quiet_after
                break
            if die_host == h and s > die_after:
                last_step = die_after
                break
            is_straggler = straggler == h
            if is_straggler:
                spans["host_sync"] += 0.5
                if cause == "data_stall":
                    spans["data_wait"] += 0.6 * window
                elif cause == "checkpoint_blocked":
                    spans["checkpoint"] += 0.6 * window
                # compute_slow: the busy time is just... compute (no span)
            else:
                spans["host_sync"] += 0.93 * window
                spans["data_wait"] += 0.2
            mfu = 0.35 if is_straggler else 0.55 - 0.01 * h
            goodput = 0.62 if is_straggler else 0.90 - 0.01 * h
            lines.append(json.dumps({
                "host": h,
                "step": s,
                "t_mono": round(mono0 + s * window, 6),
                "t_wall": round(SIM_T0 + s * window + 0.05 * h, 6),
                "metrics": {"loss": round(8.0 - 0.2 * s, 4), "mfu": mfu,
                            "goodput_fraction": goodput,
                            "step_time": window / 10.0},
                "spans": {k: round(v, 6) for k, v in spans.items()},
            }))
        if die_host == h:
            lines.append(json.dumps({
                "host": h, "step": last_step,
                "t_mono": round(mono0 + (last_step + 1) * window, 6),
                "t_wall": round(SIM_T0 + (last_step + 0.1) * window, 6),
                "metrics": {},
                "last_exception": "RuntimeError: injected device loss",
            }))
        elif close_clean and quiet_host != h:
            lines.append(json.dumps({
                "host": h, "step": last_step,
                "t_mono": round(mono0 + (last_step + 0.01) * window, 6),
                "t_wall": round(SIM_T0 + last_step * window + 1.0, 6),
                "metrics": {}, "closing": True,
            }))
        (fleet_dir / f"host_{h}.jsonl").write_text("\n".join(lines) + "\n")
    return fleet_dir


def regen_fixture() -> None:
    """The committed fixture: 5 hosts, host 2 data-stalls, host 3 goes
    quiet after step 4, host 4 dies at step 6 — the fleet_monitor smoke
    must name all three."""
    import shutil

    shutil.rmtree(FIXTURE, ignore_errors=True)
    write_sim_fleet(FIXTURE, n_hosts=5, n_steps=8, straggler=2,
                    cause="data_stall", quiet_host=3, quiet_after=4,
                    die_host=4, die_after=6)


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


class TestFleetConfig:
    def test_defaults_disabled(self):
        cfg = FleetConfig.from_config(None)
        assert not cfg.enabled
        assert cfg.stale_after_seconds == 600.0

    def test_bool_form(self):
        assert FleetConfig.from_config(True).enabled
        assert not FleetConfig.from_config(False).enabled

    def test_unknown_key_did_you_mean(self):
        with pytest.raises(ValueError, match="stale_after_seconds"):
            FleetConfig.from_config({"stale_after_secs": 5})

    def test_bad_values(self):
        with pytest.raises(ValueError, match="stale_after_seconds"):
            FleetConfig.from_config({"stale_after_seconds": 0})
        with pytest.raises(ValueError, match="max_windows"):
            FleetConfig.from_config({"max_windows": 0})
        with pytest.raises(ValueError, match="boolean"):
            FleetConfig.from_config({"enabled": "yes"})
        with pytest.raises(ValueError, match="mapping"):
            FleetConfig.from_config([1])

    def test_nested_in_telemetry(self):
        tc = TelemetryConfig.from_config(
            {"fleet": {"enabled": True, "stale_after_seconds": 5.0},
             "batch_stats": True})
        assert tc.fleet.enabled and tc.fleet.stale_after_seconds == 5.0
        assert tc.batch_stats

    def test_telemetry_bool_keeps_fleet_disabled(self):
        assert not TelemetryConfig.from_config(True).fleet.enabled
        assert TelemetryConfig.from_config(True).alerts == ()

    def test_validated_at_config_load(self):
        with pytest.raises(ValueError, match="fleet"):
            load_config({"exp_manager": {"telemetry": {
                "fleet": {"enable": True}}}})


class TestAlertRules:
    def test_parse_minimal(self):
        (r,) = parse_alerts([{"metric": "loss", "threshold": 10.0}])
        assert r.name == "loss_threshold" and r.action == "log"
        assert r.window == 1 and r.mode == "threshold"

    def test_parse_full(self):
        rules = parse_alerts([
            {"metric": "data_wait", "window": 3, "threshold": 30.0,
             "action": "halt", "name": "dw"},
            {"metric": "mfu", "window": 5, "rel_drop": 0.2,
             "action": "dump"},
            {"metric": "loss", "below": 0.0},
        ])
        assert [r.mode for r in rules] == ["threshold", "rel_drop", "below"]
        assert rules[0].name == "dw"

    def test_none_and_empty(self):
        assert parse_alerts(None) == ()
        assert parse_alerts([]) == ()

    def test_not_a_list(self):
        with pytest.raises(ValueError, match="LIST"):
            parse_alerts({"metric": "loss", "threshold": 1})
        with pytest.raises(ValueError, match="LIST"):
            parse_alerts("loss")

    def test_missing_metric(self):
        with pytest.raises(ValueError, match="metric is required"):
            parse_alerts([{"threshold": 1.0}])

    def test_exactly_one_mode(self):
        with pytest.raises(ValueError, match="exactly ONE"):
            parse_alerts([{"metric": "loss"}])
        with pytest.raises(ValueError, match="exactly ONE"):
            parse_alerts([{"metric": "loss", "threshold": 1, "below": 0}])

    def test_bad_action_and_window(self):
        with pytest.raises(ValueError, match="action"):
            parse_alerts([{"metric": "loss", "threshold": 1,
                           "action": "page_oncall"}])
        with pytest.raises(ValueError, match="window"):
            parse_alerts([{"metric": "loss", "threshold": 1, "window": 0}])

    def test_rel_drop_range(self):
        with pytest.raises(ValueError, match="rel_drop"):
            parse_alerts([{"metric": "mfu", "rel_drop": 1.5}])

    def test_rel_rise_parses_and_ranges(self):
        (r,) = parse_alerts([{"metric": "data_wait", "rel_rise": 0.5}])
        assert r.mode == "rel_rise" and r.name == "data_wait_rel_rise"
        # unlike rel_drop there is no upper bound: 3.0 = "quadrupled"
        (r,) = parse_alerts([{"metric": "data_wait", "rel_rise": 3.0}])
        assert r.rel_rise == 3.0
        with pytest.raises(ValueError, match="rel_rise"):
            parse_alerts([{"metric": "data_wait", "rel_rise": 0.0}])
        with pytest.raises(ValueError, match="rel_rise"):
            parse_alerts([{"metric": "data_wait", "rel_rise": -0.2}])
        with pytest.raises(ValueError, match="exactly ONE"):
            parse_alerts([{"metric": "mfu", "rel_drop": 0.2,
                           "rel_rise": 0.2}])

    def test_rel_rise_did_you_mean(self):
        with pytest.raises(ValueError, match="rel_rise"):
            parse_alerts([{"metric": "loss", "rel_ris": 0.5}])

    def test_unknown_key_did_you_mean(self):
        with pytest.raises(ValueError, match="threshold"):
            parse_alerts([{"metric": "loss", "treshold": 1.0}])

    def test_duplicate_names(self):
        with pytest.raises(ValueError, match="duplicate"):
            parse_alerts([{"metric": "loss", "threshold": 1},
                          {"metric": "loss", "threshold": 2}])

    def test_validated_at_config_load(self):
        with pytest.raises(ValueError, match="alerts"):
            load_config({"exp_manager": {"telemetry": {
                "alerts": [{"metric": "loss"}]}}})


# ---------------------------------------------------------------------------
# beacons
# ---------------------------------------------------------------------------


class TestBeacon:
    def test_emit_lines_parse(self, tmp_path):
        b = FleetBeacon(tmp_path, host=3)
        b.emit(10, {"loss": 2.5, "mfu": 0.5, "health/nonfinite_count": 0,
                    "data/padding_fraction": 0.1, "grad_norm": 1.0},
               spans={"data_wait": 0.25})
        b.emit(20, {"loss": float("nan")})
        b.close()
        lines = beacon_path(tmp_path, 3).read_text().strip().splitlines()
        recs = [json.loads(l) for l in lines]
        assert recs[0]["host"] == 3 and recs[0]["step"] == 10
        assert recs[0]["metrics"]["loss"] == 2.5
        # health/ and data/ keys ride; unknown scalars don't
        assert "health/nonfinite_count" in recs[0]["metrics"]
        assert "data/padding_fraction" in recs[0]["metrics"]
        assert "grad_norm" not in recs[0]["metrics"]
        assert recs[0]["spans"]["data_wait"] == 0.25
        # strict JSON: NaN -> null, never a bare NaN token
        assert recs[1]["metrics"]["loss"] is None
        assert recs[-1]["closing"] is True

    def test_close_with_exception_marks_death(self, tmp_path):
        b = FleetBeacon(tmp_path, host=0)
        b.emit(1, {"loss": 1.0})
        b.close(last_exception="RuntimeError: boom", step=1)
        recs = [json.loads(l) for l in
                beacon_path(tmp_path, 0).read_text().strip().splitlines()]
        assert recs[-1]["last_exception"].startswith("RuntimeError")
        assert "closing" not in recs[-1]

    def test_emit_after_close_is_noop(self, tmp_path):
        b = FleetBeacon(tmp_path, host=0)
        b.close()
        b.emit(5, {"loss": 1.0})
        lines = beacon_path(tmp_path, 0).read_text().strip().splitlines()
        assert len(lines) == 1

    def test_torn_tail_line_skipped(self, tmp_path):
        write_sim_fleet(tmp_path, n_hosts=2, n_steps=3, straggler=None)
        p = beacon_path(tmp_path, 0)
        with open(p, "a") as f:
            f.write('{"host": 0, "step": 99, "t_mono":')  # no newline: torn
        summary = aggregate_fleet(tmp_path)
        assert summary["hosts"]["0"]["last_step"] == 3

    def test_malformed_complete_line_skipped(self, tmp_path):
        write_sim_fleet(tmp_path, n_hosts=1, n_steps=2, straggler=None)
        p = beacon_path(tmp_path, 0)
        with open(p, "a") as f:
            f.write("not json at all\n")
        summary = aggregate_fleet(tmp_path)
        assert summary["hosts"]["0"]["beacons"] == 3  # 2 + closing


# ---------------------------------------------------------------------------
# the aggregator on simulated fleets
# ---------------------------------------------------------------------------


class TestAggregatorStraggler:
    @pytest.mark.parametrize("cause", ["data_stall", "checkpoint_blocked",
                                       "compute_slow"])
    def test_names_straggler_and_cause(self, tmp_path, cause):
        write_sim_fleet(tmp_path, n_hosts=4, n_steps=6, straggler=2,
                        cause=cause)
        s = aggregate_fleet(tmp_path)
        assert s["straggler"] is not None, s["windows"]
        assert s["straggler"]["host"] == 2
        assert s["straggler"]["cause"] == cause
        # every attributed window agrees
        for w in s["windows"]:
            assert w["straggler_host"] == 2
            assert w["cause"] == cause

    def test_balanced_fleet_names_no_straggler(self, tmp_path):
        write_sim_fleet(tmp_path, n_hosts=3, n_steps=5, straggler=None)
        s = aggregate_fleet(tmp_path)
        assert s["straggler"] is None
        assert all(w["straggler_host"] is None for w in s["windows"])

    def test_arrival_skew_reported(self, tmp_path):
        write_sim_fleet(tmp_path, n_hosts=4, n_steps=4, straggler=1)
        s = aggregate_fleet(tmp_path)
        # seeded jitter: 0.05 * host -> skew 0.15 across 4 hosts
        assert s["windows"][-1]["arrival_skew_seconds"] == pytest.approx(
            0.15, abs=1e-6)

    def test_windows_capped(self, tmp_path):
        write_sim_fleet(tmp_path, n_hosts=2, n_steps=30, straggler=1)
        agg = FleetAggregator(tmp_path, max_windows=5)
        s = agg.refresh()
        assert len(s["windows"]) == 5
        assert s["windows"][-1]["step"] == 30

    def test_monotonic_origins_never_compared(self, tmp_path):
        # host origins differ by ~8s in the sim; busy seconds must still be
        # window-duration-sized, not origin-delta-sized
        write_sim_fleet(tmp_path, n_hosts=3, n_steps=4, straggler=0)
        s = aggregate_fleet(tmp_path)
        for w in s["windows"]:
            for busy in w["busy_seconds"].values():
                assert 0.0 <= busy <= SIM_WINDOW * 1.01


class TestAggregatorQuietAndDead:
    def test_quiet_host_detected_with_cause(self, tmp_path):
        write_sim_fleet(tmp_path, n_hosts=4, n_steps=8, straggler=None,
                        quiet_host=3, quiet_after=4)
        s = aggregate_fleet(tmp_path)
        assert [q["host"] for q in s["quiet_hosts"]] == [3]
        assert s["quiet_hosts"][0]["last_step"] == 4
        # 4 windows of silence at 300s >> the 600s default
        assert s["quiet_hosts"][0]["silent_seconds"] > 600
        stalls = [f for f in s["findings"] if f["kind"] == "fleet_stall"]
        assert len(stalls) == 1 and stalls[0]["host"] == 3
        assert "absence of progress" in stalls[0]["message"]

    def test_cleanly_closed_hosts_never_quiet(self, tmp_path):
        write_sim_fleet(tmp_path, n_hosts=3, n_steps=4, straggler=None)
        # host 0's clean close landed long before "now"
        s = aggregate_fleet(tmp_path, now=SIM_T0 + 1e6)
        assert s["quiet_hosts"] == []

    def test_live_now_reference(self, tmp_path):
        # offline: newest beacon anchors staleness -> nobody quiet in a
        # freshly-stopped balanced fleet; live `now` far ahead -> an
        # UNCLOSED host is quiet
        write_sim_fleet(tmp_path, n_hosts=2, n_steps=3, straggler=None,
                        close_clean=False)
        assert aggregate_fleet(tmp_path)["quiet_hosts"] == []
        s = aggregate_fleet(tmp_path, now=SIM_T0 + 3 * SIM_WINDOW + 10_000)
        assert [q["host"] for q in s["quiet_hosts"]] == [0, 1]

    def test_dead_host_is_a_death_not_a_stall(self, tmp_path):
        write_sim_fleet(tmp_path, n_hosts=3, n_steps=8, straggler=None,
                        die_host=2, die_after=3)
        s = aggregate_fleet(tmp_path)
        deaths = [f for f in s["findings"] if f["kind"] == "host_died"]
        assert len(deaths) == 1 and deaths[0]["host"] == 2
        assert "injected device loss" in deaths[0]["message"]
        assert all(q["host"] != 2 for q in s["quiet_hosts"])


class TestAggregatorSpreadAndGoodput:
    def test_spread_names_hosts(self, tmp_path):
        write_sim_fleet(tmp_path, n_hosts=4, n_steps=5, straggler=2)
        s = aggregate_fleet(tmp_path)
        mfu = s["spread"]["mfu"]
        assert mfu["min"]["host"] == 2 and mfu["min"]["value"] == 0.35
        assert mfu["max"]["host"] == 0 and mfu["max"]["value"] == 0.55
        assert mfu["min"]["value"] <= mfu["p50"] <= mfu["max"]["value"]
        dw = s["spread"]["data_wait_seconds"]
        assert dw["max"]["host"] == 2  # the data-stall straggler

    def test_goodput_decomposition(self, tmp_path):
        write_sim_fleet(tmp_path, n_hosts=4, n_steps=5, straggler=2)
        gp = aggregate_fleet(tmp_path)["goodput"]
        assert gp["worst_host"] == 2 and gp["best_host"] == 0
        assert gp["fleet_goodput_fraction"] == pytest.approx(0.62)
        assert gp["common_overhead_fraction"] == pytest.approx(0.10)
        assert gp["straggler_loss_fraction"] == pytest.approx(0.28)
        # the decomposition is exact: lost = common + straggler
        assert (gp["fleet_goodput_fraction"]
                + gp["common_overhead_fraction"]
                + gp["straggler_loss_fraction"]) == pytest.approx(1.0)

    def test_incremental_refresh(self, tmp_path):
        write_sim_fleet(tmp_path, n_hosts=2, n_steps=3, straggler=1,
                        close_clean=False)
        agg = FleetAggregator(tmp_path)
        s1 = agg.refresh()
        assert s1["hosts"]["0"]["last_step"] == 3
        n_windows = len(s1["windows"])
        # append two more steps to each stream; only the new lines are read
        for h in range(2):
            spans = {"data_wait": 0.0, "host_sync": 0.0, "checkpoint": 0.0}
            with open(beacon_path(tmp_path, h), "a") as f:
                for s in (4, 5):
                    f.write(json.dumps({
                        "host": h, "step": s,
                        "t_mono": 1000.0 + 7.77 * h + s * SIM_WINDOW,
                        "t_wall": SIM_T0 + s * SIM_WINDOW,
                        "metrics": {"loss": 1.0}, "spans": spans}) + "\n")
        s2 = agg.refresh()
        assert s2["hosts"]["0"]["last_step"] == 5
        assert len(s2["windows"]) > n_windows


# ---------------------------------------------------------------------------
# the alert engine
# ---------------------------------------------------------------------------


class TestAlertEngine:
    def _engine(self, *rules, sink=None):
        return AlertEngine(parse_alerts(list(rules)),
                           write_run_summary=sink)

    def test_threshold_fires(self):
        eng = self._engine({"metric": "loss", "threshold": 5.0})
        assert eng.observe(1, {"loss": 4.0}) == []
        (f,) = eng.observe(2, {"loss": 6.0})
        assert f.rule == "loss_threshold" and f.action == "log"
        assert f.value == 6.0 and "threshold" in f.message

    def test_below_fires(self):
        eng = self._engine({"metric": "mfu", "below": 0.3})
        assert eng.observe(1, {"mfu": 0.5}) == []
        (f,) = eng.observe(2, {"mfu": 0.2})
        assert "floor" in f.message

    def test_window_mean(self):
        eng = self._engine({"metric": "loss", "threshold": 5.0, "window": 3})
        # one spike in a 3-window mean must NOT fire (6+1+1)/3 = 2.67
        assert eng.observe(1, {"loss": 6.0}) == []  # window not full yet
        assert eng.observe(2, {"loss": 1.0}) == []
        assert eng.observe(3, {"loss": 1.0}) == []
        assert eng.observe(4, {"loss": 9.0}) == []  # mean 3.67
        (f,) = eng.observe(5, {"loss": 9.0})  # mean 6.33
        assert "mean of last 3" in f.message

    def test_rel_drop_vs_running_peak(self):
        eng = self._engine({"metric": "mfu", "rel_drop": 0.2})
        assert eng.observe(1, {"mfu": 0.50}) == []  # establishes the peak
        assert eng.observe(2, {"mfu": 0.45}) == []  # -10%: inside band
        (f,) = eng.observe(3, {"mfu": 0.35})        # -30%: fires
        assert "running peak 0.5" in f.message
        # the collapsed value must NOT ratchet the peak down: recovery to
        # 0.45 clears, a second collapse re-fires against the SAME peak
        assert eng.observe(4, {"mfu": 0.45}) == []
        (f2,) = eng.observe(5, {"mfu": 0.30})
        assert "0.5" in f2.message

    def test_rel_rise_vs_running_minimum(self):
        eng = self._engine({"metric": "tensorstats/pre/embed/subnormal_frac",
                            "rel_rise": 0.5})
        m = "tensorstats/pre/embed/subnormal_frac"
        assert eng.observe(1, {m: 0.10}) == []  # establishes the trough
        assert eng.observe(2, {m: 0.13}) == []  # +30%: inside band
        (f,) = eng.observe(3, {m: 0.20})        # +100%: fires
        assert "running minimum 0.1" in f.message
        # the spiked value must NOT ratchet the trough up: recovery to 0.13
        # clears, a second spike re-fires against the SAME trough
        assert eng.observe(4, {m: 0.13}) == []
        (f2,) = eng.observe(5, {m: 0.20})
        assert "0.1" in f2.message
        # a clean window BELOW the trough advances it down: 0.05 becomes the
        # new floor, so 0.08 (+60%) now fires where it never would before
        assert eng.observe(6, {m: 0.05}) == []
        (f3,) = eng.observe(7, {m: 0.08})
        assert "0.05" in f3.message

    def test_rel_rise_never_fires_from_zero_trough(self):
        # relative rise from a 0.0 trough is undefined (mirrors rel_drop's
        # non-positive-peak guard): the rule stays silent forever
        eng = self._engine({"metric": "x", "rel_rise": 0.5})
        assert eng.observe(1, {"x": 0.0}) == []
        assert eng.observe(2, {"x": 1e9}) == []

    def test_edge_triggered_no_refire_while_active(self):
        eng = self._engine({"metric": "loss", "threshold": 5.0})
        assert len(eng.observe(1, {"loss": 9.0})) == 1
        assert eng.observe(2, {"loss": 9.0}) == []  # still in violation
        assert eng.observe(3, {"loss": 1.0}) == []  # clears
        assert len(eng.observe(4, {"loss": 9.0})) == 1  # re-arms

    def test_span_prefix_fallback(self):
        eng = self._engine({"metric": "data_wait", "threshold": 1.0})
        (f,) = eng.observe(1, {"time/data_wait": 2.0})
        assert f.metric == "data_wait"

    def test_missing_and_nan_metrics_skipped(self):
        eng = self._engine({"metric": "mfu", "below": 0.3})
        assert eng.observe(1, {"loss": 1.0}) == []
        assert eng.observe(2, {"mfu": float("nan")}) == []

    def test_trail_written_and_capped(self):
        writes = []
        eng = self._engine({"metric": "loss", "threshold": 5.0},
                           sink=lambda s: writes.append(s))
        for step in range(1, 60):
            eng.observe(2 * step, {"loss": 9.0})
            eng.observe(2 * step + 1, {"loss": 1.0})  # clear -> re-arm
        from neuronx_distributed_training_tpu.telemetry.alerts import (
            MAX_FIRINGS_PER_RULE,
        )

        assert len(eng.firings) == MAX_FIRINGS_PER_RULE
        assert writes and writes[-1] == {"alerts": eng.firings}

    def test_multiple_rules_independent(self):
        eng = self._engine({"metric": "loss", "threshold": 5.0},
                           {"metric": "mfu", "below": 0.3, "action": "halt"})
        fires = eng.observe(1, {"loss": 9.0, "mfu": 0.1})
        assert {f.action for f in fires} == {"log", "halt"}


# ---------------------------------------------------------------------------
# atomic summary writes (satellite)
# ---------------------------------------------------------------------------


class TestAtomicSummaries:
    def test_unserializable_section_leaves_file_intact(self, tmp_path):
        from neuronx_distributed_training_tpu.trainer.exp_manager import (
            ExpManager,
        )

        exp = ExpManager(exp_dir=tmp_path, name="t",
                         create_tensorboard_logger=False, log_files=False)
        exp.write_run_summary({"good": 1})
        before = (exp.log_dir / "run_summary.json").read_text()
        with pytest.raises(TypeError):
            exp.write_run_summary({"bad": object()})
        # the old document is byte-identical — pre-fix this truncated it
        assert (exp.log_dir / "run_summary.json").read_text() == before
        exp.close()

    def test_kill_mid_write_leaves_valid_json(self, tmp_path, monkeypatch):
        from neuronx_distributed_training_tpu.utils import io as io_mod

        target = tmp_path / "run_summary.json"
        io_mod.atomic_write_json(target, {"step": 1})
        # simulate SIGKILL between temp write and rename: the temp file is
        # fully written but the rename never happens
        real_replace = os.replace

        def killed(src, dst):
            raise KeyboardInterrupt("SIGKILL stand-in")

        monkeypatch.setattr(os, "replace", killed)
        with pytest.raises(KeyboardInterrupt):
            io_mod.atomic_write_json(target, {"step": 2})
        monkeypatch.setattr(os, "replace", real_replace)
        assert json.loads(target.read_text()) == {"step": 1}
        # and a leftover temp file never shadows the real document
        assert json.loads(target.read_text())["step"] == 1

    def test_fleet_summary_write_atomic(self, tmp_path):
        from neuronx_distributed_training_tpu.telemetry.fleet import (
            write_fleet_summary,
        )

        p = tmp_path / "fleet_summary.json"
        write_fleet_summary({"n_hosts": 2}, p)
        assert json.loads(p.read_text())["n_hosts"] == 2
        assert not list(tmp_path.glob("*.tmp.*"))


# ---------------------------------------------------------------------------
# non-scalar sink fix (satellite)
# ---------------------------------------------------------------------------


class TestNonScalarSinks:
    def _exp(self, tmp_path):
        from neuronx_distributed_training_tpu.trainer.exp_manager import (
            ExpManager,
        )

        exp = ExpManager(exp_dir=tmp_path, name="t", log_every_n_steps=1,
                         create_tensorboard_logger=False, log_files=False)

        class StubTB:
            def __init__(self):
                self.scalars = []

            def add_scalar(self, k, v, step):
                assert isinstance(v, float)
                self.scalars.append((k, v, step))

            def flush(self):
                pass

            def close(self):
                pass

        class StubWandb:
            def __init__(self):
                self.logged = []

            def log(self, flat, step=None):
                assert all(isinstance(v, float) for v in flat.values())
                self.logged.append((dict(flat), step))

            def finish(self):
                pass

        exp._tb, exp._wandb = StubTB(), StubWandb()
        return exp

    def test_nonscalar_dropped_with_one_warning(self, tmp_path, caplog):
        exp = self._exp(tmp_path)
        bad = np.array([1.0, 2.0, 3.0])
        with caplog.at_level("WARNING"):
            exp.log_metrics(1, {"loss": 2.0, "per_layer_norms": bad})
            exp.log_metrics(2, {"loss": 1.5, "per_layer_norms": bad})
        warns = [r for r in caplog.records
                 if "per_layer_norms" in r.getMessage()]
        assert len(warns) == 1  # once, naming the key
        assert "shape (3,)" in warns[0].getMessage()
        # both sinks saw the scalar and never the array
        assert [k for k, _, _ in exp._tb.scalars] == ["loss", "loss"]
        assert all("per_layer_norms" not in f for f, _ in exp._wandb.logged)
        exp.close()

    def test_size_one_array_coerced(self, tmp_path, caplog):
        exp = self._exp(tmp_path)
        with caplog.at_level("WARNING"):
            exp.log_metrics(1, {"loss": np.array([3.25]),
                                "lr": np.float32(0.5)})
        assert not [r for r in caplog.records if "dropping" in r.getMessage()]
        assert ("loss", 3.25, 1) in exp._tb.scalars
        exp.close()


# ---------------------------------------------------------------------------
# batch stats (satellite)
# ---------------------------------------------------------------------------


class TestBatchStats:
    def test_token_stats_with_pad_id(self):
        from neuronx_distributed_training_tpu.data.loader import (
            batch_token_stats,
        )

        ids = np.array([[5, 6, 7, 0, 0, 0, 0, 0],
                        [5, 6, 7, 8, 9, 10, 11, 12]], dtype=np.int32)
        st = batch_token_stats({"input_ids": ids}, pad_id=0)
        assert st["data/padding_fraction"] == pytest.approx(5 / 16)
        assert st["data/seq_len_min"] == 3.0
        assert st["data/seq_len_max"] == 8.0
        assert st["data/seq_len_mean"] == pytest.approx(5.5)
        assert st["data/packing_efficiency"] == pytest.approx(5.5 / 8)

    def test_token_stats_from_loss_mask(self):
        from neuronx_distributed_training_tpu.data.loader import (
            batch_token_stats,
        )

        ids = np.ones((2, 4), dtype=np.int32)
        mask = np.array([[1, 1, 0, 0], [1, 1, 1, 1]], dtype=np.float32)
        st = batch_token_stats({"input_ids": ids, "loss_mask": mask})
        assert st["data/padding_fraction"] == pytest.approx(0.25)
        assert st["data/seq_len_p50"] == pytest.approx(3.0)

    def test_accumulator_drains_means(self):
        from neuronx_distributed_training_tpu.data.loader import BatchStats

        bs = BatchStats(pad_id=0)
        bs.update({"input_ids": np.array([[1, 2, 0, 0]])})
        bs.update({"input_ids": np.array([[1, 2, 3, 4]])})
        out = bs.drain()
        assert out["data/padding_fraction"] == pytest.approx(0.25)
        assert out["data/seq_len_min"] == 2.0  # min survives the window
        assert out["data/seq_len_max"] == 4.0
        assert bs.drain() == {}  # drained


# ---------------------------------------------------------------------------
# live fit() integration
# ---------------------------------------------------------------------------


def _fleet_cfg(tmp_path, **over):
    cfg = {
        "name": "fleet",
        "trainer": {"max_steps": 6, "log_every_n_steps": 2},
        "exp_manager": {"exp_dir": str(tmp_path / "exp"),
                        "create_tensorboard_logger": False,
                        "log_files": False,
                        "telemetry": {
                            "batch_stats": True,
                            "fleet": {"enabled": True,
                                      "stale_after_seconds": 120.0},
                        }},
        "distributed_strategy": {"tensor_model_parallel_size": 1},
        "data": {"global_batch_size": 8, "micro_batch_size": 1,
                 "seq_length": 32, "synthetic": True},
        "model": {"vocab_size": 128, "hidden_size": 64,
                  "intermediate_size": 128, "num_layers": 2,
                  "num_attention_heads": 4, "num_key_value_heads": 2,
                  "max_position_embeddings": 32,
                  "optim": {"name": "adamw_fp32OptState", "lr": 1e-3}},
        "precision": {"type": "mixed_precision"},
    }
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(cfg.get(k), dict):
            cfg[k] = {**cfg[k], **v}
        else:
            cfg[k] = v
    return load_config(cfg)


@pytest.fixture(scope="module")
def fleet_run(tmp_path_factory, devices8):
    """One tiny fit() with fleet + batch_stats + a log-action alert on."""
    from neuronx_distributed_training_tpu.trainer.loop import Trainer

    tmp_path = tmp_path_factory.mktemp("fleet_run")
    cfg = _fleet_cfg(
        tmp_path,
        exp_manager={"exp_dir": str(tmp_path / "exp"),
                     "create_tensorboard_logger": False, "log_files": False,
                     "telemetry": {
                         "batch_stats": True,
                         "fleet": {"enabled": True,
                                   "stale_after_seconds": 120.0},
                         "alerts": [{"metric": "loss", "threshold": 1e9,
                                     "action": "log", "name": "never"}],
                     }})
    t = Trainer.from_config(cfg, enable_checkpointing=False)
    t.fit()
    d = Path(str(t.exp.log_dir))
    return t, d


class TestFleetLive:
    def test_beacons_written_per_boundary(self, fleet_run):
        t, d = fleet_run
        recs = [json.loads(l) for l in
                (d / "fleet" / "host_0.jsonl").read_text().splitlines()]
        steps = [r["step"] for r in recs if not r.get("closing")]
        assert steps == [2, 4, 6]  # every boundary, nothing between
        assert recs[-1]["closing"] is True  # clean close, no exception
        assert all("last_exception" not in r for r in recs)
        # beacons carry the fetched metrics + span snapshot, incl. data/
        assert recs[0]["metrics"]["loss"] > 0
        assert "data/padding_fraction" in recs[0]["metrics"]
        assert "data_wait" in recs[0]["spans"]

    def test_fleet_summary_and_run_summary(self, fleet_run):
        t, d = fleet_run
        fs = json.loads((d / "fleet_summary.json").read_text())
        assert fs["n_hosts"] == 1
        assert fs["hosts"]["0"]["closed"] is True
        assert fs["quiet_hosts"] == []
        rs = json.loads((d / "run_summary.json").read_text())
        assert rs["fleet"]["n_hosts"] == 1
        assert rs["fleet"]["summary_path"].endswith("fleet_summary.json")

    def test_batch_stats_in_metric_stream(self, fleet_run):
        t, d = fleet_run
        recs = [json.loads(l) for l in
                (d / "metrics.jsonl").read_text().splitlines()]
        last = [r for r in recs if "step_time" in r][-1]
        assert last["data/padding_fraction"] == 0.0  # synthetic: unpadded
        assert last["data/packing_efficiency"] == 1.0
        assert last["data/seq_len_max"] == 32.0

    def test_aot_once_with_fleet_enabled(self, fleet_run):
        t, _ = fleet_run
        # census swapped in the AOT executable; fleet/alerts added no
        # recompile (the retrace detector would also have logged)
        assert not hasattr(t.train_step, "lower")

    def test_alert_log_action_does_not_stop(self, fleet_run):
        t, d = fleet_run
        assert t.step == 6  # never-firing log rule: full run
        rs = json.loads((d / "run_summary.json").read_text())
        assert "alerts" not in rs  # threshold 1e9 never fired


class TestAlertHaltDrill:
    def test_data_wait_halt_lands_in_run_summary(self, tmp_path, devices8):
        """The ISSUE's acceptance drill: an alert on data_wait with
        action: halt stops the run gracefully and the reason lands in
        run_summary.json (elastic.stop_reason + the alerts trail)."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _fleet_cfg(
            tmp_path,
            exp_manager={"exp_dir": str(tmp_path / "exp"),
                         "create_tensorboard_logger": False,
                         "log_files": False,
                         "telemetry": {
                             "fleet": {"enabled": True},
                             "alerts": [{"metric": "data_wait",
                                         "threshold": 1e-12,
                                         "action": "halt", "name": "dw"}],
                         }})
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        t.fit()
        assert t.step == 2  # halted at the first boundary
        rs = json.loads(
            (Path(str(t.exp.log_dir)) / "run_summary.json").read_text())
        assert rs["elastic"]["stop_reason"].startswith("alert dw:")
        assert "data_wait" in rs["elastic"]["stop_reason"]
        (fire,) = rs["alerts"]
        assert fire["rule"] == "dw" and fire["action"] == "halt"
        assert fire["step"] == 2

    def test_alert_dump_writes_flight_recorder_bundle(self, tmp_path,
                                                      devices8):
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _fleet_cfg(
            tmp_path,
            trainer={"max_steps": 4, "log_every_n_steps": 2},
            exp_manager={"exp_dir": str(tmp_path / "exp"),
                         "create_tensorboard_logger": False,
                         "log_files": False,
                         "telemetry": {
                             "alerts": [{"metric": "loss", "threshold": 0.0,
                                         "action": "dump", "name": "dl"}],
                         }})
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        t.fit()
        d = Path(str(t.exp.log_dir))
        bundles = sorted(p.name for p in d.glob("alert_*"))
        assert bundles == ["alert_00000002"]  # edge-triggered: ONE bundle
        payload = json.loads((d / bundles[0] / "anomaly.json").read_text())
        assert payload["kind"] == "alert"
        assert payload["alert"]["rule"] == "dl"
        rs = json.loads((d / "run_summary.json").read_text())
        assert any(a["bundle"] == "alert_00000002"
                   for a in rs["anomalies"])

    def test_dispatch_ahead_contract_with_fleet_and_alerts(self, tmp_path,
                                                           devices8):
        """Fleet + alerts enabled must add ZERO host syncs between logging
        boundaries — the same instrumented-step proof the telemetry layer
        pins, with the new knobs on."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _fleet_cfg(
            tmp_path,
            trainer={"max_steps": 6, "log_every_n_steps": 3},
            exp_manager={"exp_dir": str(tmp_path / "exp"),
                         "create_tensorboard_logger": False,
                         "log_files": False,
                         "telemetry": {
                             "batch_stats": True,
                             "fleet": {"enabled": True},
                             "alerts": [{"metric": "loss",
                                         "threshold": 1e9}],
                         }})
        t = Trainer.from_config(cfg, enable_checkpointing=False)

        conversions: list[int] = []

        class _Scalar:
            def __init__(self, step):
                self.step = step

            def __float__(self):
                conversions.append(self.step)
                return 1.0

        real_params, real_opt = t.params, t.opt_state

        def fake_step(params, opt_state, batch, key):
            return real_params, real_opt, {"loss": _Scalar(t.step),
                                           "grad_norm": _Scalar(t.step)}

        t.train_step = fake_step
        t.fit()
        assert conversions, "boundaries must fetch metrics"
        assert set(conversions) == {2, 5}, conversions


class TestMultiIncarnation:
    def test_beacons_extend_across_kill_and_resume(self, tmp_path, devices8):
        """The elastic drill's process machinery at fleet level: incarnation
        1 is killed mid-run by the fault injector (its beacon stream ends
        with last_exception — a DYING host leaves a valid file), incarnation
        2 resumes into the SAME version dir and extends the stream; the
        aggregator sees one host whose record covers both lives."""
        from neuronx_distributed_training_tpu.trainer.elastic import (
            FaultInjector,
            SimulatedPreemption,
        )
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        over = dict(
            trainer={"max_steps": 6, "log_every_n_steps": 1},
            exp_manager={"exp_dir": str(tmp_path / "exp"),
                         "create_tensorboard_logger": False,
                         "log_files": False,
                         "resume_if_exists": True,
                         "checkpoint_callback_params": {
                             "every_n_train_steps": 2, "save_top_k": 2},
                         "telemetry": {
                             "fleet": {"enabled": True},
                         }},
        )
        cfg = _fleet_cfg(tmp_path, **over)
        t1 = Trainer.from_config(cfg)
        t1.fault_injector = FaultInjector(at_step=3, mode="kill",
                                          phase="step")
        with pytest.raises(SimulatedPreemption):
            t1.fit()
        d = Path(str(t1.exp.log_dir))
        recs = [json.loads(l) for l in
                (d / "fleet" / "host_0.jsonl").read_text().splitlines()]
        assert recs[-1].get("last_exception", "").startswith(
            "SimulatedPreemption")

        t2 = Trainer.from_config(cfg)
        t2.fit()
        assert Path(str(t2.exp.log_dir)) == d  # same version dir
        recs2 = [json.loads(l) for l in
                 (d / "fleet" / "host_0.jsonl").read_text().splitlines()]
        assert len(recs2) > len(recs)  # the stream EXTENDED
        assert recs2[-1].get("closing") is True  # clean second life
        fs = json.loads((d / "fleet_summary.json").read_text())
        assert fs["n_hosts"] == 1
        assert fs["hosts"]["0"]["last_step"] == 6
        assert fs["hosts"]["0"]["beacons"] == len(recs2)


# ---------------------------------------------------------------------------
# in-loop quiet-host detection (seeded second host)
# ---------------------------------------------------------------------------


class TestInLoopFleetStall:
    def test_quiet_host_dumps_fleet_stall_bundle(self, tmp_path, devices8):
        """Rank 0's boundary aggregation must notice a host that stopped
        beaconing and dump ONE fleet_stall bundle through the flight
        recorder.  The quiet host is seeded: a second beacon stream whose
        last record is minutes old."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _fleet_cfg(
            tmp_path,
            trainer={"max_steps": 6, "log_every_n_steps": 2},
            exp_manager={"exp_dir": str(tmp_path / "exp"),
                         "create_tensorboard_logger": False,
                         "log_files": False,
                         "telemetry": {
                             "fleet": {"enabled": True,
                                       "stale_after_seconds": 60.0},
                             # a dump-capable monitor must exist for the
                             # stall bundle: any dump-action rule arms one
                             "alerts": [{"metric": "loss",
                                         "threshold": 1e9,
                                         "action": "dump"}],
                         }})
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        d = Path(str(t.exp.log_dir))
        # seed host 7: last beacon 10 minutes in the past, never closed
        (d / "fleet").mkdir(parents=True, exist_ok=True)
        (d / "fleet" / "host_7.jsonl").write_text(json.dumps({
            "host": 7, "step": 1, "t_mono": 1.0,
            "t_wall": time.time() - 600.0, "metrics": {"loss": 2.0},
        }) + "\n")
        t.fit()
        fs = json.loads((d / "fleet_summary.json").read_text())
        assert [q["host"] for q in fs["quiet_hosts"]] == [7]
        stalls = [f for f in fs["findings"] if f["kind"] == "fleet_stall"]
        assert len(stalls) == 1 and stalls[0]["host"] == 7
        bundles = sorted(p.name for p in d.glob("fleet_stall_*"))
        assert len(bundles) == 1  # once per host, not per boundary
        payload = json.loads(
            (d / bundles[0] / "anomaly.json").read_text())
        assert payload["kind"] == "fleet_stall"
        assert payload["quiet_hosts"][0]["host"] == 7


# ---------------------------------------------------------------------------
# CLIs: fleet_monitor + metrics_report --follow
# ---------------------------------------------------------------------------


def _load_tool(name):
    path = Path(__file__).resolve().parents[1] / "tools" / f"{name}.py"
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    import sys

    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


class TestFleetMonitorCLI:
    def test_fixture_committed_and_current(self):
        """The committed simulated-fleet fixture must match the generator
        (regen with `python tests/test_fleet.py --regen-fixture`)."""
        import tempfile

        assert FIXTURE.is_dir(), "tests/data/fleet_fixture missing"
        with tempfile.TemporaryDirectory() as td:
            write_sim_fleet(Path(td), n_hosts=5, n_steps=8, straggler=2,
                            cause="data_stall", quiet_host=3, quiet_after=4,
                            die_host=4, die_after=6)
            for p in sorted(Path(td).glob("*.jsonl")):
                assert (FIXTURE / p.name).read_text() == p.read_text(), p.name

    def test_json_last_line_contract(self, capsys):
        fm = _load_tool("fleet_monitor")
        rc = fm.main([str(FIXTURE), "--json", "-"])
        out = capsys.readouterr().out
        payload = json.loads(out.strip().splitlines()[-1])
        assert payload["n_hosts"] == 5
        assert payload["straggler"]["host"] == 2
        assert payload["straggler"]["cause"] == "data_stall"
        assert [q["host"] for q in payload["quiet_hosts"]] == [3]
        kinds = {f["kind"] for f in payload["findings"]}
        assert kinds == {"fleet_stall", "host_died"}
        assert rc == 1  # findings -> nonzero, like ckpt_verify

    def test_human_render(self, capsys):
        fm = _load_tool("fleet_monitor")
        fm.main([str(FIXTURE)])
        out = capsys.readouterr().out
        assert "straggler: host 2" in out
        assert "data_stall" in out
        assert "QUIET" in out
        assert "fleet goodput" in out
        assert "[host_died]" in out

    def test_run_dir_form_and_write(self, tmp_path, capsys):
        fm = _load_tool("fleet_monitor")
        write_sim_fleet(tmp_path / "fleet", n_hosts=2, n_steps=3,
                        straggler=None)
        rc = fm.main([str(tmp_path), "--write"])
        assert rc == 0  # no findings
        fs = json.loads((tmp_path / "fleet_summary.json").read_text())
        assert fs["n_hosts"] == 2

    def test_summary_file_form(self, tmp_path, capsys):
        fm = _load_tool("fleet_monitor")
        p = tmp_path / "fleet_summary.json"
        p.write_text(json.dumps({"n_hosts": 3, "hosts": {}, "windows": [],
                                 "findings": []}))
        assert fm.main([str(p)]) == 0
        assert "3 hosts" in capsys.readouterr().out

    def test_missing_input(self, tmp_path):
        fm = _load_tool("fleet_monitor")
        assert fm.main([str(tmp_path / "nope")]) == 2


class TestMetricsReportFollow:
    def _run_dir(self, tmp_path):
        with open(tmp_path / "metrics.jsonl", "w") as f:
            for s in (2, 4):
                f.write(json.dumps({"step": s, "loss": 7.0 - s}) + "\n")
        with open(tmp_path / "run_summary.json", "w") as f:
            json.dump({"alerts": [{"step": 4, "rule": "dw",
                                   "action": "halt", "metric": "data_wait",
                                   "message": "data_wait too high"}]}, f)
        write_sim_fleet(tmp_path / "fleet", n_hosts=2, n_steps=3,
                        straggler=1, cause="compute_slow")
        fm = _load_tool("fleet_monitor")
        fm.main([str(tmp_path), "--write"])
        return tmp_path

    def test_follow_smoke(self, tmp_path, capsys):
        mr = _load_tool("metrics_report")
        d = self._run_dir(tmp_path)
        rc = mr.main([str(d), "--follow", "--interval", "0.01",
                      "--refreshes", "2"])
        assert rc == 0
        out = capsys.readouterr().out
        assert out.count("refresh 1") == 1 and out.count("refresh 2") == 1
        assert "beacons (age" in out
        assert "host_0" in out and "host_1" in out

    def test_fleet_and_alert_sections_render(self, tmp_path, capsys):
        mr = _load_tool("metrics_report")
        d = self._run_dir(tmp_path)
        assert mr.main([str(d)]) == 0
        out = capsys.readouterr().out
        assert "fleet (2 hosts" in out
        assert "straggler" in out
        assert "alerts (1 firing" in out
        assert "data_wait too high" in out

    def test_no_fleet_dir_sections_absent(self, tmp_path, capsys):
        mr = _load_tool("metrics_report")
        with open(tmp_path / "metrics.jsonl", "w") as f:
            f.write(json.dumps({"step": 2, "loss": 1.0}) + "\n")
        assert mr.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "beacons" not in out and "fleet (" not in out


if __name__ == "__main__":
    import sys

    if "--regen-fixture" in sys.argv:
        regen_fixture()
        print(f"regenerated {FIXTURE}")
    else:
        print(__doc__)
