"""Megatron-family GPT: config surface, forward variants, TP parity, dropout."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.models import gpt
from neuronx_distributed_training_tpu.ops import moe as moe_ops
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

FP32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                   softmax_dtype=jnp.float32)

BASE = dict(
    vocab_size=96, hidden_size=32, num_layers=2, num_attention_heads=4,
    max_position_embeddings=32, activations_checkpoint_granularity=None,
)


def _batch(key, b=2, s=16, vocab=96):
    ids = jax.random.randint(key, (b, s), 0, vocab)
    return {"input_ids": ids, "labels": ids}


class TestVariants:
    @pytest.mark.parametrize("kwargs", [
        dict(),  # gelu + layernorm + learned bias + rope + tied
        dict(activation="swiglu", normalization="rmsnorm", bias=False),
        dict(position_embedding_type="learned_absolute"),
        dict(num_query_groups=2),
        dict(num_query_groups=1),  # MQA
        dict(rotary_percentage=0.5),
        dict(share_embeddings_and_output_weights=False),
        dict(sliding_window=8),
    ])
    def test_forward_finite(self, kwargs):
        cfg = gpt.GPTConfig(**{**BASE, **kwargs})
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        loss, _ = gpt.forward(params, _batch(jax.random.PRNGKey(1)), cfg, FP32)
        assert np.isfinite(float(loss))
        assert abs(float(loss) - np.log(cfg.vocab_size)) < 1.5

    def test_moe_gpt(self):
        cfg = gpt.GPTConfig(**BASE, moe=moe_ops.MoEConfig(
            num_experts=4, top_k=1, router_type="sinkhorn", dropless=True))
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        loss, aux = gpt.forward(params, _batch(jax.random.PRNGKey(1)), cfg, FP32)
        assert np.isfinite(float(loss))
        assert "router_aux_loss" in aux

    def test_dropout_deterministic_given_rng(self):
        cfg = gpt.GPTConfig(**BASE, hidden_dropout=0.2, embedding_dropout=0.1)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        batch = _batch(jax.random.PRNGKey(1))
        l1, _ = gpt.forward(params, batch, cfg, FP32, rng=jax.random.PRNGKey(7))
        l2, _ = gpt.forward(params, batch, cfg, FP32, rng=jax.random.PRNGKey(7))
        l3, _ = gpt.forward(params, batch, cfg, FP32, rng=jax.random.PRNGKey(8))
        assert float(l1) == float(l2)
        assert float(l1) != float(l3)
        # eval mode (no rng) = no dropout
        le, _ = gpt.forward(params, batch, cfg, FP32)
        assert float(le) != float(l1)

    def test_from_config_megatron_schema(self):
        cfg = gpt.GPTConfig.from_config({
            "vocab_size": 1000, "hidden_size": 64, "num_layers": 4,
            "num_attention_heads": 8, "num_query_groups": 2,
            "activation": "swiglu", "normalization": "rmsnorm",
            "position_embedding_type": "rope", "bias": False,
            "num_moe_experts": 8,
        }, {"sequence_parallel": True, "tensor_model_parallel_size": 2})
        assert cfg.kv_heads == 2
        assert cfg.is_glu
        assert cfg.moe is not None and cfg.moe.num_experts == 8
        assert cfg.sequence_parallel


@pytest.mark.slow
class TestShardedGPT:
    def test_tp_parity(self, devices8):
        cfg = gpt.GPTConfig(**BASE, num_query_groups=2, activation="swiglu")
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        batch = _batch(jax.random.PRNGKey(1), b=4)  # divisible by the dp axis (4)

        def loss_fn(p, b):
            return gpt.forward(p, b, cfg, FP32)[0]

        ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params, batch)
        mesh = build_mesh(MeshConfig(tensor_model_parallel_size=2))
        specs = gpt.param_specs(cfg)
        ns = functools.partial(NamedSharding, mesh)
        sh_params = jax.device_put(
            params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
        )
        sh_batch = jax.device_put(batch, ns(P(("data", "expert"))))
        with mesh, shd.use_mesh(mesh):
            loss, grads = jax.jit(jax.value_and_grad(loss_fn))(sh_params, sh_batch)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        np.testing.assert_allclose(
            np.asarray(grads["embed"]["embedding"]),
            np.asarray(ref_grads["embed"]["embedding"]), rtol=1e-3, atol=1e-5,
        )

    def test_pipeline_specs_exist(self):
        cfg = gpt.GPTConfig(**BASE)
        specs = gpt.param_specs(cfg, pipeline=True)
        assert specs["layers"]["attn"]["qkv"]["w"][0] == "pipe"


class TestGPTAttentionMask:
    def test_left_padded_matches_unpadded(self):
        from neuronx_distributed_training_tpu.models import gpt as gpt_mod
        from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

        fp32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                           softmax_dtype=jnp.float32)
        for pe in ("rope", "learned_absolute"):
            cfg = gpt_mod.GPTConfig(
                vocab_size=64, hidden_size=32, num_layers=2,
                num_attention_heads=4, max_position_embeddings=32,
                position_embedding_type=pe,
                activations_checkpoint_granularity=None,
            )
            params = gpt_mod.init_params(jax.random.PRNGKey(0), cfg, fp32)
            ids = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 3, 64)
            ref, _ = gpt_mod.forward(params, {"input_ids": ids}, cfg, fp32)
            pad = 4
            padded = jnp.concatenate([jnp.zeros((1, pad), ids.dtype), ids], 1)
            mask = jnp.concatenate(
                [jnp.zeros((1, pad), jnp.int32), jnp.ones((1, 12), jnp.int32)], 1)
            out, _ = gpt_mod.forward(
                params, {"input_ids": padded, "attention_mask": mask}, cfg, fp32)
            np.testing.assert_allclose(
                np.asarray(out[:, pad:]), np.asarray(ref), rtol=2e-5, atol=2e-5,
                err_msg=f"position_embedding_type={pe}")

    def test_mask_folds_into_loss(self):
        from neuronx_distributed_training_tpu.models import gpt as gpt_mod
        from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

        fp32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                           softmax_dtype=jnp.float32)
        cfg = gpt_mod.GPTConfig(
            vocab_size=64, hidden_size=32, num_layers=1, num_attention_heads=4,
            max_position_embeddings=32, activations_checkpoint_granularity=None,
        )
        params = gpt_mod.init_params(jax.random.PRNGKey(0), cfg, fp32)
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 3, 64)
        mask = jnp.ones((2, 16), jnp.int32).at[:, :6].set(0)
        loss_a, _ = gpt_mod.forward(
            params, {"input_ids": ids, "labels": ids, "attention_mask": mask},
            cfg, fp32)
        loss_b, _ = gpt_mod.forward(
            params, {"input_ids": ids, "labels": ids, "attention_mask": mask,
                     "loss_mask": mask.astype(jnp.float32)}, cfg, fp32)
        np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)


class TestGPTMoEFrequency:
    """Dense/MoE interleave for the megatron family
    (reference megatron_gpt_model.py:137 moe_frequency)."""

    def _cfg(self, freq, dropout=0.0):
        from neuronx_distributed_training_tpu.ops import moe as moe_ops

        return gpt.GPTConfig(
            vocab_size=64, hidden_size=32, num_layers=4, num_attention_heads=4,
            max_position_embeddings=32, hidden_dropout=dropout,
            activations_checkpoint_granularity=None,
            moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True,
                                  router_aux_loss_coef=0.02),
            moe_frequency=freq,
        )

    @pytest.mark.slow
    def test_interleaved_structure_and_training(self):
        cfg = self._cfg(2)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        assert "moe" in params["layers"]["mlp"] and "dense" in params["layers"]["mlp"]
        assert params["layers"]["mlp"]["moe"]["router"]["w"].shape[0] == 2  # G
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        batch = {"input_ids": ids, "labels": ids}

        def loss_fn(p):
            return gpt.forward(p, batch, cfg, FP32)[0]

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        assert float(np.abs(np.asarray(
            grads["layers"]["mlp"]["moe"]["router"]["w"])).max()) > 0
        assert float(np.abs(np.asarray(
            grads["layers"]["mlp"]["dense"]["up"]["w"])).max()) > 0
        # specs tree matches the param tree
        specs = gpt.param_specs(cfg)
        assert (jax.tree_util.tree_structure(params)
                == jax.tree_util.tree_structure(
                    specs, is_leaf=lambda x: isinstance(
                        x, jax.sharding.PartitionSpec)))

    def test_interleaved_dropout_runs(self):
        cfg = self._cfg(2, dropout=0.1)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        loss, _ = gpt.forward(params, {"input_ids": ids, "labels": ids}, cfg,
                              FP32, rng=jax.random.PRNGKey(7))
        assert np.isfinite(float(loss))

    def test_aux_normalized_over_moe_layers(self):
        cfg = self._cfg(2)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
        _, aux = gpt.forward(params, {"input_ids": ids, "labels": ids}, cfg, FP32)
        # coefficient-weighted per-layer mean >= coef * 1.0 lower bound
        assert float(aux["router_aux_loss"]) >= 0.02

    def test_indivisible_raises(self):
        cfg = self._cfg(3)
        with pytest.raises(ValueError, match="frequency"):
            gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)

    @pytest.mark.slow  # fit()-based; 40 s — keeps the CI fast tier < 5 min
    def test_interleave_under_pp_trains(self, devices8):
        """gpt + moe_frequency>1 + pp=2 now trains end-to-end (grouped stage
        slicing); one fit() step produces a finite loss."""
        from neuronx_distributed_training_tpu.config.loader import load_config
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = load_config({
            "name": "t", "model_source": "megatron", "seed": 1,
            "trainer": {"max_steps": 1},
            "distributed_strategy": {"pipeline_model_parallel_size": 2,
                                     "tensor_model_parallel_size": 2},
            "data": {"global_batch_size": 8, "micro_batch_size": 1,
                     "seq_length": 16, "synthetic": True},
            "model": {"architecture": "gpt", "vocab_size": 64,
                      "hidden_size": 32, "num_layers": 4,
                      "num_attention_heads": 4, "max_position_embeddings": 16,
                      "moe": {"num_experts": 2, "top_k": 1, "dropless": True,
                              "frequency": 2},
                      "optim": {"lr": 1e-3}},
            "precision": {"type": "mixed_precision"},
        })
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        m = t.fit()
        assert np.isfinite(m["loss"])


class TestBlockTypes:
    """transformer_block_type layouts (reference transformer.py:1468-2084)
    and tokentype embeddings (language_model.py:194-328) — VERDICT r2 item 9."""

    @pytest.mark.parametrize("bt", ["pre_ln", "post_ln", "normformer", "gpt_j"])
    def test_forward_and_grads_finite(self, bt):
        cfg = gpt.GPTConfig(**{**BASE, "num_layers": 1,
                               "transformer_block_type": bt})
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        batch = _batch(jax.random.PRNGKey(1), b=1, s=8)
        loss, grads = jax.value_and_grad(
            lambda p: gpt.forward(p, batch, cfg, FP32)[0]
        )(params)
        assert np.isfinite(float(loss))
        assert abs(float(loss) - np.log(cfg.vocab_size)) < 1.5
        gn = sum(float(jnp.sum(jnp.abs(g))) for g in jax.tree_util.tree_leaves(grads))
        assert np.isfinite(gn) and gn > 0

    def test_layouts_differ_from_pre_ln(self):
        batch = _batch(jax.random.PRNGKey(1))
        outs = {}
        for bt in ("pre_ln", "post_ln", "gpt_j"):
            cfg = gpt.GPTConfig(**{**BASE, "transformer_block_type": bt})
            # same seed: pre_ln/post_ln share the same param structure
            params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
            logits, _ = gpt.forward(
                params, {"input_ids": batch["input_ids"]}, cfg, FP32)
            outs[bt] = np.asarray(logits)
        assert not np.allclose(outs["pre_ln"], outs["post_ln"])
        assert not np.allclose(outs["pre_ln"], outs["gpt_j"])

    def test_gpt_j_matches_manual_parallel_residual(self):
        """1-layer gpt_j equals the hand-computed parallel residual: attn on
        input_norm(x), MLP on post_attn_norm(x) — TWO independent norms
        (reference transformer.py:1908-1914)."""
        cfg = gpt.GPTConfig(**{**BASE, "num_layers": 1,
                               "transformer_block_type": "gpt_j"})
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        ids = _batch(jax.random.PRNGKey(1))["input_ids"]
        logits, _ = gpt.forward(params, {"input_ids": ids}, cfg, FP32)

        lp = jax.tree_util.tree_map(lambda x: x[0], params["layers"])
        from neuronx_distributed_training_tpu.ops import linear as linear_ops
        x = linear_ops.apply_embedding(params["embed"], ids,
                                       compute_dtype=FP32.compute_dtype)
        cos, sin = gpt._rope_for(cfg, ids)
        attn_out = gpt._attention_block(
            cfg, lp["attn"], gpt._apply_norm(cfg, lp["input_norm"], x),
            cos, sin, FP32)
        mlp_out, _ = gpt._mlp_block(
            cfg, lp["mlp"], gpt._apply_norm(cfg, lp["post_attn_norm"], x), FP32)
        y = x + attn_out + mlp_out
        hidden = gpt._apply_norm(cfg, params["final_norm"], y)
        ref = gpt._logits_from_hidden(params, hidden, cfg, FP32)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_normformer_has_extra_norms(self):
        cfg = gpt.GPTConfig(**{**BASE, "transformer_block_type": "normformer"})
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        assert "nf_attn_norm" in params["layers"]
        assert "nf_mlp_norm" in params["layers"]
        assert params["layers"]["nf_mlp_norm"]["scale"].shape[-1] == cfg.ffn_size
        # specs cover every param leaf
        specs = gpt.param_specs(cfg)
        jax.tree_util.tree_map(lambda p, s: None, params, specs,
                               is_leaf=lambda x: isinstance(x, P))

    def test_gpt_j_keeps_two_norms(self):
        # the reference gpt_j layout norms attn and MLP with two SEPARATE
        # parameter sets (transformer.py:1908-1914)
        cfg = gpt.GPTConfig(**{**BASE, "transformer_block_type": "gpt_j"})
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        assert "post_attn_norm" in params["layers"]
        assert "input_norm" in params["layers"]

    def test_post_ln_has_no_final_norm(self):
        # the reference builds no final layernorm for post_ln
        # (transformer.py:2478, 2569-2570)
        cfg = gpt.GPTConfig(**{**BASE, "transformer_block_type": "post_ln"})
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        assert "final_norm" not in params
        specs = gpt.param_specs(cfg)
        assert "final_norm" not in specs

    def test_unknown_block_type_raises(self):
        cfg = gpt.GPTConfig(**{**BASE, "transformer_block_type": "sandwich"})
        with pytest.raises(ValueError, match="transformer_block_type"):
            gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)

    def test_normformer_moe_rejected(self):
        cfg = gpt.GPTConfig(**{**BASE, "transformer_block_type": "normformer"},
                            moe=moe_ops.MoEConfig(num_experts=2, top_k=1,
                                                  dropless=True))
        with pytest.raises(ValueError, match="dense-only"):
            gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)


class TestTokentype:
    def test_tokentype_changes_logits_and_matches_manual(self):
        cfg = gpt.GPTConfig(**{**BASE, "num_tokentypes": 2})
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        ids = _batch(jax.random.PRNGKey(1))["input_ids"]
        tt = jnp.zeros_like(ids).at[:, 8:].set(1)
        base_logits, _ = gpt.forward(params, {"input_ids": ids}, cfg, FP32)
        tt_logits, _ = gpt.forward(
            params, {"input_ids": ids, "tokentype_ids": tt}, cfg, FP32)
        assert not np.allclose(np.asarray(base_logits), np.asarray(tt_logits))
        # all-zero tokentypes = adding row 0 everywhere, NOT a no-op
        z_logits, _ = gpt.forward(
            params, {"input_ids": ids, "tokentype_ids": jnp.zeros_like(ids)},
            cfg, FP32)
        assert not np.allclose(np.asarray(base_logits), np.asarray(z_logits))

    def test_tokentype_ids_without_table_raises(self):
        cfg = gpt.GPTConfig(**BASE)
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        ids = _batch(jax.random.PRNGKey(1))["input_ids"]
        with pytest.raises(ValueError, match="num_tokentypes"):
            gpt.forward(params, {"input_ids": ids,
                                 "tokentype_ids": jnp.zeros_like(ids)},
                        cfg, FP32)

    def test_from_config_reads_block_type_and_tokentypes(self):
        cfg = gpt.GPTConfig.from_config(
            {"transformer_block_type": "post_ln", "num_tokentypes": 3,
             "hidden_size": 32, "num_layers": 2, "num_attention_heads": 4},
        )
        assert cfg.transformer_block_type == "post_ln"
        assert cfg.num_tokentypes == 3
