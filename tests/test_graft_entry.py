"""Driver-contract smoke tests: entry() compiles, dryrun_multichip(8) executes
a real sharded train step on the virtual 8-device CPU mesh."""

import sys
from pathlib import Path

import jax

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import __graft_entry__ as graft  # noqa: E402

import pytest as _pytest_mark

pytestmark = _pytest_mark.mark.slow  # multi-minute parity tests; CI fast tier deselects


def test_entry_compiles(devices8):
    fn, args = graft.entry()
    lowered = jax.jit(fn).lower(*args)
    compiled = lowered.compile()
    loss = compiled(*args)
    assert float(loss) > 0


def test_dryrun_multichip_8(devices8):
    graft.dryrun_multichip(8)


class TestBenchConfig:
    """bench.py pure helpers (driver-contract logic)."""

    def test_layer_budget_regime_ordering(self):
        import bench

        hbm = 16 << 30
        mixed = bench.layer_budget(hbm, 18.0)
        bf16 = bench.layer_budget(hbm, 8.0)
        assert 1 <= mixed <= bf16 <= 32
        # tied embeddings buy layers back vs untied
        assert bench.layer_budget(hbm, 18.0, tied=True) >= bench.layer_budget(
            hbm, 18.0, tied=False)

    def test_layer_budget_floor_and_cap(self):
        import bench

        assert bench.layer_budget(1 << 30, 18.0) == 1  # never 0
        assert bench.layer_budget(1 << 44, 8.0) == 32  # full model cap
