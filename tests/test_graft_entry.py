"""Driver-contract smoke tests: entry() compiles, dryrun_multichip(8) executes
a real sharded train step on the virtual 8-device CPU mesh."""

import sys
from pathlib import Path

import jax

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import __graft_entry__ as graft  # noqa: E402

import pytest as _pytest_mark

pytestmark = _pytest_mark.mark.slow  # multi-minute parity tests; CI fast tier deselects


def test_entry_compiles(devices8):
    fn, args = graft.entry()
    lowered = jax.jit(fn).lower(*args)
    compiled = lowered.compile()
    loss = compiled(*args)
    assert float(loss) > 0


def test_dryrun_multichip_8(devices8):
    graft.dryrun_multichip(8)
