"""Pre-flight graph audit: clean verdicts on shipped configs, and a seeded
violation for EVERY rule proving it fires (the fault-injection contract from
docs/static_analysis.md)."""

import dataclasses
import functools
import glob
import os

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.analysis.graph_audit import (
    AuditContext,
    abstract_batch,
    audit_artifacts,
    audit_config,
    audit_step_program,
    expected_max_device_bytes,
    parse_alias_map,
    shrink_overrides,
)
from neuronx_distributed_training_tpu.config.loader import load_config
from neuronx_distributed_training_tpu.trainer.loop import assemble_step_program
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

CONF = os.path.join(os.path.dirname(__file__), "..", "examples", "conf")
TINY = os.path.join(CONF, "tiny_smoke_config.yaml")


# --------------------------------------------------------------------------
# crafted-step harness: a minimal ctx + jitted fn per fault injection
# --------------------------------------------------------------------------


@dataclasses.dataclass
class TinyModel:
    hidden_size: int = 8
    intermediate_size: int = 8
    vocab_size: int = 8
    num_attention_heads: int = 1
    num_layers: int = 1
    max_position_embeddings: int = 8
    attention_impl: str = "flash"


def make_ctx(mesh, *, donate=True, zero1=True, policy=None, params=None,
             opt=None, pspecs=None, ospecs=None, ds_extra=None):
    params = params if params is not None else {
        "w": jax.ShapeDtypeStruct((8, 8), jnp.float32)}
    opt = opt if opt is not None else {
        "m": jax.ShapeDtypeStruct((8, 8), jnp.float32)}
    ds = {"zero1": zero1, **(ds_extra or {})}
    return AuditContext(
        cfg={"distributed_strategy": ds,
             "data": {"seq_length": 8},
             "model": {}},
        mesh=mesh,
        policy=policy or DtypePolicy.from_precision_config("fp32"),
        model_cfg=TinyModel(),
        sched={"global_batch_size": 8, "micro_batch_size": 1},
        donate=donate,
        params_tree=params, opt_tree=opt, pspecs=pspecs, ospecs=ospecs,
    )


def compile_step(mesh, fn, in_specs, out_specs, args, *, donate=()):
    ns = functools.partial(NamedSharding, mesh)
    sh = lambda specs: jax.tree_util.tree_map(
        ns, specs, is_leaf=lambda x: isinstance(x, P))
    j = jax.jit(fn, in_shardings=sh(in_specs), out_shardings=sh(out_specs),
                donate_argnums=donate)
    with mesh:
        lowered = j.lower(*args)
        return lowered.as_text(), lowered.compile()


def mesh_of(devices8, shape, axes):
    import numpy as np

    return Mesh(np.asarray(devices8).reshape(shape), axes)


# --------------------------------------------------------------------------
# rule fault injections
# --------------------------------------------------------------------------


class TestRuleInjections:
    def test_ga001_donated_but_copied(self, devices8):
        """A donated buffer whose output changed dtype cannot alias."""
        mesh = mesh_of(devices8, (8,), ("data",))

        def step(p, o, b, k):
            # output dtype differs from the donated input -> no alias
            return ({"w": (p["w"] + 1).astype(jnp.bfloat16)},
                    {"m": o["m"] * 2}, {"loss": b.sum()})

        args = ({"w": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                {"m": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                jax.ShapeDtypeStruct((8, 8), jnp.float32),
                jax.ShapeDtypeStruct((2,), jnp.uint32))
        shlo, comp = compile_step(
            mesh, step,
            ({"w": P()}, {"m": P()}, P("data"), P()),
            ({"w": P()}, {"m": P()}, {"loss": P()}),
            args, donate=(0, 1),
        )
        rep = audit_artifacts(make_ctx(mesh), comp, shlo)
        ga001 = [f for f in rep.findings if f.rule == "GA001"]
        # the bf16 output can't reuse EITHER donated f32 buffer, so exactly
        # one of the two donated inputs goes unreused (XLA picks which)
        assert len(ga001) == 1, rep.format()
        assert rep.stats["donation_coverage"] == 0.5
        assert rep.failed("error")

    def test_ga001_clean_when_aliasable(self, devices8):
        mesh = mesh_of(devices8, (8,), ("data",))

        def step(p, o, b, k):
            return ({"w": p["w"] + 1}, {"m": o["m"] * 2}, {"loss": b.sum()})

        args = ({"w": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                {"m": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                jax.ShapeDtypeStruct((8, 8), jnp.float32),
                jax.ShapeDtypeStruct((2,), jnp.uint32))
        shlo, comp = compile_step(
            mesh, step,
            ({"w": P()}, {"m": P()}, P("data"), P()),
            ({"w": P()}, {"m": P()}, {"loss": P()}),
            args, donate=(0, 1),
        )
        rep = audit_artifacts(make_ctx(mesh), comp, shlo)
        assert not [f for f in rep.findings if f.rule == "GA001"], rep.format()
        assert rep.stats["donation_coverage"] == 1.0

    def test_ga101_dp_only_all_gather(self, devices8):
        """dp-only, zero1 off: an all-gather of params is the classic
        'replicated optimizer regathers the world' bug."""
        mesh = mesh_of(devices8, (8,), ("data",))

        def step(p, o, b, k):
            # batch-sharded value forced to replicated output -> all-gather
            big = jnp.broadcast_to(b[:, None], (8, 64)) * p["w"].sum()
            return ({"w": p["w"] + 1}, {"m": o["m"] * 2},
                    {"gathered": big})

        args = ({"w": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                {"m": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                jax.ShapeDtypeStruct((8,), jnp.float32),
                jax.ShapeDtypeStruct((2,), jnp.uint32))
        shlo, comp = compile_step(
            mesh, step,
            ({"w": P()}, {"m": P()}, P("data"), P()),
            ({"w": P()}, {"m": P()}, {"gathered": P()}),
            args, donate=(0, 1),
        )
        rep = audit_artifacts(make_ctx(mesh, zero1=False), comp, shlo)
        assert any(f.rule == "GA101" and "all-gather" in f.message
                   for f in rep.findings), rep.format()

    def test_ga102_tp_without_model_comms(self, devices8):
        """tp=2 mesh but a step with zero collectives: silent replication."""
        mesh = mesh_of(devices8, (4, 2), ("data", "model"))

        def step(p, o, b, k):
            return ({"w": p["w"] + 1}, {"m": o["m"] * 2}, {"loss": b.sum(0)})

        args = ({"w": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                {"m": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                jax.ShapeDtypeStruct((8, 8), jnp.float32),
                jax.ShapeDtypeStruct((2,), jnp.uint32))
        shlo, comp = compile_step(
            mesh, step,
            ({"w": P()}, {"m": P()}, P(None), P()),
            ({"w": P()}, {"m": P()}, {"loss": P()}),
            args, donate=(0, 1),
        )
        ctx = make_ctx(mesh, ds_extra={"tensor_model_parallel_size": 2})
        rep = audit_artifacts(ctx, comp, shlo)
        rules = {f.rule for f in rep.findings}
        assert "GA102" in rules, rep.format()
        # both the tp-comms and the dp-grad-reduction contracts fire
        msgs = " | ".join(f.message for f in rep.findings)
        assert "model-axis" in msgs and "never reduced" in msgs

    def test_ga201_replicated_intermediate(self, devices8):
        """A big batch-replicated broadcast blows the per-device budget."""
        mesh = mesh_of(devices8, (8,), ("data",))

        def step(p, o, b, k):
            # [8, 4096] f32 fully replicated = 128 KiB/device vs a ~KB budget
            blob = jnp.broadcast_to(p["w"].reshape(-1)[:1], (8, 4096)) + b.sum()
            return ({"w": p["w"] + 1}, {"m": o["m"] * 2},
                    {"loss": blob.sum()})

        args = ({"w": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                {"m": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                jax.ShapeDtypeStruct((8,), jnp.float32),
                jax.ShapeDtypeStruct((2,), jnp.uint32))
        shlo, comp = compile_step(
            mesh, step,
            ({"w": P()}, {"m": P()}, P("data"), P()),
            ({"w": P()}, {"m": P()}, {"loss": P()}),
            args, donate=(0, 1),
        )
        ctx = make_ctx(mesh)
        budget = expected_max_device_bytes(ctx)
        assert budget < 8 * 4096 * 4
        rep = audit_artifacts(ctx, comp, shlo, replication_slack=2.0)
        assert any(f.rule == "GA201" for f in rep.findings), rep.format()

    def test_ga301_f32_matmul_under_bf16(self, devices8):
        """Both-f32 dot under a bf16 regime fires; the policy's own widening
        (bf16 -> f32 convert feeding the dot) does not."""
        mesh = mesh_of(devices8, (8,), ("data",))
        bf16 = DtypePolicy.from_precision_config("mixed_precision")

        def bad(p, o, b, k):
            y = b @ p["w"]  # f32 x f32: the policy cast never happened
            return ({"w": p["w"] + 1}, {"m": o["m"] * 2}, {"loss": y.sum()})

        args = ({"w": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                {"m": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                jax.ShapeDtypeStruct((8, 8), jnp.float32),
                jax.ShapeDtypeStruct((2,), jnp.uint32))
        shlo, comp = compile_step(
            mesh, bad,
            ({"w": P()}, {"m": P()}, P("data"), P()),
            ({"w": P()}, {"m": P()}, {"loss": P()}),
            args, donate=(0, 1),
        )
        rep = audit_artifacts(make_ctx(mesh, policy=bf16), comp, shlo)
        assert any(f.rule == "GA301" for f in rep.findings), rep.format()

        def promoted(p, o, b, k):
            # bf16 data widened to f32 on purpose — policy-intended
            y = b.astype(jnp.float32) @ p["w"].astype(jnp.float32)
            return ({"w": p["w"] + 1}, {"m": o["m"] * 2}, {"loss": y.sum()})

        args_bf16 = ({"w": jax.ShapeDtypeStruct((8, 8), jnp.bfloat16)},
                     {"m": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                     jax.ShapeDtypeStruct((8, 8), jnp.bfloat16),
                     jax.ShapeDtypeStruct((2,), jnp.uint32))
        shlo2, comp2 = compile_step(
            mesh, promoted,
            ({"w": P()}, {"m": P()}, P("data"), P()),
            ({"w": P()}, {"m": P()}, {"loss": P()}),
            args_bf16, donate=(1,),
        )
        params_bf16 = {"w": jax.ShapeDtypeStruct((8, 8), jnp.bfloat16)}
        rep2 = audit_artifacts(
            make_ctx(mesh, policy=bf16, donate="params",
                     params=params_bf16), comp2, shlo2)
        assert not [f for f in rep2.findings if f.rule == "GA301"], \
            rep2.format()

    def test_ga401_bad_specs_curated(self, devices8):
        cfg = load_config(TINY, {
            "data.global_batch_size": 16,
            "data.micro_batch_size": 1,
        })
        asm = assemble_step_program(cfg, devices=devices8, build_data=False)
        asm = dataclasses.replace(
            asm, pspecs={**asm.pspecs, "embed": P("nonexistent_axis")})
        rep = audit_step_program(asm)
        ga401 = [f for f in rep.findings if f.rule == "GA401"]
        assert ga401 and "nonexistent_axis" in ga401[0].message
        assert rep.failed("error")


# --------------------------------------------------------------------------
# alias-map parsing
# --------------------------------------------------------------------------


def test_parse_alias_map_nested_braces():
    hdr = ("HloModule jit_step, is_scheduled=true, input_output_alias={ "
           "{0}: (0, {}, may-alias), {2}: (5, {}, must-alias) }, "
           "entry_computation_layout={(f32[2]{0})->f32[2]{0}}")
    assert parse_alias_map(hdr) == {0: 0, 2: 5}


def test_parse_alias_map_absent():
    assert parse_alias_map("HloModule foo, entry_computation_layout=x") == {}


# --------------------------------------------------------------------------
# config-level audits (the pre-flight CLI path)
# --------------------------------------------------------------------------


class TestConfigAudit:
    def test_tiny_smoke_clean(self):
        rep = audit_config(TINY)
        assert rep.worst() is None, rep.format()
        assert rep.stats["donation_coverage"] == 1.0

    def test_invalid_config_becomes_finding(self):
        rep = audit_config({
            "name": "bad",
            "distributed_strategy": {"sequence_parallel": True},
            "data": {"global_batch_size": 8, "micro_batch_size": 1,
                     "synthetic": True},
            "model": {"num_layers": 2},
        })
        assert any(f.rule == "GA000" for f in rep.findings)
        assert rep.failed("error")

    def test_shrink_preserves_structure(self):
        cfg = load_config(os.path.join(CONF, "hf_llama3_8B_config.yaml"))
        o = shrink_overrides(cfg, max_devices=8)
        assert o["distributed_strategy.tensor_model_parallel_size"] == 2
        assert o["model.num_attention_heads"] % 2 == 0
        assert o["model.hidden_size"] % o["model.num_attention_heads"] == 0
        assert o["model.vocab_size"] % 2 == 0
        # structural knobs untouched: precision / zero1 / fusions flags
        shrunk = load_config(os.path.join(CONF, "hf_llama3_8B_config.yaml"), o)
        assert shrunk.distributed_strategy.sequence_parallel \
            == cfg.distributed_strategy.sequence_parallel
        assert shrunk.get("precision") == cfg.get("precision")

    def test_abstract_batch_alignment_keys(self, devices8):
        cfg = load_config(os.path.join(CONF, "hf_llama3_8B_DPO_config.yaml"),
                          shrink_overrides(load_config(
                              os.path.join(CONF,
                                           "hf_llama3_8B_DPO_config.yaml"))))
        asm = assemble_step_program(cfg, devices=devices8[:4],
                                    build_data=False)
        batch = abstract_batch(asm)
        assert set(batch) == {
            "chosen_input_ids", "rejected_input_ids",
            "reference_chosen_logps", "reference_rejected_logps",
        }


#: every shipped example config must audit clean (acceptance criterion);
#: each lowers in ~1-2 s shrunk, so the sweep stays tier-1
@pytest.mark.parametrize(
    "config_path",
    sorted(glob.glob(os.path.join(CONF, "*.yaml"))),
    ids=lambda p: os.path.basename(p).replace("_config.yaml", ""),
)
def test_example_config_audits_clean(config_path):
    rep = audit_config(config_path)
    assert rep.worst() is None, rep.format()
    assert rep.stats.get("donation_coverage") == 1.0, rep.format()


# --------------------------------------------------------------------------
# in-loop wiring: telemetry.graph_audit audits the census executable
# --------------------------------------------------------------------------


def test_trainer_graph_audit_in_run_summary(tmp_path):
    import json

    from neuronx_distributed_training_tpu.trainer.loop import Trainer

    cfg = load_config(TINY, {
        "exp_manager.exp_dir": str(tmp_path),
        "exp_manager.telemetry.graph_audit": True,
        "data.global_batch_size": 16,
        "data.micro_batch_size": 1,
        "trainer.max_steps": 2,
    })
    trainer = Trainer.from_config(cfg, enable_checkpointing=False)
    trainer.fit()
    with open(os.path.join(trainer.exp.log_dir, "run_summary.json")) as f:
        summary = json.load(f)
    assert "graph_audit" in summary
    assert summary["graph_audit"]["verdict"] == "clean"
    assert summary["graph_audit"]["stats"]["donation_coverage"] == 1.0


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------


def test_preflight_cli_main(monkeypatch, capsys):
    import sys

    tools = os.path.join(os.path.dirname(__file__), "..", "tools")
    sys.path.insert(0, tools)
    try:
        import preflight_audit

        monkeypatch.setattr(sys, "argv", [
            "preflight_audit.py", "--config", TINY, "--lint"])
        with pytest.raises(SystemExit) as exc:
            preflight_audit.main()
        assert exc.value.code == 0
        out = capsys.readouterr().out
        assert "clean" in out and "jaxlint" in out
    finally:
        sys.path.remove(tools)
