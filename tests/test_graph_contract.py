"""Graph contracts: the compile-artifact regression ratchet.

Per-rule fault injections prove the differ fires on every seeded contract
break (added collective, GSPMD reshard, lost donation, dtype upcast, memory
+20%); snapshots are byte-stable across identical runs; the update flow
refuses growth without a justification; and every shipped example config
checks clean against its committed contract with every collective
attributed (the acceptance criterion)."""

import copy
import glob
import json
import os

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.analysis import graph_contract as gc
from neuronx_distributed_training_tpu.analysis.graph_contract import (
    ContractError,
    DeclaredComms,
    attribution_report,
    check_contract,
    diff_fingerprint,
    fingerprint_artifacts,
    fingerprint_config,
    unattributed_entries,
    update_contract,
)
from neuronx_distributed_training_tpu.telemetry.census import (
    _parse_iota_groups,
    collective_ops_from_texts,
)
from tests.test_graph_audit import compile_step, make_ctx, mesh_of

CONF = os.path.join(os.path.dirname(__file__), "..", "examples", "conf")
TINY = os.path.join(CONF, "tiny_smoke_config.yaml")


# --------------------------------------------------------------------------
# HLO collective-line parsing (telemetry.census structured census)
# --------------------------------------------------------------------------


class TestCollectiveParse:
    def test_explicit_groups_and_metadata(self):
        text = (
            "ENTRY %main {\n"
            "  %ar = f32[4]{0} all-reduce(f32[4]{0} %dot), channel_id=1, "
            "replica_groups={{0,1},{2,3}}, use_global_device_ids=true, "
            "to_apply=%add, metadata={op_name=\"jit(f)/dot_general\" "
            "source_file=\"x.py\"}\n"
            "}\n"
        )
        ops = collective_ops_from_texts([text])
        assert len(ops) == 1
        assert ops[0]["kind"] == "all-reduce"
        assert ops[0]["groups"] == [[0, 1], [2, 3]]
        assert ops[0]["source_op"] == "jit(f)/dot_general"

    def test_iota_groups_with_transpose(self):
        # [4,2]<=[2,4]T(1,0): arange(8).reshape(2,4).T.reshape(4,2)
        assert _parse_iota_groups("4,2", "2,4", "1,0") == [
            [0, 4], [1, 5], [2, 6], [3, 7]]

    def test_iota_groups_without_transpose(self):
        assert _parse_iota_groups("2,4", "2,4", None) == [
            [0, 1, 2, 3], [4, 5, 6, 7]]

    def test_iota_line_form(self):
        text = ("  %ag = f32[8]{0} all-gather(f32[4]{0} %p), channel_id=2, "
                "replica_groups=[4,2]<=[2,4]T(1,0), dimensions={0}\n")
        ops = collective_ops_from_texts([text])
        assert ops[0]["groups"] == [[0, 4], [1, 5], [2, 6], [3, 7]]

    def test_start_counts_done_does_not(self):
        text = (
            "  %s = (f32[4], f32[4]) all-gather-start(f32[4] %p), "
            "replica_groups={{0,1}}\n"
            "  %d = f32[4] all-gather-done((f32[4], f32[4]) %s)\n"
        )
        ops = collective_ops_from_texts([text])
        assert len(ops) == 1 and ops[0]["op"] == "s"

    def test_source_target_pairs(self):
        text = ("  %cp = f32[4] collective-permute(f32[4] %x), "
                "source_target_pairs={{0,1},{1,0}}\n")
        ops = collective_ops_from_texts([text])
        assert ops[0]["pairs"] == [(0, 1), (1, 0)]


class TestAxisResolution:
    def test_groups_resolve_to_axes(self, devices8):
        mesh = mesh_of(devices8, (2, 2, 2), ("data", "context", "model"))
        parts = gc._mesh_partitions(mesh)
        coords = gc._device_coords(mesh)
        # consecutive pairs = innermost (model) axis
        axes = gc._axes_of_op(
            {"groups": [[0, 1], [2, 3], [4, 5], [6, 7]], "pairs": None},
            mesh, parts, coords)
        assert axes == ("model",)
        # stride-4 pairs = outermost (data) axis
        axes = gc._axes_of_op(
            {"groups": [[0, 4], [1, 5], [2, 6], [3, 7]], "pairs": None},
            mesh, parts, coords)
        assert axes == ("data",)
        # groups of 4 spanning the two inner axes
        axes = gc._axes_of_op(
            {"groups": [[0, 1, 2, 3], [4, 5, 6, 7]], "pairs": None},
            mesh, parts, coords)
        assert axes == ("context", "model")

    def test_pairs_resolve_and_self_pairs_degenerate(self, devices8):
        mesh = mesh_of(devices8, (2, 2, 2), ("data", "context", "model"))
        parts = gc._mesh_partitions(mesh)
        coords = gc._device_coords(mesh)
        axes = gc._axes_of_op(
            {"groups": None, "pairs": [(0, 4), (4, 0), (1, 5), (5, 1)]},
            mesh, parts, coords)
        assert axes == ("data",)
        # identity pairs only: a no-op edge, not communication
        axes = gc._axes_of_op(
            {"groups": None, "pairs": [(0, 0), (1, 1)]}, mesh, parts, coords)
        assert axes == ()

    def test_irregular_partition_resolves_to_minimal_cover(self, devices8):
        """GSPMD sub-axis groups (no exact axis-subset partition) attribute
        to the MINIMAL axis set whose blocks contain every group — traffic
        confined within an axis's blocks is that axis's communication."""
        mesh = mesh_of(devices8, (2, 2, 2), ("data", "context", "model"))
        parts = gc._mesh_partitions(mesh)
        coords = gc._device_coords(mesh)
        # irregular pairing inside each (context, model) block of 4
        axes = gc._axes_of_op(
            {"groups": [[0, 3], [1, 2], [4, 7], [5, 6]], "pairs": None},
            mesh, parts, coords)
        assert axes == ("context", "model")
        # half-axis groups on a flat data mesh still read as data traffic
        flat = mesh_of(devices8, (8,), ("data",))
        fparts = gc._mesh_partitions(flat)
        fcoords = gc._device_coords(flat)
        axes = gc._axes_of_op(
            {"groups": [[0, 1, 2, 3], [4, 5, 6, 7]], "pairs": None},
            flat, fparts, fcoords)
        assert axes == ("data",)


# --------------------------------------------------------------------------
# provenance: a seeded GSPMD reshard is flagged with the nearest named op
# --------------------------------------------------------------------------


class TestProvenance:
    def test_declared_zero1_attributes(self, devices8):
        mesh = mesh_of(devices8, (8,), ("data",))

        def step(p, o, b, k):
            return ({"w": p["w"] + 1}, {"m": o["m"] * 2}, {"loss": b.sum()})

        args = ({"w": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                {"m": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                jax.ShapeDtypeStruct((8, 8), jnp.float32),
                jax.ShapeDtypeStruct((2,), jnp.uint32))
        shlo, comp = compile_step(
            mesh, step,
            ({"w": P()}, {"m": P("data")}, P("data"), P()),
            ({"w": P()}, {"m": P("data")}, {"loss": P()}),
            args, donate=(0, 1),
        )
        fp = fingerprint_artifacts(make_ctx(mesh), comp, shlo)
        rep = attribution_report(fp)
        assert rep.stats["collectives_unattributed"] == 0, rep.format()
        assert not rep.findings

    def test_seeded_reshard_fires_gc201(self, devices8):
        """A dp-only config with zero1 off has no declared source for an
        all-gather: a batch-sharded value regathered to replicated is a
        GSPMD-inserted reshard — GC201, naming the op."""
        mesh = mesh_of(devices8, (8,), ("data",))

        def step(p, o, b, k):
            big = jnp.broadcast_to(b[:, None], (8, 64)) * p["w"].sum()
            return ({"w": p["w"] + 1}, {"m": o["m"] * 2},
                    {"gathered": big})

        args = ({"w": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                {"m": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                jax.ShapeDtypeStruct((8,), jnp.float32),
                jax.ShapeDtypeStruct((2,), jnp.uint32))
        shlo, comp = compile_step(
            mesh, step,
            ({"w": P()}, {"m": P()}, P("data"), P()),
            ({"w": P()}, {"m": P()}, {"gathered": P()}),
            args, donate=(0, 1),
        )
        ctx = make_ctx(mesh, zero1=False)
        fp = fingerprint_artifacts(ctx, comp, shlo)
        unattr = unattributed_entries(fp)
        assert unattr, fp["collectives"]
        rep = attribution_report(fp)
        assert rep.failed("error")
        f = [x for x in rep.findings if x.rule == "GC201"][0]
        assert "no declared source" in f.message
        assert "nearest named op" in f.message
        assert f.location  # the offending HLO op is named

    def test_waiver_silences_gc201(self, devices8):
        mesh = mesh_of(devices8, (4, 2), ("data", "model"))
        fp = {"config": "x", "collectives": {
            "all-to-all|model": {"count": 2, "source": None, "hint": "",
                                 "sample_ops": ["all-to-all.1"],
                                 "sample_source_ops": ["jit(f)/transpose"]}}}
        assert attribution_report(fp).failed("error")
        rep = attribution_report(fp, waivers={"all-to-all|model": "known"})
        assert not rep.findings

    def test_source_classes_respect_declarations(self):
        d = DeclaredComms(tp=2, pp=1, cp=1, ep=1, dp=4, zero1=True,
                          seq_par=False, moe=False, ulysses=False, ring=False)
        rules = gc.declared_source_classes(d)
        assert gc.attribute("all-reduce", ("model",), [], rules)[0] \
            == "tp/SP layer collective"
        # no seq_par: an all-to-all over model has no declared source
        assert gc.attribute("all-to-all", ("model",), [], rules) is None
        # zero1 explains data-axis gathers
        assert "ZeRO-1" in gc.attribute(
            "all-gather", ("data",), [], rules)[0]
        d2 = DeclaredComms(tp=2, pp=1, cp=1, ep=1, dp=4, zero1=False,
                           seq_par=True, moe=False, ulysses=False, ring=False)
        rules2 = gc.declared_source_classes(d2)
        assert gc.attribute("all-to-all", ("model",), [], rules2)[0] \
            == "SP seq<->hidden reshard"
        assert gc.attribute("all-gather", ("data",), [], rules2) is None


# --------------------------------------------------------------------------
# the semantic differ: per-rule fault injections
# --------------------------------------------------------------------------


def base_fp():
    return {
        "version": gc.FINGERPRINT_VERSION,
        "config": "fault.yaml",
        "mesh": {"pipe": 1, "data": 2, "expert": 1, "context": 1, "model": 2},
        "collectives": {
            "all-gather|data": {
                "count": 2, "source": "ZeRO-1 parameter all-gather",
                "hint": "ZeRO-1 resharding duplicated; likely spec change "
                        "in optim/zero1",
                "sample_ops": ["all-gather.1"], "sample_source_ops": ["w"]},
            "all-reduce|model": {
                "count": 4, "source": "tp/SP layer collective", "hint": "",
                "sample_ops": ["all-reduce.2"], "sample_source_ops": ["d"]},
        },
        "donation": {"expected": 4, "aliased": 4, "coverage": 1.0,
                     "missing": []},
        "matmul_dtypes": {"counts": {"bf16xbf16": 10},
                          "samples": {"bf16xbf16": "dot_general (...)"}},
        "memory": {"argument_size_in_bytes": 800, "temp_size_in_bytes": 200,
                   "resident_bytes": 1000},
    }


class TestDiffer:
    def test_identical_is_clean(self):
        rep = diff_fingerprint(base_fp(), base_fp())
        assert not rep.findings

    def test_added_collective_explained_in_config_terms(self):
        new = base_fp()
        new["collectives"]["all-gather|data"]["count"] = 4
        rep = diff_fingerprint(base_fp(), new)
        assert rep.failed("error")
        f = [x for x in rep.findings if x.rule == "GC101"][0]
        assert "[data]-axis all-gather count 2 -> 4" in f.message
        assert "ZeRO-1 parameter all-gather" in f.message
        assert "optim/zero1" in f.hint
        assert "all-gather.1" in f.message  # names the offending HLO op

    def test_new_unattributed_key_is_gc201(self):
        new = base_fp()
        new["collectives"]["all-to-all|model"] = {
            "count": 3, "source": None, "hint": "",
            "sample_ops": ["all-to-all.7"],
            "sample_source_ops": ["jit(step)/transpose"]}
        rep = diff_fingerprint(base_fp(), new)
        f = [x for x in rep.findings if x.rule == "GC201"][0]
        assert "GSPMD-inserted reshard" in f.message
        assert "jit(step)/transpose" in f.message
        assert rep.failed("error")

    def test_lost_donation_names_leaf(self):
        new = base_fp()
        new["donation"] = {"expected": 4, "aliased": 3, "coverage": 0.75,
                           "missing": ["params/w"]}
        rep = diff_fingerprint(base_fp(), new)
        f = [x for x in rep.findings if x.rule == "GC301"][0]
        assert "params/w" in f.message and "alias" in f.message
        assert rep.failed("error")

    def test_dtype_upcast_fires(self):
        new = base_fp()
        new["matmul_dtypes"]["counts"]["f32xf32"] = 2
        new["matmul_dtypes"]["samples"]["f32xf32"] = \
            "dot_general (tensor<8x8xf32> x tensor<8x8xf32>)"
        rep = diff_fingerprint(base_fp(), new)
        f = [x for x in rep.findings if x.rule == "GC401"][0]
        assert f.severity == "error" and "upcast" in f.message
        assert "f32" in f.location  # names the offending dot
        assert rep.failed("error")

    def test_memory_growth_20pct_fires_10pct_tolerated(self):
        new = base_fp()
        new["memory"]["resident_bytes"] = 1200
        rep = diff_fingerprint(base_fp(), new)
        assert any(f.rule == "GC501" and f.severity == "error"
                   for f in rep.findings)
        ok = base_fp()
        ok["memory"]["resident_bytes"] = 1050
        assert not diff_fingerprint(base_fp(), ok).failed("error")

    def test_shrink_is_info_only(self):
        new = base_fp()
        new["collectives"]["all-reduce|model"]["count"] = 2
        new["memory"]["resident_bytes"] = 500
        rep = diff_fingerprint(base_fp(), new)
        assert rep.findings  # the improvement is reported...
        assert not rep.failed("error")  # ...but the ratchet passes
        assert all(f.severity == "info" for f in rep.findings)

    def test_mesh_change_invalidates_contract(self):
        new = base_fp()
        new["mesh"]["model"] = 4
        rep = diff_fingerprint(base_fp(), new)
        assert any(f.rule == "GC002" for f in rep.findings)
        assert rep.failed("error")

    def test_waived_key_growth_still_fails(self):
        old = base_fp()
        old["collectives"]["all-to-all|model"] = {
            "count": 1, "source": None, "hint": "", "sample_ops": ["a.1"],
            "sample_source_ops": []}
        new = copy.deepcopy(old)
        new["collectives"]["all-to-all|model"]["count"] = 3
        rep = diff_fingerprint(old, new, waivers={"all-to-all|model": "ok"})
        assert any(f.rule == "GC101" for f in rep.findings)
        assert rep.failed("error")


# --------------------------------------------------------------------------
# snapshots: byte stability + the justification ratchet
# --------------------------------------------------------------------------


class TestSnapshotRatchet:
    def test_update_then_check_clean(self, tmp_path):
        path, rep = update_contract("fault.yaml", base_fp(),
                                    contracts_dir=tmp_path)
        assert path.exists()
        crep = check_contract("fault.yaml", base_fp(), contracts_dir=tmp_path)
        assert not crep.findings

    def test_missing_contract_is_gc000(self, tmp_path):
        rep = check_contract("fault.yaml", base_fp(), contracts_dir=tmp_path)
        assert any(f.rule == "GC000" for f in rep.findings)
        assert rep.failed("error")

    def test_rewrite_is_byte_stable(self, tmp_path):
        path, _ = update_contract("fault.yaml", base_fp(),
                                  contracts_dir=tmp_path)
        first = path.read_bytes()
        update_contract("fault.yaml", base_fp(), contracts_dir=tmp_path)
        assert path.read_bytes() == first

    def test_growth_refuses_without_justify(self, tmp_path):
        update_contract("fault.yaml", base_fp(), contracts_dir=tmp_path)
        grown = base_fp()
        grown["collectives"]["all-gather|data"]["count"] = 4
        with pytest.raises(ContractError, match="justify"):
            update_contract("fault.yaml", grown, contracts_dir=tmp_path)
        # the committed file is untouched by the refused update
        crep = check_contract("fault.yaml", base_fp(), contracts_dir=tmp_path)
        assert not crep.findings

    def test_growth_with_justify_records_in_file(self, tmp_path):
        update_contract("fault.yaml", base_fp(), contracts_dir=tmp_path)
        grown = base_fp()
        grown["collectives"]["all-gather|data"]["count"] = 4
        path, _ = update_contract(
            "fault.yaml", grown, justify="fused CE adds one regather pair",
            contracts_dir=tmp_path)
        snap = json.loads(path.read_text())
        assert "fused CE adds one regather pair" in snap["justifications"]
        crep = check_contract("fault.yaml", grown, contracts_dir=tmp_path)
        assert not crep.failed("error")

    def test_shrink_updates_silently(self, tmp_path):
        update_contract("fault.yaml", base_fp(), contracts_dir=tmp_path)
        better = base_fp()
        better["collectives"]["all-reduce|model"]["count"] = 2
        path, rep = update_contract("fault.yaml", better,
                                    contracts_dir=tmp_path)  # no justify
        assert not rep.failed("error")
        snap = json.loads(path.read_text())
        assert snap["fingerprint"]["collectives"]["all-reduce|model"][
            "count"] == 2

    def test_unattributed_needs_justify_and_becomes_waiver(self, tmp_path):
        fp = base_fp()
        fp["collectives"]["all-to-all|model"] = {
            "count": 1, "source": None, "hint": "", "sample_ops": ["a.9"],
            "sample_source_ops": []}
        with pytest.raises(ContractError):
            update_contract("fault.yaml", fp, contracts_dir=tmp_path)
        path, _ = update_contract("fault.yaml", fp,
                                  justify="known ulysses boundary reshard",
                                  contracts_dir=tmp_path)
        snap = json.loads(path.read_text())
        assert snap["waivers"] == {
            "all-to-all|model": "known ulysses boundary reshard"}
        # and the waived reshard no longer fails the check
        crep = check_contract("fault.yaml", fp, contracts_dir=tmp_path)
        assert not crep.failed("error")


# --------------------------------------------------------------------------
# end to end: fingerprint a real config, break it, watch the ratchet fire
# --------------------------------------------------------------------------


class TestEndToEnd:
    @pytest.fixture(scope="class")
    def tiny_fp(self):
        return fingerprint_config(TINY)

    def test_fingerprint_byte_stable_across_runs(self, tiny_fp):
        fp2 = fingerprint_config(TINY)
        assert json.dumps(tiny_fp, sort_keys=True) \
            == json.dumps(fp2, sort_keys=True)

    def test_tiny_attributes_fully(self, tiny_fp):
        rep = attribution_report(tiny_fp)
        assert rep.stats["collectives_unattributed"] == 0, rep.format()
        assert rep.stats["collectives_total"] > 0

    def test_tiny_checks_clean_against_committed(self, tiny_fp):
        rep = check_contract(TINY, tiny_fp)
        assert not rep.failed("error"), rep.format()

    def test_seeded_breaks_fail_check(self, tiny_fp, tmp_path):
        update_contract(TINY, tiny_fp, contracts_dir=tmp_path)
        broken = copy.deepcopy(tiny_fp)
        key = next(iter(broken["collectives"]))
        broken["collectives"][key]["count"] += 2
        broken["donation"]["missing"] = ["params/embed"]
        broken["donation"]["coverage"] = 0.97
        broken["matmul_dtypes"]["counts"]["f32xf32"] = \
            broken["matmul_dtypes"]["counts"].get("f32xf32", 0) + 5
        broken["memory"]["resident_bytes"] = int(
            broken["memory"]["resident_bytes"] * 1.2)
        rep = check_contract(TINY, broken, contracts_dir=tmp_path)
        rules = {f.rule for f in rep.findings if f.severity == "error"}
        assert {"GC101", "GC301", "GC401", "GC501"} <= rules, rep.format()


#: every shipped example config must check clean against its committed
#: contract with every collective attributed (acceptance criterion); the
#: shrunk lowering is ~1-2 s per config, so the sweep stays tier-1
@pytest.mark.parametrize(
    "config_path",
    sorted(glob.glob(os.path.join(CONF, "*.yaml"))),
    ids=lambda p: os.path.basename(p).replace("_config.yaml", ""),
)
def test_example_config_contract_clean(config_path):
    fp = fingerprint_config(config_path)
    assert not unattributed_entries(fp), json.dumps(
        unattributed_entries(fp), indent=1)
    rep = check_contract(config_path, fp)
    assert not rep.failed("error"), rep.format()


# --------------------------------------------------------------------------
# in-loop wiring: the telemetry.graph_audit verdict carries provenance
# --------------------------------------------------------------------------


def test_trainer_graph_audit_contract_in_run_summary(tmp_path):
    from neuronx_distributed_training_tpu.config.loader import load_config
    from neuronx_distributed_training_tpu.trainer.loop import Trainer

    cfg = load_config(TINY, {
        "exp_manager.exp_dir": str(tmp_path),
        "exp_manager.telemetry.graph_audit": True,
        "data.global_batch_size": 16,
        "data.micro_batch_size": 1,
        "trainer.max_steps": 2,
    })
    trainer = Trainer.from_config(cfg, enable_checkpointing=False)
    trainer.fit()
    with open(os.path.join(trainer.exp.log_dir, "run_summary.json")) as f:
        summary = json.load(f)
    audit = summary["graph_audit"]
    assert audit["verdict"] == "clean"
    contract = audit["contract"]
    assert contract["collectives_unattributed"] == 0
    assert contract["collectives_total"] > 0
    assert all(v["source"] for v in contract["collectives"].values())
    assert contract["matmul_dtypes"]


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------


def test_graph_contract_cli_check(monkeypatch, capsys):
    import sys

    tools = os.path.join(os.path.dirname(__file__), "..", "tools")
    sys.path.insert(0, tools)
    try:
        import graph_contract as cli

        monkeypatch.setattr(sys, "argv", [
            "graph_contract.py", "--check", "--config", TINY, "--json", "-"])
        with pytest.raises(SystemExit) as exc:
            cli.main()
        assert exc.value.code == 0
        out = capsys.readouterr().out
        assert "clean" in out
        payload = json.loads(out.strip().splitlines()[-1])
        assert payload["reports"][0]["verdict"] == "clean"
        assert payload["reports"][0]["fingerprint"]["collectives"]
    finally:
        sys.path.remove(tools)


def test_graph_contract_cli_update_to_tmpdir(monkeypatch, capsys, tmp_path):
    import sys

    tools = os.path.join(os.path.dirname(__file__), "..", "tools")
    sys.path.insert(0, tools)
    try:
        import graph_contract as cli

        monkeypatch.setattr(sys, "argv", [
            "graph_contract.py", "--update-contracts", "--config", TINY,
            "--contracts-dir", str(tmp_path)])
        with pytest.raises(SystemExit) as exc:
            cli.main()
        assert exc.value.code == 0
        assert (tmp_path / "tiny_smoke_config.json").exists()
        monkeypatch.setattr(sys, "argv", [
            "graph_contract.py", "--check", "--config", TINY,
            "--contracts-dir", str(tmp_path)])
        with pytest.raises(SystemExit) as exc:
            cli.main()
        assert exc.value.code == 0
    finally:
        sys.path.remove(tools)


def test_preflight_contracts_flag(monkeypatch, capsys):
    import sys

    tools = os.path.join(os.path.dirname(__file__), "..", "tools")
    sys.path.insert(0, tools)
    try:
        import preflight_audit

        monkeypatch.setattr(sys, "argv", [
            "preflight_audit.py", "--config", TINY, "--contracts"])
        with pytest.raises(SystemExit) as exc:
            preflight_audit.main()
        assert exc.value.code == 0
        out = capsys.readouterr().out
        assert "contract [tiny_smoke_config.yaml]: clean" in out
    finally:
        sys.path.remove(tools)
